"""Python client: DB-API-flavored access to a broker fleet.

Equivalent of the reference's client libraries (pinot-clients/
pinot-java-client's Connection/ResultSetGroup and the external pinotdb
driver): ``connect()`` to one broker HTTP endpoint, a broker URL *list*,
a cluster registry (fleet discovery), or an in-process Broker — cursors
with ``execute`` / ``fetch*`` / ``description`` / ``rowcount``, and
broker response stats on the cursor. Read-only by design — DML raises,
like the reference.

    from pinot_tpu.client import connect
    conn = connect("http://localhost:8099")                  # one broker
    conn = connect(broker_urls=["http://a:8099", "http://b:8099"])
    conn = connect(registry=reg, discover=True)              # fleet
    cur = conn.cursor()
    cur.execute("SELECT city, COUNT(*) FROM t GROUP BY city")
    for row in cur:
        ...

Fleet behavior (ISSUE 18): queries round-robin across the target list;
a draining broker (HTTP 503 / in-band ``brokerDraining``) or a connect
failure rotates to the next target, bounded at two passes over the
fleet before failing typed (``NoLiveBrokersError``) — a fleet of
draining brokers fails fast instead of spinning. The 429 over-quota
policy is single-sourced in ``retry_after_s`` / ``is_quota_rejection``
and composes with rotation: a 429 retries ONCE against the same broker
after its Retry-After (quota is pacing, not placement), while 503s and
connect failures move on.

Streaming (``Cursor.execute_stream``): rows arrive incrementally
(in-process generator or HTTP chunked NDJSON from /query/sql/stream) —
``fetchone``/iteration pull from the live stream, so a 10M-row SELECT
never materializes client- or broker-side; ``cursor.stats`` fills when
the final chunk lands.
"""

from __future__ import annotations

import itertools
import json
import urllib.error
import urllib.request
from typing import Optional

apilevel = "2.0"
threadsafety = 1
paramstyle = "qmark"


class Error(Exception):
    """DB-API base error."""


class DatabaseError(Error):
    """Query-level failure reported by the cluster."""


class ProgrammingError(Error):
    """Client misuse (closed cursor, fetch before execute...)."""


class NoLiveBrokersError(DatabaseError):
    """Every broker in the rotation refused (draining) or was
    unreachable for two full passes — the typed fleet-exhaustion
    failure (never an unbounded spin)."""


# ---- 429 over-quota policy: ONE definition for every path --------------
# (in-process, HTTP unary, HTTP streaming): one bounded retry after
# Retry-After — a per-table QPS quota / admission 429 is a *pacing*
# signal, not a hard failure; the sleep is capped so a hostile or buggy
# header can't hang a client.
MAX_RETRY_AFTER_S = 5.0


def retry_after_s(value) -> float:
    """Clamp a Retry-After hint (header string or retryAfterSeconds
    number) to [0.05, MAX_RETRY_AFTER_S]; unparseable → 0.5 s."""
    try:
        return max(0.05, min(float(value), MAX_RETRY_AFTER_S))
    except (TypeError, ValueError):
        return 0.5


def is_quota_rejection(resp: dict) -> bool:
    """True when EVERY exception in a broker response is a 429 (quota /
    admission rejection — retriable after the response's own hint)."""
    excs = resp.get("exceptions") or []
    return bool(excs) and all(x.get("errorCode") == 429 for x in excs)


def _is_drain_rejection(resp: dict) -> bool:
    excs = resp.get("exceptions") or []
    return bool(resp.get("brokerDraining")) or (
        bool(excs) and all(x.get("errorCode") == 503 for x in excs))


class _RotateToPeer(Exception):
    """Internal: this target refused (draining) or is unreachable —
    try the next broker in the rotation."""


class Cursor:
    arraysize = 1

    def __init__(self, conn: "Connection"):
        self._conn = conn
        self._rows: Optional[list] = None
        self._pos = 0
        self.description = None
        self.rowcount = -1
        self.stats: dict = {}
        self._closed = False
        # streaming mode (execute_stream): live chunk iterator + the
        # current rows-chunk buffer
        self._chunks = None
        self._buf: list = []
        self._buf_pos = 0
        self._streamed = False

    # ---- DB-API surface -------------------------------------------------
    def execute(self, sql: str, params=None) -> "Cursor":
        if self._closed:
            raise ProgrammingError("cursor is closed")
        self._chunks = None
        self._buf, self._buf_pos = [], 0
        self._streamed = False
        sql = self._bind(sql, params)
        resp = self._conn._execute(sql)
        if resp.get("exceptions"):
            raise DatabaseError(resp["exceptions"])
        rt = resp.get("resultTable") or {"dataSchema": {"columnNames": [],
                                                        "columnDataTypes": []},
                                         "rows": []}
        names = rt["dataSchema"]["columnNames"]
        types = rt["dataSchema"]["columnDataTypes"]
        self.description = [(n, t, None, None, None, None, None)
                            for n, t in zip(names, types)]
        self._rows = [tuple(r) for r in rt["rows"]]
        self._pos = 0
        self.rowcount = len(self._rows)
        self.stats = {k: v for k, v in resp.items()
                      if k not in ("resultTable", "exceptions")}
        return self

    def execute_stream(self, sql: str, params=None) -> "Cursor":
        """Streaming execute (ISSUE 18): rows flow through ``fetchone``/
        iteration as the broker produces them. ``description`` fills from
        the stream's schema chunk before this returns; ``rowcount`` stays
        -1 (unknown until exhaustion) and ``stats`` fills when the final
        chunk arrives. Works for every query shape — the broker falls
        back to buffered-re-chunked delivery for non-streamable plans."""
        if self._closed:
            raise ProgrammingError("cursor is closed")
        sql = self._bind(sql, params)
        self._rows = None
        self._streamed = True
        self._buf, self._buf_pos = [], 0
        self._pos = 0
        self.rowcount = -1
        self.stats = {}
        self.description = None
        self._chunks = self._conn._execute_stream(sql)
        # pull until the schema (or a rowless final) so description is
        # usable immediately, like execute()
        while self.description is None and self._chunks is not None:
            if not self._pull_chunk():
                break
        return self

    def _pull_chunk(self) -> bool:
        """Advance the stream one chunk. Returns False at exhaustion."""
        try:
            chunk = next(self._chunks)
        except StopIteration:
            self._chunks = None
            return False
        kind = chunk.get("type")
        if kind == "schema":
            self.description = [
                (n, t, None, None, None, None, None)
                for n, t in zip(chunk.get("columnNames") or [],
                                chunk.get("columnDataTypes") or [])]
        elif kind == "rows":
            self._buf = chunk.get("rows") or []
            self._buf_pos = 0
        elif kind == "final":
            self._chunks = None
            self.stats = {k: v for k, v in chunk.items()
                          if k not in ("type", "exceptions")}
            if chunk.get("exceptions"):
                raise DatabaseError(chunk["exceptions"])
            return False
        return True

    @staticmethod
    def _bind(sql: str, params) -> str:
        if params is None:
            return sql
        # qmark substitution with conservative literal quoting;
        # ? inside single-quoted literals is not a placeholder
        parts = _split_placeholders(sql)
        if len(parts) != len(params) + 1:
            raise ProgrammingError(
                f"query has {len(parts) - 1} placeholders, "
                f"{len(params)} params given")
        out = []
        for i, p in enumerate(parts):
            out.append(p)
            if i < len(params):
                out.append(_quote(params[i]))
        return "".join(out)

    def _require_rows(self) -> list:
        if self._closed:
            raise ProgrammingError("cursor is closed")
        if self._rows is None and not self._streamed:
            raise ProgrammingError("fetch before execute")
        return self._rows if self._rows is not None else []

    def fetchone(self):
        if self._chunks is not None or self._buf_pos < len(self._buf):
            # streaming mode: drain the buffered chunk, then pull more
            while self._buf_pos >= len(self._buf):
                if self._chunks is None or not self._pull_chunk():
                    return None
            row = tuple(self._buf[self._buf_pos])
            self._buf_pos += 1
            return row
        rows = self._require_rows()
        if self._pos >= len(rows):
            return None
        row = rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None) -> list:
        if size is None:
            size = self.arraysize
        if self._chunks is not None or self._buf_pos < len(self._buf):
            out = []
            while len(out) < size:
                row = self.fetchone()
                if row is None:
                    break
                out.append(row)
            return out
        rows = self._require_rows()
        out = rows[self._pos: self._pos + size]
        self._pos += len(out)
        return out

    def fetchall(self) -> list:
        if self._chunks is not None or self._buf_pos < len(self._buf):
            out = []
            while True:
                row = self.fetchone()
                if row is None:
                    return out
                out.append(row)
        rows = self._require_rows()
        out = rows[self._pos:]
        self._pos = len(rows)
        return out

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

    def close(self) -> None:
        self._closed = True
        self._rows = None
        self._chunks = None
        self._buf = []


def _split_placeholders(sql: str) -> list:
    """Split on ? placeholders, ignoring ?s inside single-quoted strings
    AND double-quoted identifiers."""
    parts, cur = [], []
    in_sq = in_dq = False
    for ch in sql:
        if ch == "'" and not in_dq:
            in_sq = not in_sq
            cur.append(ch)
        elif ch == '"' and not in_sq:
            in_dq = not in_dq
            cur.append(ch)
        elif ch == "?" and not in_sq and not in_dq:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


def _quote(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    s = str(v).replace("'", "''")
    return f"'{s}'"


class Connection:
    # two full passes over the rotation before failing typed: enough to
    # ride out one rolling drain, never an unbounded spin
    MAX_ROTATION_PASSES = 2

    # legacy aliases — the policy itself is single-sourced module-level
    MAX_RETRY_AFTER_S = MAX_RETRY_AFTER_S
    _retry_after_s = staticmethod(retry_after_s)
    _is_quota_rejection = staticmethod(is_quota_rejection)

    def __init__(self, broker_url: Optional[str] = None, broker=None,
                 registry=None, timeout_s: float = 30.0, auth=None,
                 ssl_context=None, broker_urls: Optional[list] = None,
                 brokers: Optional[list] = None, discover: bool = False):
        """``auth``: optional (username, password) for brokers running
        with HTTP Basic auth. ``ssl_context``: optional ssl.SSLContext for
        https:// broker URLs (e.g. TlsConfig.client_ssl_context() to trust
        a private CA). ``broker_urls``/``brokers``: a rotation list of
        HTTP endpoints / in-process Broker objects. ``registry`` with
        ``discover=True`` re-discovers the live fleet's URLs from broker
        heartbeats each query; ``registry`` alone keeps the embedded
        single-broker behavior."""
        self._ssl_context = ssl_context
        if broker_url is None and broker is None and registry is None \
                and not broker_urls and not brokers:
            raise ProgrammingError(
                "connect() needs a broker_url (or broker_urls), a Broker "
                "(or brokers), or a registry")
        self._urls = [u.rstrip("/") for u in (broker_urls or []) if u]
        if broker_url:
            self._urls.insert(0, broker_url.rstrip("/"))
        self._url = self._urls[0] if self._urls else None  # legacy attr
        self._auth_header = None
        if auth is not None:
            import base64

            cred = base64.b64encode(
                f"{auth[0]}:{auth[1]}".encode("utf-8")).decode("ascii")
            self._auth_header = f"Basic {cred}"
        self._brokers = list(brokers or [])
        if broker is not None:
            self._brokers.insert(0, broker)
        self._registry = registry if discover else None
        self._owns_broker = False
        if not self._brokers and not self._urls and registry is not None \
                and not discover:
            from pinot_tpu.broker.broker import Broker

            self._brokers = [Broker(registry, timeout_s=timeout_s)]
            self._owns_broker = True
        self._timeout_s = timeout_s
        self._rr = itertools.count()  # round-robin start offset
        self._closed = False

    # ---- target rotation -------------------------------------------------
    def _targets(self) -> list:
        """The current rotation list: ("proc", Broker) and ("http", url)
        entries; registry-discovery mode re-reads the live fleet."""
        targets = [("proc", b) for b in self._brokers]
        urls = list(self._urls)
        if self._registry is not None:
            from pinot_tpu.broker.fleet import discover_broker_urls

            urls += [u for u in discover_broker_urls(self._registry)
                     if u not in urls]
        targets += [("http", u) for u in urls]
        return targets

    def _rotate(self, fn):
        """Run ``fn(kind, target)`` against the rotation: round-robin
        start, advance on _RotateToPeer, bounded passes, typed
        exhaustion. The single rotation loop both unary and streaming
        executes ride."""
        if self._closed:
            raise ProgrammingError("connection is closed")
        targets = self._targets()
        if not targets:
            raise NoLiveBrokersError(
                "no live brokers (discovery returned an empty fleet)")
        start = next(self._rr)
        last: Optional[Exception] = None
        for n in range(self.MAX_ROTATION_PASSES * len(targets)):
            kind, target = targets[(start + n) % len(targets)]
            try:
                return fn(kind, target)
            except _RotateToPeer as e:
                last = e.__cause__ or e
                continue
        raise NoLiveBrokersError(
            f"all {len(targets)} broker(s) draining or unreachable "
            f"after {self.MAX_ROTATION_PASSES} passes "
            f"(last: {last})") from last

    # ---- unary execute ---------------------------------------------------
    def _execute(self, sql: str) -> dict:
        return self._rotate(
            lambda kind, target: self._execute_proc(target, sql)
            if kind == "proc" else self._execute_http(target, sql,
                                                      retry_quota=True))

    def _execute_proc(self, broker, sql: str) -> dict:
        resp = broker.execute(sql)
        if _is_drain_rejection(resp):
            raise _RotateToPeer(f"broker {resp.get('brokerId')} draining")
        if is_quota_rejection(resp):
            # in-process brokers ship the 429 in-band; honor the
            # response's own hint when present, then retry ONCE
            import time

            time.sleep(retry_after_s(resp.get("retryAfterSeconds", 0.5)))
            resp = broker.execute(sql)
            if _is_drain_rejection(resp):
                raise _RotateToPeer(
                    f"broker {resp.get('brokerId')} draining")
        return resp

    def _http_request(self, url: str, path: str, sql: str):
        headers = {"Content-Type": "application/json"}
        if self._auth_header:
            headers["Authorization"] = self._auth_header
        return urllib.request.Request(
            url + path,
            data=json.dumps({"sql": sql}).encode("utf-8"),
            headers=headers,
        )

    def _execute_http(self, url: str, sql: str, retry_quota: bool) -> dict:
        req = self._http_request(url, "/query/sql", sql)
        try:
            with urllib.request.urlopen(req, timeout=self._timeout_s,
                                        context=self._ssl_context) as resp:
                return json.loads(resp.read())
        except Error:
            raise
        except urllib.error.HTTPError as e:
            if e.code == 401:
                raise DatabaseError(
                    "authentication failed (HTTP 401): check the "
                    "connection's auth=(user, password)") from e
            if e.code == 429 and retry_quota:
                # over-quota: back off for the broker's Retry-After
                # (bounded) and retry once before surfacing the error
                import time

                time.sleep(retry_after_s(
                    e.headers.get("Retry-After") if e.headers else None))
                return self._execute_http(url, sql, retry_quota=False)
            if e.code == 503:
                # draining broker: typed refusal — rotate to a peer
                raise _RotateToPeer(f"broker {url} draining") from e
            raise DatabaseError(f"broker returned HTTP {e.code}") from e
        except urllib.error.URLError as e:
            # connect failure (broker down / not listening): rotate
            raise _RotateToPeer(f"broker {url} unreachable") from e
        except Exception as e:  # noqa: BLE001 — transport failure
            raise DatabaseError(f"broker unreachable: {e}") from e

    # ---- streaming execute -----------------------------------------------
    def _execute_stream(self, sql: str):
        """Chunk-dict iterator for Cursor.execute_stream. Rotation
        happens at stream OPEN (drain / connect failure / leading 429);
        once row chunks flow, failures surface in-band in the final
        chunk — a mid-stream replay could duplicate rows."""
        return self._rotate(
            lambda kind, target: self._open_proc_stream(target, sql)
            if kind == "proc" else self._open_http_stream(target, sql,
                                                          retry_quota=True))

    def _open_proc_stream(self, broker, sql: str, retry_quota: bool = True):
        gen = broker.execute_stream(sql)
        first = next(gen, None)
        if first is None:
            return iter(())
        if first.get("type") == "final":
            if _is_drain_rejection(first):
                raise _RotateToPeer(
                    f"broker {first.get('brokerId')} draining")
            if is_quota_rejection(first) and retry_quota:
                import time

                time.sleep(retry_after_s(
                    first.get("retryAfterSeconds", 0.5)))
                return self._open_proc_stream(broker, sql,
                                              retry_quota=False)
        return itertools.chain([first], gen)

    def _open_http_stream(self, url: str, sql: str, retry_quota: bool):
        req = self._http_request(url, "/query/sql/stream", sql)
        try:
            resp = urllib.request.urlopen(req, timeout=self._timeout_s,
                                          context=self._ssl_context)
        except urllib.error.HTTPError as e:
            if e.code == 401:
                raise DatabaseError(
                    "authentication failed (HTTP 401): check the "
                    "connection's auth=(user, password)") from e
            if e.code == 503:
                raise _RotateToPeer(f"broker {url} draining") from e
            raise DatabaseError(f"broker returned HTTP {e.code}") from e
        except urllib.error.URLError as e:
            raise _RotateToPeer(f"broker {url} unreachable") from e

        def gen():
            # urllib/http.client decode the chunked framing; each line is
            # one NDJSON chunk dict
            with resp:
                for line in resp:
                    line = line.strip()
                    if line:
                        yield json.loads(line)

        it = gen()
        first = next(it, None)
        if first is None:
            return iter(())
        if first.get("type") == "final" and is_quota_rejection(first) \
                and retry_quota:
            import time

            for _ in it:  # drain the connection before reuse
                pass
            time.sleep(retry_after_s(first.get("retryAfterSeconds", 0.5)))
            return self._open_http_stream(url, sql, retry_quota=False)
        return itertools.chain([first], it)

    def cursor(self) -> Cursor:
        if self._closed:
            raise ProgrammingError("connection is closed")
        return Cursor(self)

    def close(self) -> None:
        self._closed = True
        if self._owns_broker:
            for b in self._brokers:
                b.close()

    def commit(self) -> None:
        pass  # read-only: DB-API requires the method to exist

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def connect(broker_url: Optional[str] = None, **kwargs) -> Connection:
    return Connection(broker_url, **kwargs)
