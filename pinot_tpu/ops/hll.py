"""HyperLogLog on device: DISTINCTCOUNTHLL's kernel.

The reference delegates to the clearspring HyperLogLog Java lib
(DistinctCountHLLAggregationFunction.java, ObjectSerDeUtils); here the
register update is a TPU-friendly scatter-max over (m,) int32 registers —
registers merge across segments/chips with an elementwise max (psum-style
combine), and the cardinality estimate runs host-side from the registers.
When the Pallas scatter tier is on, register spaces up to its slot bound
build through the rho-threshold-presence kernel instead
(ops/pallas_scatter.py hll_register_max; engine/device.py _hll_regs
routes) — the serialized scatter-max here stays compiled-in as the
differential reference and fallback rung.

Hashing: 32-bit murmur3 finalizer (avalanche) over int32 keys — global dict
ids for dictionary columns (value-consistent across segments), raw bits for
numeric columns.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

DEFAULT_LOG2M = 10  # reference default is log2m=8 (DistinctCountHLL...); we
# default finer (±3.2% vs ±6.5%) since device registers are cheap — and
# small enough that the matmul register build (ops/groupby_mm.py
# hll_registers) stays within its VMEM accumulator budget


def hash32(x):
    """Murmur3 fmix32 avalanche over int32 lanes (device)."""
    h = x.astype(jnp.uint32)
    h ^= h >> 16
    h *= jnp.uint32(0x85EBCA6B)
    h ^= h >> 13
    h *= jnp.uint32(0xC2B2AE35)
    h ^= h >> 16
    return h


def hll_idx_rho(h, log2m: int):
    """(register index, rank) from uint32 hashes — the one place the
    register math lives; host parity depends on registers_np matching."""
    idx = (h >> (32 - log2m)).astype(jnp.int32)
    w = (h << log2m) | jnp.uint32(1 << (log2m - 1))  # sentinel caps rho
    rho = jax.lax.clz(w.astype(jnp.int32)).astype(jnp.int32) + 1
    return idx, rho


def hll_registers_prehashed(h, mask, log2m: int = DEFAULT_LOG2M):
    """Register build from pre-computed uint32 hashes (e.g. a per-dictid hash
    LUT gathered on device). Masked-out docs land in an overflow register that
    is sliced away. Returns int32 (m,) registers."""
    m = 1 << log2m
    idx, rho = hll_idx_rho(h, log2m)
    idx = jnp.where(mask, idx, m)
    regs = jnp.zeros(m + 1, dtype=jnp.int32).at[idx.reshape(-1)].max(rho.reshape(-1))
    return regs[:m]


def hll_registers(keys, mask, log2m: int = DEFAULT_LOG2M):
    """Scatter-max HLL register build over an (S, L) or (L,) int32 key array."""
    return hll_registers_prehashed(hash32(keys), mask, log2m)


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """Canonical murmur3_32 over bytes — deterministic across processes and
    restarts, unlike builtin ``hash()`` (PYTHONHASHSEED-salted), so HLL
    register partials for string columns built on different servers merge to
    the union, not the sum. Matches the reference's murmur-based hashing of
    raw values (clearspring HyperLogLog via DistinctCountHLLAggregationFunction)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    n = len(data) & ~3
    for i in range(0, n, 4):
        k = int.from_bytes(data[i : i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    tail = data[n:]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def hash32_np(values: np.ndarray) -> np.ndarray:
    """Host-side canonical hash, bit-identical to :func:`hash32` so host and
    device HLL partials merge consistently. 64-bit inputs fold hi^lo;
    strings/bytes hash via deterministic murmur3_32 over UTF-8 bytes
    (hashed once per unique value, mapped back through the inverse index)."""
    v = np.asarray(values)
    if v.dtype.kind in ("U", "S", "O"):
        uniq, inv = np.unique(v, return_inverse=True)
        uh = np.array(
            [
                murmur3_32(x.encode("utf-8") if isinstance(x, str) else bytes(x))
                for x in uniq.tolist()
            ],
            dtype=np.uint32,
        )
        h = uh[inv.reshape(v.shape)]
    elif v.dtype.itemsize == 8:
        bits = v.view(np.uint64)
        h = ((bits >> np.uint64(32)) ^ (bits & np.uint64(0xFFFFFFFF))).astype(np.uint32)
    elif v.dtype.itemsize == 4:
        h = v.view(np.uint32)
    else:
        h = v.astype(np.uint32)
    h = h.copy()
    h ^= h >> 16
    h = (h * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h ^= h >> 13
    h = (h * np.uint32(0xC2B2AE35)).astype(np.uint32)
    h ^= h >> 16
    return h


def registers_np(values: np.ndarray, group_idx: np.ndarray, n_groups: int,
                 log2m: int = DEFAULT_LOG2M) -> np.ndarray:
    """Host-side register build over raw values (canonical form)."""
    m = 1 << log2m
    h = hash32_np(values)
    idx = (h >> np.uint32(32 - log2m)).astype(np.int64)
    w = ((h.astype(np.uint64) << np.uint64(log2m)) | np.uint64(1 << (log2m - 1))) \
        & np.uint64(0xFFFFFFFF)
    w = np.maximum(w, 1)
    rho = (32 - np.floor(np.log2(w.astype(np.float64))).astype(np.int32)).astype(np.int32)
    regs = np.zeros((n_groups, m), dtype=np.int32)
    np.maximum.at(regs, (np.asarray(group_idx), idx), rho)
    return regs


def merge_registers(a, b):
    return jnp.maximum(a, b)


def _alpha(m: int) -> float:
    if m >= 128:
        return 0.7213 / (1 + 1.079 / m)
    if m == 64:
        return 0.709
    if m == 32:
        return 0.697
    return 0.673


def estimate_batch_np(regs2d: np.ndarray) -> np.ndarray:
    """Vectorized host estimate over (G, m) register planes → (G,) int64.

    Must produce bit-identical results to ``estimate`` per row: the device
    finalize path (estimate_jnp) and the host finalize path both route
    through this math, and oracle tests compare them."""
    regs = np.asarray(regs2d, dtype=np.float64)
    G, m = regs.shape
    raw = _alpha(m) * m * m / np.sum(np.exp2(-regs), axis=1)
    zeros = np.sum(regs2d == 0, axis=1)
    small = (raw <= 2.5 * m) & (zeros > 0)
    lin = m * np.log(m / np.maximum(zeros, 1))
    big = raw > (1 << 32) / 30.0
    large = -float(1 << 32) * np.log(1.0 - raw / float(1 << 32))
    est = np.where(small, lin, np.where(big, large, raw))
    return np.round(est).astype(np.int64)


def estimate_jnp(regs):
    """Device (traced) estimate over (G, m) registers → (G,) int64 — the
    terminal-query finalize that spares shipping G*m register bytes over
    the host link (the bench tunnel moves ~5MB/s; a 2000-group log2m=11
    plane is 4MB ≈ 1s of transfer for 16KB of answers)."""
    G, m = regs.shape
    rf = regs.astype(jnp.float64)
    raw = _alpha(m) * m * m / jnp.sum(jnp.exp2(-rf), axis=1)
    zeros = jnp.sum(regs == 0, axis=1)
    small = (raw <= 2.5 * m) & (zeros > 0)
    lin = m * jnp.log(m / jnp.maximum(zeros, 1).astype(jnp.float64))
    big = raw > (1 << 32) / 30.0
    large = -float(1 << 32) * jnp.log(1.0 - raw / float(1 << 32))
    est = jnp.where(small, lin, jnp.where(big, large, raw))
    return jnp.round(est).astype(jnp.int64)


def estimate(registers: np.ndarray) -> int:
    """Host-side cardinality estimate (standard HLL with corrections) —
    one row of the batch form, so the correction math lives in exactly one
    np implementation (plus its jnp mirror)."""
    return int(estimate_batch_np(np.asarray(registers)[None, :])[0])


def estimate_from_sums_jnp(sums, log2m: int):
    """(3, G) f64 scaled register sums → (G,) int64 estimates,
    BIT-IDENTICAL to ``estimate_jnp`` over the dense register planes.

    sums rows (engine/device.py _hll_sorted_sums):
      [0] count of registers with at least one row (so zeros = m - s0)
      [1] Σ 2^(split - reg)  over present registers with reg <= split
      [2] Σ 2^(rho_max - reg) over present registers with reg > split
    with split = rho_max // 2, rho_max = 33 - log2m. Every term is a
    power of two (bf16/f32-exact) and each scaled sum stays below 2^24
    (f32 matmul accumulation exact), so the f64 recombination below is
    the EXACT value of Σ 2^-reg — the same real number estimate_jnp's
    f64 summation produces — making the correction branches and the
    final round bit-identical."""
    m = 1 << log2m
    rho_max = 33 - log2m
    split = rho_max // 2
    s1, s2, s3 = sums[0], sums[1], sums[2]
    zeros = m - s1
    denom = zeros + s2 * (2.0 ** -split) + s3 * (2.0 ** -rho_max)
    raw = _alpha(m) * m * m / denom
    small = (raw <= 2.5 * m) & (zeros > 0)
    lin = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    big = raw > (1 << 32) / 30.0
    large = -float(1 << 32) * jnp.log(1.0 - raw / float(1 << 32))
    est = jnp.where(small, lin, jnp.where(big, large, raw))
    return jnp.round(est).astype(jnp.int64)
