"""Device hash-join kernels for the multi-stage engine (query2/).

The reference snapshot predates Pinot's multi-stage engine ("no
pinot-query-planner/pinot-query-runtime" — PAPER.md), whose
``HashJoinOperator`` builds a Java hash map per worker. TPU-first, a hash
table is the wrong shape: the device equivalent of hashing into buckets is
SORTING the packed key array (the radix basis ops/radix_groupby.py already
established for the group-by) and probing with ``searchsorted`` — the same
O(n log n) comparator passes a radix partition pays, with no data-dependent
memory access. The kernels here are the three phases of that join:

1. ``sort_build``: order the build side's packed keys once; the argsort
   permutation maps sorted positions back to build rows.
2. ``probe_ranges``: two vectorized binary searches give each probe row its
   [lo, hi) run of matching build rows. ``probe_unique`` is the 1:1 fast
   path when build keys are unique (a dimension table's primary key — the
   LOOKUP-transform case), where the probe IS the join.
3. ``expand_pairs``: materialize matched (probe_row, build_row) pairs under
   a STATIC output bound — the same static-bound-compaction idea the radix
   group-by uses. The bound comes from a host read of the total match
   count, rounded to the next power of two so jit caches stay small.

Key packing reuses ``radix_groupby.pack_keys``'s cartesian arithmetic:
multi-column equi-keys factorize host-side into one int64 code per row
(query2/runner.py), so every kernel sees a single (n,) key array.

Mesh execution (parallel/mesh.py): the BROADCAST strategy replicates the
sorted build table to every device and shards the probe axis inside one
``shard_map`` (``mesh_probe_ranges`` / ``mesh_probe_unique``) — the
distributed form of the reference's fan-out of a dim table to all servers,
but over ICI instead of a wire. The SHUFFLE strategy partitions BOTH sides
by key radix into one bucket per device (host-side scatter standing in for
the wire exchange) and runs every bucket's sort+probe in parallel in one
``shard_map`` (``mesh_bucket_ranges``); per-bucket pair expansion rides a
vmapped ``expand_pairs``.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from pinot_tpu.parallel.mesh import SEG_AXIS, _SM_KW, _shard_map
from jax.sharding import PartitionSpec as P


def next_pow2(n: int) -> int:
    m = 1
    while m < max(n, 1):
        m <<= 1
    return m


# ---------------------------------------------------------------------------
# solo kernels
# ---------------------------------------------------------------------------


@jax.jit
def sort_build(keys):
    """(n,) int64 packed build keys → (sorted_keys, perm): perm maps sorted
    positions back to original build rows."""
    perm = jnp.argsort(keys)
    return keys[perm], perm


@jax.jit
def probe_ranges(sorted_keys, probe):
    """Each probe key's matching run [lo, lo+count) in the sorted build."""
    lo = jnp.searchsorted(sorted_keys, probe, side="left")
    hi = jnp.searchsorted(sorted_keys, probe, side="right")
    return lo, hi - lo


@jax.jit
def probe_unique(sorted_keys, perm, probe):
    """1:1 probe against UNIQUE build keys (dim-table pk / LOOKUP case):
    (found(n,), build_row(n,) with -1 misses)."""
    n = sorted_keys.shape[0]
    idx = jnp.clip(jnp.searchsorted(sorted_keys, probe, side="left"),
                   0, n - 1)
    found = sorted_keys[idx] == probe
    return found, jnp.where(found, perm[idx], -1)


@partial(jax.jit, static_argnames=("bound",))
def expand_pairs(lo, counts, bound: int):
    """Materialize matched pairs under a static bound.

    Output slot j belongs to the probe row whose cumulative-count interval
    contains j; its offset within the row's run picks the build position.
    Returns (probe_row, build_pos, valid) of length ``bound``; slots past
    the true total are invalid (-1). ``bound`` must be >= counts.sum().
    """
    n = counts.shape[0]
    cum = jnp.cumsum(counts)
    total = cum[n - 1]
    j = jnp.arange(bound, dtype=counts.dtype)
    row = jnp.clip(jnp.searchsorted(cum, j, side="right"), 0, n - 1)
    start = cum[row] - counts[row]
    build_pos = lo[row] + (j - start)
    valid = j < total
    return (jnp.where(valid, row, -1),
            jnp.where(valid, build_pos, -1),
            valid)


# ---------------------------------------------------------------------------
# mesh (shard_map) kernels — BROADCAST: replicated build, sharded probe
# ---------------------------------------------------------------------------


def _mesh_call(mesh, fn, in_specs, out_specs, *args):
    sm = _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    **_SM_KW)
    return jax.jit(sm)(*args)


def mesh_probe_ranges(mesh, sorted_keys, probe):
    """probe (D*Lp,) sharded over the mesh; build replicated. One shard_map,
    no collectives needed — reassembly along the probe axis is the gather."""

    def local(sk, pr):
        lo = jnp.searchsorted(sk, pr, side="left")
        hi = jnp.searchsorted(sk, pr, side="right")
        return lo, hi - lo

    return _mesh_call(
        mesh, local, (P(), P(SEG_AXIS)), (P(SEG_AXIS), P(SEG_AXIS)),
        sorted_keys, probe)


def mesh_probe_unique(mesh, sorted_keys, perm, probe):
    """Sharded 1:1 probe against a replicated unique-key build table."""

    def local(sk, pm, pr):
        n = sk.shape[0]
        idx = jnp.clip(jnp.searchsorted(sk, pr, side="left"), 0, n - 1)
        found = sk[idx] == pr
        return found, jnp.where(found, pm[idx], -1)

    return _mesh_call(
        mesh, local, (P(), P(), P(SEG_AXIS)), (P(SEG_AXIS), P(SEG_AXIS)),
        sorted_keys, perm, probe)


# ---------------------------------------------------------------------------
# mesh (shard_map) kernels — SHUFFLE: both sides partitioned by key radix
# ---------------------------------------------------------------------------


def mesh_bucket_ranges(mesh, build_buckets, probe_buckets):
    """One device per key bucket: sort the local build bucket, probe the
    local probe bucket. build_buckets (D, Lb) / probe_buckets (D, Lp) are
    the host-partitioned key arrays (pads: build INT64 sentinel > any real
    key, probe -1 < any real key — neither side ever matches a pad).

    Returns (lo (D, Lp), counts (D, Lp), perm (D, Lb)): positions are
    LOCAL to each bucket; the caller maps them back through its bucket →
    global row index arrays."""

    def local(bk, pk):
        perm = jnp.argsort(bk[0])
        sk = bk[0][perm]
        lo = jnp.searchsorted(sk, pk[0], side="left")
        hi = jnp.searchsorted(sk, pk[0], side="right")
        return lo[None], (hi - lo)[None], perm[None]

    return _mesh_call(
        mesh, local, (P(SEG_AXIS, None), P(SEG_AXIS, None)),
        (P(SEG_AXIS, None), P(SEG_AXIS, None), P(SEG_AXIS, None)),
        build_buckets, probe_buckets)


@partial(jax.jit, static_argnames=("bound",))
def expand_pairs_buckets(lo, counts, bound: int):
    """Vmapped expand_pairs over the bucket axis: lo/counts (D, Lp) →
    (probe_row, build_pos, valid) each (D, bound), positions bucket-local."""
    return jax.vmap(lambda l, c: expand_pairs(l, c, bound))(lo, counts)


# ---------------------------------------------------------------------------
# host-side partition helper (the exchange stand-in for SHUFFLE)
# ---------------------------------------------------------------------------

BUILD_PAD = (1 << 62)   # sorts after every real key, never probed
PROBE_PAD = -1          # below every real (non-negative) key code


def partition_by_key(keys: np.ndarray, n_buckets: int, pad_value: int):
    """Host-side radix scatter: rows → n_buckets buckets by key modulo
    (codes are dense factorized ints, so modulo spreads uniformly). Returns
    (bucketed (D, L) keys padded with pad_value, row_index (D, L) int64
    with -1 pads) — the wire-exchange stand-in; the per-bucket join runs
    sharded on the mesh."""
    keys = np.asarray(keys, dtype=np.int64)
    bucket = keys % n_buckets
    order = np.argsort(bucket, kind="stable")
    sorted_bucket = bucket[order]
    counts = np.bincount(sorted_bucket, minlength=n_buckets)
    L = max(int(counts.max()) if len(keys) else 0, 1)
    out_keys = np.full((n_buckets, L), pad_value, dtype=np.int64)
    out_rows = np.full((n_buckets, L), -1, dtype=np.int64)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    for d in range(n_buckets):
        sl = order[starts[d]: starts[d] + counts[d]]
        out_keys[d, : counts[d]] = keys[sl]
        out_rows[d, : counts[d]] = sl
    return out_keys, out_rows


def hash_partition_rows(part_ids: np.ndarray, n_parts: int) -> list:
    """Ragged counterpart of ``partition_by_key`` for the WIRE exchange
    (query2/exchange.py): given each row's partition id (hash % n_parts,
    already computed from the join key), return one int64 row-index array
    per partition. No padding — partitions ship server-to-server as
    variable-length payloads, so the dense (D, L) layout the mesh kernels
    want would only inflate the wire bytes; the receiving server re-packs
    for its device locally."""
    part_ids = np.asarray(part_ids, dtype=np.int64)
    order = np.argsort(part_ids, kind="stable")
    counts = np.bincount(part_ids, minlength=n_parts)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    return [order[starts[p]: starts[p] + counts[p]]
            for p in range(n_parts)]
