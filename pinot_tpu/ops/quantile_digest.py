"""Mergeable quantile sketch: a t-digest (merging variant) on flat arrays.

Replaces raw-value collection for percentile aggregation with fixed-size
mergeable state, the role TDigest plays in the reference
(PercentileTDigestAggregationFunction.java + ObjectSerDeUtils'
TDigest ser/de). State is a pair of parallel arrays (centroid means,
centroid weights) sorted by mean — deliberately NOT an object graph, so a
partial rides the DataTable wire as two flat lists and merging is
concatenate + compress.

Algorithm: the "merging digest" of Dunning & Ertl (public t-digest paper),
k1 scale function k(q) = δ/(2π)·asin(2q−1): centroid sizes taper toward the
tails, giving ~O(1/δ) relative rank error in the middle and much tighter
tails. Compression is a single sort + one greedy pass, numpy-friendly.

Error bound used by tests: rank error ≤ 1.5/δ for mid quantiles.
"""

from __future__ import annotations

import numpy as np

DEFAULT_COMPRESSION = 200


def _k(q: np.ndarray, delta: float) -> np.ndarray:
    return (delta / (2 * np.pi)) * np.arcsin(2 * np.clip(q, 0.0, 1.0) - 1)


def _k_inv(k: np.ndarray, delta: float) -> np.ndarray:
    return (np.sin(2 * np.pi * k / delta) + 1) / 2


def compress(means, weights, delta: float = DEFAULT_COMPRESSION):
    """Merge (means, weights) centroid soup into ≤ ~2δ centroids respecting
    the k1 size bound. Input need not be sorted; output is sorted by mean."""
    m = np.asarray(means, dtype=np.float64)
    w = np.asarray(weights, dtype=np.float64)
    if len(m) == 0:
        return m, w
    order = np.argsort(m, kind="stable")
    m, w = m[order], w[order]
    total = w.sum()
    out_m: list = []
    out_w: list = []
    cum = 0.0                      # weight already flushed
    acc_mw = m[0] * w[0]           # weighted-mean accumulator
    acc_w = w[0]
    q_limit = float(_k_inv(_k(np.float64(0.0), delta) + 1.0, delta))
    for i in range(1, len(m)):
        if (cum + acc_w + w[i]) / total <= q_limit:
            acc_mw += m[i] * w[i]
            acc_w += w[i]
        else:
            out_m.append(acc_mw / acc_w)
            out_w.append(acc_w)
            cum += acc_w
            q_limit = float(_k_inv(_k(np.float64(cum / total), delta) + 1.0, delta))
            acc_mw = m[i] * w[i]
            acc_w = w[i]
    out_m.append(acc_mw / acc_w)
    out_w.append(acc_w)
    return np.asarray(out_m), np.asarray(out_w)


def add_values(means, weights, values, delta: float = DEFAULT_COMPRESSION):
    """Fold raw values (unit weight) into a digest."""
    v = np.asarray(values, dtype=np.float64)
    v = v[~np.isnan(v)]
    if len(v) == 0:
        return np.asarray(means, dtype=np.float64), np.asarray(weights, dtype=np.float64)
    m = np.concatenate([np.asarray(means, dtype=np.float64), v])
    w = np.concatenate([np.asarray(weights, dtype=np.float64), np.ones(len(v))])
    return compress(m, w, delta)


def merge(m1, w1, m2, w2, delta: float = DEFAULT_COMPRESSION):
    """Merge two digests (the scatter_merge algebra)."""
    m = np.concatenate([np.asarray(m1, dtype=np.float64),
                        np.asarray(m2, dtype=np.float64)])
    w = np.concatenate([np.asarray(w1, dtype=np.float64),
                        np.asarray(w2, dtype=np.float64)])
    if len(m) == 0:
        return m, w
    return compress(m, w, delta)


def quantile(means, weights, q: float) -> float:
    """Estimate the q-quantile (q in [0,1]) by interpolating between
    centroid centers (standard t-digest quantile estimation)."""
    m = np.asarray(means, dtype=np.float64)
    w = np.asarray(weights, dtype=np.float64)
    if len(m) == 0:
        return float("nan")
    if len(m) == 1:
        return float(m[0])
    total = w.sum()
    target = np.clip(q, 0.0, 1.0) * total
    # centroid "centers" in cumulative-weight space
    cum = np.cumsum(w)
    centers = cum - w / 2
    if target <= centers[0]:
        return float(m[0])
    if target >= centers[-1]:
        return float(m[-1])
    j = int(np.searchsorted(centers, target, side="right"))
    c0, c1 = centers[j - 1], centers[j]
    t = 0.0 if c1 == c0 else (target - c0) / (c1 - c0)
    return float(m[j - 1] + t * (m[j] - m[j - 1]))


# ---- binary ser/de (ObjectSerDeUtils TDigest blob role) -------------------
# Layout: uint32 centroid count, then count f64 means, then count f64
# weights, all little-endian. Trailing padding bytes (fixed-width BYTES
# column storage) are ignored thanks to the count header.


def digest_to_bytes(means, weights) -> bytes:
    m = np.asarray(means, dtype=np.float64)
    w = np.asarray(weights, dtype=np.float64)
    head = np.asarray([len(m)], dtype=np.uint32)
    return head.tobytes() + m.tobytes() + w.tobytes()


def digest_from_bytes(blob) -> tuple:
    b = bytes(blob)
    if len(b) < 4:
        return np.empty(0), np.empty(0)
    n = int(np.frombuffer(b[:4], dtype=np.uint32)[0])
    m = np.frombuffer(b[4: 4 + 8 * n], dtype=np.float64)
    w = np.frombuffer(b[4 + 8 * n: 4 + 16 * n], dtype=np.float64)
    return m.copy(), w.copy()
