"""Device window-function kernel: one sort, segmented scans, scatter-back.

The window half of the multi-stage engine (query2/): ROW_NUMBER / RANK /
DENSE_RANK and the running aggregates SUM / AVG / COUNT / MIN / MAX over
``OVER (PARTITION BY ... ORDER BY ...)`` specs. The reference snapshot
predates Pinot's multi-stage engine entirely (PAPER.md: no
pinot-query-runtime ``WindowAggregateOperator``), so this is a leapfrog —
designed TPU-first on the sorted regime the radix group-by already relies
on (ops/radix_groupby.py):

1. ONE ``lax.sort`` orders rows by (partition key, order key, original row
   id) — the row id both breaks ties deterministically and is the
   scatter-back permutation, so no second sort is ever needed.
2. Partition and peer (tie) boundaries come from neighbor diffs of the
   sorted keys, exactly like ``_boundaries`` in the radix module.
3. Every function is a segmented scan over those boundaries
   (``seg_sum``/``seg_min``/``seg_max`` + a carry-first scan for RANK).
   SQL's default frame with ORDER BY is RANGE UNBOUNDED PRECEDING ..
   CURRENT ROW — peers share the frame value — which is the running scan
   value at each peer-run END, broadcast back over the run by a reversed
   carry-first scan (``_run_end_broadcast``). Without ORDER BY the frame
   is the whole partition: the same code path with a constant order key
   (one peer run per partition).
4. Results scatter back to original row order through the sorted row ids.

Shapes are static per (padded n, spec signature): callers pad rows to the
next power of two with the partition sentinel so jit caches stay small.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from pinot_tpu.ops.join import next_pow2  # noqa: F401 — one shared helper
from pinot_tpu.ops.radix_groupby import _seg_scan, seg_max, seg_min, seg_sum

# partition sentinel for padded rows: sorts after every real partition and
# never merges with one (real partition codes are non-negative)
PART_SENTINEL = (1 << 62)

# window function -> needs a value operand?
WINDOW_FUNCTIONS = {
    "row_number": False,
    "rank": False,
    "dense_rank": False,
    "count": True,   # COUNT(x) — callers pass ones for COUNT(*)
    "sum": True,
    "avg": True,
    "min": True,
    "max": True,
}

RANK_FUNCTIONS = ("row_number", "rank", "dense_rank", "count")


def _carry_first(values, is_start, axis=0):
    """Segmented carry: every element takes its run's FIRST value (the
    RANK broadcast). op keeps the left operand, which is associative."""
    return _seg_scan(values, is_start, lambda a, b: a, axis)


def _run_end_broadcast(x, run_start):
    """Every element takes its run's LAST value — the peer-inclusive frame
    read. Reversal turns run ends into run starts, so the carry-first scan
    applies; reversing back restores row order."""
    lead = jnp.ones((1,), dtype=bool)
    run_end = jnp.concatenate([run_start[1:], lead])
    y = _carry_first(x[::-1], run_end[::-1])
    return y[::-1]


@partial(jax.jit, static_argnames=("specs",))
def window_eval(part, order, rowid, values, specs):
    """Evaluate window specs sharing one (PARTITION BY, ORDER BY) sort.

    part:   (n,) int64 partition codes; padded rows carry PART_SENTINEL.
    order:  (n,) int64 order codes (descending handled by the caller's
            code construction); constant when the spec has no ORDER BY.
    rowid:  (n,) int64 original positions (pads continue past n_real).
    values: tuple of (n,) float64 operand columns.
    specs:  static tuple of (fn_name, value_index) — value_index -1 for
            the rank family, else an index into ``values``.

    Returns a tuple of (n,) arrays aligned with the ORIGINAL row order,
    int64 for the rank family / COUNT, float64 otherwise.
    """
    ops = jax.lax.sort([part, order, rowid, *values], num_keys=3)
    p, o, r = ops[0], ops[1], ops[2]
    vs = ops[3:]
    n = p.shape[0]
    lead = jnp.ones((1,), dtype=bool)
    part_start = jnp.concatenate([lead, p[1:] != p[:-1]])
    peer_start = jnp.concatenate(
        [lead, (p[1:] != p[:-1]) | (o[1:] != o[:-1])])
    ones = jnp.ones(n, dtype=jnp.int64)
    row_number = seg_sum(ones, part_start, axis=0)

    # memoized per-operand running scans (several specs often share one)
    run_sums: dict = {}

    def running_sum(vi):
        if vi not in run_sums:
            run_sums[vi] = seg_sum(vs[vi], part_start, axis=0)
        return run_sums[vi]

    outs = []
    for fn, vi in specs:
        if fn == "row_number":
            res = row_number
        elif fn == "rank":
            res = _carry_first(row_number, peer_start)
        elif fn == "dense_rank":
            res = seg_sum(peer_start.astype(jnp.int64), part_start, axis=0)
        elif fn == "count":
            res = _run_end_broadcast(row_number, peer_start)
        elif fn == "sum":
            res = _run_end_broadcast(running_sum(vi), peer_start)
        elif fn == "avg":
            res = _run_end_broadcast(running_sum(vi), peer_start) \
                / _run_end_broadcast(row_number, peer_start).astype(
                    jnp.float64)
        elif fn == "min":
            res = _run_end_broadcast(
                seg_min(vs[vi], part_start, axis=0), peer_start)
        elif fn == "max":
            res = _run_end_broadcast(
                seg_max(vs[vi], part_start, axis=0), peer_start)
        else:  # pragma: no cover - validated upstream
            raise ValueError(f"unknown window function {fn}")
        # scatter back to original order through the sorted row ids
        outs.append(jnp.zeros(n, res.dtype).at[r].set(res))
    return tuple(outs)


def pad_inputs(part, order, rowid, values):
    """Pad to the next power of two with the partition sentinel so padded
    rows form their own trailing partition (host-side numpy helper)."""
    import numpy as np

    n = len(part)
    m = next_pow2(max(n, 1))
    if m == n:
        return part, order, rowid, values
    pad = m - n

    def ext(a, fill):
        return np.concatenate([np.asarray(a), np.full(pad, fill, a.dtype)])

    part = ext(np.asarray(part, dtype=np.int64), PART_SENTINEL)
    order = ext(np.asarray(order, dtype=np.int64), 0)
    rowid = np.concatenate(
        [np.asarray(rowid, dtype=np.int64),
         np.arange(n, m, dtype=np.int64)])
    values = tuple(ext(np.asarray(v, dtype=np.float64), 0.0) for v in values)
    return part, order, rowid, values
