"""Device top-k selection for ORDER BY ... LIMIT.

Replaces the reference's per-segment selection-order-by priority queues +
min/max-pruned combine (SelectionOrderByOperator,
MinMaxValueBasedSelectionOrderByCombineOperator): the TPU path computes the
full multi-key ordering permutation over the (flattened) batch with
fixed-shape stable sorts and takes the first k — full sort per block is
cheaper than data-dependent early exit on this hardware; the host merges
only tiny (k,) results across batches.
"""

from __future__ import annotations

import jax.numpy as jnp


def order_permutation(keys, valid, k: int):
    """Indices of the top-k docs by lexicographic (key, ascending) order.

    keys: list of (array (N,), ascending: bool) — most significant first.
          Keys must be numeric (dict ids order by value because dictionaries
          are sorted — same trick as the reference's dictId-based ordering).
    valid: bool (N,) — invalid docs sort last regardless of key.
    Returns int32 (k,) indices into the flattened batch.
    """
    n = valid.shape[0]
    perm = jnp.arange(n, dtype=jnp.int32)
    # stable lexicographic: sort by least-significant key first
    for key, asc in reversed(list(keys)):
        kp = key[perm]
        order = jnp.argsort(kp, stable=True, descending=not asc)
        perm = perm[order]
    # validity as most significant: stable-partition valid docs to the front
    vp = valid[perm]
    order = jnp.argsort(~vp, stable=True)
    perm = perm[order]
    return perm[:k]
