"""Device kernel library (masks, aggregation scatters, top-k, HLL).

x64 is enabled at the package root (pinot_tpu/__init__.py) — accumulators
widen to int64/float64 while column data stays narrow in HBM.
"""
