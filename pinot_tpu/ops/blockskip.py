"""Zone-map block-skip primitives for the device filter path.

Pinot's performance contract is that a selective filter touches only the
docs an index says it must (sorted/inverted/range indexes narrow the doc-id
set before projection). The batched device pipeline had no analog: every
predicate ran as a dense mask over every padded row of every segment, so a
1e-4-selectivity query cost the same HBM traffic as a full scan. This
module supplies the device-side analog of ColumnValueSegmentPruner's
min/max check, pushed down to ``ZONE_BLOCK_ROWS``-row granularity:

1. **Zone verdicts** (``zone_verdict``): the filter template evaluated in
   INTERVAL semantics over small (S, n_blocks) per-block min/max arrays
   resident in HBM (engine/params.py BatchContext.zone_map). Tri-state
   collapsed to "may match" booleans exactly like broker/segment_pruner.py:
   AND = all children may match, OR = any, NOT / regex-LUT / MV = always
   "may match" (conservative).
2. **Static-bound compaction** (``compact_candidates``): candidate block
   indices sort to the front of an index array and slice to a trace-time
   bound B = ceil(total_blocks / CAND_FRACTION). More candidates than B is
   OVERFLOW — detected on device as a scalar and routed to the dense path
   by the caller (same detect-and-fall-back pattern as
   ops/radix_groupby.py's group-table bound, except the fallback is the
   already-compiled dense branch of the same kernel, not a host re-run).
3. **Block gather**: each needed column reshapes to (total_blocks, R, ...)
   and gathers only the candidate blocks; the filter + aggregation then run
   over B*R rows instead of S*L. When the Pallas scatter tier is on and
   the template fits its surface, the gather/filter/aggregate step runs
   instead as ONE fused kernel (ops/pallas_scatter.py fused_filter_agg):
   the candidate indices from step 2 scalar-prefetch into the kernel's
   BlockSpec index maps, so the (B, R) gather buffer this step would
   materialize in HBM never exists.

Everything is trace-time static in shapes: B derives from the (S, L) batch
shape, so jit caches stay keyed on the same (template, batch-shape) pairs
the executor already uses, and the per-query verdict depends only on
params (predicate literals + the per-segment alive vector) — one compiled
template serves all literal values.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pinot_tpu.storage.segment import ZONE_BLOCK_ROWS as BLOCK_ROWS

# static candidate bound: B = ceil(total_blocks / CAND_FRACTION). The skip
# branch always gathers B blocks (static shape), so the kernel's best case
# reads total/CAND_FRACTION of the batch; queries selecting more blocks
# than B overflow to the dense branch, bounding the worst-case overhead to
# the verdict + compaction work (a few thousand elements).
CAND_FRACTION = 16

ZLO = "zlo::"  # zone-map column key prefixes (cols dict)
ZHI = "zhi::"


def _expr_colkey(expr_tpl):
    """Column key a raw-space predicate's expression reads directly, or
    None when the expression computes (no interval structure we track)."""
    if not isinstance(expr_tpl, tuple):
        return None
    if expr_tpl[0] == "raw":
        return expr_tpl[1]
    if expr_tpl[0] == "dictval":
        return "dv::" + expr_tpl[1]
    return None


def prunable_columns(tpl) -> tuple[bool, set]:
    """(prunable, column keys) for a filter template: ``prunable`` is True
    when the zone verdict can exclude at least some blocks (a conservative
    node at the top of an OR poisons the whole disjunct, and NOT proves
    nothing about a block — same tri-state algebra as the broker pruner);
    the column set names the zone-map arrays the verdict will read."""
    kind = tpl[0]
    if kind == "and":
        cols: set = set()
        any_p = False
        for c in tpl[1:]:
            p, cc = prunable_columns(c)
            any_p |= p
            cols |= cc
        return any_p, cols
    if kind == "or":
        cols = set()
        for c in tpl[1:]:
            p, cc = prunable_columns(c)
            if not p:
                return False, set()  # one conservative child: OR never prunes
            cols |= cc
        return bool(cols), cols
    if kind == "false":
        return True, set()
    if kind in ("eq_dict", "in_dict", "range_dict"):
        if tpl[1].startswith("mv::"):
            return False, set()
        return True, {tpl[1]}
    if kind in ("eq_raw", "in_raw", "range_raw"):
        ck = _expr_colkey(tpl[1])
        if ck is None:
            return False, set()
        return True, {ck}
    # true / not / lut_dict / mv_any: conservative "may match"
    return False, set()


def _zones(cols, params, colkey, widths=None):
    """(lo, hi) zone arrays for a column key, DECODED to the column's
    register value space. Zone planes narrow WITH their column
    (engine/params.py ColPlan): id-space zones compare at native width
    (the int32 literal promotes in-register), but frame-of-reference
    (min-offset) planes store zones in FOR space — widen and add the
    per-batch "fo::<key>" offset param so predicate literals (raw value
    space) compare correctly. The (S, NB) zone arrays are a few thousand
    elements; the widening is register noise."""
    lo = cols.get(ZLO + colkey)
    hi = cols.get(ZHI + colkey)
    if lo is None or hi is None:
        return None, None
    w = widths.get(colkey) if widths else None
    if w is not None and w[3]:  # (dtype, bits, has_offset, wide)
        wd = jnp.dtype(w[3])
        lo = lo.astype(wd)
        hi = hi.astype(wd)
        fo = params.get("fo::" + colkey)
        if w[2] and fo is not None:
            lo = lo + fo
            hi = hi + fo
    return lo, hi


def zone_verdict(tpl, cols, params, shape, widths=None):
    """(S, n_blocks) bool: True where the block MAY contain a matching doc.
    Mirrors device.py's ``_eval_filter`` node set in interval semantics;
    any node without interval structure returns all-True (never prunes a
    block the dense mask would match). ``widths``: the pipeline's column
    width plan (build_pipeline) — zone planes decode like their column."""
    kind = tpl[0]
    ones = jnp.ones(shape, dtype=bool)
    if kind == "true":
        return ones
    if kind == "false":
        return jnp.zeros(shape, dtype=bool)
    if kind == "and":
        v = zone_verdict(tpl[1], cols, params, shape, widths)
        for c in tpl[2:]:
            v &= zone_verdict(c, cols, params, shape, widths)
        return v
    if kind == "or":
        v = zone_verdict(tpl[1], cols, params, shape, widths)
        for c in tpl[2:]:
            v |= zone_verdict(c, cols, params, shape, widths)
        return v
    if kind == "eq_dict":
        lo, hi = _zones(cols, params, tpl[1], widths)
        if lo is None:
            return ones
        t = params[tpl[2]]  # -2 when the value is absent: matches no block
        return (t >= lo) & (t <= hi)
    if kind == "in_dict":
        lo, hi = _zones(cols, params, tpl[1], widths)
        if lo is None:
            return ones
        ids = params[tpl[2]]  # (K,) with -2 padding (< any real zone lo)
        return jnp.any((ids >= lo[..., None]) & (ids <= hi[..., None]),
                       axis=-1)
    if kind == "range_dict":
        lo, hi = _zones(cols, params, tpl[1], widths)
        if lo is None:
            return ones
        rlo, rhi = params[tpl[2]], params[tpl[3]]  # id interval [rlo, rhi)
        return (lo < rhi) & (hi >= rlo)
    if kind == "eq_raw":
        lo, hi = _zones(cols, params, _expr_colkey(tpl[1]) or "", widths)
        if lo is None:
            return ones
        t = params[tpl[2]]
        return (t >= lo) & (t <= hi)
    if kind == "in_raw":
        lo, hi = _zones(cols, params, _expr_colkey(tpl[1]) or "", widths)
        if lo is None:
            return ones
        lits = params[tpl[2]]
        return jnp.any((lits >= lo[..., None]) & (lits <= hi[..., None]),
                       axis=-1)
    if kind == "range_raw":
        _, expr_tpl, klo, khi, has_lo, has_hi, lo_inc, hi_inc = tpl
        lo, hi = _zones(cols, params, _expr_colkey(expr_tpl) or "", widths)
        if lo is None:
            return ones
        v = ones
        if has_lo:
            b = params[klo]
            v &= (hi >= b) if lo_inc else (hi > b)
        if has_hi:
            b = params[khi]
            v &= (lo <= b) if hi_inc else (lo < b)
        return v
    # not / lut_dict / mv_any / anything new: conservative
    return ones


def compact_candidates(flat_verdict, bound: int):
    """Compact the True positions of a flat (total_blocks,) verdict to the
    front with a static bound: (candidate indices (bound,), valid mask
    (bound,)). Padding candidates point at block 0 with valid=False — the
    caller masks their rows out, so they contribute nothing. The sort runs
    over total_blocks int32 keys (thousands, not rows), trivially
    VMEM-resident."""
    total = flat_verdict.shape[0]
    iota = jnp.arange(total, dtype=jnp.int32)
    keyed = jnp.where(flat_verdict, iota, jnp.int32(total))
    cand = jax.lax.sort(keyed)[:bound]
    valid = cand < total
    return jnp.where(valid, cand, 0), valid


def gather_blocks(x, cand, n_blocks_per_seg: int, block_rows: int):
    """Gather candidate blocks out of an (S, L, ...) column: reshape to
    (S * n_blocks, block_rows, ...) and take the candidate rows — the
    device analog of an index handing the scan a doc-id subset."""
    S = x.shape[0]
    rest = x.shape[2:]
    flat = x.reshape((S * n_blocks_per_seg, block_rows) + rest)
    return flat[cand]
