"""Masked aggregation kernels + dense group-by scatter.

Replaces the reference's AggregationFunction.aggregate /
aggregateGroupBySV scatter loops (pinot-core/.../query/aggregation/function/,
e.g. SumAggregationFunction) and the per-server IndexedTable merge: because
group ids are in *global* dictionary space (engine/params.py), the whole
(S, L) batch aggregates into one dense (G,) accumulator — segment combine
happens inside the kernel launch, and cross-chip combine is a psum of the
same accumulators (parallel/mesh.py).

Accumulator dtypes: sums in float64 when x64 is enabled else float32
(DOUBLE columns already narrowed on upload); int sums in int64 to match the
reference's long accumulators (SumAggregationFunction uses double; COUNT
long).
"""

from __future__ import annotations

import jax.numpy as jnp

NEG_INF = float("-inf")
POS_INF = float("inf")


# ---- scalar (non-group-by) aggregations over a mask -----------------------


def agg_count(mask):
    return jnp.sum(mask, dtype=jnp.int64)


def agg_sum(values, mask):
    # int64 / float64 accumulation regardless of the narrow column dtype
    # (reference sums into long/double)
    dt = jnp.int64 if jnp.issubdtype(values.dtype, jnp.integer) else jnp.float64
    return jnp.sum(jnp.where(mask, values, 0), dtype=dt)


def agg_min(values, mask):
    if jnp.issubdtype(values.dtype, jnp.integer):
        big = jnp.iinfo(values.dtype).max
    else:
        big = POS_INF
    return jnp.min(jnp.where(mask, values, big))


def agg_max(values, mask):
    if jnp.issubdtype(values.dtype, jnp.integer):
        small = jnp.iinfo(values.dtype).min
    else:
        small = NEG_INF
    return jnp.max(jnp.where(mask, values, small))


# ---- dense group-by scatter ----------------------------------------------
# gids: int32 (S, L) global group ids; invalid/padded docs get gid = G
# (one overflow slot, sliced off afterwards) so no branch is needed.


def group_count(gids, num_groups: int):
    flat = gids.reshape(-1)
    out = jnp.zeros(num_groups + 1, dtype=jnp.int64).at[flat].add(1)
    return out[:num_groups]


def group_sum(gids, values, num_groups: int):
    flat = gids.reshape(-1)
    v = values.reshape(-1)
    dt = jnp.int64 if jnp.issubdtype(v.dtype, jnp.integer) else jnp.float64
    out = jnp.zeros(num_groups + 1, dtype=dt).at[flat].add(v.astype(dt))
    return out[:num_groups]


def group_min(gids, values, num_groups: int):
    flat = gids.reshape(-1)
    v = values.reshape(-1)
    if jnp.issubdtype(v.dtype, jnp.integer):
        init = jnp.iinfo(v.dtype).max
    else:
        init = POS_INF
    out = jnp.full(num_groups + 1, init, dtype=v.dtype).at[flat].min(v)
    return out[:num_groups]


def group_max(gids, values, num_groups: int):
    flat = gids.reshape(-1)
    v = values.reshape(-1)
    if jnp.issubdtype(v.dtype, jnp.integer):
        init = jnp.iinfo(v.dtype).min
    else:
        init = NEG_INF
    out = jnp.full(num_groups + 1, init, dtype=v.dtype).at[flat].max(v)
    return out[:num_groups]


def group_ids_combine(per_col_gids, cardinalities, mask, num_groups: int):
    """Combine per-column global ids into one dense group id (ARRAY_BASED
    regime of DictionaryBasedGroupKeyGenerator.java:43-45: raw key == group
    id via cartesian arithmetic).

    per_col_gids: list of int32 (S, L) arrays in [0, C_j)
    cardinalities: static list of C_j
    mask: filter & validity mask (S, L)
    Returns int32 (S, L) with masked-out docs sent to `num_groups` (overflow
    slot).
    """
    gid = None
    for g, c in zip(per_col_gids, cardinalities):
        gid = g if gid is None else gid * c + g
    return jnp.where(mask, gid, num_groups)


def distinct_presence(gids, num_groups: int):
    """Presence vector over global ids (DISTINCT / DISTINCTCOUNT on a dict
    column): 1 where any doc carries the id."""
    flat = gids.reshape(-1)
    out = jnp.zeros(num_groups + 1, dtype=jnp.int32).at[flat].max(1)
    return out[:num_groups]
