"""Masked aggregation kernels + dense group-by scatter.

Replaces the reference's AggregationFunction.aggregate /
aggregateGroupBySV scatter loops (pinot-core/.../query/aggregation/function/,
e.g. SumAggregationFunction) and the per-server IndexedTable merge: group ids
arrive in *global* dictionary space (engine/params.py), so the whole (S, L)
batch aggregates into one dense (G,) accumulator — segment combine happens
inside the kernel launch, and cross-chip combine is a psum of the same
accumulators (parallel/mesh.py).

TPU dtype strategy (measured on v5e): int32/float32 scatters are ~8x faster
than int64/float64 scatters, so 64-bit-exact sums run **two-stage** — stage 1
scatters into per-block int32/float32 partials (block size chosen so a block
sum cannot overflow / lose precision), stage 2 densely reduces blocks in
int64/float64, which is cheap. Counts fit int32 (< 2^31 docs per launch) and
widen on the way out.

NOTE (ISSUE 15): these XLA scatters are now the DIFFERENTIAL REFERENCE
and fallback rung for the Pallas scatter-kernel tier
(ops/pallas_scatter.py) — engine/device.py routes the group
sum/count/min/max family through the tiled local-accumulate Pallas
kernels when the tier is on (PINOT_TPU_PALLAS, SET usePallas), and
every tier kernel is pinned bit-exact against the functions here
(tests/test_pallas_scatter.py).
"""

from __future__ import annotations

import jax.numpy as jnp

NEG_INF = float("-inf")
POS_INF = float("inf")

DEFAULT_ROWS_PER_BLOCK = 1 << 15

# float group sums: below this row count the two-stage f32 block scatter
# saves nothing (the scatter is sub-ms either way) but costs precision —
# stay on the exact single-stage f64 scatter. Keeps small launches (e.g.
# star-tree cube batches, gathered block-skip row sets) bit-stable across
# padding changes.
FLOAT_TWO_STAGE_MIN_ROWS = 1 << 20


def rows_per_block_for(max_abs_value: float):
    """Largest power-of-two block size whose int32 block-sum cannot overflow,
    or None when values are too large for two-stage to pay off (callers then
    use the exact single-stage 64-bit scatter)."""
    if max_abs_value <= 0:
        return 1 << 20
    rpb = 1
    while rpb * 2 * (max_abs_value + 1) < 2**31 and rpb < (1 << 20):
        rpb *= 2
    return rpb if rpb >= 256 else None


# ---- scalar (non-group-by) aggregations over a mask -----------------------


def agg_count(mask):
    return jnp.sum(mask, dtype=jnp.int64)


def agg_sum(values, mask):
    # dense reductions (not scatters) are cheap in 64-bit: keep them exact
    dt = jnp.int64 if jnp.issubdtype(values.dtype, jnp.integer) else jnp.float64
    return jnp.sum(jnp.where(mask, values, 0), dtype=dt)


def agg_min(values, mask):
    if jnp.issubdtype(values.dtype, jnp.integer):
        big = jnp.iinfo(values.dtype).max
    else:
        big = POS_INF
    return jnp.min(jnp.where(mask, values, big))


def agg_max(values, mask):
    if jnp.issubdtype(values.dtype, jnp.integer):
        small = jnp.iinfo(values.dtype).min
    else:
        small = NEG_INF
    return jnp.max(jnp.where(mask, values, small))


def agg_arg_time(values, times, mask, is_first: bool):
    """FIRSTWITHTIME/LASTWITHTIME scalar shape: (best_time, best_value)
    where best_time = min (first) / max (last) over matched rows and
    best_value = max value among rows carrying best_time — the same
    deterministic tie-break as the host spec and the mesh combine
    (engine/aggspec.py FirstLastWithTimeSpec)."""
    t = times.astype(jnp.int64)
    v = values.astype(jnp.float64)
    if is_first:
        fill = jnp.iinfo(jnp.int64).max
        tb = jnp.min(jnp.where(mask, t, fill))
    else:
        fill = jnp.iinfo(jnp.int64).min
        tb = jnp.max(jnp.where(mask, t, fill))
    # NaN values never win (host _val_gt rule); -inf encodes "no non-NaN
    # winner" and is restored to NaN host-side (_with_time_partial)
    vb = jnp.max(jnp.where(mask & (t == tb) & ~jnp.isnan(v), v, NEG_INF))
    return tb, vb


# ---- dense group-by scatter ----------------------------------------------
# gids: int32 (S, L) global group ids; invalid/padded docs carry gid = G
# (one overflow slot, sliced off afterwards) so no branch is needed.


def group_count(gids, num_groups: int):
    flat = gids.reshape(-1)
    out = jnp.zeros(num_groups + 1, dtype=jnp.int32).at[flat].add(1)
    return out[:num_groups].astype(jnp.int64)


def group_sum(gids, values, num_groups: int,
              rows_per_block: int = DEFAULT_ROWS_PER_BLOCK):
    """Two-stage exact group sum: int32/f32 block scatters + 64-bit dense
    block reduce. ``rows_per_block`` must satisfy
    rows_per_block * max|value| < 2^31 for integer inputs (the planner picks
    it from column metadata via rows_per_block_for)."""
    flat_g = gids.reshape(-1)
    v = values.reshape(-1)
    n = v.shape[0]
    integer = jnp.issubdtype(v.dtype, jnp.integer)
    stage1_dt = jnp.int32 if integer else jnp.float32
    stage2_dt = jnp.int64 if integer else jnp.float64
    nb = (n + rows_per_block - 1) // rows_per_block
    stride = num_groups + 1
    if nb <= 1 or nb * stride >= 2**31 or \
            (not integer and n < FLOAT_TWO_STAGE_MIN_ROWS):
        # single block, block-slot space would overflow int32 indexing, or
        # a float launch too small for two-stage to pay its precision
        # cost: exact single-stage 64-bit scatter
        out = jnp.zeros(num_groups + 1, dtype=stage2_dt).at[flat_g].add(
            v.astype(stage2_dt)
        )
        return out[:num_groups]
    block = jnp.arange(n, dtype=jnp.int32) // rows_per_block
    slot = block * stride + flat_g
    part = jnp.zeros(nb * stride, dtype=stage1_dt).at[slot].add(v.astype(stage1_dt))
    out = jnp.sum(part.reshape(nb, stride), axis=0, dtype=stage2_dt)
    return out[:num_groups]


def group_min(gids, values, num_groups: int):
    flat = gids.reshape(-1)
    v = values.reshape(-1)
    if jnp.issubdtype(v.dtype, jnp.integer):
        init = jnp.iinfo(v.dtype).max
    else:
        init = POS_INF
    out = jnp.full(num_groups + 1, init, dtype=v.dtype).at[flat].min(v)
    return out[:num_groups]


def group_max(gids, values, num_groups: int):
    flat = gids.reshape(-1)
    v = values.reshape(-1)
    if jnp.issubdtype(v.dtype, jnp.integer):
        init = jnp.iinfo(v.dtype).min
    else:
        init = NEG_INF
    out = jnp.full(num_groups + 1, init, dtype=v.dtype).at[flat].max(v)
    return out[:num_groups]


def group_arg_time(gids, values, times, num_groups: int, is_first: bool):
    """Dense-group FIRSTWITHTIME/LASTWITHTIME: per-group (best_time,
    best_value) via two scatters — extremal time, then max value among
    rows whose time equals their group's winner (deterministic tie-break
    matching the host spec). Masked rows carry gid = num_groups (overflow
    slot, sliced off)."""
    flat_g = gids.reshape(-1)
    t = times.reshape(-1).astype(jnp.int64)
    v = values.reshape(-1).astype(jnp.float64)
    if is_first:
        fill = jnp.iinfo(jnp.int64).max
        tb = jnp.full(num_groups + 1, fill, dtype=jnp.int64).at[flat_g].min(t)
    else:
        fill = jnp.iinfo(jnp.int64).min
        tb = jnp.full(num_groups + 1, fill, dtype=jnp.int64).at[flat_g].max(t)
    # NaN values never win the value tie-break (host _val_gt rule): mask
    # them to -inf so the scatter-max ignores them; a group whose winning
    # rows are ALL NaN keeps -inf, which the host conversion restores to
    # NaN (_with_time_partial). Known edge: a literal -inf data value that
    # is a group's only winner also renders NaN.
    winner = (t == tb[flat_g]) & ~jnp.isnan(v)
    vm = jnp.where(winner, v, NEG_INF)
    vb = jnp.full(num_groups + 1, NEG_INF).at[flat_g].max(vm)
    return tb[:num_groups], vb[:num_groups]


def group_ids_combine(per_col_gids, cardinalities, mask, num_groups: int):
    """Combine per-column global ids into one dense group id (ARRAY_BASED
    regime of DictionaryBasedGroupKeyGenerator.java:43-45: raw key == group
    id via cartesian arithmetic).

    per_col_gids: list of (S, L) id arrays in [0, C_j) at their planned
    width (uint8/uint16/int32 — engine/params.py ColPlan); padding may be
    negative (signed planes) or C (unsigned), so ids are clipped before
    the arithmetic. The cartesian multiply widens to int32 IN-REGISTER —
    narrow planes keep HBM traffic down, but uint8 * weak-int stays uint8
    under jax promotion and would silently wrap past 255. Masked docs land
    in the `num_groups` overflow slot.
    """
    gid = None
    for g, c in zip(per_col_gids, cardinalities):
        g = jnp.clip(g, 0, c - 1).astype(jnp.int32)
        gid = g if gid is None else gid * c + g
    return jnp.where(mask, gid, num_groups)


# high-cardinality key packing moved to ops/radix_groupby.py pack_keys
# (same cartesian arithmetic, dtype-narrowing + sentinel handling there)


def distinct_presence(gids, num_groups: int):
    """Presence vector over global ids (DISTINCT / DISTINCTCOUNT on a dict
    column): 1 where any doc carries the id."""
    flat = gids.reshape(-1)
    out = jnp.zeros(num_groups + 1, dtype=jnp.int8).at[flat].max(1)
    return out[:num_groups]
