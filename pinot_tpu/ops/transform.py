"""Transform-function registry: one semantic definition, two backends.

The reference's ~50 TransformFunction classes
(pinot-core/.../operator/transform/function/) plus the @ScalarFunction
registry (pinot-common/.../function/scalar/) collapse here into a table of
(numpy impl, jnp impl) pairs. The device column selects which impl a query
template traces; host-only functions (strings, datetime) force the engine's
host path for that expression.

Division follows the reference: DOUBLE division, x/0 → inf (Java double
semantics), so results match across backends and the duckdb oracle modulo
float formatting.
"""

from __future__ import annotations

import numpy as np

try:
    import jax.numpy as jnp
except Exception:  # pragma: no cover
    jnp = None


class FunctionDef:
    def __init__(self, name, np_fn, jnp_fn=None, min_args=1, max_args=None,
                 returns_bool=False):
        self.name = name
        self.np_fn = np_fn
        self.jnp_fn = jnp_fn  # None → host-only
        self.min_args = min_args
        self.max_args = max_args if max_args is not None else min_args
        self.returns_bool = returns_bool

    @property
    def device_capable(self) -> bool:
        return self.jnp_fn is not None


REGISTRY: dict[str, FunctionDef] = {}


def _reg(name, np_fn, jnp_fn=None, min_args=1, max_args=None, returns_bool=False):
    REGISTRY[name] = FunctionDef(name, np_fn, jnp_fn, min_args, max_args, returns_bool)


def get_function(name: str) -> FunctionDef:
    f = REGISTRY.get(name)
    if f is None:
        raise KeyError(f"unknown function: {name}")
    return f


# ---- arithmetic -----------------------------------------------------------

def _np_div(a, b):
    with np.errstate(divide="ignore", invalid="ignore"):
        return np.asarray(a, dtype=np.float64) / np.asarray(b, dtype=np.float64)


def _jnp_div(a, b):
    return jnp.asarray(a, dtype=jnp.float32) / jnp.asarray(b, dtype=jnp.float32)


_reg("plus", lambda a, b: np.add(a, b), lambda a, b: jnp.add(a, b), 2)
_reg("minus", lambda a, b: np.subtract(a, b), lambda a, b: jnp.subtract(a, b), 2)
_reg("times", lambda a, b: np.multiply(a, b), lambda a, b: jnp.multiply(a, b), 2)
_reg("divide", _np_div, _jnp_div, 2)
_reg("mod", lambda a, b: np.mod(a, b), lambda a, b: jnp.mod(a, b), 2)
_reg("abs", np.abs, (lambda a: jnp.abs(a)), 1)
_reg("ceil", np.ceil, (lambda a: jnp.ceil(a)), 1)
_reg("floor", np.floor, (lambda a: jnp.floor(a)), 1)
_reg("exp", np.exp, (lambda a: jnp.exp(a)), 1)
_reg("ln", np.log, (lambda a: jnp.log(a)), 1)
_reg("log2", np.log2, (lambda a: jnp.log2(a)), 1)
_reg("log10", np.log10, (lambda a: jnp.log10(a)), 1)
_reg("sqrt", np.sqrt, (lambda a: jnp.sqrt(a)), 1)
_reg("power", np.power, (lambda a, b: jnp.power(a, b)), 2)
_reg("pow", np.power, (lambda a, b: jnp.power(a, b)), 2)
_reg("least", np.minimum, (lambda a, b: jnp.minimum(a, b)), 2)
_reg("greatest", np.maximum, (lambda a, b: jnp.maximum(a, b)), 2)
_reg("sign", np.sign, (lambda a: jnp.sign(a)), 1)
_reg("round", np.round, (lambda a: jnp.round(a)), 1, 2)

# trigonometric (scalar/Trigonometric*.java)
for _n, _np, _j in [
    ("sin", np.sin, "sin"), ("cos", np.cos, "cos"), ("tan", np.tan, "tan"),
    ("asin", np.arcsin, "arcsin"), ("acos", np.arccos, "arccos"),
    ("atan", np.arctan, "arctan"), ("sinh", np.sinh, "sinh"),
    ("cosh", np.cosh, "cosh"), ("tanh", np.tanh, "tanh"),
    ("degrees", np.degrees, "degrees"), ("radians", np.radians, "radians"),
]:
    _reg(_n, _np, (lambda a, _f=_j: getattr(jnp, _f)(a)), 1)

# ---- comparisons (usable inside CASE / arithmetic contexts) ---------------

_reg("equals", lambda a, b: np.equal(a, b), lambda a, b: jnp.equal(a, b), 2, returns_bool=True)
_reg("not_equals", lambda a, b: np.not_equal(a, b), lambda a, b: jnp.not_equal(a, b), 2, returns_bool=True)
_reg("greater_than", lambda a, b: np.greater(a, b), lambda a, b: jnp.greater(a, b), 2, returns_bool=True)
_reg("greater_than_or_equal", lambda a, b: np.greater_equal(a, b), lambda a, b: jnp.greater_equal(a, b), 2, returns_bool=True)
_reg("less_than", lambda a, b: np.less(a, b), lambda a, b: jnp.less(a, b), 2, returns_bool=True)
_reg("less_than_or_equal", lambda a, b: np.less_equal(a, b), lambda a, b: jnp.less_equal(a, b), 2, returns_bool=True)
_reg("and", lambda *a: np.logical_and.reduce(a), lambda *a: jnp.stack(a).all(0), 2, 99, returns_bool=True)
_reg("or", lambda *a: np.logical_or.reduce(a), lambda *a: jnp.stack(a).any(0), 2, 99, returns_bool=True)
_reg("not", np.logical_not, (lambda a: jnp.logical_not(a)), 1, returns_bool=True)


# ---- CASE / CAST ----------------------------------------------------------

def _np_case(*args):
    # (c1, v1, c2, v2, ..., else)
    conds = list(args[:-1:2])
    vals = list(args[1:-1:2])
    return np.select(conds, vals, default=args[-1])


def _jnp_case(*args):
    out = args[-1]
    for c, v in zip(reversed(args[:-1:2]), reversed(args[1:-1:2])):
        out = jnp.where(c, v, out)
    return out


_reg("case", _np_case, _jnp_case, 3, 99)

_CAST_NP = {
    "INT": np.int32, "INTEGER": np.int32, "LONG": np.int64, "BIGINT": np.int64,
    "FLOAT": np.float32, "DOUBLE": np.float64, "BOOLEAN": np.bool_,
    "STRING": np.str_, "VARCHAR": np.str_, "TIMESTAMP": np.int64,
}
_CAST_JNP = {
    "INT": "int32", "INTEGER": "int32", "LONG": "int64", "BIGINT": "int64",
    "FLOAT": "float32", "DOUBLE": "float32", "BOOLEAN": "bool_",
    "TIMESTAMP": "int64",
}


def _np_cast(a, type_name):
    t = _CAST_NP.get(str(type_name).upper())
    if t is None:
        raise KeyError(f"CAST to unsupported type {type_name}")
    if t is np.str_:
        return np.asarray(a).astype(str)
    if np.issubdtype(t, np.integer):
        # SQL CAST truncates toward zero
        return np.trunc(np.asarray(a, dtype=np.float64)).astype(t) \
            if np.asarray(a).dtype.kind == "f" else np.asarray(a).astype(t)
    return np.asarray(a).astype(t)


def _jnp_cast(a, type_name):
    t = _CAST_JNP.get(str(type_name).upper())
    if t is None:
        raise KeyError(f"CAST to {type_name} is host-only")
    if t.startswith("int") and jnp.issubdtype(a.dtype, jnp.floating):
        a = jnp.trunc(a)
    return a.astype(getattr(jnp, t))


_reg("cast", _np_cast, _jnp_cast, 2)


# ---- string functions (host-only; device work stays in dict-id space) -----

def _u(a):
    return np.asarray(a).astype(str)


_reg("lower", lambda a: np.char.lower(_u(a)))
_reg("upper", lambda a: np.char.upper(_u(a)))
_reg("trim", lambda a: np.char.strip(_u(a)))
_reg("ltrim", lambda a: np.char.lstrip(_u(a)))
_reg("rtrim", lambda a: np.char.rstrip(_u(a)))
_reg("reverse", lambda a: np.array([s[::-1] for s in _u(a)]))
_reg("length", lambda a: np.char.str_len(_u(a)).astype(np.int32))
_reg("strlen", lambda a: np.char.str_len(_u(a)).astype(np.int32))
_reg("concat", lambda *a: np.char.add(*[_u(x) for x in a]) if len(a) == 2
     else _concat_many(a), min_args=2, max_args=99)
_reg("substr", lambda a, start, end=None: _substr(a, start, end), 2, 3)
_reg("startswith", lambda a, p: np.char.startswith(_u(a), p), 2, returns_bool=True)
_reg("endswith", lambda a, p: np.char.endswith(_u(a), p), 2, returns_bool=True)
_reg("replace", lambda a, f, t: np.char.replace(_u(a), f, t), 3)
_reg("lpad", lambda a, n, p: np.array([s.rjust(int(n), str(p)) for s in _u(a)]), 3)
_reg("rpad", lambda a, n, p: np.array([s.ljust(int(n), str(p)) for s in _u(a)]), 3)
_reg("codepoint", lambda a: np.array([ord(s[0]) if s else 0 for s in _u(a)], dtype=np.int32))
_reg("chr", lambda a: np.array([chr(int(x)) for x in np.asarray(a).ravel()]))


def _concat_many(arrs):
    out = _u(arrs[0])
    for x in arrs[1:]:
        out = np.char.add(out, _u(x))
    return out


def _substr(a, start, end=None):
    # Pinot substr(col, start[, end]) is 0-based, end exclusive
    s = _u(a)
    start = int(start)
    if end is None:
        return np.array([x[start:] for x in s])
    return np.array([x[start:int(end)] for x in s])


# ---- JSON (host-only; JsonFunctions.java / JsonExtractScalar analog) ------

_JSON_PATH_RE = None  # compiled lazily


def _json_path_steps(path: str) -> list:
    import re as _re

    global _JSON_PATH_RE
    if _JSON_PATH_RE is None:
        _JSON_PATH_RE = _re.compile(r"\.([^.\[\]]+)|\[(\d+)\]")
    if not path.startswith("$"):
        raise ValueError(f"json path must start with $: {path!r}")
    steps = []
    pos = 1
    for m in _JSON_PATH_RE.finditer(path, 1):
        if m.start() != pos:
            # unparsable segment (e.g. [*] or a typo): reject instead of
            # silently navigating a different path
            raise ValueError(f"unsupported json path {path!r} "
                             f"(scalar paths only, no wildcards)")
        steps.append(m.group(1) if m.group(1) is not None else int(m.group(2)))
        pos = m.end()
    if pos != len(path):
        raise ValueError(f"unsupported json path {path!r} "
                         f"(scalar paths only, no wildcards)")
    return steps


def _json_nav(obj, steps):
    for s in steps:
        if isinstance(s, int):
            if not isinstance(obj, list) or s >= len(obj):
                return None
            obj = obj[s]
        else:
            if not isinstance(obj, dict):
                return None
            obj = obj.get(s)
        if obj is None:
            return None
    return obj


_JSON_RESULT_TYPES = {
    "INT": (np.int32, 0), "LONG": (np.int64, 0),
    "FLOAT": (np.float32, 0.0), "DOUBLE": (np.float64, 0.0),
    "STRING": (np.str_, ""), "BOOLEAN": (np.bool_, False),
}


def _json_extract_scalar(col, path, result_type, default=None):
    import json as _json

    def lit(x):
        a = np.asarray(x)
        return a.item() if a.ndim == 0 else x

    path, result_type = str(lit(path)), str(lit(result_type)).upper()
    if result_type not in _JSON_RESULT_TYPES:
        raise KeyError(f"json_extract_scalar result type {result_type}")
    dtype, type_default = _JSON_RESULT_TYPES[result_type]
    default = type_default if default is None else lit(default)
    steps = _json_path_steps(path)
    out = []
    for s in np.asarray(col).ravel():
        try:
            v = _json_nav(_json.loads(str(s)), steps)
        except (ValueError, TypeError):
            v = None
        if v is None or isinstance(v, (dict, list)):
            out.append(default)
        elif result_type == "BOOLEAN":
            out.append(v if isinstance(v, bool) else str(v).lower() == "true")
        else:
            out.append(v)
    if dtype is np.str_:
        return np.asarray([str(v) for v in out], dtype=np.str_)
    return np.asarray(out).astype(dtype)


_reg("json_extract_scalar", _json_extract_scalar, min_args=3, max_args=4)
_reg("jsonextractscalar", _json_extract_scalar, min_args=3, max_args=4)


# ---- geospatial (host-only; ops/geo.py — ST_* function analogs) -----------

def _geo(name):
    from pinot_tpu.ops import geo

    return getattr(geo, name)


_reg("st_point", lambda lon, lat: _geo("st_point")(lon, lat), min_args=2,
     max_args=2)
_reg("st_distance", lambda a, b: _geo("st_distance")(a, b), min_args=2,
     max_args=2)
_reg("st_contains", lambda p, pt: _geo("st_contains")(p, pt), min_args=2,
     max_args=2, returns_bool=True)
_reg("st_within", lambda pt, p: _geo("st_within")(pt, p), min_args=2,
     max_args=2, returns_bool=True)
_reg("st_geogfromtext", lambda w: _geo("st_geog_from_text")(w), min_args=1)
_reg("st_geomfromtext", lambda w: _geo("st_geog_from_text")(w), min_args=1)
_reg("st_astext", lambda g: _geo("st_as_text")(g), min_args=1)


# ---- lookup join (host-only; evaluated by SegmentEvaluator._lookup with
# engine dim-table state — the np_fn here is never called directly) ---------

def _lookup_stub(*a):
    raise ValueError("LOOKUP needs an engine with dimension tables")


_reg("lookup", _lookup_stub, min_args=4, max_args=4)


# ---- datetime (host-only) -------------------------------------------------

_reg("year", lambda a: _dtfield(a, "year"))
_reg("month", lambda a: _dtfield(a, "month"))
_reg("dayofmonth", lambda a: _dtfield(a, "day"))
_reg("dayofweek", lambda a: _dtfield(a, "dayofweek"))
_reg("hour", lambda a: _dtfield(a, "hour"))
_reg("minute", lambda a: _dtfield(a, "minute"))
_reg("second", lambda a: _dtfield(a, "second"))
_reg("frommillis", lambda a: np.asarray(a, dtype=np.int64))
_reg("tomillis", lambda a: np.asarray(a, dtype=np.int64))


def _dtfield(millis, field):
    dt = np.asarray(millis, dtype="int64").astype("datetime64[ms]")
    Y = dt.astype("datetime64[Y]")
    M = dt.astype("datetime64[M]")
    D = dt.astype("datetime64[D]")
    if field == "year":
        return Y.astype(int) + 1970
    if field == "month":
        return (M - Y).astype(int) + 1
    if field == "day":
        return (D - M).astype(int) + 1
    if field == "dayofweek":
        return ((D.astype(int) + 4) % 7) + 1  # 1970-01-01 was a Thursday
    sec = dt.astype("datetime64[s]")
    if field == "hour":
        return ((sec - D).astype(int) // 3600).astype(np.int32)
    if field == "minute":
        return (((sec - D).astype(int) // 60) % 60).astype(np.int32)
    if field == "second":
        return ((sec - D).astype(int) % 60).astype(np.int32)
    raise KeyError(field)


def _datetrunc(unit, millis):
    unit = str(unit).lower()
    ms = np.asarray(millis, dtype=np.int64)
    table = {
        "millisecond": 1, "second": 1000, "minute": 60_000, "hour": 3_600_000,
        "day": 86_400_000, "week": 7 * 86_400_000,
    }
    if unit in table:
        q = table[unit]
        return (ms // q) * q
    dt = ms.astype("datetime64[ms]")
    if unit == "month":
        return dt.astype("datetime64[M]").astype("datetime64[ms]").astype(np.int64)
    if unit == "year":
        return dt.astype("datetime64[Y]").astype("datetime64[ms]").astype(np.int64)
    raise KeyError(f"datetrunc unit {unit}")


_reg("datetrunc", _datetrunc, min_args=2, max_args=2)
