"""Transform-function registry: one semantic definition, two backends.

The reference's ~50 TransformFunction classes
(pinot-core/.../operator/transform/function/) plus the @ScalarFunction
registry (pinot-common/.../function/scalar/) collapse here into a table of
(numpy impl, jnp impl) pairs. The device column selects which impl a query
template traces; host-only functions (strings, datetime) force the engine's
host path for that expression.

Division follows the reference: DOUBLE division, x/0 → inf (Java double
semantics), so results match across backends and the duckdb oracle modulo
float formatting.
"""

from __future__ import annotations

import numpy as np

try:
    import jax.numpy as jnp
except Exception:  # pragma: no cover
    jnp = None


class FunctionDef:
    def __init__(self, name, np_fn, jnp_fn=None, min_args=1, max_args=None,
                 returns_bool=False):
        self.name = name
        self.np_fn = np_fn
        self.jnp_fn = jnp_fn  # None → host-only
        self.min_args = min_args
        self.max_args = max_args if max_args is not None else min_args
        self.returns_bool = returns_bool

    @property
    def device_capable(self) -> bool:
        return self.jnp_fn is not None


REGISTRY: dict[str, FunctionDef] = {}


def _reg(name, np_fn, jnp_fn=None, min_args=1, max_args=None, returns_bool=False):
    REGISTRY[name] = FunctionDef(name, np_fn, jnp_fn, min_args, max_args, returns_bool)


def get_function(name: str) -> FunctionDef:
    f = REGISTRY.get(name)
    if f is None:
        raise KeyError(f"unknown function: {name}")
    return f


# ---- arithmetic -----------------------------------------------------------

def _np_div(a, b):
    with np.errstate(divide="ignore", invalid="ignore"):
        return np.asarray(a, dtype=np.float64) / np.asarray(b, dtype=np.float64)


def _jnp_div(a, b):
    return jnp.asarray(a, dtype=jnp.float32) / jnp.asarray(b, dtype=jnp.float32)


_reg("plus", lambda a, b: np.add(a, b), lambda a, b: jnp.add(a, b), 2)
_reg("minus", lambda a, b: np.subtract(a, b), lambda a, b: jnp.subtract(a, b), 2)
_reg("times", lambda a, b: np.multiply(a, b), lambda a, b: jnp.multiply(a, b), 2)
_reg("divide", _np_div, _jnp_div, 2)
_reg("mod", lambda a, b: np.mod(a, b), lambda a, b: jnp.mod(a, b), 2)
_reg("abs", np.abs, (lambda a: jnp.abs(a)), 1)
_reg("ceil", np.ceil, (lambda a: jnp.ceil(a)), 1)
_reg("floor", np.floor, (lambda a: jnp.floor(a)), 1)
_reg("exp", np.exp, (lambda a: jnp.exp(a)), 1)
_reg("ln", np.log, (lambda a: jnp.log(a)), 1)
_reg("log2", np.log2, (lambda a: jnp.log2(a)), 1)
_reg("log10", np.log10, (lambda a: jnp.log10(a)), 1)
_reg("sqrt", np.sqrt, (lambda a: jnp.sqrt(a)), 1)
_reg("power", np.power, (lambda a, b: jnp.power(a, b)), 2)
_reg("pow", np.power, (lambda a, b: jnp.power(a, b)), 2)
_reg("least", np.minimum, (lambda a, b: jnp.minimum(a, b)), 2)
_reg("greatest", np.maximum, (lambda a, b: jnp.maximum(a, b)), 2)
_reg("sign", np.sign, (lambda a: jnp.sign(a)), 1)
_reg("round", np.round, (lambda a: jnp.round(a)), 1, 2)

# trigonometric (scalar/Trigonometric*.java)
for _n, _np, _j in [
    ("sin", np.sin, "sin"), ("cos", np.cos, "cos"), ("tan", np.tan, "tan"),
    ("asin", np.arcsin, "arcsin"), ("acos", np.arccos, "arccos"),
    ("atan", np.arctan, "arctan"), ("sinh", np.sinh, "sinh"),
    ("cosh", np.cosh, "cosh"), ("tanh", np.tanh, "tanh"),
    ("degrees", np.degrees, "degrees"), ("radians", np.radians, "radians"),
]:
    _reg(_n, _np, (lambda a, _f=_j: getattr(jnp, _f)(a)), 1)

# ---- comparisons (usable inside CASE / arithmetic contexts) ---------------

_reg("equals", lambda a, b: np.equal(a, b), lambda a, b: jnp.equal(a, b), 2, returns_bool=True)
_reg("not_equals", lambda a, b: np.not_equal(a, b), lambda a, b: jnp.not_equal(a, b), 2, returns_bool=True)
_reg("greater_than", lambda a, b: np.greater(a, b), lambda a, b: jnp.greater(a, b), 2, returns_bool=True)
_reg("greater_than_or_equal", lambda a, b: np.greater_equal(a, b), lambda a, b: jnp.greater_equal(a, b), 2, returns_bool=True)
_reg("less_than", lambda a, b: np.less(a, b), lambda a, b: jnp.less(a, b), 2, returns_bool=True)
_reg("less_than_or_equal", lambda a, b: np.less_equal(a, b), lambda a, b: jnp.less_equal(a, b), 2, returns_bool=True)
_reg("and", lambda *a: np.logical_and.reduce(a), lambda *a: jnp.stack(a).all(0), 2, 99, returns_bool=True)
_reg("or", lambda *a: np.logical_or.reduce(a), lambda *a: jnp.stack(a).any(0), 2, 99, returns_bool=True)
_reg("not", np.logical_not, (lambda a: jnp.logical_not(a)), 1, returns_bool=True)


# ---- CASE / CAST ----------------------------------------------------------

def _np_case(*args):
    # (c1, v1, c2, v2, ..., else)
    conds = list(args[:-1:2])
    vals = list(args[1:-1:2])
    return np.select(conds, vals, default=args[-1])


def _jnp_case(*args):
    out = args[-1]
    for c, v in zip(reversed(args[:-1:2]), reversed(args[1:-1:2])):
        out = jnp.where(c, v, out)
    return out


_reg("case", _np_case, _jnp_case, 3, 99)

_CAST_NP = {
    "INT": np.int32, "INTEGER": np.int32, "LONG": np.int64, "BIGINT": np.int64,
    "FLOAT": np.float32, "DOUBLE": np.float64, "BOOLEAN": np.bool_,
    "STRING": np.str_, "VARCHAR": np.str_, "TIMESTAMP": np.int64,
}
_CAST_JNP = {
    "INT": "int32", "INTEGER": "int32", "LONG": "int64", "BIGINT": "int64",
    "FLOAT": "float32", "DOUBLE": "float32", "BOOLEAN": "bool_",
    "TIMESTAMP": "int64",
}


def _np_cast(a, type_name):
    t = _CAST_NP.get(str(type_name).upper())
    if t is None:
        raise KeyError(f"CAST to unsupported type {type_name}")
    if t is np.str_:
        return np.asarray(a).astype(str)
    if np.issubdtype(t, np.integer):
        # SQL CAST truncates toward zero
        return np.trunc(np.asarray(a, dtype=np.float64)).astype(t) \
            if np.asarray(a).dtype.kind == "f" else np.asarray(a).astype(t)
    return np.asarray(a).astype(t)


def _jnp_cast(a, type_name):
    t = _CAST_JNP.get(str(type_name).upper())
    if t is None:
        raise KeyError(f"CAST to {type_name} is host-only")
    if t.startswith("int") and jnp.issubdtype(a.dtype, jnp.floating):
        a = jnp.trunc(a)
    return a.astype(getattr(jnp, t))


_reg("cast", _np_cast, _jnp_cast, 2)


# ---- string functions (host-only; device work stays in dict-id space) -----

def _u(a):
    return np.asarray(a).astype(str)


_reg("lower", lambda a: np.char.lower(_u(a)))
_reg("upper", lambda a: np.char.upper(_u(a)))
_reg("trim", lambda a: np.char.strip(_u(a)))
_reg("ltrim", lambda a: np.char.lstrip(_u(a)))
_reg("rtrim", lambda a: np.char.rstrip(_u(a)))
_reg("reverse", lambda a: np.array([s[::-1] for s in _u(a)]))
_reg("length", lambda a: np.char.str_len(_u(a)).astype(np.int32))
_reg("strlen", lambda a: np.char.str_len(_u(a)).astype(np.int32))
_reg("concat", lambda *a: np.char.add(*[_u(x) for x in a]) if len(a) == 2
     else _concat_many(a), min_args=2, max_args=99)
_reg("substr", lambda a, start, end=None: _substr(a, start, end), 2, 3)
_reg("startswith", lambda a, p: np.char.startswith(_u(a), p), 2, returns_bool=True)
_reg("endswith", lambda a, p: np.char.endswith(_u(a), p), 2, returns_bool=True)
_reg("replace", lambda a, f, t: np.char.replace(_u(a), f, t), 3)
_reg("lpad", lambda a, n, p: np.array([s.rjust(int(n), str(p)) for s in _u(a)]), 3)
_reg("rpad", lambda a, n, p: np.array([s.ljust(int(n), str(p)) for s in _u(a)]), 3)
_reg("codepoint", lambda a: np.array([ord(s[0]) if s else 0 for s in _u(a)], dtype=np.int32))
_reg("chr", lambda a: np.array([chr(int(x)) for x in np.asarray(a).ravel()]))


def _concat_many(arrs):
    out = _u(arrs[0])
    for x in arrs[1:]:
        out = np.char.add(out, _u(x))
    return out


def _substr(a, start, end=None):
    # Pinot substr(col, start[, end]) is 0-based, end exclusive
    s = _u(a)
    start = int(start)
    if end is None:
        return np.array([x[start:] for x in s])
    return np.array([x[start:int(end)] for x in s])


# ---- JSON (host-only; JsonFunctions.java / JsonExtractScalar analog) ------

_JSON_PATH_RE = None  # compiled lazily


def _json_path_steps(path: str) -> list:
    import re as _re

    global _JSON_PATH_RE
    if _JSON_PATH_RE is None:
        _JSON_PATH_RE = _re.compile(r"\.([^.\[\]]+)|\[(\d+)\]")
    if not path.startswith("$"):
        raise ValueError(f"json path must start with $: {path!r}")
    steps = []
    pos = 1
    for m in _JSON_PATH_RE.finditer(path, 1):
        if m.start() != pos:
            # unparsable segment (e.g. [*] or a typo): reject instead of
            # silently navigating a different path
            raise ValueError(f"unsupported json path {path!r} "
                             f"(scalar paths only, no wildcards)")
        steps.append(m.group(1) if m.group(1) is not None else int(m.group(2)))
        pos = m.end()
    if pos != len(path):
        raise ValueError(f"unsupported json path {path!r} "
                         f"(scalar paths only, no wildcards)")
    return steps


def _json_nav(obj, steps):
    for s in steps:
        if isinstance(s, int):
            if not isinstance(obj, list) or s >= len(obj):
                return None
            obj = obj[s]
        else:
            if not isinstance(obj, dict):
                return None
            obj = obj.get(s)
        if obj is None:
            return None
    return obj


_JSON_RESULT_TYPES = {
    "INT": (np.int32, 0), "LONG": (np.int64, 0),
    "FLOAT": (np.float32, 0.0), "DOUBLE": (np.float64, 0.0),
    "STRING": (np.str_, ""), "BOOLEAN": (np.bool_, False),
}


def _json_extract_scalar(col, path, result_type, default=None):
    import json as _json

    def lit(x):
        a = np.asarray(x)
        return a.item() if a.ndim == 0 else x

    path, result_type = str(lit(path)), str(lit(result_type)).upper()
    if result_type not in _JSON_RESULT_TYPES:
        raise KeyError(f"json_extract_scalar result type {result_type}")
    dtype, type_default = _JSON_RESULT_TYPES[result_type]
    default = type_default if default is None else lit(default)
    steps = _json_path_steps(path)
    out = []
    for s in np.asarray(col).ravel():
        try:
            v = _json_nav(_json.loads(str(s)), steps)
        except (ValueError, TypeError):
            v = None
        if v is None or isinstance(v, (dict, list)):
            out.append(default)
        elif result_type == "BOOLEAN":
            out.append(v if isinstance(v, bool) else str(v).lower() == "true")
        else:
            out.append(v)
    if dtype is np.str_:
        return np.asarray([str(v) for v in out], dtype=np.str_)
    return np.asarray(out).astype(dtype)


_reg("json_extract_scalar", _json_extract_scalar, min_args=3, max_args=4)
_reg("jsonextractscalar", _json_extract_scalar, min_args=3, max_args=4)


# ---- geospatial (host-only; ops/geo.py — ST_* function analogs) -----------

def _geo(name):
    from pinot_tpu.ops import geo

    return getattr(geo, name)


_reg("st_point", lambda lon, lat: _geo("st_point")(lon, lat), min_args=2,
     max_args=2)
_reg("st_distance", lambda a, b: _geo("st_distance")(a, b), min_args=2,
     max_args=2)
_reg("st_contains", lambda p, pt: _geo("st_contains")(p, pt), min_args=2,
     max_args=2, returns_bool=True)
_reg("st_within", lambda pt, p: _geo("st_within")(pt, p), min_args=2,
     max_args=2, returns_bool=True)
_reg("st_geogfromtext", lambda w: _geo("st_geog_from_text")(w), min_args=1)
_reg("st_geomfromtext", lambda w: _geo("st_geog_from_text")(w), min_args=1)
_reg("st_astext", lambda g: _geo("st_as_text")(g), min_args=1)
_reg("st_polygon", lambda w: _geo("st_polygon")(w), min_args=1)
_reg("st_area", lambda p: _geo("st_area")(p), min_args=1)
_reg("st_asbinary", lambda p: _geo("st_as_binary")(p), min_args=1)
_reg("st_geomfromwkb", lambda b: _geo("st_geom_from_wkb")(b), min_args=1)
_reg("st_geogfromwkb", lambda b: _geo("st_geom_from_wkb")(b), min_args=1)


# ---- lookup join (host-only; evaluated by SegmentEvaluator._lookup with
# engine dim-table state — the np_fn here is never called directly) ---------

def _lookup_stub(*a):
    raise ValueError("LOOKUP needs an engine with dimension tables")


_reg("lookup", _lookup_stub, min_args=4, max_args=4)


# ---- datetime (host-only) -------------------------------------------------

_reg("year", lambda a: _dtfield(a, "year"))
_reg("month", lambda a: _dtfield(a, "month"))
_reg("dayofmonth", lambda a: _dtfield(a, "day"))
_reg("dayofweek", lambda a: _dtfield(a, "dayofweek"))
_reg("hour", lambda a: _dtfield(a, "hour"))
_reg("minute", lambda a: _dtfield(a, "minute"))
_reg("second", lambda a: _dtfield(a, "second"))
_reg("frommillis", lambda a: np.asarray(a, dtype=np.int64))
_reg("tomillis", lambda a: np.asarray(a, dtype=np.int64))


def _dtfield(millis, field):
    dt = np.asarray(millis, dtype="int64").astype("datetime64[ms]")
    Y = dt.astype("datetime64[Y]")
    M = dt.astype("datetime64[M]")
    D = dt.astype("datetime64[D]")
    if field == "year":
        return Y.astype(int) + 1970
    if field == "month":
        return (M - Y).astype(int) + 1
    if field == "day":
        return (D - M).astype(int) + 1
    if field == "dayofweek":
        return ((D.astype(int) + 4) % 7) + 1  # 1970-01-01 was a Thursday
    sec = dt.astype("datetime64[s]")
    if field == "hour":
        return ((sec - D).astype(int) // 3600).astype(np.int32)
    if field == "minute":
        return (((sec - D).astype(int) // 60) % 60).astype(np.int32)
    if field == "second":
        return ((sec - D).astype(int) % 60).astype(np.int32)
    raise KeyError(field)


# ---- TIMECONVERT / DATETIMECONVERT (host-only) ----------------------------
# Reference: TimeConversionTransformFunction.java,
# DateTimeConversionTransformFunction.java:80 + DateTimeFormatSpec — the
# workhorse Pinot time-rollup functions.

_UNIT_MS = {
    "NANOSECONDS": 1e-6, "MICROSECONDS": 1e-3, "MILLISECONDS": 1,
    "SECONDS": 1_000, "MINUTES": 60_000, "HOURS": 3_600_000,
    "DAYS": 86_400_000,
}


def _unit_ms(unit: str) -> float:
    u = str(unit).upper()
    if u not in _UNIT_MS:
        raise ValueError(f"unknown time unit {unit!r}")
    return _UNIT_MS[u]


def _div_trunc(v: np.ndarray, d: np.int64) -> np.ndarray:
    """Integer division truncating toward ZERO (Java long division) — numpy
    // floors, which differs on negatives."""
    return np.sign(v) * (np.abs(v) // d)


def _to_millis(values: np.ndarray, unit: str) -> np.ndarray:
    """TimeUnit.MILLISECONDS.convert(value, unit) — exact integer
    arithmetic, truncating toward zero like Java (a float64 path here
    rounded epoch-nanos into the wrong millisecond bucket)."""
    f = _unit_ms(unit)
    v = np.asarray(values, dtype=np.int64)
    if f >= 1:
        return v * np.int64(f)
    return _div_trunc(v, np.int64(round(1 / f)))


def _from_millis(millis: np.ndarray, unit: str) -> np.ndarray:
    f = _unit_ms(unit)
    ms = np.asarray(millis, dtype=np.int64)
    if f >= 1:
        return _div_trunc(ms, np.int64(f))
    return ms * np.int64(round(1 / f))


def _timeconvert(values, from_unit, to_unit):
    return _from_millis(_to_millis(values, str(from_unit)), str(to_unit))


_reg("timeconvert", _timeconvert, min_args=3, max_args=3)

# Java SimpleDateFormat tokens → strftime (longest-first so yyyy wins over
# yy). SSS maps to %f for PARSING (strptime right-pads fraction digits to
# microseconds, matching SDF millis); formatting post-processes %f's 6
# digits down to SDF's 3 (_fix_sss).
_SDF_TOKENS = [
    ("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"),
    ("HH", "%H"), ("mm", "%M"), ("ss", "%S"), ("SSS", "%f"), ("a", "%p"),
    ("EEE", "%a"), ("M", "%m"), ("d", "%d"), ("H", "%H"), ("h", "%I"),
]


def _sdf_to_strftime(pattern: str) -> str:
    out, i = [], 0
    while i < len(pattern):
        for tok, rep in _SDF_TOKENS:
            if pattern.startswith(tok, i):
                out.append(rep)
                i += len(tok)
                break
        else:
            out.append(pattern[i])
            i += 1
    return "".join(out)


class _DateTimeFormat:
    """DateTimeFormatSpec: 'size:unit:EPOCH' or
    'size:unit:SIMPLE_DATE_FORMAT:pattern[ tz(...)]'."""

    def __init__(self, spec: str):
        parts = str(spec).split(":", 3)
        if len(parts) < 3:
            raise ValueError(f"bad datetime format {spec!r}")
        self.size = int(parts[0])
        self.unit = parts[1].upper()
        self.fmt = parts[2].upper()
        self.pattern = parts[3] if len(parts) > 3 else None
        if self.fmt == "SIMPLE_DATE_FORMAT" and self.pattern:
            pat = self.pattern
            if " tz(" in pat:
                pat, tz = pat.split(" tz(", 1)
                tz = tz.rstrip(")")
                if tz.upper() not in ("UTC", "GMT"):
                    raise ValueError(
                        f"only UTC SIMPLE_DATE_FORMAT timezones supported "
                        f"(got {tz!r})")
            self.strftime = _sdf_to_strftime(pat)

    def to_millis(self, values: np.ndarray) -> np.ndarray:
        if self.fmt == "EPOCH":
            return _to_millis(
                np.asarray(values, dtype=np.int64) * self.size, self.unit)
        if self.fmt in ("SIMPLE_DATE_FORMAT", "TIMESTAMP"):
            import pandas as pd

            if self.fmt == "TIMESTAMP":
                dt = pd.to_datetime(np.asarray(values))
            else:
                vals = np.asarray(values).astype(str)
                fmt = self.strftime
                if "%Y" not in fmt and "%y" not in fmt:
                    # Java SDF defaults missing date fields to the 1970
                    # epoch; C strptime defaults to 1900 — pin the base
                    vals = np.char.add("1970-01-01 ", vals)
                    fmt = "%Y-%m-%d " + fmt
                dt = pd.to_datetime(vals, format=fmt)
            # normalize to ms regardless of the index's native resolution
            # (pandas 2.x may parse to s/us/ns depending on the format)
            return np.asarray(dt, dtype="datetime64[ms]").astype(np.int64)
        raise ValueError(f"unknown datetime format {self.fmt!r}")

    def from_millis(self, millis: np.ndarray):
        if self.fmt == "EPOCH":
            return _div_trunc(_from_millis(millis, self.unit),
                              np.int64(self.size))
        import pandas as pd

        ms = np.asarray(millis, dtype=np.int64)
        dt = pd.to_datetime(ms, unit="ms")
        fmt = self.strftime
        # U-dtype (not object): string results flow into group keys and the
        # DataTable wire codec, which round-trips numpy string arrays but
        # refuses pickled object arrays
        if "%f" in fmt:
            # SDF's SSS is 3-digit millis; strftime %f would emit 6-digit
            # micros — format around a sentinel and splice the millis in
            sent = "\x00"
            base = np.asarray(dt.strftime(fmt.replace("%f", sent)))
            frac = np.char.zfill((ms % 1000).astype(str), 3)
            return np.asarray(
                [s.replace(sent, f) for s, f in zip(base, frac)],
                dtype=np.str_)
        return np.asarray(dt.strftime(fmt), dtype=np.str_)


def _datetimeconvert(values, in_fmt, out_fmt, granularity):
    inf = _DateTimeFormat(str(in_fmt))
    outf = _DateTimeFormat(str(out_fmt))
    gsize, gunit = str(granularity).split(":", 1)
    g = np.int64(int(gsize) * _unit_ms(gunit))
    ms = inf.to_millis(values)
    bucketed = _div_trunc(ms, g) * g
    return outf.from_millis(bucketed)


_reg("datetimeconvert", _datetimeconvert, min_args=4, max_args=4)


# ---- array / MV transforms (host-only) ------------------------------------
# Reference: ArrayLength/ArraySum/ArrayAverage/ArrayMin/ArrayMax
# TransformFunction.java, ValueInTransformFunction.java:1,
# MapValueTransformFunction. MV identifier evaluation yields an object
# array of per-doc entry arrays (or a 2-D array when all docs have equal
# entry counts) — helpers handle both.


def _mv_rows(col):
    arr = np.asarray(col)
    if arr.ndim == 2:
        return list(arr)
    if arr.dtype == object:
        return [np.asarray(r) for r in arr]
    # an SV column is a 1-entry MV per the reference's implicit widening
    return [np.asarray([v]) for v in arr]


def _array_reduce(col, fn, empty):
    rows = _mv_rows(col)
    return np.asarray([fn(r) if len(r) else empty for r in rows])


_reg("arraylength", lambda c: np.asarray([len(r) for r in _mv_rows(c)],
                                         dtype=np.int64), min_args=1)
_reg("cardinality", lambda c: np.asarray([len(r) for r in _mv_rows(c)],
                                         dtype=np.int64), min_args=1)
_reg("arraysum", lambda c: _array_reduce(c, np.sum, 0.0), min_args=1)
_reg("arrayaverage",
     lambda c: _array_reduce(c, np.mean, float("nan")), min_args=1)
_reg("arraymin", lambda c: _array_reduce(c, np.min, float("inf")), min_args=1)
_reg("arraymax", lambda c: _array_reduce(c, np.max, float("-inf")), min_args=1)


def _valuein(col, *wanted):
    """Per-doc entry filter: keep MV entries ∈ {wanted} (the reference
    dedups while preserving first-seen order)."""
    want = {np.asarray(w).item() for w in wanted}
    rows = _mv_rows(col)
    out = np.empty(len(rows), dtype=object)
    for i, r in enumerate(rows):
        seen, kept = set(), []
        for v in r.tolist():
            if v in want and v not in seen:
                seen.add(v)
                kept.append(v)
        out[i] = kept
    return out


_reg("valuein", _valuein, min_args=2, max_args=99)


def _mapvalue(keys_col, key, values_col):
    """MAPVALUE(map__KEYS, 'k', map__VALUES): per doc, the value at the
    key's position in the keys MV (reference MapValueTransformFunction);
    missing keys yield the value column's type default."""
    k = np.asarray(key).item()
    krows = _mv_rows(keys_col)
    vrows = _mv_rows(values_col)
    first = next((r for r in vrows if len(r)), None)
    if first is not None and np.asarray(first).dtype.kind in "UOS":
        default = ""
    else:
        default = 0
    out = []
    for kr, vr in zip(krows, vrows):
        hits = np.nonzero(np.asarray(kr) == k)[0]
        if len(hits) and hits[0] < len(vr):
            out.append(np.asarray(vr)[hits[0]])
        else:
            out.append(default)
    return np.asarray(out)


_reg("mapvalue", _mapvalue, min_args=3, max_args=3)


# ---- REGEXP_EXTRACT (host-only) -------------------------------------------


def _regexp_extract(col, pattern, group=None, default=None):
    """REGEXP_EXTRACT(value, pattern[, group[, default]]) — first match's
    group (0 = whole match), or the default ('' like the reference's null
    string) when the pattern doesn't match
    (RegexpExtractTransformFunction.java)."""
    import re

    rx = re.compile(str(np.asarray(pattern).item()))
    g = int(np.asarray(group).item()) if group is not None else 0
    d = str(np.asarray(default).item()) if default is not None else ""
    vals = np.asarray(col)
    if vals.ndim == 0:
        vals = vals[None]
    # U-dtype so results can serve as group keys over the DataTable wire
    return np.asarray([
        (m.group(g) if (m := rx.search(str(v))) and g <= rx.groups else d)
        for v in vals.tolist()
    ])


_reg("regexp_extract", _regexp_extract, min_args=2, max_args=4)
_reg("regexpextract", _regexp_extract, min_args=2, max_args=4)


def _datetrunc(unit, millis):
    unit = str(unit).lower()
    ms = np.asarray(millis, dtype=np.int64)
    table = {
        "millisecond": 1, "second": 1000, "minute": 60_000, "hour": 3_600_000,
        "day": 86_400_000, "week": 7 * 86_400_000,
    }
    if unit in table:
        q = table[unit]
        return (ms // q) * q
    dt = ms.astype("datetime64[ms]")
    if unit == "month":
        return dt.astype("datetime64[M]").astype("datetime64[ms]").astype(np.int64)
    if unit == "year":
        return dt.astype("datetime64[Y]").astype("datetime64[ms]").astype(np.int64)
    raise KeyError(f"datetrunc unit {unit}")


_reg("datetrunc", _datetrunc, min_args=2, max_args=2)


# ---- transform-enum tail (TransformFunctionType.java) ---------------------
# QUARTER / WEEK_OF_YEAR / DAY_OF_YEAR / YEAR_OF_WEEK / MILLISECOND
# (DateTimeFunctions.java, UTC like the other datetime fns here),
# ATAN2 / COT / ROUND_DECIMAL / TRUNCATE (ArithmeticFunctions.java),
# JSONEXTRACTKEY, INIDSET, GEOTOH3 (grid-scheme role), ST_EQUALS,
# ST_GEOMETRY_TYPE.


def _epoch_days(millis):
    ms = np.asarray(millis, dtype=np.int64)
    return ms.astype("datetime64[ms]").astype("datetime64[D]").astype(np.int64)


def _iso_week_fields(millis):
    """(weekOfYear, yearOfWeek) under ISO-8601 week numbering (joda
    ISOChronology, DateTimeFunctions.weekOfYear/yearOfWeek): a week
    belongs to the year containing its Thursday."""
    D = _epoch_days(millis)
    wd = (D + 3) % 7                       # 0 = Monday (1970-01-01 was Thu)
    thu = D - wd + 3
    thu_dt = thu.astype("datetime64[D]")
    iso_year = thu_dt.astype("datetime64[Y]").astype(np.int64) + 1970
    jan1 = (iso_year - 1970).astype("datetime64[Y]").astype(
        "datetime64[D]").astype(np.int64)
    week = (thu - jan1) // 7 + 1
    return week.astype(np.int64), iso_year


def _quarter(millis):
    return (np.asarray(_dtfield(millis, "month"), dtype=np.int64) - 1) // 3 + 1


def _day_of_year(millis):
    dt = np.asarray(millis, dtype=np.int64).astype("datetime64[ms]")
    D = dt.astype("datetime64[D]")
    Y = dt.astype("datetime64[Y]")
    return (D - Y.astype("datetime64[D]")).astype(np.int64) + 1


def _millisecond(millis):
    # joda millisOfSecond: non-negative even for pre-epoch instants
    return np.mod(np.asarray(millis, dtype=np.int64), 1000)


_reg("quarter", _quarter)
_reg("weekofyear", lambda a: _iso_week_fields(a)[0])
_reg("week", lambda a: _iso_week_fields(a)[0])
_reg("yearofweek", lambda a: _iso_week_fields(a)[1])
_reg("yow", lambda a: _iso_week_fields(a)[1])
_reg("dayofyear", _day_of_year)
_reg("doy", _day_of_year)
_reg("millisecond", _millisecond)

_reg("atan2", np.arctan2, (lambda a, b: jnp.arctan2(a, b)), 2)
_reg("cot", lambda a: _np_div(1.0, np.tan(np.asarray(a, dtype=np.float64))),
     (lambda a: 1.0 / jnp.tan(a)), 1)


def _round_decimal(a, scale=None):
    """BigDecimal HALF_UP rounding (ArithmeticFunctions.roundDecimal) —
    np.round is half-EVEN, which differs on exact .5 boundaries."""
    v = np.asarray(a, dtype=np.float64)
    if scale is None:
        return np.floor(v + 0.5)  # Math.round
    s = 10.0 ** int(np.asarray(scale).item())
    return np.sign(v) * np.floor(np.abs(v) * s + 0.5) / s


def _truncate(a, scale=None):
    """Truncate toward zero to ``scale`` decimals (RoundingMode.DOWN)."""
    v = np.asarray(a, dtype=np.float64)
    if scale is None:
        return np.sign(v) * np.floor(np.abs(v))
    s = 10.0 ** int(np.asarray(scale).item())
    return np.sign(v) * np.floor(np.abs(v) * s) / s


_reg("rounddecimal", _round_decimal, None, 1, 2)
_reg("round_decimal", _round_decimal, None, 1, 2)
_reg("truncate", _truncate, None, 1, 2)


def _json_extract_key(col, path):
    """jsonExtractKey(jsonCol, 'jsonPath') → STRING_MV of the jayway-style
    paths matching the expression (JsonExtractKeyTransformFunction's
    AS_PATH_LIST contract). Scalar paths plus one trailing wildcard
    (``$.a.*`` / ``$.a[*]``) are supported — the subset the engine's json
    navigation models."""
    import json as _json

    p = str(np.asarray(path).item())
    wildcard = p.endswith(".*") or p.endswith("[*]")
    base = p[:-2] if p.endswith(".*") else (p[:-3] if p.endswith("[*]") else p)
    steps = _json_path_steps(base)

    def jay(parts):
        return "$" + "".join(
            f"[{s}]" if isinstance(s, int) else f"['{s}']" for s in parts)

    vals = np.asarray(col)
    if vals.ndim == 0:
        vals = vals[None]
    out = np.empty(len(vals), dtype=object)
    for i, s in enumerate(vals.tolist()):
        try:
            obj = _json_nav(_json.loads(str(s)), steps)
        except (ValueError, TypeError):
            obj = None
        paths = []
        if wildcard:
            if isinstance(obj, dict):
                paths = [jay(steps + [k]) for k in obj]
            elif isinstance(obj, list):
                paths = [jay(steps + [j]) for j in range(len(obj))]
        elif obj is not None:
            paths = [jay(steps)]
        out[i] = paths
    return out


_reg("jsonextractkey", _json_extract_key, min_args=2, max_args=2)
_reg("json_extract_key", _json_extract_key, min_args=2, max_args=2)


def _in_id_set(col, idset_b64):
    """inIdSet(col, 'serialized-idset') → BOOLEAN membership against an
    IDSET aggregation result (engine/aggspec.py IdSetSpec rendering:
    base64(gzip(json(sorted values))))."""
    import base64
    import gzip
    import json as _json

    blob = str(np.asarray(idset_b64).item())
    try:
        ids = set(_json.loads(gzip.decompress(
            base64.b64decode(blob)).decode("utf-8")))
    except Exception as e:  # noqa: BLE001 — malformed literal is a user error
        raise ValueError(f"inIdSet: malformed idset literal: {e}") from None
    vals = np.asarray(col)
    if vals.ndim == 0:
        vals = vals[None]
    out = np.zeros(len(vals), dtype=bool)
    for i, v in enumerate(vals.tolist()):
        out[i] = v in ids or str(v) in ids
    return out


_reg("inidset", _in_id_set, min_args=2, max_args=2, returns_bool=True)
_reg("in_id_set", _in_id_set, min_args=2, max_args=2, returns_bool=True)


def _geo_to_cell(*args):
    """geoToH3's two reference signatures on the grid scheme:
    geoToH3(point, res) or geoToH3(lon, lat, res) (GeoToH3Function.java:
    38-39). Returns grid cell ids, not H3 ids — this build's geo index is
    the 2-D lat/lon grid (storage/geoindex.py), documented in PARITY.md."""
    from pinot_tpu.ops import geo as _g

    if len(args) == 2:
        lon, lat = _g.parse_points(args[0])
        return _g.grid_cell(lon, lat, args[1])
    return _g.grid_cell(args[0], args[1], args[2])


_reg("geotoh3", _geo_to_cell, min_args=2, max_args=3)
_reg("gridcell", _geo_to_cell, min_args=2, max_args=3)

_reg("st_equals", lambda a, b: _geo("st_equals")(a, b), min_args=2,
     max_args=2, returns_bool=True)
_reg("st_geometrytype", lambda g: _geo("st_geometry_type")(g), min_args=1)
