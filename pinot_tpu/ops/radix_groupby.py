"""Radix-partitioned high-cardinality group-by: the chunked-sort basis.

Replaces the monolithic-``lax.sort`` basis of the sorted/high-cardinality
device regime (the MAP_BASED analog of DictionaryBasedGroupKeyGenerator).
The old basis sorted the full (n,) int64 combined-key array once per payload
family — at 100M rows that single sort ran at ~1.6 GB/s (0.4% of v5e HBM
peak; BENCH_r05 ``micro.sortkey_int64``), because XLA's comparator network
over a 0.8GB operand is HBM-bound on O(log^2 n) passes. This module keeps
the *sortedness* the regime depends on but restructures WHERE the sorting
happens so almost all comparator passes run over VMEM-resident operands:

1. **Radix key packing** (``pack_keys``): the cartesian dict-id key packs
   into int32 whenever the key space fits (< 2^31) — half the bytes through
   every comparator pass. The int32 key is viewed as (high radix bits =
   partition, low bits = in-partition id); int64 remains the fallback basis
   for wider key spaces, through the same code path.
2. **Chunked level-1 sorts**: rows split into C chunks of L rows (L sized
   for VMEM-resident sorting, ``CHUNK_ROWS``) and ONE batched ``lax.sort``
   sorts all chunks independently — log^2(L) passes instead of log^2(n),
   each over an L-row operand instead of the full array.
3. **Run-end partials, no scatters, no secondary sorts**: within a sorted
   chunk every group is a contiguous run. COUNT/integer-SUM come from
   position/cumsum differences at run ends (two's-complement-exact for
   ints); float sums and MIN/MAX come from *segmented* associative scans
   (``jax.lax.associative_scan``) over the single sorted order — the old
   basis paid a full extra (key, value) sort per MIN/MAX argument and an
   n-row position scatter for the table build; both are gone.
4. **Static-bound compaction**: each chunk's run-end entries are compacted
   to the front by a second batched sort of the end-masked keys and sliced
   to E = min(L, K+1) entries, where K is the group-table cap
   (numGroupsLimit). A chunk with more than E distinct groups proves the
   whole query overflows K (chunk-distinct <= global-distinct), so the
   slice can never silently drop a surviving group — overflow is detected
   and reported through ``n_groups_total`` exactly like the old basis.
5. **Level-2+ merge**: the C*E compacted partials (~n / (L/E) rows)
   re-enter the same chunk/sort/combine/compact structure until chunking
   stops paying, then one answer-scale sort builds the final (K,) group
   table — no pass ever sorts a monolithic row-scale operand. The same
   merge, applied to device-gathered (D, K) tables, makes the regime
   MESH-COMBINABLE (``merge_tables``; parallel/mesh.py) — the old basis
   had to route every multi-chip high-card query to the host.

The radix histogram (``bucket_histogram``) rides the factored one-hot
matmul kernel (ops/groupby_mm.py) over the key's high bits — the
bandwidth-shaped occupancy probe for the partition structure (bench
``micro.radix_bucket_histogram`` pins its rate; tests pin it against
np.bincount).

Everything here is trace-time static in shapes: chunk plans derive from
array lengths and the template's K, so jit caches stay keyed on the same
(template, batch-shape) pairs the executor already uses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

INT32_SENTINEL = (1 << 31) - 1   # masked/padded rows: sorts after real keys
INT64_SENTINEL = (1 << 63) - 1   # same role for the int64 fallback basis
# int32 packing bound: keys must stay strictly below the sentinel
MAX_KEYSPACE_32 = (1 << 31) - 1

CHUNK_ROWS = 1 << 20          # level-1 chunk length target (VMEM-scale sort)
CHUNK_ROWS_MAX = 1 << 23      # growth cap when K forces bigger chunks (the
                              # q4 HLL slot space — 2000 groups x 1024
                              # registers ≈ 2M keys — needs 8M-row chunks
                              # before even ratio-2 compaction engages)
MIN_COMPACT_RATIO = 4         # chunking pays only when E <= L / this
HLL_COMPACT_RATIO = 2         # the HLL dedup keeps ONE entry per slot per
                              # chunk and iterates, so even a 2x shrink per
                              # pass converges in O(log) passes


def _sentinel_for(dtype) -> int:
    return INT32_SENTINEL if jnp.dtype(dtype) == jnp.int32 else INT64_SENTINEL


def pack_keys(per_col_gids, cardinalities, mask):
    """Cartesian combined key in the NARROWEST dtype the key space allows:
    int32 when the product of cardinalities fits (< 2^31), else int64.
    Masked docs get the dtype's sentinel so they sort to the tail. Same
    cartesian arithmetic as ops/agg.py group_ids_combine, uncapped — the
    caller guarantees the product fits int64."""
    total = 1
    for c in cardinalities:
        total *= int(c)
    dt = jnp.int32 if total < MAX_KEYSPACE_32 else jnp.int64
    sentinel = _sentinel_for(dt)
    key = None
    for g, c in zip(per_col_gids, cardinalities):
        g = jnp.clip(g, 0, c - 1).astype(dt)
        key = g if key is None else key * c + g
    return jnp.where(mask, key, sentinel)


def plan_chunks(n: int, table_k: int, chunk_rows: int | None = None,
                min_ratio: int = MIN_COMPACT_RATIO):
    """(C, L): level-1 chunk count and length. Static per (n, K). Chunking
    engages only when the compaction width E = min(L, K+1) shrinks the
    next merge level by at least ``min_ratio`` — otherwise C=1 degenerates
    to a single monolithic sort (still through the run-end/segmented-scan
    aggregation, which needs no secondary sorts either way)."""
    L = chunk_rows or CHUNK_ROWS
    cap = max(L, CHUNK_ROWS_MAX)
    while L < min_ratio * (table_k + 1) and L < cap:
        L *= 2
    if n < 2 * L or min(L, table_k + 1) * min_ratio > L:
        return 1, n
    return -(-n // L), L


def _pad_chunks(x, C: int, L: int, fill):
    n = x.shape[0]
    if C * L > n:
        x = jnp.concatenate([x, jnp.full(C * L - n, fill, x.dtype)])
    return x.reshape(C, L)


# ---------------------------------------------------------------------------
# segmented scans (the scatter-free / secondary-sort-free aggregation core)
# ---------------------------------------------------------------------------


def _seg_scan(values, is_start, op, axis):
    """Inclusive segmented scan along ``axis``: ``op`` accumulates within
    runs, resetting wherever ``is_start`` is True (the standard segmented
    monoid — associative, so it rides jax.lax.associative_scan)."""

    def comb(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, op(av, bv)), af | bf

    v, _ = jax.lax.associative_scan(comb, (values, is_start), axis=axis)
    return v


def seg_sum(values, is_start, axis=1):
    return _seg_scan(values, is_start, lambda a, b: a + b, axis)


def seg_min(values, is_start, axis=1):
    return _seg_scan(values, is_start, jnp.minimum, axis)


def seg_max(values, is_start, axis=1):
    return _seg_scan(values, is_start, jnp.maximum, axis)


def _red_for(name):
    """Segmented reduction for a partial-column name (the ``min::``/
    ``max::`` prefixes pick the extremal monoid; counts and sums add)."""
    if name.startswith("min::"):
        return seg_min
    if name.startswith("max::"):
        return seg_max
    return seg_sum


def _boundaries(sk):
    """(is_start, is_end) along the last axis of a sorted key array."""
    lead = jnp.ones(sk.shape[:-1] + (1,), dtype=bool)
    is_start = jnp.concatenate([lead, sk[..., 1:] != sk[..., :-1]], axis=-1)
    is_end = jnp.concatenate([sk[..., :-1] != sk[..., 1:], lead], axis=-1)
    return is_start, is_end


# ---------------------------------------------------------------------------
# the two-level aggregation
# ---------------------------------------------------------------------------


def chunked_group_aggregate(key, payloads, sums, mins, maxs, table_k: int,
                            chunk_rows: int | None = None):
    """Radix-partitioned group aggregation over a packed key array.

    key:      (n,) int32/int64 packed keys; masked rows carry the dtype
              sentinel (pack_keys).
    payloads: {name: (values(n,), kind)} with kind "int" | "float" — each
              distinct argument rides the level-1 sort exactly once.
    sums/mins/maxs: payload names needing that reduction.
    table_k:  group-table cap (min(numGroupsLimit, MAX_SORTED_GROUPS)).

    Returns {"skeys": (K,) int64 (INT64_SENTINEL empties),
             "empty": (K,) bool, "gcount": (K,) int64,
             "sum::<name>"/"min::<name>"/"max::<name>": (K,) raw columns
             (callers apply empty-slot fills), "n_groups_total": scalar}.
    Overflow contract: n_groups_total counts every distinct real key; when
    any level-1 chunk holds more than E = min(L, K+1) distinct keys (which
    implies global distinct > K), the total is forced above K so the
    executor's host fallback fires exactly as on the old basis.
    """
    n = key.shape[0]
    K = table_k
    sentinel = _sentinel_for(key.dtype)
    C, L = plan_chunks(n, K, chunk_rows)
    E = min(L, K + 1)

    kc = _pad_chunks(key, C, L, sentinel)
    names = list(payloads)
    ops = [kc] + [_pad_chunks(payloads[nm][0], C, L, 0) for nm in names]
    sorted_ops = jax.lax.sort(ops, dimension=1, num_keys=1)
    sk = sorted_ops[0]
    pv = dict(zip(names, sorted_ops[1:]))
    is_start, is_end = _boundaries(sk)
    real = sk != sentinel
    chunk_distinct = jnp.sum(is_start & real, axis=1)

    # level-1 per-run partials, read at run ends. Counts and integer sums
    # are *differences of plain cumulatives* taken after compaction (the
    # compacted prefix preserves end order, so entry j-1 is the previous
    # run's end); int64 cumsum differences stay exact even if the running
    # total wraps. Float sums use a SEGMENTED scan: a global-cumsum
    # difference suffers catastrophic cancellation when a tiny group sits
    # next to huge ones (r3 review), while the segmented form only ever
    # adds a run's own values. Min/max are segmented scans too — this is
    # what retires the old basis's per-argument secondary sorts.
    pos = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32), (C, L))
    cols = {"pos": pos}
    for nm in sums:
        v = pv[nm]
        if payloads[nm][1] == "int":
            cols["csum::" + nm] = jnp.cumsum(v, axis=1, dtype=jnp.int64)
        else:
            cols["ssum::" + nm] = seg_sum(v, is_start)
    for nm in mins:
        cols["min::" + nm] = seg_min(pv[nm], is_start)
    for nm in maxs:
        cols["max::" + nm] = seg_max(pv[nm], is_start)

    # compaction: end-masked keys sort to the front (non-ends become the
    # sentinel), slice to the static E bound. Keys are unique per chunk
    # among ends, so stability is irrelevant.
    cnames = list(cols)
    comp = jax.lax.sort(
        [jnp.where(is_end, sk, sentinel)] + [cols[nm] for nm in cnames],
        dimension=1, num_keys=1)
    ck = comp[0][:, :E]
    cc = {nm: arr[:, :E] for nm, arr in zip(cnames, comp[1:])}

    # cumulative -> per-run partials via neighbor differences
    def _diff(arr, first):
        prev = jnp.concatenate(
            [jnp.full((C, 1), first, arr.dtype), arr[:, :-1]], axis=1)
        return arr - prev

    part = {"cnt": _diff(cc["pos"], -1).astype(jnp.int64)}
    for nm in sums:
        part["sum::" + nm] = _diff(cc["csum::" + nm], 0) \
            if payloads[nm][1] == "int" else cc["ssum::" + nm]
    for nm in mins:
        part["min::" + nm] = cc["min::" + nm]
    for nm in maxs:
        part["max::" + nm] = cc["max::" + nm]

    # level-2+ merge: the C*E compacted partials re-enter the SAME
    # chunk/sort/segmented-combine/compact structure until chunking stops
    # paying, then ONE answer-scale sort combines what is left — every
    # merge pass runs over chunk-local operands too, so no pass ever sorts
    # a monolithic row-scale array
    pnames = list(part)
    overflow = jnp.any(chunk_distinct > E)
    mk = ck.reshape(-1)
    mval = {nm: part[nm].reshape(-1) for nm in pnames}
    while True:
        C2, L2 = plan_chunks(mk.shape[0], K, chunk_rows)
        if C2 == 1:
            break
        E2 = min(L2, K + 1)
        ops2 = [_pad_chunks(mk, C2, L2, sentinel)] + [
            _pad_chunks(mval[nm], C2, L2, 0) for nm in pnames]
        sorted2 = jax.lax.sort(ops2, dimension=1, num_keys=1)
        sk2 = sorted2[0]
        pv2 = dict(zip(pnames, sorted2[1:]))
        st2, en2 = _boundaries(sk2)
        overflow = overflow | jnp.any(
            jnp.sum(st2 & (sk2 != sentinel), axis=1) > E2)
        cols2 = {nm: _red_for(nm)(pv2[nm], st2) for nm in pnames}
        comp2 = jax.lax.sort(
            [jnp.where(en2, sk2, sentinel)] + [cols2[nm] for nm in pnames],
            dimension=1, num_keys=1)
        mk = comp2[0][:, :E2].reshape(-1)
        mval = {nm: arr[:, :E2].reshape(-1)
                for nm, arr in zip(pnames, comp2[1:])}

    merged = jax.lax.sort([mk] + [mval[nm] for nm in pnames], num_keys=1)
    mk = merged[0]
    mval = dict(zip(pnames, merged[1:]))
    mstart, mend = _boundaries(mk)
    mreal = mk != sentinel
    out_cols = {nm: _red_for(nm)(mval[nm], mstart, axis=0) for nm in pnames}

    n_groups_total = jnp.sum(mstart & mreal, dtype=jnp.int64)
    # chunk-local compaction overflow at ANY level implies global overflow
    # (> K): force the total past the cap so the executor defers to the
    # host path
    n_groups_total = jnp.where(
        overflow, jnp.maximum(n_groups_total, jnp.int64(K + 1)),
        n_groups_total)

    fnames = list(out_cols)
    final = jax.lax.sort(
        [jnp.where(mend, mk, sentinel)] + [out_cols[nm] for nm in fnames],
        num_keys=1)
    fk = final[0][:K]
    fv = {nm: arr[:K] for nm, arr in zip(fnames, final[1:])}
    empty = fk == sentinel

    outs = {
        "skeys": jnp.where(empty, INT64_SENTINEL, fk.astype(jnp.int64)),
        "empty": empty,
        "gcount": jnp.where(empty, 0, fv["cnt"]),
        "n_groups_total": n_groups_total,
    }
    for nm in fnames:
        if nm != "cnt":
            outs[nm] = fv[nm]
    return outs


# ---------------------------------------------------------------------------
# mesh table merge (parallel/mesh.py)
# ---------------------------------------------------------------------------


def merge_tables(skeys, columns, reductions, table_k: int):
    """Merge device-gathered radix group tables: skeys (D, K) int64 with
    INT64_SENTINEL empties; columns {name: (D, K)}; reductions {name:
    "sum" | "min" | "max"}. Shards' tables align by KEY, not slot — one
    answer-sized sort of the D*K entries re-runs the level-2 combine.
    Returns ({name: (K,)}, skeys (K,), empty (K,), merged_distinct)."""
    D, K = skeys.shape
    names = list(columns)
    merged = jax.lax.sort(
        [skeys.reshape(-1)] + [columns[nm].reshape(-1) for nm in names],
        num_keys=1)
    mk = merged[0]
    mval = dict(zip(names, merged[1:]))
    mstart, mend = _boundaries(mk)
    mreal = mk != INT64_SENTINEL
    out = {}
    for nm in names:
        red = {"sum": seg_sum, "min": seg_min, "max": seg_max}[reductions[nm]]
        out[nm] = red(mval[nm], mstart, axis=0)
    merged_distinct = jnp.sum(mstart & mreal, dtype=jnp.int64)
    final = jax.lax.sort(
        [jnp.where(mend, mk, INT64_SENTINEL)] + [out[nm] for nm in names],
        num_keys=1)
    fk = final[0][:table_k]
    empty = fk == INT64_SENTINEL
    # the sentinel region of the final sort holds NON-run-end entries whose
    # columns carry partial scan values — re-fill every empty slot with its
    # reduction's neutral element so merged tables look exactly like a
    # single device's (gcount 0, sums 0, extremal fills)
    cols = {}
    for nm, arr in zip(names, final[1:]):
        arr = arr[:table_k]
        red = reductions[nm]
        if red == "sum":
            fill = jnp.zeros((), arr.dtype)
        elif jnp.issubdtype(arr.dtype, jnp.integer):
            fill = jnp.array(jnp.iinfo(arr.dtype).max if red == "min"
                             else jnp.iinfo(arr.dtype).min, arr.dtype)
        else:
            fill = jnp.array(jnp.inf if red == "min" else -jnp.inf,
                             arr.dtype)
        cols[nm] = jnp.where(empty, fill, arr)
    return cols, fk, empty, merged_distinct


# ---------------------------------------------------------------------------
# HLL register-plane variant (engine/device.py _hll_sorted_sums)
# ---------------------------------------------------------------------------


def hll_chunked_sorted_keys(packed, n_slots: int,
                            chunk_rows: int | None = None):
    """Chunked dedup-to-slot-max for the terminal sorted HLL build: packed
    (n,) int32 ``slot << 5 | rho`` keys in, a (possibly much smaller)
    SORTED int32 key array out with the same per-slot max-rho structure —
    a drop-in operand for _hll_sums_from_sorted, which only reads slot-run
    ends. Each pass sorts chunk-locally (VMEM-scale), keeps one entry per
    slot per chunk (its run end = the chunk's max rho, since rho occupies
    the low bits), and compacts to E = min(L, n_slots + 2) entries (slots
    + the masked-row overflow slot + the pad sentinel — a bound, not a
    heuristic: the slice can never drop a slot). Passes ITERATE on the
    C*E survivors — dedup is idempotent, so even the ratio-2 shrink the
    wide q4 slot space allows (HLL_COMPACT_RATIO) converges in O(log)
    chunk-local passes — until chunking stops paying and one final
    answer-scale sort restores global order. Degenerates to the monolithic
    sort when the slot space is too wide for any compaction to pay."""
    out = packed
    while True:
        C, L = plan_chunks(out.shape[0], n_slots + 1, chunk_rows,
                           min_ratio=HLL_COMPACT_RATIO)
        if C == 1:
            return jax.lax.sort(out)
        E = min(L, n_slots + 2)
        kc = _pad_chunks(out, C, L, INT32_SENTINEL)
        sk = jax.lax.sort(kc, dimension=1)
        slot = sk >> 5
        lead = jnp.ones((C, 1), dtype=bool)
        slot_end = jnp.concatenate(
            [slot[:, :-1] != slot[:, 1:], lead], axis=1)
        out = jax.lax.sort(
            jnp.where(slot_end, sk, INT32_SENTINEL),
            dimension=1)[:, :E].reshape(-1)


# ---------------------------------------------------------------------------
# radix histogram (occupancy probe; micro-bench + test-pinned primitive)
# ---------------------------------------------------------------------------


def bucket_histogram(key, keyspace: int, n_buckets: int, *,
                     interpret: bool = False):
    """(n_buckets,) int64 row counts per radix partition (the key's high
    bits), via the factored one-hot matmul kernel — the histogram half of
    the radix scheme, measured standalone by ``micro`` in bench.py.
    Sentinel/masked keys land in the kernel's overflow slot. n_buckets
    must be a power of two; the bucket shift derives from ``keyspace``."""
    from pinot_tpu.ops import groupby_mm as mm

    shift = 0
    while (keyspace - 1) >> shift >= n_buckets:
        shift += 1
    flat = key.reshape(-1)
    bucket = jnp.clip(
        (flat >> shift).astype(jnp.int32), 0, n_buckets)
    bucket = jnp.where(flat == _sentinel_for(key.dtype), n_buckets, bucket)
    n = flat.shape[0]
    ones = jnp.ones((1, n), dtype=jnp.bfloat16)
    counts = mm.group_sums(bucket, ones, n_buckets, interpret=interpret,
                           first_channel_ones=True)
    return jnp.round(counts[0]).astype(jnp.int64)
