"""Factored one-hot matmul group-by: the Pallas TPU kernel for dense
COUNT/SUM/AVG aggregation.

Replaces the per-channel scatter-add (ops/agg.py group_sum / group_count —
the DefaultGroupByExecutor.java:116-147 aggregateGroupBySV analog) for the
hot group-by shapes. Measured on v5e at 12M rows, G=6240, 6 channels:
scatter path ~250ms compute, this kernel ~26ms — channels are nearly free
because they ride the MXU.

Design (factored one-hot, planned low radix ``lo`` in {32, 64, 128}):
    gid = hi*lo + lo_bits.  Per row-block of ``blk`` rows:
      oh_loT (lo, blk)  : oh_loT[j, l] = (lo_l == j)  — rows on lanes
      oh_hi (hpad, blk) : oh_hi[h, l]  = (hi_l == h)  — rows on lanes
      per channel a:     chh_a = oh_hi * ch_a(1, blk)  (masked channel)
                         acc[a] += chh_a @ oh_loT^T    (NT dot_general,
                                                        MXU contracts rows)
    acc[a, h, j] == sum over rows with gid == h*lo+j of channel a.
    ``_plan_lo`` picks the radix that balances VPU one-hot build cost
    against hpad growth per shape; an all-ones first channel (the count
    channel every dense group-by carries) is FOLDED into oh_hi — its
    masked-channel multiply is the identity, so the kernel skips it
    (``first_channel_ones``).

The 3-way contraction channel x hi-onehot x lo-onehot never materializes
the full (blk, G) one-hot: the VPU builds two small one-hots (~0.3
cycles/row), the MXU does the G-wide work. Both one-hots keep the row
index on LANES, so ids stream in once, lane-major ``(n/128, 128)`` — no
degenerate-dim operand anywhere. (A previous revision fed ids a second
time as ``(n, 1)``; XLA tiles that layout to (8,128), padding the size-1
minor dim to 128 lanes — a 128x HBM blowup that OOMed at 100M rows. The
NT ``dot_general`` — the standard TPU flash-attention contraction — is
how the row axis gets contracted from a lane-major one-hot.)

Exactness: channels are bf16 *planes* — one-hot(bf16) x plane(bf16)
products are exact for plane values <= 255, and f32 accumulation over one
superblock (65536 rows x 255 < 2^24) stays exact; superblock partials
reduce in f64 outside the kernel, and integer recombination happens in
int64. Float channels use an exact 3-way bf16 split built by bit-masking
(immune to XLA excess-precision folding of bf16 round-trips), giving
~2e-12 relative error on f32 sums — tighter than the f32 scatter path.

HLL register builds run the same kernel in ``rho_mode``: the rho-threshold
indicator channels are built INSIDE the kernel from a lane-major rho
operand (4 bytes/row) instead of materializing (nrho, n) bf16 channels in
HBM (~46 bytes/row — several GB at 100M rows).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax <= 0.4.x spells the Mosaic params class TPUCompilerParams; newer
# releases renamed it. Resolve once so the kernel runs (interpret mode
# included) on both.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

BLK = 8192              # rows per grid step (64 lane-rows of 128); larger
                        # blocks amortize per-step overhead — measured 35.8
                        # -> 30.3ms for the 4-channel q1 shape at 100M rows
                        # on v5e (plateau at >=8192)
NINNER = 8              # steps per superblock: 65536 rows (f32-exact bound)
SUPERBLOCK = BLK * NINNER
MM_MIN_ROWS = 1 << 17   # below this the scatter path's fixed cost wins
MAX_CHANNELS = 15       # + the count channel; bounded by VMEM acc size
MAX_ACC_CELLS = 1 << 21 # A * hpad * 128 f32 cells (8MB VMEM accumulator;
                        # _launch raises the scoped-vmem limit to cover
                        # acc + double-buffered out block)
STACK_MAX_BYTES = 8 << 20   # stacked-channel operand cap: chh_all is
                            # (A*hpad, blk) bf16
TRANSIENT_BUDGET = 24 << 20  # in-kernel bf16 one-hot/channel transients;
                             # _plan_blk halves blk (floor 2048 = the
                             # pre-retune value) until they fit


def _plan_blk(a_real: int, hpad: int, lo: int):
    """(blk, ninner, stacked): per-shape block size. The one-hot and
    channel transients scale with hpad*blk, so large-hpad shapes (HLL rho
    mode near its support bound) shrink blk back toward 2048 — the value
    the VMEM budget was originally calibrated at — while small-hpad
    group-bys run at 8192 (measured 35.8 -> 30.3ms for the 4-channel
    G=2000 shape at 100M rows on v5e)."""
    blk = BLK
    while True:
        stacked = a_real * hpad * blk * 2 <= STACK_MAX_BYTES
        chh_rows = a_real * hpad if stacked else hpad
        transient = (lo + hpad + chh_rows) * blk * 2
        if transient <= TRANSIENT_BUDGET or blk <= 2048:
            return blk, SUPERBLOCK // blk, stacked
        blk //= 2

_i32 = jnp.int32
_NT = (((1,), (1,)), ((), ()))  # contract lanes-with-lanes (rows axis)


def _plan_lo(num_groups: int, a_real: int, ones_first: bool) -> int:
    """Low-radix factor of the factored one-hot (gid = hi*lo + lo_bits).
    The kernel is VPU-bound on building the one-hots: per row it compares
    ``lo`` lanes for the lo one-hot, ``hpad`` for the hi one-hot, and
    multiplies ``(a_real - folded) * hpad`` channel lanes, so the radix
    that balances the two one-hots beats a fixed 128 for small G (q1's
    G=2000 shape: lo=64 trades 128 lo-lanes for 32 hi-rows). The MXU pads
    the dot's N dim to the 128-lane tile either way — but so does VMEM:
    the accumulator's minor dim pads to 128 LANES regardless of ``lo``,
    so a small radix doubles the physical accumulator (hpad doubles,
    lanes don't shrink). Radixes whose physical acc would not fit are
    skipped, which keeps the support surface exactly the radix-128 one."""
    folded = 1 if ones_first else 0
    best, best_cost = 128, None
    for lo in (32, 64, 128):
        hpad = _hpad(num_groups, lo)
        if lo != 128 and a_real * hpad * 128 > MAX_ACC_CELLS:
            continue
        cost = 2 * lo + 2 * hpad + max(0, a_real - folded) * hpad
        if best_cost is None or cost < best_cost:
            best, best_cost = lo, cost
    return best


def mm_supported(num_groups: int, n_channels: int,
                 ones_first: bool = True) -> bool:
    lo = _plan_lo(num_groups, n_channels + 1, ones_first)
    hpad = _hpad(num_groups, lo)
    # physical cells: the acc minor dim pads to the 128-lane tile
    return (n_channels + 1) * hpad * 128 <= MAX_ACC_CELLS


def _hpad(num_groups: int, lo: int = 128) -> int:
    return max(8, ((num_groups // lo + 1 + 7) // 8) * 8)


def _kernel(ids_ref, ch_ref, out_ref, acc_ref,
            *, ninner, hpad, a_real, blk, lo, rho_mode, stacked,
            ones_first):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    lo_shift = lo.bit_length() - 1                  # lo is a power of two
    ids_r = ids_ref[:].reshape(1, blk)              # sublane→lane merge: OK
    lo_r = ids_r & (lo - 1)
    hi_r = ids_r >> lo_shift

    jsub = jax.lax.broadcasted_iota(jnp.int32, (lo, blk), 0)
    oh_loT = jnp.where(lo_r == jsub, jnp.float32(1), jnp.float32(0)) \
        .astype(jnp.bfloat16)
    hsub = jax.lax.broadcasted_iota(jnp.int32, (hpad, blk), 0)
    oh_hi = jnp.where(hi_r == hsub, jnp.float32(1), jnp.float32(0)) \
        .astype(jnp.bfloat16)

    if rho_mode:
        rho_r = ch_ref[:].reshape(1, blk)           # lane-major int32 rho

    def chh(a):
        if rho_mode:
            # channel a = indicator(rho == a+1), built in-VMEM
            ch = jnp.where(rho_r == a + 1, jnp.float32(1), jnp.float32(0)) \
                .astype(jnp.bfloat16)
            return oh_hi * ch
        if a == 0 and ones_first:
            # all-ones count channel: the masked-channel multiply is the
            # identity — oh_hi IS the product (one (hpad, blk) multiply
            # saved per block; callers guarantee overflow-slot slicing
            # absorbs the pad rows this also counts)
            return oh_hi
        return oh_hi * ch_ref[pl.ds(a, 1), :]       # (1, blk) bf16

    if stacked:
        # stack every channel's masked hi one-hot into ONE dot: per-channel
        # M=hpad dots underfill the MXU's M tile, so 4 channels cost ~4x one
        # — stacked to M = a_real*hpad they cost ~1x (measured 58.6 -> 27ms
        # for 4 channels at G=2000, 100M rows on v5e)
        chh_all = jnp.concatenate([chh(a) for a in range(a_real)], axis=0)
        acc_flat = jax.lax.dot_general(
            chh_all, oh_loT, _NT, preferred_element_type=jnp.float32)
        acc_ref[:] += acc_flat.reshape(a_real, hpad, lo)
    else:
        # large-hpad (HLL rho) shapes: a stacked operand would blow VMEM
        for a in range(a_real):
            acc_ref[a] += jax.lax.dot_general(
                chh(a), oh_loT, _NT, preferred_element_type=jnp.float32
            )

    @pl.when(i == ninner - 1)
    def _():
        out_ref[0] = acc_ref[:]


def _launch(ids_lane, ch_operand, ch_spec_kind, *, a_real, hpad, lo, nsuper,
            rho_mode, interpret, ones_first=False):
    blk, ninner, stacked = _plan_blk(a_real, hpad, lo)
    kern = functools.partial(
        _kernel, ninner=ninner, hpad=hpad, a_real=a_real, blk=blk, lo=lo,
        rho_mode=rho_mode, stacked=stacked, ones_first=ones_first,
    )
    if ch_spec_kind == "channels":
        ch_spec = pl.BlockSpec(
            (a_real, blk), lambda s, i: (_i32(0), s * ninner + i),
            memory_space=pltpu.VMEM)
    else:  # lane-major rho operand
        ch_spec = pl.BlockSpec(
            (blk // 128, 128), lambda s, i: (s * ninner + i, _i32(0)),
            memory_space=pltpu.VMEM)
    # acc scratch + out block are each a_real*hpad*128 f32; the out block is
    # double-buffered by the pipeline and Mosaic stacks further transient
    # copies. Default scoped-vmem limit is 16MB — raise it for large-G
    # accumulators (v5e has 128MB VMEM). Empirically the compiler's stack
    # peak reaches ~8x the accumulator at 400k groups (measured: 40.2MB at
    # acc=4.8MB), so budget 8x + headroom PLUS the blk-proportional
    # transients _plan_blk bounded; MAX_ACC_CELLS keeps the result under
    # the ceiling.
    acc_bytes = a_real * hpad * 128 * 4  # minor dim pads to 128 lanes
    chh_rows = a_real * hpad if stacked else hpad
    transient_bytes = (lo + hpad + chh_rows) * blk * 2
    vmem_limit = max(16 * 2**20,
                     min(110 * 2**20,
                         8 * acc_bytes + transient_bytes + 16 * 2**20))
    out = pl.pallas_call(
        kern,
        grid=(nsuper, ninner),
        in_specs=[
            pl.BlockSpec((blk // 128, 128), lambda s, i: (s * ninner + i, _i32(0)),
                         memory_space=pltpu.VMEM),
            ch_spec,
        ],
        out_specs=pl.BlockSpec(
            (1, a_real, hpad, lo),
            lambda s, i: (s, _i32(0), _i32(0), _i32(0)),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((nsuper, a_real, hpad, lo), jnp.float32),
        scratch_shapes=[pltpu.VMEM((a_real, hpad, lo), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(vmem_limit_bytes=vmem_limit),
        interpret=interpret,
    )(ids_lane, ch_operand)
    return jnp.sum(out, axis=0, dtype=jnp.float64)


def _pad_ids(gid, num_groups: int, n_pad: int, n: int):
    ids = jnp.concatenate(
        [gid.astype(jnp.int32), jnp.full(n_pad - n, num_groups, dtype=jnp.int32)]
    )
    return ids.reshape(-1, 128)


def group_sums(gid, channels, num_groups: int, *, interpret: bool = False,
               first_channel_ones: bool = False):
    """Dense per-group sums of bf16 plane channels.

    gid: (n,) int32 in [0, num_groups]; id == num_groups is the overflow
    slot for masked/padded rows (sliced off).
    channels: (A, n) bf16 planes, |value| <= 255 for exact integer sums.
    first_channel_ones: channels[0] is all-ones (the count channel) — the
    kernel folds its masked-channel multiply into the hi one-hot. Pad rows
    then count into the overflow slot, which this function slices off.
    Returns (A, num_groups) float64.
    """
    a_real, n = channels.shape
    lo = _plan_lo(num_groups, a_real, first_channel_ones)
    hpad = _hpad(num_groups, lo)
    n_pad = ((n + SUPERBLOCK - 1) // SUPERBLOCK) * SUPERBLOCK
    nsuper = n_pad // SUPERBLOCK

    ids_lane = _pad_ids(gid, num_groups, n_pad, n)
    ch = jnp.concatenate(
        [channels, jnp.zeros((a_real, n_pad - n), channels.dtype)], axis=1
    )
    tot = _launch(ids_lane, ch, "channels", a_real=a_real, hpad=hpad, lo=lo,
                  nsuper=nsuper, rho_mode=False, interpret=interpret,
                  ones_first=first_channel_ones)
    return tot.reshape(a_real, hpad * lo)[:, :num_groups]


def rho_group_counts(slot, rho, num_groups: int, nrho: int, *,
                     interpret: bool = False):
    """counts[r, g] = #rows with slot == g and rho == r+1, r in [0, nrho).

    The nrho indicator channels are built inside the kernel from the
    lane-major rho operand — nothing rho-shaped ever hits HBM beyond the
    (n,) int32 itself. Padded rows get rho = 0, matching no channel.
    Returns (nrho, num_groups) float64 counts.
    """
    n = slot.shape[0]
    lo = _plan_lo(num_groups, nrho, False)
    hpad = _hpad(num_groups, lo)
    n_pad = ((n + SUPERBLOCK - 1) // SUPERBLOCK) * SUPERBLOCK
    nsuper = n_pad // SUPERBLOCK

    ids_lane = _pad_ids(slot, num_groups, n_pad, n)
    rho_lane = jnp.concatenate(
        [rho.astype(jnp.int32), jnp.zeros(n_pad - n, dtype=jnp.int32)]
    ).reshape(-1, 128)
    tot = _launch(ids_lane, rho_lane, "rho_lane", a_real=nrho, hpad=hpad,
                  lo=lo, nsuper=nsuper, rho_mode=True, interpret=interpret)
    return tot.reshape(nrho, hpad * lo)[:, :num_groups]


# ---------------------------------------------------------------------------
# channel planes: values → bf16 channels + recombination
# ---------------------------------------------------------------------------


def int_planes_needed(lo: float, hi: float) -> int:
    """Byte planes needed for ints in [lo, hi] after offset-by-floor(lo).
    Ceil/floor (not truncation) so fractional metadata bounds — e.g. from a
    float column behind a CAST — can't under-count the span."""
    import math

    rng = math.ceil(hi) - math.floor(lo)
    planes = 1
    while rng > (1 << (8 * planes)) - 1:
        planes += 1
    return planes


def int_planes(values, offset, nplanes: int):
    """values - offset split into ``nplanes`` byte planes (bf16-exact)."""
    v = values.astype(jnp.int64) - offset
    out = []
    for k in range(nplanes):
        out.append(((v >> (8 * k)) & 0xFF).astype(jnp.bfloat16))
    return out


def recombine_int(plane_sums, count, offset):
    """int64 recombination: Σv = Σ_k 256^k·S_k + count·offset (exact)."""
    tot = jnp.zeros_like(plane_sums[0], dtype=jnp.int64)
    for k, s in enumerate(plane_sums):
        tot = tot + (s.astype(jnp.int64) << (8 * k))
    return tot + count.astype(jnp.int64) * offset


def hll_nrho(log2m: int) -> int:
    """Max rho value: clz over (32 - log2m) value bits + 1 (sentinel caps)."""
    return 32 - log2m + 1


def hll_supported(num_groups: int, log2m: int) -> bool:
    nslots = num_groups * (1 << log2m)
    # rho mode has no folded count channel (ones_first=False)
    return mm_supported(nslots, hll_nrho(log2m), ones_first=False) \
        and nslots <= (1 << 20)


def hll_registers(slot, rho, num_groups: int, log2m: int, *,
                  interpret: bool = False):
    """HLL register build as rho-threshold indicator channels through the
    factored matmul kernel: counts[r, slot] = #rows with rho == r, register
    = max r with count > 0. Replaces the 12M-row scatter-max (~100ms on
    v5e) with a ~20ms matmul when G·m is small enough for VMEM.

    slot: (n,) int32 = gid * m + idx, masked rows → num_groups * m.
    rho:  (n,) int32 in [1, nrho].
    Returns (num_groups, m) int32 registers.
    """
    m = 1 << log2m
    nslots = num_groups * m
    nrho = hll_nrho(log2m)
    counts = rho_group_counts(slot, rho, nslots, nrho, interpret=interpret)
    rvals = jnp.arange(1, nrho + 1, dtype=jnp.int32)[:, None]
    regs = jnp.max(jnp.where(counts > 0.5, rvals, 0), axis=0).astype(jnp.int32)
    return regs.reshape(num_groups, m)


def _bf16_hi(v):
    """Top-16-bit truncation of f32 — exactly bf16-representable, built by
    bit-masking so XLA's excess-precision pass cannot fold it away."""
    bits = jax.lax.bitcast_convert_type(v, jnp.uint32)
    return jax.lax.bitcast_convert_type(bits & jnp.uint32(0xFFFF0000), jnp.float32)


def float_planes(values):
    """f32 → 3 bf16 channels summing exactly to the f32 value."""
    v = values.astype(jnp.float32)
    m0 = _bf16_hi(v)
    r1 = v - m0
    m1 = _bf16_hi(r1)
    r2 = r1 - m1
    m2 = _bf16_hi(r2)
    return [m0.astype(jnp.bfloat16), m1.astype(jnp.bfloat16),
            m2.astype(jnp.bfloat16)]


def recombine_float(plane_sums):
    tot = plane_sums[0]
    for s in plane_sums[1:]:
        tot = tot + s
    return tot
