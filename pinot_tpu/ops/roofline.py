"""HBM/memcpy peak probe: the denominator of every roofline line.

The r05 micro table showed a ~400x spread between kernels (masked_sum at
822 GB/s vs scatter_group_sum at 0.7 GB/s) that was only visible inside
bench.py.  ISSUE 11 makes achieved-vs-peak a per-query number, which
needs ONE per-process answer to "what does this device's memory system
sustain": a tiny jitted element-wise pass (read + write the whole
buffer — the streaming-bandwidth shape XLA cannot avoid moving bytes
for), timed amortized, best of a few repeats.

The probe is LAZY and cached per process:

- ``PINOT_TPU_HBM_PEAK_GBPS`` overrides it entirely (no device work) —
  the bench/tests knob, and the operator's escape hatch on boxes where
  the probe is unrepresentative;
- the first caller of :func:`hbm_peak_gbps` pays the measurement once
  (~tens of ms: one trivial jit compile + a few iterations over a 16 MB
  buffer); every later call is a dict read;
- :func:`peak_if_probed` never triggers the measurement — scrape-time
  consumers (the server's ``hbmPeakGbps`` gauge) must not spend device
  time inside a metrics poll, and jax-free processes (ingest workers,
  plain brokers) must not import jax through this module.

Import cost: numpy only.  jax loads inside the measurement, so merely
importing this module from a jax-free process stays jax-free.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

log = logging.getLogger("pinot_tpu.ops.roofline")

# probe working-set size: big enough to spill any cache tier the device
# backend models, small enough that the one-off measurement stays in the
# tens of milliseconds even on a 2-core CPU backend
PROBE_BYTES = int(os.environ.get("PINOT_TPU_HBM_PROBE_BYTES", 16 << 20))
_PROBE_REPEATS = 5

_lock = threading.Lock()
_peak_gbps: Optional[float] = None


def _env_peak() -> Optional[float]:
    v = os.environ.get("PINOT_TPU_HBM_PEAK_GBPS")
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


def reset_probe() -> None:
    """Forget the cached measurement (tests)."""
    global _peak_gbps
    with _lock:
        _peak_gbps = None


def peak_if_probed() -> Optional[float]:
    """The cached peak (or the env override) WITHOUT triggering a
    measurement — None when nothing measured yet.  The scrape-safe and
    jax-free-process-safe read."""
    env = _env_peak()
    if env is not None:
        return env
    return _peak_gbps


def hbm_peak_gbps() -> float:
    """Per-process HBM/memcpy peak in GB/s (read+write bytes counted),
    measured once and cached.  Returns 0.0 when the probe cannot run
    (no jax backend) — consumers must treat <= 0 as "peak unknown" and
    skip the %-of-peak annotation rather than divide by it."""
    global _peak_gbps
    env = _env_peak()
    if env is not None:
        return env
    with _lock:
        if _peak_gbps is None:
            try:
                _peak_gbps = _measure()
            except Exception:  # noqa: BLE001 — accounting must never fail a query
                log.exception("HBM peak probe failed; roofline %% disabled")
                _peak_gbps = 0.0
        return _peak_gbps


def _measure() -> float:
    import jax
    import jax.numpy as jnp

    n = max(1 << 16, PROBE_BYTES // 4)
    x = jnp.zeros(n, dtype=jnp.float32)
    f = jax.jit(lambda a: a + jnp.float32(1))
    jax.block_until_ready(f(x))  # compile + first-touch
    bytes_moved = 2 * n * 4  # one read + one write of the buffer
    best = 0.0
    for _ in range(_PROBE_REPEATS):
        t0 = time.perf_counter()
        jax.block_until_ready(f(x))
        dt = time.perf_counter() - t0
        best = max(best, bytes_moved / max(dt, 1e-9) / 1e9)
    log.info("HBM peak probe: %.2f GB/s over %d MB (%s backend)",
             best, (n * 4) >> 20, jax.default_backend())
    return best


def pct_of_peak(gbps: Optional[float],
                peak: Optional[float] = None) -> Optional[float]:
    """``gbps`` as a percentage of ``peak`` (default: the cached probe),
    or None when either side is unknown."""
    if gbps is None:
        return None
    if peak is None:
        peak = peak_if_probed()
    if not peak or peak <= 0:
        return None
    return round(100.0 * gbps / peak, 3)
