"""Pallas scatter-kernel tier: tiled local-accumulate group-by, HLL
register-max, and fused filter+gather+aggregate (ISSUE 15).

The r05 micro table's standing indictment was the scatter family:
``masked_sum`` saturates HBM (822 GB/s on v5e) while ``scatter_group_sum``
runs at 0.7 GB/s, ``hll_register_scatter`` at 1.2 and the sorted HLL
dedup at 2.1 — XLA lowers ``.at[].add/.max`` on TPU to a serialized
scatter loop, so exactly the ops that decide high-cardinality group-bys
and HLL queries ran ~400x under the roofline. This module replaces those
scatters with purpose-built Pallas kernels following the pattern
``ops/groupby_mm.py`` proved: ``pl.pallas_call`` with TPU params on
device, **interpret mode under JAX_PLATFORMS=cpu** so tier-1 tests
exercise the real kernels, and the XLA scatter path kept compiled-in as
the differential reference and fallback (engine/device.py routes a
failing Pallas pipeline to the XLA rung, then host — never an error).

Three kernels:

1. **Tiled local-accumulate group scatter** (``plane_group_sums``): each
   program instance owns a *group-range partition* of the output
   accumulators; row tiles stream through every partition and accumulate
   locally in VMEM via a partition-relative hi/lo factored one-hot
   matmul (the MXU contraction of ops/groupby_mm.py, generalized), one
   HBM write per partition per superblock — no global sort, no serial
   scatter. Partitioning bounds the VMEM accumulator regardless of G:
   npart passes over the row stream trade bandwidth for unbounded group
   counts, extending the exact plane-sum coverage past the single-
   accumulator ceiling ``mm_supported`` enforces.
2. **Group min/max scatter** (``group_minmax``): the aggregation family
   with no MXU identity (max doesn't factor through a dot) — a masked
   broadcast-select over the partition's group range with a VPU lane
   reduction. O(span) work per row bounds it to moderate G, where the
   XLA scatter was slowest per row.
3. **HLL register-max scatter** (``hll_register_max``): rho-threshold
   indicator channels built in-kernel from the lane-major rho operand
   (groupby_mm's rho_mode), accumulated as *presence* (f32 counts —
   nonneg adds keep every touched slot >= 1 under rounding, so presence
   is exact) over slot-range partitions, registers extracted at flush.
   Replaces the serialized f32 scatter-max for slot spaces up to
   ``HLL_MAX_SLOTS``; beyond that the threshold-channel work per row
   grows linearly with the slot space and the sorted dedup basis
   (ops/radix_groupby.py) remains the right algorithm.
4. **Fused filter+gather+aggregate** (``fused_filter_agg``): the
   block-skip path's candidate blocks are gathered BY THE PIPELINE —
   scalar-prefetched candidate indices drive the BlockSpec index maps,
   so the kernel's DMA engine reads exactly the candidate blocks from
   HBM and the filter + aggregation run in VMEM; the (B, R) gather
   buffer the XLA path materializes (one extra HBM write + read of
   every gathered byte) never exists.

Exactness: every accumulation is order-independent by construction —
integer sums ride bf16 byte planes with f32 superblock partials reduced
in f64 outside (the groupby_mm argument), min/max/presence are
idempotent — so Pallas == XLA scatter == host is bit-exact, which is
what lets the differential suite (tests/test_pallas_scatter.py) pin the
tier against the compiled-in reference.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# ONE copy of the MXU-kernel tuning machinery: the jax-version shim, the
# VMEM/transient budgets, and the block-size planner live in
# ops/groupby_mm.py (already re-measured and retuned there once) — a
# retune must reach both kernel tiers, so this module imports rather
# than restating them
from pinot_tpu.ops.groupby_mm import (  # noqa: F401 — re-exported budgets
    _COMPILER_PARAMS,
    _plan_blk as _mm_plan_blk,
    BLK,
    MAX_ACC_CELLS,
    MAX_CHANNELS,
    NINNER,
    STACK_MAX_BYTES,
    SUPERBLOCK,
    TRANSIENT_BUDGET,
)

LO = 128                 # low-radix factor: the dot's N dim = one lane tile
MAX_PARTITIONS = 8       # row re-reads per launch: npart passes over the
                         # tile stream bound the bandwidth trade
PALLAS_MIN_ROWS = 1 << 17  # below this the scatter's fixed cost wins (the
                           # MM_MIN_ROWS analog; interpret mode ignores it)

# min/max scatter: O(span) VPU work per row — profitable only against the
# serialized XLA scatter at moderate group counts
MINMAX_SPAN = 1024       # groups per partition (one-hot select width)
MINMAX_BLK = 2048        # rows per step (bounds the (span, blk) transient)
MAX_MINMAX_PARTS = 8     # → num_groups <= 8191

# HLL register-max: threshold-channel cost per row grows with the slot
# space (ceil(nrho*hpad/128) MXU cycles/row) — past this bound the sorted
# dedup basis wins and the kernel declines (env-tunable for bigger VMEM
# parts)
HLL_MAX_SLOTS = int(os.environ.get("PINOT_TPU_PALLAS_HLL_SLOTS", 1 << 12))

# fused filter+gather+aggregate
FUSED_BLOCK_ROWS = 4096  # rows per grid step; the fused plan is only
                         # built when storage.segment.ZONE_BLOCK_ROWS
                         # equals this (engine/device.py build_pipeline
                         # declines otherwise — a silent mismatch would
                         # read a prefix of every candidate block)
FUSED_MAX_IN = 8         # IN-list bound for the in-kernel OR chain
_i32 = jnp.int32
_NT = (((1,), (1,)), ((), ()))  # contract lanes-with-lanes (rows axis)


def _hpad_total(num_groups: int) -> int:
    """hi rows covering ``num_groups`` ids plus the overflow slot
    (masked/padded rows carry id == num_groups), in sublane multiples."""
    return max(8, ((num_groups // LO + 1 + 7) // 8) * 8)


def _span_hpad(a_real: int) -> int:
    """Per-partition hi-row budget from the VMEM accumulator cap."""
    h = MAX_ACC_CELLS // (a_real * LO)
    return max(8, (h // 8) * 8)


def _plan_blk(a_real: int, hpad: int):
    """(blk, ninner, stacked): ops/groupby_mm.py's planner with the
    radix fixed at LO — shrinks the row tile until the one-hot /
    stacked-channel transients fit the shared budget."""
    return _mm_plan_blk(a_real, hpad, LO)


def _vmem_limit(a_real: int, hpad: int, blk: int, stacked: bool) -> int:
    acc_bytes = a_real * hpad * LO * 4
    chh_rows = a_real * hpad if stacked else hpad
    transient_bytes = (LO + hpad + chh_rows) * blk * 2
    return max(16 * 2**20,
               min(110 * 2**20, 8 * acc_bytes + transient_bytes + 16 * 2**20))


def _pad_lane(x, n_pad: int, n: int, fill):
    if n_pad > n:
        x = jnp.concatenate(
            [x, jnp.full(n_pad - n, fill, dtype=x.dtype)])
    return x.reshape(-1, 128)


def _rel_onehots(ids_r, p, gp: int, hpad: int, blk: int):
    """Partition-relative factored one-hots: rows outside [p*gp, (p+1)*gp)
    map to the sentinel gp, whose hi row (== hpad) matches no iota row —
    out-of-partition rows contribute nothing, which is what makes the
    partition sweep a disjoint cover of the group space."""
    rel = ids_r - p * gp
    rel = jnp.where((rel >= 0) & (rel < gp), rel, gp)
    lo_r = rel & (LO - 1)
    hi_r = rel >> 7  # LO = 128
    jsub = jax.lax.broadcasted_iota(jnp.int32, (LO, blk), 0)
    oh_loT = jnp.where(lo_r == jsub, jnp.float32(1), jnp.float32(0)) \
        .astype(jnp.bfloat16)
    hsub = jax.lax.broadcasted_iota(jnp.int32, (hpad, blk), 0)
    oh_hi = jnp.where(hi_r == hsub, jnp.float32(1), jnp.float32(0)) \
        .astype(jnp.bfloat16)
    return oh_loT, oh_hi


# ---------------------------------------------------------------------------
# 1) tiled local-accumulate group scatter (sums / counts)
# ---------------------------------------------------------------------------


def sums_supported(num_groups: int, n_channels: int) -> bool:
    """True when the partitioned plane-sum kernel covers this shape:
    the group space splits into <= MAX_PARTITIONS VMEM-sized ranges."""
    if n_channels > MAX_CHANNELS + 1:
        return False
    hp = _span_hpad(n_channels)
    return -(-_hpad_total(num_groups) // hp) <= MAX_PARTITIONS


def _sums_kernel(ids_ref, ch_ref, out_ref, acc_ref, *,
                 ninner, hpad, a_real, blk, gp, stacked, ones_first):
    p = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ids_r = ids_ref[:].reshape(1, blk)
    oh_loT, oh_hi = _rel_onehots(ids_r, p, gp, hpad, blk)

    def chh(a):
        if a == 0 and ones_first:
            return oh_hi  # folded all-ones count channel
        return oh_hi * ch_ref[pl.ds(a, 1), :]

    if stacked:
        chh_all = jnp.concatenate([chh(a) for a in range(a_real)], axis=0)
        acc_flat = jax.lax.dot_general(
            chh_all, oh_loT, _NT, preferred_element_type=jnp.float32)
        acc_ref[:] += acc_flat.reshape(a_real, hpad, LO)
    else:
        for a in range(a_real):
            acc_ref[a] += jax.lax.dot_general(
                chh(a), oh_loT, _NT, preferred_element_type=jnp.float32)

    @pl.when(i == ninner - 1)
    def _():
        out_ref[0] = acc_ref[:]


def plane_group_sums(gid, channels, num_groups: int, *,
                     interpret: bool = False,
                     first_channel_ones: bool = False,
                     span_hpad: int | None = None):
    """Dense per-group sums of bf16 plane channels with group-range
    partitioning — the tiled local-accumulate scatter.

    gid: (n,) int32 in [0, num_groups]; id == num_groups is the overflow
    slot (sliced off). channels: (A, n) bf16 planes, |value| <= 255 for
    exact integer sums (ops/groupby_mm.py int_planes/float_planes build
    them). ``span_hpad`` overrides the per-partition budget (tests force
    multi-partition launches on small group counts). Returns
    (A, num_groups) float64 — f32 superblock partials reduced in f64, the
    exactness argument of the mm kernel, per partition.
    """
    a_real, n = channels.shape
    total_h = _hpad_total(num_groups)
    hp = min(span_hpad or _span_hpad(a_real), total_h)
    npart = -(-total_h // hp)
    gp = hp * LO
    blk, ninner, stacked = _plan_blk(a_real, hp)
    n_pad = ((n + SUPERBLOCK - 1) // SUPERBLOCK) * SUPERBLOCK
    nsuper = n_pad // SUPERBLOCK

    ids_lane = _pad_lane(gid.astype(jnp.int32), n_pad, n, num_groups)
    ch = jnp.concatenate(
        [channels, jnp.zeros((a_real, n_pad - n), channels.dtype)], axis=1
    ) if n_pad > n else channels
    kern = functools.partial(
        _sums_kernel, ninner=ninner, hpad=hp, a_real=a_real, blk=blk,
        gp=gp, stacked=stacked, ones_first=first_channel_ones)
    out = pl.pallas_call(
        kern,
        grid=(npart, nsuper, ninner),
        in_specs=[
            pl.BlockSpec((blk // 128, 128),
                         lambda p, s, i: (s * ninner + i, _i32(0)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((a_real, blk),
                         lambda p, s, i: (_i32(0), s * ninner + i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, a_real, hp, LO),
            lambda p, s, i: (p * nsuper + s, _i32(0), _i32(0), _i32(0)),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (npart * nsuper, a_real, hp, LO), jnp.float32),
        scratch_shapes=[pltpu.VMEM((a_real, hp, LO), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=_vmem_limit(a_real, hp, blk, stacked)),
        interpret=interpret,
    )(ids_lane, ch)
    # (npart*nsuper, A, hp, LO) → superblock partials reduce in f64, then
    # partitions concatenate along the group axis
    tot = jnp.sum(out.reshape(npart, nsuper, a_real, hp, LO), axis=1,
                  dtype=jnp.float64)
    return jnp.transpose(tot, (1, 0, 2, 3)).reshape(
        a_real, npart * gp)[:, :num_groups]


# ---------------------------------------------------------------------------
# 2) group min/max scatter
# ---------------------------------------------------------------------------

_MINMAX_KERNEL_DTYPES = {
    "int8": jnp.int32, "int16": jnp.int32, "int32": jnp.int32,
    "uint8": jnp.int32, "uint16": jnp.int32, "float32": jnp.float32,
}


def minmax_supported(num_groups: int, dtype) -> bool:
    """int64/float64 values stay on the XLA scatter (Mosaic has no 64-bit
    vector path); group count bounded by the O(span)-per-row select."""
    if str(jnp.dtype(dtype)) not in _MINMAX_KERNEL_DTYPES:
        return False
    return -(-(num_groups + 1) // MINMAX_SPAN) <= MAX_MINMAX_PARTS


def _minmax_kernel(ids_ref, v_ref, *refs, ops, span, blk, nsteps, fills):
    out_refs = refs[:len(ops)]
    acc_refs = refs[len(ops):]
    p = pl.program_id(0)
    s = pl.program_id(1)

    @pl.when(s == 0)
    def _():
        for a, fill in zip(acc_refs, fills):
            a[:] = jnp.full_like(a, fill)

    ids_r = ids_ref[:].reshape(1, blk)
    rel = ids_r - p * span
    rel = jnp.where((rel >= 0) & (rel < span), rel, span)
    gsub = jax.lax.broadcasted_iota(jnp.int32, (span, blk), 0)
    onehot = rel == gsub  # rel == span matches no group row
    v = v_ref[:].reshape(1, blk)
    for op, acc, fill in zip(ops, acc_refs, fills):
        vm = jnp.where(onehot, v, fill)
        red = vm.min(axis=1, keepdims=True) if op == "min" \
            else vm.max(axis=1, keepdims=True)
        folded = jnp.broadcast_to(red, (span, 128))
        acc[:] = jnp.minimum(acc[:], folded) if op == "min" \
            else jnp.maximum(acc[:], folded)

    @pl.when(s == nsteps - 1)
    def _():
        for o, a in zip(out_refs, acc_refs):
            o[0] = a[:]


def group_minmax(gid, values, num_groups: int, ops: tuple, *,
                 interpret: bool = False, fills: tuple = None):
    """Per-group min and/or max via masked broadcast-select over group-
    range partitions. ``ops`` ⊆ ("min", "max"); ``fills`` overrides the
    empty-group fill per op (callers pass the ORIGINAL dtype's extremes
    so empty slots match the XLA scatter path bit-for-bit). Returns one
    (num_groups,) array per op, in the kernel compute dtype (callers cast
    back — min/max never leave the value set, so the cast is exact)."""
    kdt = _MINMAX_KERNEL_DTYPES[str(jnp.dtype(values.dtype))]
    v = values.astype(kdt).reshape(-1)
    n = v.shape[0]
    if fills is None:
        info = jnp.finfo(kdt) if kdt == jnp.float32 else jnp.iinfo(kdt)
        fills = tuple(info.max if op == "min" else info.min for op in ops)
    npart = -(-(num_groups + 1) // MINMAX_SPAN)
    blk = MINMAX_BLK
    n_pad = ((n + blk - 1) // blk) * blk
    nsteps = n_pad // blk
    ids_lane = _pad_lane(gid.reshape(-1).astype(jnp.int32), n_pad, n,
                         num_groups)
    # padded rows need a value; they target the overflow slot so any fill
    # works — reuse the first op's neutral
    v_lane = _pad_lane(v, n_pad, n, fills[0])
    kern = functools.partial(
        _minmax_kernel, ops=ops, span=MINMAX_SPAN, blk=blk, nsteps=nsteps,
        fills=fills)
    outs = pl.pallas_call(
        kern,
        grid=(npart, nsteps),
        in_specs=[
            pl.BlockSpec((blk // 128, 128), lambda p, s: (s, _i32(0)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((blk // 128, 128), lambda p, s: (s, _i32(0)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, MINMAX_SPAN, 128),
                         lambda p, s: (p, _i32(0), _i32(0)),
                         memory_space=pltpu.VMEM)
            for _ in ops],
        out_shape=[jax.ShapeDtypeStruct((npart, MINMAX_SPAN, 128), kdt)
                   for _ in ops],
        scratch_shapes=[pltpu.VMEM((MINMAX_SPAN, 128), kdt) for _ in ops],
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=max(
                16 << 20, (len(ops) + 3) * MINMAX_SPAN * blk * 4)),
        interpret=interpret,
    )(ids_lane, v_lane)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    return tuple(o[:, :, 0].reshape(npart * MINMAX_SPAN)[:num_groups]
                 for o in outs)


# ---------------------------------------------------------------------------
# 3) HLL register-max scatter
# ---------------------------------------------------------------------------


def hll_supported(nslots: int, nrho: int) -> bool:
    """Slot spaces the presence kernel beats the serialized scatter on:
    threshold-channel work per row is ~ceil(nrho*hpad/128) MXU cycles, so
    the advantage decays linearly with the slot space — past the bound
    the sorted dedup basis (ops/radix_groupby.py) is the right tool."""
    if nslots > HLL_MAX_SLOTS:
        return False
    hp = _span_hpad(nrho)
    return -(-_hpad_total(nslots) // hp) <= MAX_PARTITIONS


def _hll_kernel(ids_ref, rho_ref, out_ref, acc_ref, *,
                nsteps, hpad, nrho, blk, gp, stacked):
    p = pl.program_id(0)
    s = pl.program_id(1)

    @pl.when(s == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ids_r = ids_ref[:].reshape(1, blk)
    oh_loT, oh_hi = _rel_onehots(ids_r, p, gp, hpad, blk)
    rho_r = rho_ref[:].reshape(1, blk)

    def chh(r):
        ch = jnp.where(rho_r == r + 1, jnp.float32(1), jnp.float32(0)) \
            .astype(jnp.bfloat16)
        return oh_hi * ch

    # presence accumulates as f32 counts: nonneg adds never take a touched
    # slot below 1 (round-to-nearest of a value >= 1 stays >= 1), so the
    # >0.5 threshold at flush is exact without per-superblock flushes
    if stacked:
        chh_all = jnp.concatenate([chh(r) for r in range(nrho)], axis=0)
        acc_flat = jax.lax.dot_general(
            chh_all, oh_loT, _NT, preferred_element_type=jnp.float32)
        acc_ref[:] += acc_flat.reshape(nrho, hpad, LO)
    else:
        for r in range(nrho):
            acc_ref[r] += jax.lax.dot_general(
                chh(r), oh_loT, _NT, preferred_element_type=jnp.float32)

    @pl.when(s == nsteps - 1)
    def _():
        pres = acc_ref[:] > 0.5
        rvals = jax.lax.broadcasted_iota(
            jnp.int32, (nrho, hpad, LO), 0) + 1
        out_ref[0] = jnp.max(jnp.where(pres, rvals, 0), axis=0)


def hll_register_max(slot, rho, nslots: int, nrho: int, *,
                     interpret: bool = False,
                     span_hpad: int | None = None):
    """(nslots,) int32 registers = per-slot max rho — the real register-
    max scatter. slot: int32 ids in [0, nslots] (== nslots masks the
    row); rho: int32 in [1, nrho] (0 on padded rows matches no channel).
    Exact max-of-rho, bit-identical to the f32 scatter-max and the host
    build (presence is idempotent — accumulation order can't matter)."""
    s = slot.reshape(-1).astype(jnp.int32)
    r = rho.reshape(-1).astype(jnp.int32)
    n = s.shape[0]
    total_h = _hpad_total(nslots)
    hp = min(span_hpad or _span_hpad(nrho), total_h)
    npart = -(-total_h // hp)
    gp = hp * LO
    blk, _ninner, stacked = _plan_blk(nrho, hp)
    n_pad = ((n + blk - 1) // blk) * blk
    nsteps = n_pad // blk
    ids_lane = _pad_lane(s, n_pad, n, nslots)
    rho_lane = _pad_lane(r, n_pad, n, 0)
    kern = functools.partial(
        _hll_kernel, nsteps=nsteps, hpad=hp, nrho=nrho, blk=blk, gp=gp,
        stacked=stacked)
    out = pl.pallas_call(
        kern,
        grid=(npart, nsteps),
        in_specs=[
            pl.BlockSpec((blk // 128, 128), lambda p, s: (s, _i32(0)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((blk // 128, 128), lambda p, s: (s, _i32(0)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, hp, LO), lambda p, s: (p, _i32(0), _i32(0)),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((npart, hp, LO), jnp.int32),
        scratch_shapes=[pltpu.VMEM((nrho, hp, LO), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=_vmem_limit(nrho, hp, blk, stacked)),
        interpret=interpret,
    )(ids_lane, rho_lane)
    return out.reshape(npart * gp)[:nslots]


# ---------------------------------------------------------------------------
# 4) fused filter + gather + aggregate (block-skip candidates)
# ---------------------------------------------------------------------------

# storage dtypes the kernel loads directly; raw-space predicate literals
# additionally need a value range strictly inside int32 so host-side
# clipping into storage space preserves every comparison
_FUSED_COL_DTYPES = ("uint8", "uint16", "int8", "int16", "int32", "float32")
_FUSED_PRED_DTYPES = ("uint8", "uint16", "int8", "int16")

_FUSED_AGGS = ("count", "sum", "avg", "min", "max", "minmaxrange")


def _direct_colkey(expr_tpl):
    """Column key of a direct column read, or None for computed exprs."""
    if not isinstance(expr_tpl, tuple):
        return None
    if expr_tpl[0] == "raw":
        return expr_tpl[1]
    if expr_tpl[0] == "dictval":
        return "dv::" + expr_tpl[1]
    return None


class FusedPlan:
    """Static plan for one fused launch: operand order, per-agg output
    slots, and the parameter transforms the caller applies (shift raw
    literals into storage space, clip into the plane's value range)."""

    __slots__ = ("cols", "filter_tpl", "pred_params", "aggs",
                 "n_int", "n_flt")

    def __init__(self, cols, filter_tpl, pred_params, aggs, n_int, n_flt):
        self.cols = cols              # tuple of column keys (operand order)
        self.filter_tpl = filter_tpl
        # {param key: (colkey or None, "id" | "storage")} — "storage"
        # params subtract the column's FOR offset and clip to the plane's
        # value range before entering the kernel
        self.pred_params = pred_params
        # list of (agg index, name, colkey, buffer, slot, fill)
        self.aggs = aggs
        self.n_int = n_int
        self.n_flt = n_flt


def _plan_filter(tpl, widths, cols, pred_params) -> bool:
    """Walk the filter template: True iff every node is kernel-evaluable.
    Fills ``cols``/``pred_params`` as it goes."""
    kind = tpl[0]
    if kind in ("true", "false"):
        return True
    if kind in ("and", "or"):
        return all(_plan_filter(c, widths, cols, pred_params)
                   for c in tpl[1:])
    if kind == "not":
        return _plan_filter(tpl[1], widths, cols, pred_params)

    def col_ok(key, pred: bool) -> bool:
        if key is None or key.startswith("mv::"):
            return False
        w = widths.get(key) if widths else None
        if w is not None and w[1]:
            return False  # sub-byte packed plane: unpack not fused
        dt = str(jnp.dtype(w[0])) if w is not None else None
        if dt is None:
            return False  # unplanned plane (legacy wide) — dtype unknown
        allowed = _FUSED_PRED_DTYPES if pred else _FUSED_COL_DTYPES
        if dt not in allowed:
            return False
        cols.add(key)
        return True

    if kind == "eq_dict":
        if not col_ok(tpl[1], False) or str(jnp.dtype(
                widths[tpl[1]][0])) == "float32":
            return False
        pred_params[tpl[2]] = (tpl[1], "id")
        return True
    if kind == "in_dict":
        if not col_ok(tpl[1], False) or str(jnp.dtype(
                widths[tpl[1]][0])) == "float32":
            return False
        pred_params[tpl[2]] = (tpl[1], "id")
        return True
    if kind == "range_dict":
        if not col_ok(tpl[1], False) or str(jnp.dtype(
                widths[tpl[1]][0])) == "float32":
            return False
        pred_params[tpl[2]] = (tpl[1], "id")
        pred_params[tpl[3]] = (tpl[1], "id")
        return True
    if kind in ("eq_raw", "in_raw"):
        ck = _direct_colkey(tpl[1])
        if not col_ok(ck, True):
            return False
        pred_params[tpl[2]] = (ck, "storage")
        return True
    if kind == "range_raw":
        _, expr_tpl, klo, khi, has_lo, has_hi, _li, _hi_inc = tpl
        ck = _direct_colkey(expr_tpl)
        if not col_ok(ck, True):
            return False
        if has_lo:
            pred_params[klo] = (ck, "storage")
        if has_hi:
            pred_params[khi] = (ck, "storage")
        return True
    return False  # lut_dict / mv_any / anything new


def plan_fused(filter_tpl, agg_tpls, widths):
    """Static fused-launch plan for a scalar-shape block-skip template, or
    None when any node falls outside the kernel's surface (the generic
    gather path then runs, exactly as before)."""
    cols: set = set()
    pred_params: dict = {}
    if not _plan_filter(filter_tpl, widths, cols, pred_params):
        return None
    aggs = []
    n_int, n_flt = 1, 0  # int slot 0 = per-block matched count
    for i, (name, argt, extra) in enumerate(agg_tpls):
        if name not in _FUSED_AGGS:
            return None
        if name == "count":
            continue
        ck = _direct_colkey(argt)
        if ck is None or ck.startswith("mv::"):
            return None
        w = widths.get(ck) if widths else None
        if w is None or w[1]:
            return None
        dt = str(jnp.dtype(w[0]))
        if dt not in _FUSED_COL_DTYPES:
            return None
        is_float = dt == "float32"
        if name in ("sum", "avg"):
            if is_float:
                return None  # f32 sums are order-sensitive: stay on XLA
            rpb = extra[1] if isinstance(extra, tuple) else extra
            if rpb is None or rpb < FUSED_BLOCK_ROWS:
                return None  # per-block int32 partial could overflow
            cols.add(ck)
            aggs.append((i, "sum", ck, "int", n_int, 0))
            n_int += 1
            continue
        ops = ("min", "max") if name == "minmaxrange" else (name,)
        cols.add(ck)
        for op in ops:
            if is_float:
                fill = float("inf") if op == "min" else float("-inf")
                aggs.append((i, op, ck, "flt", n_flt, fill))
                n_flt += 1
            else:
                info = jnp.iinfo(jnp.dtype(w[0]))
                fill = info.max if op == "min" else info.min
                aggs.append((i, op, ck, "int", n_int, fill))
                n_int += 1
    return FusedPlan(tuple(sorted(cols)), filter_tpl, pred_params,
                     tuple(aggs), n_int, n_flt)


def fused_params_ok(plan: FusedPlan, params: dict) -> bool:
    """Trace-time runtime check: every predicate param present with a
    kernel-compatible shape (IN lists bounded) and dtype. Raw-space
    params must be INTEGER: a fractional literal (``ts < 10.5``) would
    truncate under the storage-space int cast while the generic branch
    compares with float promotion — the query falls to the generic
    gather path instead, keeping Pallas == XLA bit-exact."""
    for key, (_ck, kindp) in plan.pred_params.items():
        p = params.get(key)
        if p is None:
            return False
        if p.ndim > 1 or (p.ndim == 1 and p.shape[0] > FUSED_MAX_IN):
            return False
        if kindp == "storage" and not jnp.issubdtype(p.dtype, jnp.integer):
            return False
    return True


def _fused_eval(tpl, colv, parv, shape):
    """In-kernel filter evaluation over the gathered block — the VMEM
    mirror of engine/device.py _eval_filter's interval/dict subset."""
    kind = tpl[0]
    if kind == "true":
        return jnp.ones(shape, dtype=bool)
    if kind == "false":
        return jnp.zeros(shape, dtype=bool)
    if kind == "and":
        m = _fused_eval(tpl[1], colv, parv, shape)
        for c in tpl[2:]:
            m &= _fused_eval(c, colv, parv, shape)
        return m
    if kind == "or":
        m = _fused_eval(tpl[1], colv, parv, shape)
        for c in tpl[2:]:
            m |= _fused_eval(c, colv, parv, shape)
        return m
    if kind == "not":
        return ~_fused_eval(tpl[1], colv, parv, shape)
    if kind in ("eq_dict", "eq_raw"):
        key = tpl[1] if kind == "eq_dict" else _direct_colkey(tpl[1])
        return colv[key] == parv[tpl[2]][0]
    if kind in ("in_dict", "in_raw"):
        key = tpl[1] if kind == "in_dict" else _direct_colkey(tpl[1])
        v = colv[key]
        p = parv[tpl[2]]
        m = v == p[0]
        for k in range(1, p.shape[0]):
            m |= v == p[k]
        return m
    if kind == "range_dict":
        v = colv[tpl[1]]
        return (v >= parv[tpl[2]][0]) & (v < parv[tpl[3]][0])
    if kind == "range_raw":
        _, expr_tpl, klo, khi, has_lo, has_hi, lo_inc, hi_inc = tpl
        v = colv[_direct_colkey(expr_tpl)]
        m = jnp.ones(shape, dtype=bool)
        if has_lo:
            b = parv[klo][0]
            m &= (v >= b) if lo_inc else (v > b)
        if has_hi:
            b = parv[khi][0]
            m &= (v <= b) if hi_inc else (v < b)
        return m
    raise AssertionError(f"fused filter node {kind}")


def _fused_kernel(cand_ref, rows_ref, *refs, plan: FusedPlan, sub, pshapes):
    ncols = len(plan.cols)
    i = pl.program_id(0)
    colv = {}
    for j, key in enumerate(plan.cols):
        blk = refs[j][0]  # (sub, 128) storage dtype
        if blk.dtype == jnp.float32:
            colv[key] = blk
        else:
            colv[key] = blk.astype(jnp.int32)
    parv = {key: refs[ncols + j][:]
            for j, key in enumerate(sorted(pshapes))}
    out_i = refs[ncols + len(pshapes)]
    out_f = None if plan.n_flt == 0 else refs[ncols + len(pshapes) + 1]

    shape = (sub, 128)
    mask = _fused_eval(plan.filter_tpl, colv, parv, shape)
    rowid = jax.lax.broadcasted_iota(jnp.int32, shape, 0) * 128 \
        + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    mask &= rowid < rows_ref[i]

    ints = [jnp.sum(mask, dtype=jnp.int32)]  # slot 0: matched rows
    flts = []
    for (_i, op, ck, buf, _slot, fill) in plan.aggs:
        v = colv[ck]
        if op == "sum":
            ints.append(jnp.sum(jnp.where(mask, v, 0), dtype=jnp.int32))
        elif buf == "int":
            vm = jnp.where(mask, v, jnp.int32(fill))
            ints.append(vm.min() if op == "min" else vm.max())
        else:
            vm = jnp.where(mask, v, jnp.float32(fill))
            flts.append(vm.min() if op == "min" else vm.max())
    ki = out_i.shape[1]
    vec_i = jnp.stack(ints + [jnp.int32(0)] * (ki - len(ints)))
    out_i[0] = jnp.broadcast_to(vec_i[:, None], (ki, 128))
    if out_f is not None:
        kf = out_f.shape[1]
        vec_f = jnp.stack(flts + [jnp.float32(0)] * (kf - len(flts)))
        out_f[0] = jnp.broadcast_to(vec_f[:, None], (kf, 128))


def fused_filter_agg(cand, rows_in_block, col_arrays: dict,
                     param_arrays: dict, plan: FusedPlan, *,
                     interpret: bool = False):
    """ONE kernel: gather candidate blocks (scalar-prefetched indices
    drive the BlockSpec index maps — the pipeline DMAs exactly the
    candidate blocks out of HBM), evaluate the filter, aggregate. The
    XLA path's (B, R) gather buffer never materializes.

    cand: (B,) int32 candidate block ids into the flattened
    (S*NB, R) view; rows_in_block: (B,) int32 valid rows per candidate
    (0 for padding candidates). col_arrays: {key: (S*NB, R//128, 128)}
    storage-dtype views; param_arrays: {key: (K,) int32/float32} already
    shifted into storage space. Returns (ints (B, KI), flts (B, KF) or
    None): per-candidate partials — matched count in int slot 0, agg
    partials per the plan's slots. Combining them (answer-scale, outside)
    is exact: int sums never overflow their per-block int32 partial
    (plan-gated via rows_per_block bounds) and min/max are idempotent.
    """
    B = cand.shape[0]
    sub = FUSED_BLOCK_ROWS // 128
    ki = max(8, plan.n_int)
    kf = max(8, plan.n_flt) if plan.n_flt else 0
    pkeys = sorted(param_arrays)
    pshapes = {k: param_arrays[k].shape for k in pkeys}
    kern = functools.partial(_fused_kernel, plan=plan, sub=sub,
                             pshapes=pshapes)
    in_specs = [
        pl.BlockSpec((1, sub, 128), lambda i, c, r: (c[i], 0, 0),
                     memory_space=pltpu.VMEM)
        for _ in plan.cols
    ] + [
        pl.BlockSpec(memory_space=pltpu.SMEM) for _ in pkeys
    ]
    out_specs = [pl.BlockSpec((1, ki, 128), lambda i, c, r: (i, 0, 0),
                              memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct((B, ki, 128), jnp.int32)]
    if kf:
        out_specs.append(
            pl.BlockSpec((1, kf, 128), lambda i, c, r: (i, 0, 0),
                         memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((B, kf, 128), jnp.float32))
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=in_specs,
        out_specs=out_specs if kf else out_specs[0],
    )
    outs = pl.pallas_call(
        kern, grid_spec=gs,
        out_shape=out_shape if kf else out_shape[0],
        interpret=interpret,
    )(cand.astype(jnp.int32), rows_in_block.astype(jnp.int32),
      *[col_arrays[k] for k in plan.cols],
      *[param_arrays[k] for k in pkeys])
    if kf:
        return outs[0][:, :, 0], outs[1][:, :, 0]
    return outs[:, :, 0], None
