"""Geospatial scalar functions (host-side).

Equivalent of the reference's geospatial package
(pinot-core/.../geospatial/transform/function/: StPointFunction,
StDistanceFunction, StContainsFunction, StAsTextFunction,
StGeogFromTextFunction...). The reference delegates geometry to JTS and
H3 (JNI); here geography stays WKT-string-encoded (POINT/POLYGON) with
numpy haversine math — SURVEY §7 keeps geo host-side permanently.

Coordinates are (longitude, latitude) in degrees, like the reference's
geography type; distances are meters on the WGS84 mean sphere.
"""

from __future__ import annotations

import re

import numpy as np

EARTH_RADIUS_M = 6_371_008.8

_POINT_RE = re.compile(
    r"\s*POINT\s*\(\s*(-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)\s+"
    r"(-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)\s*\)\s*", re.IGNORECASE)
_POLY_RE = re.compile(r"\s*POLYGON\s*\(\((.*?)\)\)\s*", re.IGNORECASE | re.DOTALL)


def _as_str_array(a) -> np.ndarray:
    return np.atleast_1d(np.asarray(a)).astype(str)


def parse_points(arr) -> tuple:
    """(lon, lat) float64 arrays from WKT POINT strings; malformed -> NaN."""
    s = _as_str_array(arr)
    lon = np.full(len(s), np.nan)
    lat = np.full(len(s), np.nan)
    for i, w in enumerate(s):
        m = _POINT_RE.fullmatch(w)
        if m:
            lon[i] = float(m.group(1))
            lat[i] = float(m.group(2))
    return lon, lat


def parse_polygon(wkt: str) -> np.ndarray:
    """(n, 2) lon/lat ring from a WKT POLYGON's outer ring."""
    m = _POLY_RE.fullmatch(str(wkt))
    if not m:
        raise ValueError(f"not a WKT POLYGON: {wkt!r}")
    pts = []
    for pair in m.group(1).split(","):
        x, y = pair.split()
        pts.append((float(x), float(y)))
    return np.asarray(pts, dtype=np.float64)


def st_point(lon, lat) -> np.ndarray:
    lon = np.atleast_1d(np.asarray(lon, dtype=np.float64))
    lat = np.atleast_1d(np.asarray(lat, dtype=np.float64))
    lon, lat = np.broadcast_arrays(lon, lat)
    return np.asarray([f"POINT ({x:.10g} {y:.10g})" for x, y in zip(lon, lat)])


def st_geog_from_text(wkt) -> np.ndarray:
    return _as_str_array(wkt)


def st_as_text(geo) -> np.ndarray:
    return _as_str_array(geo)


def haversine_m(lon1, lat1, lon2, lat2) -> np.ndarray:
    p1, p2 = np.radians(lat1), np.radians(lat2)
    dp = p2 - p1
    dl = np.radians(lon2) - np.radians(lon1)
    a = np.sin(dp / 2) ** 2 + np.cos(p1) * np.cos(p2) * np.sin(dl / 2) ** 2
    return 2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0, 1)))


def st_distance(a, b) -> np.ndarray:
    """Sphere distance in meters between two POINT columns/literals
    (StDistanceFunction geography semantics)."""
    lon1, lat1 = parse_points(a)
    lon2, lat2 = parse_points(b)
    lon1, lon2 = np.broadcast_arrays(lon1, lon2)
    lat1, lat2 = np.broadcast_arrays(lat1, lat2)
    return haversine_m(lon1, lat1, lon2, lat2)


def st_polygon(wkt) -> np.ndarray:
    """ST_Polygon: validate + normalize a WKT POLYGON (reference
    StPolygonFunction constructs the geometry; here geometries stay WKT)."""
    s = _as_str_array(wkt)
    for w in s:
        parse_polygon(w)  # raises on malformed input
    return s


def st_area(poly_wkt) -> np.ndarray:
    """Spherical polygon area in m² (StAreaFunction geography semantics):
    the spherical excess via L'Huilier-free line-integral form."""
    s = _as_str_array(poly_wkt)
    out = np.zeros(len(s), dtype=np.float64)
    for i, w in enumerate(s):
        ring = parse_polygon(w)
        lon = np.radians(ring[:, 0])
        lat = np.radians(ring[:, 1])
        if lon[0] != lon[-1] or lat[0] != lat[-1]:
            lon = np.append(lon, lon[0])
            lat = np.append(lat, lat[0])
        # spherical excess line integral: sum (λ2-λ1)·(2+sinφ1+sinφ2)/2
        area = np.sum(
            (lon[1:] - lon[:-1])
            * (2 + np.sin(lat[:-1]) + np.sin(lat[1:]))) / 2.0
        out[i] = abs(area) * EARTH_RADIUS_M * EARTH_RADIUS_M
    return out


# ---- WKB (well-known binary) points ---------------------------------------
# Reference: ST_GeomFromWKB / ST_AsBinary over JTS; here little-endian WKB
# point encoding per the OGC spec (byte order 1, type 1, two f64s).

import struct as _struct


def st_as_binary(points) -> np.ndarray:
    lon, lat = parse_points(points)
    out = np.empty(len(lon), dtype=object)
    for i in range(len(lon)):
        out[i] = _struct.pack("<BIdd", 1, 1, lon[i], lat[i])
    return out


def st_geom_from_wkb(blobs) -> np.ndarray:
    arr = np.atleast_1d(np.asarray(blobs, dtype=object))
    lon = np.full(len(arr), np.nan)
    lat = np.full(len(arr), np.nan)
    for i, b in enumerate(arr):
        if isinstance(b, (bytes, bytearray)) and len(b) >= 21:
            (order,) = _struct.unpack_from("<B", b, 0)
            fmt = "<" if order == 1 else ">"
            (gtype,) = _struct.unpack_from(fmt + "I", b, 1)
            if gtype == 1:
                lon[i], lat[i] = _struct.unpack_from(fmt + "dd", b, 5)
    return st_point(lon, lat)


def _points_in_ring(ring: np.ndarray, lon: np.ndarray, lat: np.ndarray) -> np.ndarray:
    """Vectorized even-odd ray cast (planar lon/lat, like JTS contains on
    geometries): True where (lon, lat) falls inside the ring."""
    inside = np.zeros(len(lon), dtype=bool)
    x0, y0 = ring[-1]
    for x1, y1 in ring:
        crosses = ((y1 > lat) != (y0 > lat))
        with np.errstate(divide="ignore", invalid="ignore"):
            xint = (x0 - x1) * (lat - y1) / (y0 - y1) + x1
        inside ^= crosses & (lon < xint)
        x0, y0 = x1, y1
    return inside


def st_contains(poly_wkt, points) -> np.ndarray:
    """Polygon contains point — polygon is a (usually literal) WKT POLYGON,
    points a POINT column (StContainsFunction arg order). Either side may
    be scalar; both broadcast like any binary transform."""
    polys = _as_str_array(poly_wkt)
    lon, lat = parse_points(points)
    if len(polys) == 1:
        ring = parse_polygon(polys[0])
        out = _points_in_ring(ring, lon, lat)
        return out & ~np.isnan(lon)
    polys, lon, lat = np.broadcast_arrays(polys, lon, lat)
    out = np.zeros(len(lon), dtype=bool)
    for i, p in enumerate(polys):
        out[i] = bool(_points_in_ring(parse_polygon(p),
                                      lon[i: i + 1], lat[i: i + 1])[0])
    return out & ~np.isnan(lon)


def st_within(points, poly_wkt) -> np.ndarray:
    """Point within polygon — flipped argument order (StWithinFunction)."""
    return st_contains(poly_wkt, points)


_WKT_TYPES = {
    "POINT": "Point", "LINESTRING": "LineString", "POLYGON": "Polygon",
    "MULTIPOINT": "MultiPoint", "MULTILINESTRING": "MultiLineString",
    "MULTIPOLYGON": "MultiPolygon",
    "GEOMETRYCOLLECTION": "GeometryCollection",
}


def st_geometry_type(geo) -> np.ndarray:
    """JTS Geometry.getGeometryType() analog: the WKT type token in JTS
    capitalization (StGeometryTypeFunction.java:74)."""
    s = _as_str_array(geo)
    out = np.empty(len(s), dtype=object)
    for i, w in enumerate(s):
        tok = str(w).strip().split("(")[0].strip().split()[0].upper() \
            if str(w).strip() else ""
        out[i] = _WKT_TYPES.get(tok, tok.title() if tok else "")
    return out


def _normalize_wkt(w: str) -> str:
    return " ".join(str(w).upper().replace("(", " ( ").replace(")", " ) ")
                    .replace(",", " , ").split())


def st_equals(a, b) -> np.ndarray:
    """Geometry equality (StEqualsFunction role): POINT pairs compare by
    coordinates; other WKT pairs by normalized text — sufficient for the
    point/polygon geometry model this build carries (ops/geo.py)."""
    aa, bb = _as_str_array(a), _as_str_array(b)
    aa, bb = np.broadcast_arrays(aa, bb)
    lon_a, lat_a = parse_points(aa)
    lon_b, lat_b = parse_points(bb)
    out = np.zeros(len(aa), dtype=bool)
    for i in range(len(aa)):
        if not np.isnan(lon_a[i]) and not np.isnan(lon_b[i]):
            out[i] = lon_a[i] == lon_b[i] and lat_a[i] == lat_b[i]
        else:
            out[i] = _normalize_wkt(aa[i]) == _normalize_wkt(bb[i])
    return out


def grid_cell(lon, lat, resolution) -> np.ndarray:
    """geoToH3's role on this build's grid scheme (storage/geoindex.py):
    pack (floor(lat/res_deg), floor(lon/res_deg)) into an int64 cell id
    with the resolution in the top byte, so ids from different resolutions
    never collide (like H3's resolution-tagged indexes). res_deg halves
    per resolution step: res 0 = 360 deg, res r = 360/2^r deg.
    NaN coordinates yield -1 (no cell)."""
    lon = np.atleast_1d(np.asarray(lon, dtype=np.float64))
    lat = np.atleast_1d(np.asarray(lat, dtype=np.float64))
    res = np.atleast_1d(np.asarray(resolution, dtype=np.int64))
    lon, lat, res = np.broadcast_arrays(lon, lat, res)
    # at res r, cj spans 2^r values and ci 2^(r-1): both must fit their
    # packed fields (27 / 26 bits), so 27 is the finest resolution
    # (~0.3m cells) before indices would alias across the planet
    res = np.clip(res, 0, 27)
    res_deg = 360.0 / (np.int64(1) << res)
    ci = np.floor(lat / res_deg).astype(np.int64)
    cj = np.floor(lon / res_deg).astype(np.int64)
    cell = (res.astype(np.int64) << 54) | ((ci & 0x3FFFFFF) << 27) \
        | (cj & 0x7FFFFFF)
    return np.where(np.isnan(lon) | np.isnan(lat), np.int64(-1), cell)
