"""Predicate-mask kernels: the device replacement for filter operators.

The reference walks per-doc iterators (pinot-core/.../operator/dociditerators/
SVScanDocIdIterator.java:56-94) and RoaringBitmap algebra
(AndFilterOperator/OrFilterOperator). On TPU the filter result is a dense
boolean mask over the padded (S, L) segment batch — fixed shape, fuse-friendly
— and AND/OR/NOT are elementwise ops XLA fuses into the surrounding kernel.

Predicate literals arrive as *parameter arrays* resolved per segment on the
host (dict-id space, see engine/params.py), so the jitted pipeline is reused
across literal values — only shapes retrace.

All functions here are shape-polymorphic jnp ops, traced inside the engine's
jitted pipeline; nothing allocates per-doc.
"""

from __future__ import annotations

import jax.numpy as jnp


def valid_mask(n_docs, padded_len: int, batched: bool):
    """(S, L) or (L,) mask of real (non-padding) docs.

    ``n_docs``: int32 (S,) vector when batched, scalar otherwise.
    """
    iota = jnp.arange(padded_len, dtype=jnp.int32)
    if batched:
        return iota[None, :] < n_docs[:, None]
    return iota < n_docs


# ---- dict-id space predicates (DICT-encoded columns) ----------------------
# `ids` is the forward index: int32 (S, L); padding is -1.
# Per-segment params use -2 (or empty ranges) as "no match in this segment".


def eq_dict(ids, target_ids):
    """EQ: ``target_ids`` int32 (S,) — the literal's dict id per segment."""
    return ids == target_ids[:, None]


def in_dict(ids, id_matrix):
    """IN: ``id_matrix`` int32 (S, K), padded with -2.

    K is small (the literal count); the (S, L, K) broadcast stays in
    registers under XLA fusion.
    """
    return jnp.any(ids[:, :, None] == id_matrix[:, None, :], axis=-1)


def range_dict(ids, lo, hi):
    """RANGE on a sorted dictionary: per-segment id interval [lo, hi).

    ``lo``/``hi`` int32 (S,). The host resolved value bounds to id bounds via
    binary search (Dictionary.range_ids) — the dictionary-based range
    evaluator trick (RangePredicateEvaluatorFactory).
    """
    return (ids >= lo[:, None]) & (ids < hi[:, None])


def lut_dict(ids, lut):
    """Arbitrary predicate on a dict column via per-dictid boolean LUT.

    ``lut``: bool (S, C_max) — entry [s, d] says whether dict id d of segment
    s matches (host evaluated the predicate once per dictionary entry, e.g.
    regex over a few thousand strings instead of millions of rows — the same
    leverage the reference gets from dictionary-based predicate evaluators).
    Padding ids (-1) index entry 0 after clamping; callers AND with
    valid_mask at the top of the tree, so the value is irrelevant.
    """
    clamped = jnp.clip(ids, 0, lut.shape[1] - 1)
    return jnp.take_along_axis(lut, clamped, axis=1)


# ---- raw-value space predicates (RAW-encoded columns / computed exprs) ----


def eq_raw(values, literal):
    return values == literal


def neq_raw(values, literal):
    return values != literal


def in_raw(values, literals):
    """``literals``: (K,) device vector."""
    return jnp.any(values[..., None] == literals, axis=-1)


def range_raw(values, lower, upper, lower_inclusive: bool, upper_inclusive: bool,
              has_lower: bool, has_upper: bool):
    """Static inclusivity/boundedness (part of the jit template); bounds are
    traced scalars."""
    m = jnp.ones(values.shape, dtype=bool)
    if has_lower:
        m &= (values >= lower) if lower_inclusive else (values > lower)
    if has_upper:
        m &= (values <= upper) if upper_inclusive else (values < upper)
    return m
