"""Predicate-mask kernels: the device replacement for filter operators.

The reference walks per-doc iterators (pinot-core/.../operator/dociditerators/
SVScanDocIdIterator.java:56-94) and RoaringBitmap algebra
(AndFilterOperator/OrFilterOperator). On TPU the filter result is a dense
boolean mask over the padded (S, L) segment batch — fixed shape, fuse-friendly
— and AND/OR/NOT are elementwise ops XLA fuses into the surrounding kernel.

Dict-encoded columns arrive in **global dictionary id space** (the batch
loader remapped them on upload, engine/params.py), so predicate literals
resolve to batch-wide scalars/vectors on the host — one binary search over
the global dictionary replaces the reference's per-segment
PredicateEvaluator, and the kernel is a bare vector comparison with no
per-segment indirection. Id planes arrive at their cardinality-chosen width
(uint8/uint16/int32, optionally sub-byte-packed — engine/params.py
ColPlan); predicates compare at native width (the int32 literal promotes
in-register, HBM traffic stays narrow). Padding docs carry id -1 (signed
planes) or the cardinality C (unsigned planes — ids are < C, so C matches
no literal) and literal params use -2 for "absent", so padding never
matches; callers still AND with valid_mask.

All functions here are shape-polymorphic jnp ops, traced inside the engine's
jitted pipeline; nothing allocates per-doc.
"""

from __future__ import annotations

import jax.numpy as jnp


def unpack_subbyte(packed, bits: int):
    """(…, Lp) uint8 sub-byte plane → (…, Lp * 8//bits) uint8 dict ids,
    unpacked with shifts/masks at REGISTER level (the in-kernel analog of
    FixedBitSVForwardIndexReader's bit extraction): the HBM read stays at
    the packed width, XLA fuses the shift/mask into whatever consumes the
    ids. Values are little-endian within each byte — id j lives in byte
    j // f at bit offset (j % f) * bits (f = 8 // bits), matching
    engine/params.py's host-side packer."""
    f = 8 // bits
    shifts = jnp.arange(f, dtype=jnp.uint8) * jnp.uint8(bits)
    sub = (packed[..., None] >> shifts) & jnp.uint8((1 << bits) - 1)
    return sub.reshape(packed.shape[:-1] + (packed.shape[-1] * f,))


def valid_mask(n_docs, padded_len: int, batched: bool):
    """(S, L) or (L,) mask of real (non-padding) docs.

    ``n_docs``: int32 (S,) vector when batched, scalar otherwise.
    """
    iota = jnp.arange(padded_len, dtype=jnp.int32)
    if batched:
        return iota[None, :] < n_docs[:, None]
    return iota < n_docs


# ---- global-dict-id space predicates (DICT-encoded columns) ---------------


def eq_dict(ids, target_id):
    """EQ: ``target_id`` int32 scalar global id (-2 if value absent)."""
    return ids == target_id


def in_dict(ids, id_vector):
    """IN: ``id_vector`` int32 (K,) global ids, padded with -2."""
    return jnp.any(ids[..., None] == id_vector, axis=-1)


def range_dict(ids, lo, hi):
    """RANGE: global id interval [lo, hi) — a value range on the sorted
    global dictionary is contiguous in id space (the dictionary-based range
    evaluator trick, RangePredicateEvaluatorFactory)."""
    return (ids >= lo) & (ids < hi)


def lut_dict(ids, lut):
    """Arbitrary predicate via a (C,) boolean LUT over global ids: the host
    evaluated the predicate once per dictionary entry (e.g. regex over a few
    thousand strings instead of millions of rows). Padding ids clamp to 0;
    callers AND with valid_mask, so the value is irrelevant."""
    return lut[jnp.clip(ids, 0, lut.shape[0] - 1)]


# ---- raw-value space predicates (RAW-encoded columns / computed exprs) ----


def eq_raw(values, literal):
    return values == literal


def neq_raw(values, literal):
    return values != literal


def in_raw(values, literals):
    """``literals``: (K,) device vector."""
    return jnp.any(values[..., None] == literals, axis=-1)


def range_raw(values, lower, upper, lower_inclusive: bool, upper_inclusive: bool,
              has_lower: bool, has_upper: bool):
    """Static inclusivity/boundedness (part of the jit template); bounds are
    traced scalars."""
    m = jnp.ones(values.shape, dtype=bool)
    if has_lower:
        m &= (values >= lower) if lower_inclusive else (values > lower)
    if has_upper:
        m &= (values <= upper) if upper_inclusive else (values < upper)
    return m
