"""Theta sketch: mergeable approximate distinct counting (KMV variant).

Equivalent of the reference's theta-sketch distinct count
(DistinctCountThetaSketchAggregationFunction.java over Apache
DataSketches' QuickSelect theta sketch): keep the k smallest 63-bit
hashes; theta is the (k+1)-th smallest, every retained hash is < theta,
and the estimate is |retained| / (theta / 2^63). Merging is
min(theta) + union + re-trim — order-insensitive, fixed-size state that
rides the DataTable wire as a plain int list per group.

Hashing reuses the canonical murmur-finalizer pipeline (ops/hll.py
hash32_np) twice with decorrelated seeds to form 63-bit hashes, so host
and (future) device builders agree bit-for-bit.
"""

from __future__ import annotations

import numpy as np

from pinot_tpu.ops.hll import hash32_np

DEFAULT_NOMINAL = 16384  # reference default nominalEntries
MAX_HASH = np.int64(1) << np.int64(62)  # theta space: hashes in [0, 2^62)


def _fmix32(h: np.ndarray) -> np.ndarray:
    h = h.copy()
    h ^= h >> 16
    h = (h * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h ^= h >> 13
    h = (h * np.uint32(0xC2B2AE35)).astype(np.uint32)
    h ^= h >> 16
    return h


def hash63(values: np.ndarray) -> np.ndarray:
    """Deterministic 62-bit hashes as int64 (top bits clear so the values
    survive the int64 wire format and float math without sign trouble)."""
    h1 = hash32_np(values).astype(np.uint64)
    h2 = _fmix32((h1 ^ np.uint64(0x9E3779B9)).astype(np.uint32)).astype(np.uint64)
    h = ((h1 << np.uint64(31)) ^ h2) & np.uint64((1 << 62) - 1)
    return h.astype(np.int64)


def build(values: np.ndarray, k: int) -> tuple:
    """values -> (theta:int, sorted retained hashes:int64 array)."""
    h = np.unique(hash63(values))
    return trim(int(MAX_HASH), h, k)


def trim(theta: int, hashes: np.ndarray, k: int) -> tuple:
    """Enforce the k-entry bound: theta becomes the (k+1)-th smallest and
    only hashes strictly below it are retained."""
    hashes = hashes[hashes < theta]
    if len(hashes) > k:
        hashes = np.sort(hashes)
        theta = int(hashes[k])
        hashes = hashes[:k]
        hashes = hashes[hashes < theta]  # duplicates of theta fall out
    return theta, hashes


def merge(theta_a: int, ha: np.ndarray, theta_b: int, hb: np.ndarray,
          k: int) -> tuple:
    theta = min(theta_a, theta_b)
    union = np.union1d(np.asarray(ha, dtype=np.int64),
                       np.asarray(hb, dtype=np.int64))
    return trim(theta, union, k)


def estimate(theta: int, hashes) -> float:
    n = len(hashes)
    if theta >= int(MAX_HASH):
        return float(n)  # exact mode: never trimmed
    return n / (theta / float(MAX_HASH))
