"""Theta sketch: mergeable approximate distinct counting (KMV variant).

Equivalent of the reference's theta-sketch distinct count
(DistinctCountThetaSketchAggregationFunction.java over Apache
DataSketches' QuickSelect theta sketch): keep the k smallest 63-bit
hashes; theta is the (k+1)-th smallest, every retained hash is < theta,
and the estimate is |retained| / (theta / 2^63). Merging is
min(theta) + union + re-trim — order-insensitive, fixed-size state that
rides the DataTable wire as a plain int list per group.

Hashing reuses the canonical murmur-finalizer pipeline (ops/hll.py
hash32_np) twice with decorrelated seeds to form 63-bit hashes, so host
and (future) device builders agree bit-for-bit.
"""

from __future__ import annotations

import re

import numpy as np

from pinot_tpu.ops.hll import hash32_np

DEFAULT_NOMINAL = 16384  # reference default nominalEntries
MAX_HASH = np.int64(1) << np.int64(62)  # theta space: hashes in [0, 2^62)


def _fmix32(h: np.ndarray) -> np.ndarray:
    h = h.copy()
    h ^= h >> 16
    h = (h * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h ^= h >> 13
    h = (h * np.uint32(0xC2B2AE35)).astype(np.uint32)
    h ^= h >> 16
    return h


def hash63(values: np.ndarray) -> np.ndarray:
    """Deterministic 62-bit hashes as int64 (top bits clear so the values
    survive the int64 wire format and float math without sign trouble)."""
    h1 = hash32_np(values).astype(np.uint64)
    h2 = _fmix32((h1 ^ np.uint64(0x9E3779B9)).astype(np.uint32)).astype(np.uint64)
    h = ((h1 << np.uint64(31)) ^ h2) & np.uint64((1 << 62) - 1)
    return h.astype(np.int64)


def build(values: np.ndarray, k: int) -> tuple:
    """values -> (theta:int, sorted retained hashes:int64 array)."""
    h = np.unique(hash63(values))
    return trim(int(MAX_HASH), h, k)


def trim(theta: int, hashes: np.ndarray, k: int) -> tuple:
    """Enforce the k-entry bound: theta becomes the (k+1)-th smallest and
    only hashes strictly below it are retained."""
    hashes = hashes[hashes < theta]
    if len(hashes) > k:
        hashes = np.sort(hashes)
        theta = int(hashes[k])
        hashes = hashes[:k]
        hashes = hashes[hashes < theta]  # duplicates of theta fall out
    return theta, hashes


def merge(theta_a: int, ha: np.ndarray, theta_b: int, hb: np.ndarray,
          k: int) -> tuple:
    theta = min(theta_a, theta_b)
    union = np.union1d(np.asarray(ha, dtype=np.int64),
                       np.asarray(hb, dtype=np.int64))
    return trim(theta, union, k)


def estimate(theta: int, hashes) -> float:
    n = len(hashes)
    if theta >= int(MAX_HASH):
        return float(n)  # exact mode: never trimmed
    return n / (theta / float(MAX_HASH))


# ---------------------------------------------------------------------------
# set algebra (the reference's Intersection / AnotB / Union post-aggregation
# over theta sketches: DistinctCountThetaSketchAggregationFunction's
# filtered-sketch + set-expression form)
# ---------------------------------------------------------------------------


def intersect(theta_a: int, ha: np.ndarray, theta_b: int, hb: np.ndarray) -> tuple:
    """Sketch intersection (DataSketches Intersection semantics): both
    sides are uniform samples below their thetas, so the common hashes
    below min(theta) are a uniform sample of the value intersection."""
    theta = min(int(theta_a), int(theta_b))
    common = np.intersect1d(np.asarray(ha, dtype=np.int64),
                            np.asarray(hb, dtype=np.int64))
    return theta, common[common < theta]


def a_not_b(theta_a: int, ha: np.ndarray, theta_b: int, hb: np.ndarray) -> tuple:
    """Sketch difference (DataSketches AnotB semantics)."""
    theta = min(int(theta_a), int(theta_b))
    ha = np.asarray(ha, dtype=np.int64)
    keep = ha[ha < theta]
    return theta, np.setdiff1d(keep, np.asarray(hb, dtype=np.int64))


_SET_TOKEN = re.compile(
    r"\s*(SET_INTERSECT|SET_UNION|SET_DIFF|\$\d+|[(),])", re.IGNORECASE)


def parse_set_expression(s: str):
    """'SET_INTERSECT($1, $2)' → nested AST of ('ref', i) leaves and
    ('SET_INTERSECT'|'SET_UNION'|'SET_DIFF', child...) nodes. $1 is the
    FIRST filtered sketch (reference numbering)."""
    toks, pos = [], 0
    while pos < len(s):
        if s[pos:].strip() == "":
            break
        m = _SET_TOKEN.match(s, pos)
        if m is None:
            raise ValueError(f"bad theta set expression at {pos}: {s!r}")
        toks.append(m.group(1))
        pos = m.end()

    def parse(i):
        if i >= len(toks):
            raise ValueError(f"truncated theta set expression: {s!r}")
        t = toks[i]
        if t.startswith("$"):
            ref = int(t[1:])
            if ref < 1:
                raise ValueError(f"sketch refs are 1-based: {t}")
            return ("ref", ref - 1), i + 1
        op = t.upper()
        if op not in ("SET_INTERSECT", "SET_UNION", "SET_DIFF"):
            raise ValueError(f"unknown theta set operator {t!r}")
        if i + 1 >= len(toks) or toks[i + 1] != "(":
            raise ValueError(f"{op} needs parenthesized args: {s!r}")
        args, i = [], i + 2
        while True:
            node, i = parse(i)
            args.append(node)
            if i >= len(toks):
                raise ValueError(f"unclosed {op} in {s!r}")
            if toks[i] == ",":
                i += 1
                continue
            if toks[i] == ")":
                i += 1
                break
            raise ValueError(f"bad token {toks[i]!r} in {s!r}")
        if len(args) < 2:
            raise ValueError(f"{op} needs at least two args: {s!r}")
        if op == "SET_DIFF" and len(args) != 2:
            raise ValueError(f"SET_DIFF is binary: {s!r}")
        return (op,) + tuple(args), i

    node, i = parse(0)
    if i != len(toks):
        raise ValueError(f"trailing tokens in theta set expression: {s!r}")
    return node


def max_ref(node) -> int:
    """Highest 0-based sketch index referenced by a parsed set AST."""
    if node[0] == "ref":
        return node[1]
    return max(max_ref(c) for c in node[1:])


def evaluate_set(node, sketches: list, k: int) -> tuple:
    """Parsed AST + [(theta, hashes)] per filter → (theta, hashes)."""
    op = node[0]
    if op == "ref":
        return sketches[node[1]]
    parts = [evaluate_set(c, sketches, k) for c in node[1:]]
    th, h = parts[0]
    for th2, h2 in parts[1:]:
        if op == "SET_UNION":
            th, h = merge(th, h, th2, h2, k)
        elif op == "SET_INTERSECT":
            th, h = intersect(th, h, th2, h2)
        else:
            th, h = a_not_b(th, h, th2, h2)
    return th, h
