"""On-device final reduce: ORDER-BY-aware group trim inside the kernel.

The reference runs its final combine + trim on the broker/server host
(BrokerReduceService + TableResizer): every server ships its FULL group
table, and the reduce walks it in numpy. On this engine the group table
already lives on the device — shipping all (G,) accumulators over a
~100ms host link just so the host can keep the top-K rows made the link,
not the kernel, the cost of every interactive group-by (ROADMAP item 1;
BENCH_r05: single-digit kernel ms under ~115ms p50s).

``apply_trim`` is the device-side replacement: applied AFTER the mesh
combine (so multi-shard tables trim exactly once, reusing the existing
psum/_combine_sorted_table merge algebra in parallel/mesh.py), it

1. computes the query's ORDER BY keys from the combined accumulators —
   group-by COLUMNS order by their global-dict id component (the global
   dictionary is sorted, so id order == value order, including strings),
   aggregations by their finalized value in float64 (the host reduce
   compares finalized float64 partials, engine/reduce.py);
2. sorts the table by (present-first, keys..., slot) with one
   multi-operand ``lax.sort`` — the trailing slot operand reproduces the
   host's stable-sort tie-break (present/slot order) bit for bit;
3. keeps the first ``tr_k`` rows (a runtime PARAM — one compiled
   pipeline serves any LIMIT within the same static bound) under the
   static pow2 bound ``T``, masking the rest with each reduction's
   NEUTRAL fill, and emits the kept rows' packed int64 group keys as
   ``trim_keys``.

Only the trimmed (T,) leaves + scalar stats cross the host link in the
packed buffer (engine/device.py _pack_outs) — the fetch for a trimmed
top-K group-by shrinks from O(G) accumulators to O(K) answer rows.

Policy mirrors engine/reduce.py exactly (single-sourced through
``reduce.trim_bound``): the SOLE-partial condition and the keep bound
decide where trimming is EXACT vs reference-approximate —

- ``mode="terminal"`` (the device batch is the whole answer and nothing
  merges after): keep ``offset+limit`` — exact, ORDER BY or not, because
  finalize's own ordering/slicing sees every row it would have kept.
- ``mode="partial"`` (sole local partial, but a broker merges server
  partials afterwards): keep ``max(5*(offset+limit), group_trim_size)``
  with ORDER BY only — byte-for-byte the policy trim_group_by applies to
  the same partial on the host, including its reference-inherited
  approximation (a globally-top-K-but-not-locally-top-K group can drop).
- HAVING / gapfill / post-aggregation order expressions / DISTINCT:
  no trim (the host reduce needs every group), exactly like
  trim_group_by.

``neutral_fill`` lives here (ops level, import-cycle-free) as the ONE
copy of the kernels' empty/masked fill convention — engine/device.py
re-exports it for the fully-pruned synthesis and blockskip cond padding
(pinned by tests/test_blockskip.py::TestKernelNeutralFills).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from pinot_tpu.ops import radix_groupby as radix_ops
from pinot_tpu.ops.join import next_pow2

# observability/stat leaves every pipeline emits regardless of shape —
# passed through the trim untouched (they are per-launch scalars or (S,)
# vectors, not group-table columns)
STAT_KEYS = frozenset((
    "doc_count", "seg_matched", "n_alive", "rows_filter",
    "blocks_total", "blocks_scanned", "n_groups_total",
))

# aggregations whose finalized value the device can order by; the field
# names the finalize produces (engine/aggspec.py → engine/reduce.py env)
ORDER_AGG_FIELDS = {
    "count": "count",
    "sum": "sum",
    "avg": "avg",
    "min": "min",
    "max": "max",
    "minmaxrange": "range",
}


def neutral_fill(name: str, dt):
    """The kernels' empty/masked fill for an output leaf, by naming
    convention — ONE copy shared by the fully-pruned synthesis
    (engine/device.py _neutral_outs), the blockskip cond-branch table
    padding, the sorted-regime empty-slot fills, and the device trim's
    beyond-kept masking, so the sites can't drift: extremal sentinels
    for min/max/time planes, -inf for the arg-time value planes ("no
    winner" encoding), the radix key sentinel for sorted tables and
    trimmed keys, zero elsewhere."""
    kind = np.dtype(dt).kind
    if name in ("skeys", "trim_keys"):
        return radix_ops.INT64_SENTINEL
    if name.endswith(("_vtmin", "_vtmax")):
        return -np.inf
    if name.endswith(("_min", "_tmin")):
        return np.iinfo(dt).max if kind in "iu" else np.inf
    if name.endswith(("_max", "_tmax")):
        return np.iinfo(dt).min if kind in "iu" else -np.inf
    return 0


def trim_keep_count(q, mode: str, group_trim_size: int = 5000) -> int:
    """How many groups the trim keeps — the EXACT bound (rides as the
    ``tr_k`` runtime param; the static template bound is its pow2
    ceiling). Mirrors engine/reduce.py trim_group_by via trim_bound so
    the two policies cannot drift."""
    if mode == "terminal":
        return q.offset + q.limit
    from pinot_tpu.engine.reduce import trim_bound

    return trim_bound(q, group_trim_size)


def plan_trim(q, group_exprs, aggs, shape: str, table_len: int,
              mode, group_trim_size: int = 5000):
    """Host-side static analysis → trim spec ``(T, order_sig)`` or None.

    ``group_exprs`` / ``aggs`` are the template-build enumerations (the
    order_sig indexes into them); ``table_len`` is the full table the
    trim would shrink (dense num_groups, or sorted_k for the radix
    regime); ``mode`` is None (not a sole partial — trimming would lose
    contributions a later merge needs), "partial" (sole local partial,
    server→broker), or "terminal" (the whole answer).

    The spec is hashable and literal-free: LIMIT/OFFSET ride as the
    ``tr_k`` param, only their pow2 ceiling ``T`` shapes the template.
    """
    if mode not in ("terminal", "partial"):
        return None
    if shape not in ("groupby", "groupby_sorted"):
        return None
    if q.distinct or q.having is not None:
        return None
    from pinot_tpu.common.options import bool_option

    opts = q.options_ci()
    if bool_option(opts, "usedevicereduce", None) is False:
        return None
    if opts.get("gapfillbucketms") is not None:
        return None  # gapfill synthesizes buckets from the FULL group set
    order = []
    if q.order_by:
        for ob in q.order_by:
            e = ob.expression
            ent = None
            for j, g in enumerate(group_exprs):
                if e == g:
                    ent = ("col", j, bool(ob.ascending))
                    break
            if ent is None:
                for i, a in enumerate(aggs):
                    if e == a and a.name in ORDER_AGG_FIELDS:
                        ent = ("agg", i, ORDER_AGG_FIELDS[a.name],
                               bool(ob.ascending))
                        break
            if ent is None:
                return None  # post-aggregation order expr: host reduce
            order.append(ent)
    elif mode != "terminal":
        # a server partial without ORDER BY has no trim the broker merge
        # could survive — exactly trim_group_by's refusal
        return None
    k = trim_keep_count(q, mode, group_trim_size)
    if k <= 0:
        return None
    T = next_pow2(k)
    if T >= table_len:
        return None  # nothing to shrink; the full table is the answer
    return (T, tuple(order))


def _desc(v):
    """Descending sort key. Integer keys here are non-negative (ids,
    counts, slot indexes), so two's-complement negation is order-exact;
    float keys mirror the host's ``-v`` in float64 (engine/host.py
    _negate)."""
    return -v


def _f64(v):
    return v.astype(jnp.float64)


def apply_trim(outs: dict, params: dict, template, spec) -> dict:
    """Traced post-combine trim: outs (full table) → outs (T-row table).

    Runs INSIDE the jitted pipeline after the cross-shard combine (and
    after the terminal sketch finalize when one applies), so the packed
    buffer the host fetches only carries the kept rows. Emits

    - ``trim_keys``  (T,) int64 packed group keys of the kept rows
      (mixed-radix over group_cards — the dense gid itself, or the
      sorted regime's skeys), INT64_SENTINEL beyond ``trim_n``;
    - ``trim_n``     scalar int64 = min(n_present, tr_k);
    - ``n_present_total`` scalar int64 — the UNtrimmed non-empty group
      count, so the host can detect a numGroupsLimit truncation it can
      no longer reproduce (it falls back to the host path rather than
      let the trim reorder the limit's drop policy);
    - every group-table leaf gathered to (T, ...) with neutral fills
      beyond ``trim_n``.
    """
    shape, _f, _gcols, group_cards, _aggs, _k, _final = template[:7]
    T, order = spec
    tr_k = params["tr_k"].astype(jnp.int64)
    gcount = outs["gcount"]
    G = gcount.shape[0]
    present = gcount > 0
    n_present = jnp.sum(present, dtype=jnp.int64)
    if shape == "groupby_sorted":
        keys64 = outs["skeys"].astype(jnp.int64)
    else:
        keys64 = jnp.arange(G, dtype=jnp.int64)

    def col_component(j: int):
        stride = 1
        for c in group_cards[j + 1:]:
            stride *= c
        return (keys64 // stride) % group_cards[j]

    # sort operands: empties last, then the ORDER BY keys, then the slot
    # index — the host's stable lexsort tie-break (present order) made
    # explicit, so kept sets and their sequence match the host bit-exact
    operands = [jnp.where(present, jnp.int32(0), jnp.int32(1))]
    for ent in order:
        if ent[0] == "col":
            _tag, j, asc = ent
            k = col_component(j)
            operands.append(k if asc else _desc(k))
        else:
            _tag, i, field, asc = ent
            if field == "count":
                v = gcount.astype(jnp.int64)
            elif field == "sum":
                v = _f64(outs[f"a{i}_sum"])
            elif field == "avg":
                v = _f64(outs[f"a{i}_sum"]) / _f64(gcount)
            elif field == "min":
                v = _f64(outs[f"a{i}_min"])
            elif field == "max":
                v = _f64(outs[f"a{i}_max"])
            else:  # minmaxrange
                v = _f64(outs[f"a{i}_max"]) - _f64(outs[f"a{i}_min"])
            operands.append(v if asc else _desc(v))
    operands.append(jnp.arange(G, dtype=jnp.int64))
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=len(operands))
    perm = sorted_ops[-1][:T]
    valid = jnp.arange(T, dtype=jnp.int64) < jnp.minimum(n_present, tr_k)

    trimmed = {}
    for name, v in outs.items():
        if name in STAT_KEYS:
            trimmed[name] = v
            continue
        if name == "skeys":
            continue  # replaced by trim_keys below
        g = v[perm]
        fill = neutral_fill(name, g.dtype)
        mask = valid.reshape((T,) + (1,) * (g.ndim - 1))
        trimmed[name] = jnp.where(mask, g, jnp.asarray(fill, g.dtype))
    trimmed["trim_keys"] = jnp.where(
        valid, keys64[perm], radix_ops.INT64_SENTINEL)
    trimmed["trim_n"] = jnp.minimum(n_present, tr_k)
    trimmed["n_present_total"] = n_present
    return trimmed
