"""Per-segment access-temperature telemetry (ISSUE 11, tentpole 3).

ROADMAP item 3's tiered lifecycle (object-store/cold → host-mmap/warm →
device/hot) needs a per-segment temperature signal to drive promotion
and demotion — nothing recorded one until now.  This module is the
server-side half: exponentially-decayed per-segment access counters
(accesses/s and approximate bytes-scanned/s at a configurable half
life) plus lifetime totals, updated on every query that touches the
segment (sealed AND consuming — a chunklet-backed consuming segment
counts under its segment name, which is the granularity the lifecycle
moves).  The snapshot piggybacks in the registry heartbeat exactly like
PR 10's scheduler pressure, the controller aggregates it across
instances behind ``GET /tables/{t}/heat``
(controller/http_api.py), and ``tools/clusterstat.py`` renders it.

The decayed-rate math is the standard lazy-decay counter: on each
touch, the stored rate first decays by ``0.5 ** (dt / half_life)`` and
then absorbs the new observation.  Reads decay the same way without
mutating, so an idle segment's reported temperature falls toward zero
between queries — the demotion signal — while the lifetime totals keep
the audit trail.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class SegmentHeatTracker:
    """Decayed per-(table, segment) access/frequency/bytes counters."""

    def __init__(self, half_life_s: float = 300.0,
                 max_entries: int = 8192):
        self.half_life_s = max(1.0, float(half_life_s))
        self.max_entries = max(16, int(max_entries))
        self._lock = threading.Lock()
        # (table, segment) -> [rate, bytes_rate, accesses, bytes, last_ts]
        # insertion order doubles as the LRU for the entry bound
        self._entries: dict = {}

    # ---- recording -------------------------------------------------------
    def _decay(self, value: float, dt_s: float) -> float:
        if dt_s <= 0:
            return value
        return value * 0.5 ** (dt_s / self.half_life_s)

    def note(self, table: str, segment: str, bytes_scanned: int = 0,
             now: Optional[float] = None) -> None:
        """Record one query access of ``segment``. ``bytes_scanned`` is
        the caller's APPROXIMATION of bytes the scan touched (the server
        uses rows x referenced columns x 4 — a admission-cost proxy, not
        an exact I/O meter)."""
        now = time.time() if now is None else now
        key = (table, segment)
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is None:
                ent = [0.0, 0.0, 0, 0, now]
            dt = now - ent[4]
            ent[0] = self._decay(ent[0], dt) + 1.0
            ent[1] = self._decay(ent[1], dt) + float(bytes_scanned)
            ent[2] += 1
            ent[3] += int(bytes_scanned)
            ent[4] = now
            self._entries[key] = ent  # LRU touch
            while len(self._entries) > self.max_entries:
                self._entries.pop(next(iter(self._entries)))

    # ---- export ----------------------------------------------------------
    def size(self) -> int:
        with self._lock:
            return len(self._entries)

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()

    def iter_all(self, now: Optional[float] = None):
        """Full-iteration export (ISSUE 12): yields ``(table, segment,
        record)`` for EVERY tracked entry with decay applied as of
        ``now`` — the TierManager's demotion input. ``snapshot``'s
        top-N cap exists for the bounded heartbeat payload; demotion
        decisions need exactly the cold tail it drops (a table with >32
        segments would otherwise never see its coldest ones ranked)."""
        now = time.time() if now is None else now
        with self._lock:
            items = [(t, s, list(e)) for (t, s), e in self._entries.items()]
        for t, s, (rate, brate, acc, byt, last) in items:
            dt = now - last
            yield t, s, {
                "rate": self._decay(rate, dt),
                "bytesRate": self._decay(brate, dt),
                "accesses": acc,
                "bytes": byt,
                "lastAccessTs": last,
            }

    def snapshot(self, top_per_table: Optional[int] = 32,
                 now: Optional[float] = None) -> dict:
        """{table: {segment: {...}}} with decay applied as of ``now``,
        capped at the ``top_per_table`` hottest segments per table (the
        heartbeat payload must stay bounded at million-segment scale —
        cold segments are exactly the ones whose absence means "cold").
        ``top_per_table=None`` disables the cap (the full-export form for
        in-process consumers; heartbeats keep the capped default).

        ``rate`` / ``bytesRate`` are decayed half-life accumulators, NOT
        per-second rates: comparable across segments under one half
        life, which is all the promotion policy ranks on."""
        now = time.time() if now is None else now
        per_table: dict = {}
        for t, s, rec in self.iter_all(now=now):
            rec["rate"] = round(rec["rate"], 4)
            rec["bytesRate"] = round(rec["bytesRate"], 1)
            rec["lastAccessTs"] = round(rec["lastAccessTs"], 3)
            per_table.setdefault(t, {})[s] = rec
        out = {}
        for t, segs in per_table.items():
            ranked = sorted(segs.items(), key=lambda kv: -kv[1]["rate"])
            if top_per_table is not None:
                ranked = ranked[:max(1, top_per_table)]
            out[t] = dict(ranked)
        return out
