"""Server role: segment hosting + per-segment query execution.

Equivalent of the reference's server stack (pinot-server/: BaseServerStarter
wiring InstanceDataManager + QueryExecutor + transport, ServerInstance.java:
79-128; the Helix OFFLINE→ONLINE/CONSUMING state model,
SegmentOnlineOfflineStateModelFactory.java:75-235) — re-shaped for the
registry's level-triggered model: a sync loop reconciles locally-loaded
segments against the registry's assignment (download/load new, unload
removed), replacing push-based Helix state transitions, and starts stream
consumers for assigned realtime partitions.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

from pinot_tpu.cluster.registry import (
    ClusterRegistry,
    InstanceInfo,
    Role,
    SegmentRecord,
    SegmentState,
)
from pinot_tpu.common import faults
from pinot_tpu.common.deadline import Deadline, QueryTimeout
from pinot_tpu.engine.datatable import encode, encode_error
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.engine.reduce import trim_group_by
from pinot_tpu.engine.scheduler import (
    SchedulerSaturated,
    make_scheduler,
)
from pinot_tpu.query.optimizer import optimize_query
from pinot_tpu.sql.compiler import compile_query
from pinot_tpu.storage.segment import ImmutableSegment
from pinot_tpu.transport.grpc_transport import QueryServerTransport, parse_instance_request

log = logging.getLogger("pinot_tpu.server")


def _apply_request_overrides(q, req: dict):
    """Physical-table override + the hybrid time-boundary predicate from
    the instance request, shared by the unary and streaming paths (dropping
    the timeFilter on either path double-reads the hybrid overlap)."""
    import dataclasses

    from pinot_tpu.query.context import (
        Expression,
        FilterNode,
        Predicate,
        PredicateType,
    )

    if req.get("table"):
        q = dataclasses.replace(q, table_name=req["table"])
    tf = req.get("timeFilter")
    if tf:
        pred = Predicate(
            PredicateType.RANGE, Expression.identifier(tf["column"]),
            upper=tf["value"] if tf["op"] == "le" else None,
            lower=tf["value"] if tf["op"] == "gt" else None,
            lower_inclusive=False,
        )
        node = FilterNode.pred(pred)
        new_filter = node if q.filter is None else FilterNode.and_(q.filter, node)
        q = dataclasses.replace(q, filter=new_filter)
    return q


def _hbm_peak_if_probed():
    """Scrape-safe HBM-peak gauge (ops/roofline.py): the cached probe
    value or None — never triggers the measurement from a metrics poll."""
    from pinot_tpu.ops import roofline

    return roofline.peak_if_probed()


class ServerInstance:
    def __init__(self, instance_id: str, registry: ClusterRegistry,
                 data_dir: str, host: str = "127.0.0.1", port: int = 0,
                 sync_interval_s: float = 0.2, device_executor="auto",
                 max_concurrent_queries: int = 8, max_queued_queries: int = 32,
                 group_trim_size: int = 5000, scheduler_name: str = None,
                 tls="auto", tags=(), compile_concurrency: int = None,
                 tier_overrides: dict = None,
                 exchange_buffer_bytes: int = None):
        self.instance_id = instance_id
        self.registry = registry
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.engine = QueryEngine(device_executor=device_executor,
                                  host_name=instance_id)
        # transport threads must cover running + queued queries, or requests
        # queue invisibly in grpc's executor and time out as transport
        # failures (poisoning the broker's failure detector) before the
        # scheduler's in-band rejection can ever fire
        if tls == "auto":
            from pinot_tpu.common.tls import TlsConfig

            tls = TlsConfig.from_config()
        from pinot_tpu.server.peer import serve_segment_tar

        self.transport = QueryServerTransport(
            self._handle_submit, host=host, port=port,
            max_workers=max_concurrent_queries + max_queued_queries + 2,
            submit_streaming_fn=self._handle_submit_streaming,
            fetch_segment_fn=lambda req: serve_segment_tar(self, req),
            execute_stage_fn=self._handle_execute_stage,
            exchange_transfer_fn=self._handle_exchange_transfer,
            tls=tls,
        )
        self._tls = tls
        # distributed stage-2 mailboxes (ISSUE 16, query2/exchange.py):
        # per-exchange receive buffers with a byte ceiling past which
        # payloads spill to mmap'd .npy files under the data dir (the
        # warm-tier spill idea) — the test knob ``exchange_buffer_bytes``
        # simulates a build side exceeding one process's RAM budget
        from pinot_tpu.query2.exchange import ExchangeRegistry

        self.exchange_buffer_bytes = int(
            exchange_buffer_bytes if exchange_buffer_bytes is not None
            else os.environ.get("PINOT_TPU_EXCHANGE_BUFFER_BYTES",
                                256 << 20))
        self.exchanges = ExchangeRegistry(
            os.path.join(data_dir, "exchange_spill"),
            self.exchange_buffer_bytes)
        # server→server transfer channels, one per peer endpoint (the
        # broker's per-instance channel pool pattern); closed in stop()
        self._peer_channels: dict = {}
        self._peer_lock = threading.Lock()
        self.sync_interval_s = sync_interval_s
        from pinot_tpu.common.config import Configuration

        conf = Configuration()
        if scheduler_name is None:
            # config-selected like the reference's
            # pinot.server.query.scheduler.name (fcfs | tokenbucket)
            scheduler_name = conf.get(
                "pinot.server.query.scheduler.name", "fcfs")
        # graceful-shutdown drain window (the reference's
        # pinot.server.shutdown.timeout.ms shutdown hook): stop() rejects
        # NEW submits immediately (SERVER_SHUTTING_DOWN — retriable at the
        # broker) and waits up to this long for in-flight queries to drain
        self.drain_timeout_s = conf.get_float(
            "pinot.server.shutdown.drain.timeout.ms", 10_000.0) / 1e3
        # adopt-path peer-fetch retry window + per-attempt peer download
        # timeout (previously hardcoded 10 s / 60 s)
        self.peer_retry_timeout_s = conf.get_float(
            "pinot.server.segment.peer.retry.timeout.ms", 10_000.0) / 1e3
        self.peer_download_timeout_s = conf.get_float(
            "pinot.server.segment.peer.download.timeout.ms", 60_000.0) / 1e3
        # registry heartbeat cadence (load + freshness view), decoupled
        # from the (faster) segment-sync tick — see _sync_loop
        self.heartbeat_interval_s = conf.get_float(
            "pinot.server.heartbeat.interval.ms", 2_000.0) / 1e3
        # per-segment access-temperature telemetry (ISSUE 11,
        # server/heat.py): decayed access/bytes counters updated on every
        # query, piggybacked in the heartbeat like scheduler pressure and
        # aggregated at the controller (/tables/{t}/heat) — the input
        # ROADMAP 3's tier promotion/demotion policy will consume
        from pinot_tpu.server.heat import SegmentHeatTracker

        self.heat = SegmentHeatTracker(
            half_life_s=conf.get_float(
                "pinot.server.heat.halflife.ms", 300_000.0) / 1e3,
            max_entries=int(conf.get_float(
                "pinot.server.heat.max.segments", 8192)))
        self.heat_top_per_table = int(conf.get_float(
            "pinot.server.heat.heartbeat.top.segments", 32))
        # tiered segment lifecycle (ISSUE 12, server/tiering.py): the
        # TierManager consumes the heat tracker's UNCAPPED iter_all plus
        # the device batch hit/miss counters and drives hot/warm/cold
        # transitions from the sync loop; opt-in
        # (pinot.server.tier.enabled) so tier-less deployments keep the
        # all-hot behavior byte-for-byte
        from pinot_tpu.server.tiering import TierManager

        self.tiers = TierManager(self, overrides=tier_overrides)
        self._last_serving = None  # last published ExternalView payload
        self._shutting_down = False
        self._inflight_queries = 0
        self._inflight_cond = threading.Condition()
        self.scheduler = make_scheduler(
            scheduler_name, max_concurrent=max_concurrent_queries,
            max_queued=max_queued_queries)
        # pre-admission compile bound: SQL compiles on the gRPC transport
        # thread BEFORE scheduler admission (group/timeout come from the
        # compiled context), previously limited only by grpc max_workers —
        # a saturated server could burn every transport thread parsing
        # queries it would then reject
        self._compile_sem = threading.BoundedSemaphore(
            compile_concurrency if compile_concurrency is not None
            else max(2, max_concurrent_queries))
        self._compile_timeout_s = 5.0
        # launch coalescer gate: micro-batch windows open only under real
        # scheduler pressure (engine/inflight.py LaunchCoalescer)
        dev = getattr(self.engine, "device", None)
        if dev is not None and getattr(dev, "coalescer", None) is not None:
            dev.coalescer.pressure_fn = self.scheduler.pressure
        self.group_trim_size = group_trim_size
        from pinot_tpu.common.metrics import get_metrics

        self.metrics = get_metrics("server")
        # every callable gauge this instance registers is TRACKED so
        # stop() can unregister the lot — get_metrics registries are
        # process-global, and a forgotten gauge closure pins the stopped
        # instance (and its segments) forever while reporting stale
        # values for a restarted one (ISSUE 7 lifecycle audit)
        self._registered_gauges: list = []
        self._register_gauge("segmentsLoaded", lambda: sum(
            len(t.segments) for t in self.engine.tables.values()))
        self._register_gauge("schedulerRejected",
                             lambda: self.scheduler.num_rejected)
        # temperature + roofline gauges (ISSUE 11): tracked segments and
        # the per-process HBM peak (None until the first accounted device
        # flight probes it — a metrics scrape never spends device time)
        self._register_gauge("heatTrackedSegments",
                             lambda: self.heat.size())
        self._register_gauge("hbmPeakGbps", _hbm_peak_if_probed)
        if self.tiers.enabled:
            # tier lifecycle visibility (registered only on tiering
            # servers — same no-churn rule as the result-cache gauges)
            self._register_gauge(
                "tierColdSegments",
                (lambda _t=self.tiers: _t.stats()["cold_segments"]))
            self._register_gauge(
                "tierHydrations",
                (lambda _t=self.tiers: _t.hydrations))
            self._register_gauge(
                "tierDemotions",
                (lambda _t=self.tiers: _t.demotions_warm
                 + _t.demotions_cold))
        # HBM / batch-LRU accounting (DeviceExecutor.hbm_stats): resident
        # bytes, cache traffic, and bytes the width planning saved — the
        # operational view of ISSUE 5's narrowing (a shrinking
        # deviceNarrowSavedBytes alongside rising evictions means batches
        # stopped fitting)
        if dev is not None:
            # the device-reduce trim and the server's host trim must keep
            # ONE policy bound (engine/reduce.py trim_bound)
            dev.group_trim_size = group_trim_size
            # counters are plain executor ints (GIL-atomic reads); only
            # the byte gauges walk the batch list — one lightweight sum
            # each, not a full hbm_stats() snapshot 5x per scrape
            for gname, attr in (("deviceBatchHits", "batch_hits"),
                                ("deviceBatchMisses", "batch_misses"),
                                ("deviceBatchEvictions", "batch_evictions"),
                                ("deviceLaunchFailures", "launch_failures"),
                                # device partials cache (sub-RTT serving):
                                # repeat-query hit traffic + resident
                                # bytes the cached packed buffers pin
                                ("devicePartialsCacheBytes",
                                 "partials_bytes"),
                                ("devicePartialsCacheHits", "partials_hits"),
                                ("devicePartialsCacheMisses",
                                 "partials_misses"),
                                ("devicePartialsCacheEvictions",
                                 "partials_evictions")):
                self._register_gauge(
                    gname, (lambda _a=attr, _d=dev: getattr(_d, _a)))
            self._register_gauge(
                "deviceResidentBytes",
                (lambda _d=dev: _d.resident_bytes()))
            self._register_gauge(
                "deviceNarrowSavedBytes",
                (lambda _d=dev: _d.narrow_saved_bytes()))
            # quarantine breaker visibility: pipelines the device-error
            # recovery has routed to host (a non-zero value alongside
            # rising deviceLaunchFailures = a poisoned template/batch)
            self._register_gauge(
                "deviceQuarantinedPipelines",
                (lambda _d=dev: len(_d._quarantined)))
        self._stop = threading.Event()
        self._sync_thread: Optional[threading.Thread] = None
        self._realtime_managers: dict = {}  # table -> RealtimeTableDataManager
        self.queries_served = 0
        self.tags = tuple(tags)  # tier placement tags (Helix tag analog)

    def _register_gauge(self, name: str, fn) -> None:
        """Callable gauge tagged with this instance id, recorded for
        symmetric teardown in stop() (removeGauge-on-shutdown audit)."""
        self.metrics.gauge(name, fn, tag=self.instance_id)
        self._registered_gauges.append(name)

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self.transport.start()
        from pinot_tpu.common.environment import failure_domain_tag

        tags = list(self.tags)
        fd_tag = failure_domain_tag()
        if fd_tag and fd_tag not in tags:
            tags.append(fd_tag)  # assigner spreads replicas across domains
        self.registry.register_instance(
            InstanceInfo(self.instance_id, Role.SERVER,
                         host=self.transport.host, grpc_port=self.transport.port,
                         tags=tags)
        )
        self._sync_once()  # load assigned segments before serving
        self._sync_thread = threading.Thread(
            target=self._sync_loop, name=f"sync-{self.instance_id}", daemon=True
        )
        self._sync_thread.start()

    def stop(self, drain_timeout_s: float = None) -> None:
        """Graceful shutdown: reject NEW submits immediately with a
        retriable SERVER_SHUTTING_DOWN (the broker re-routes their
        segment lists to replicas), then drain in-flight queries for up
        to the configured window
        (``pinot.server.shutdown.drain.timeout.ms``; the old behavior
        was an unconditional hard stop) before tearing transport down."""
        drain = self.drain_timeout_s if drain_timeout_s is None \
            else drain_timeout_s
        self._shutting_down = True
        drain_deadline = time.monotonic() + max(0.0, drain)
        with self._inflight_cond:
            while self._inflight_queries > 0:
                left = drain_deadline - time.monotonic()
                if left <= 0:
                    log.warning(
                        "shutdown drain window (%.1fs) elapsed with %d "
                        "queries in flight", drain, self._inflight_queries)
                    break
                self._inflight_cond.wait(min(left, 0.1))
        self._stop.set()
        # drop EVERY callable gauge this instance registered (tracked in
        # _register_gauge): their closures would otherwise pin this
        # instance (and its loaded segments) in the process-global
        # registry, and a restarted same-id instance would alias them
        for gname in self._registered_gauges:
            self.metrics.remove_gauge(gname, tag=self.instance_id)
        self._registered_gauges = []
        if self._sync_thread is not None:
            self._sync_thread.join(5)
        self.tiers.stop()
        for mgr in self._realtime_managers.values():
            mgr.stop(commit_remaining=False)
        self.transport.stop()
        self.exchanges.close()
        with self._peer_lock:
            peers, self._peer_channels = \
                list(self._peer_channels.values()), {}
        for ch in peers:
            try:
                ch.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self.registry.drop_instance(self.instance_id)

    # ---- query path ------------------------------------------------------
    @staticmethod
    def _request_deadline(req: dict, q=None):
        """Per-query Deadline. The broker-shipped REMAINING budget
        (``timeoutMs`` in the instance request — what the broker had left
        at send time) wins; ``SET timeoutMs`` from the compiled options
        covers direct/embedded submits that never crossed a broker. Every
        downstream wait (compile semaphore, scheduler admission, device
        fetch, host fallback gate) is bounded by it and aborts with a
        typed QUERY_TIMEOUT instead of running to completion after the
        client gave up. None = no budget."""
        v = req.get("timeoutMs")
        if v is None and q is not None:
            v = q.options_ci().get("timeoutms")
        if v is None:
            return None
        return Deadline.after_ms(max(1.0, float(v)))

    @staticmethod
    def _scheduler_group(q, req: dict) -> str:
        """Tenant key for token-bucket priority. The broker-resolved
        WORKLOAD (auth principal / SET workloadName — ISSUE 14) wins when
        the instance request carries one, so the server's weighted-fair
        slot accounting isolates TENANTS, not just tables. Fallback: the
        COMPILED table name (TableBasedGroupMapper analog) — a regex over
        raw SQL would let a literal containing " FROM x" misattribute the
        query to the wrong bucket. Normalized (lowercase, physical-type
        suffix stripped) so offline/realtime halves of one table share
        ONE bucket — distinct raw strings would each mint a fresh
        full-burst group and defeat fairness."""
        wl = req.get("workload")
        if wl:
            return f"tenant:{str(wl).lower()}"
        name = (req.get("table") or q.table_name or "default").lower()
        for suffix in ("_offline", "_realtime"):
            if name.endswith(suffix):
                name = name[: -len(suffix)]
        return name

    @staticmethod
    def _scheduler_weight(q, req: dict) -> float:
        """Weighted-fair slot weight from the request's priority class
        (broker-stamped; SET priorityClass covers direct submits).
        Unknown/absent class = weight 1.0 — today's behavior exactly."""
        from pinot_tpu.engine.scheduler import PRIORITY_WEIGHTS

        prio = req.get("priority") or q.options_ci().get("priorityclass")
        return PRIORITY_WEIGHTS.get(str(prio), 1.0) if prio else 1.0

    def _compile_admitted(self, sql: str, deadline: Deadline = None):
        """SQL compile bounded by a small semaphore (ADVICE r5): compile
        runs pre-admission on the transport thread, so without a bound a
        saturated server burns unbounded CPU parsing queries it will
        reject. The semaphore wait ships as the ``compileQueueMs`` timer;
        waiting out the bound is a scheduling rejection, not a server
        fault — unless the query's own deadline expired first, which is a
        QUERY_TIMEOUT."""
        t0 = time.perf_counter()
        wait_s = self._compile_timeout_s if deadline is None \
            else deadline.clamp(self._compile_timeout_s)
        if not self._compile_sem.acquire(timeout=wait_s):
            if deadline is not None and deadline.expired():
                raise QueryTimeout(
                    "QUERY_TIMEOUT at compile admission: budget exhausted "
                    "waiting for a compile slot")
            raise SchedulerSaturated(
                f"compile queue full (no compile slot within "
                f"{self._compile_timeout_s}s)")
        try:
            self.metrics.time_ms(
                "compileQueueMs", (time.perf_counter() - t0) * 1e3)
            return optimize_query(compile_query(sql))
        finally:
            self._compile_sem.release()

    def _handle_submit(self, request: bytes) -> bytes:
        """Unary query submit, split into a LAUNCH phase under the
        scheduler slot (compile → admission → segment acquire → device
        dispatch + host partials) and a FETCH phase AFTER the slot is
        released (the blocking device_get link wait + trim + encode):
        N concurrent queries overlap their host↔device round trips
        instead of holding N slots through them
        (engine.execute_segments_async / engine/inflight.py).

        The ``queries`` metric counts at RECEIVE time, before SQL compile,
        so ``queryErrors`` (which a parse error increments) can never
        exceed ``queries`` on the dashboard. Compile runs BEFORE admission
        — the scheduler group and timeout come from the compiled context,
        and a parse error must not burn a concurrency slot — bounded by
        the compile semaphore (_compile_admitted).

        Shutdown drain: once stop() flips ``_shutting_down``, new submits
        are rejected immediately with a retriable SERVER_SHUTTING_DOWN
        (the broker re-routes them to replicas) while queries already
        counted in ``_inflight_queries`` drain inside the configured
        window."""
        req = parse_instance_request(request)
        with self._inflight_cond:
            if self._shutting_down:
                self.metrics.count("queriesRejected")
                return encode_error(
                    "server_shutting_down",
                    f"SERVER_SHUTTING_DOWN: {self.instance_id} is "
                    f"draining for shutdown")
            self._inflight_queries += 1
        try:
            return self._submit_inner(req)
        finally:
            with self._inflight_cond:
                self._inflight_queries -= 1
                self._inflight_cond.notify_all()

    def _submit_inner(self, req: dict) -> bytes:
        from pinot_tpu.common import trace

        deadline = self._request_deadline(req)
        # broker-stamped tracing (traceEnabled + traceId ride the
        # instance request, retries/hedges included): the tracer exists
        # BEFORE compile so the compile phase itself is a span. A direct
        # submit that only carries SET trace=true in its SQL gets its
        # tracer after compile (no compile span) in _handle_submit_launch.
        tracer = trace.Tracer(req.get("traceId")) \
            if req.get("traceEnabled") else None
        try:
            self.metrics.count("queries")
            with trace.span("server.compile", tracer):
                q = self._compile_admitted(req["sql"], deadline)
            if deadline is None:
                # no broker-shipped budget: fall back to SET timeoutMs
                # from the now-compiled options (embedded submits)
                deadline = self._request_deadline(req, q)
            # NOTE: the latency timer lives inside the launch/fetch pair —
            # wrapping the scheduler here would fold rejection queue-waits
            # into server.query and poison latency dashboards under load
            if faults.ACTIVE:
                # scheduler.admit chaos seam (ISSUE 14): starve admission
                # deterministically — an injected error is a typed
                # scheduling rejection (the server is healthy; the broker
                # must see the same QUERY_SCHEDULING_TIMEOUT shape a real
                # full queue produces, never a transport fault or a hang)
                try:
                    faults.inject("scheduler.admit",
                                  target=self.instance_id,
                                  bound_ms=None if deadline is None
                                  else deadline.remaining_ms())
                except faults.FaultInjected as e:
                    raise SchedulerSaturated(
                        f"admission starved (injected): {e}") from e
            acct: dict = {}
            finish = self.scheduler.run(
                lambda: self._handle_submit_launch(req, q, acct, deadline,
                                                   tracer),
                queue_timeout_s=None if deadline is None
                else max(0.001, deadline.remaining_s()),
                group=self._scheduler_group(q, req),
                stats_out=acct,
                weight=self._scheduler_weight(q, req))
            # slot released: the link wait below must not hold admission
            return finish()
        except faults.FaultInjected:
            # injected server crash: escape the in-band error path — the
            # RPC must die at the transport level, like a process kill
            raise
        except QueryTimeout as e:
            # the propagated deadline expired at one of the waits: typed
            # in-band partial (errorCode 250 shape); the server is healthy
            self.metrics.count("queryTimeouts")
            return encode_error("query_timeout", str(e))
        except SchedulerSaturated as e:
            if deadline is not None and deadline.expired():
                self.metrics.count("queryTimeouts")
                return encode_error(
                    "query_timeout",
                    f"QUERY_TIMEOUT at scheduler admission: {e}")
            # admission rejection is a query-level error: the server is
            # healthy (broker must not poison its failure detector)
            self.metrics.count("queriesRejected")
            return encode_error("query_error", f"QUERY_SCHEDULING_TIMEOUT: {e}")
        except Exception as e:  # noqa: BLE001 — query errors ship in-band
            self.metrics.count("queryErrors")
            return encode_error("query_error", f"{type(e).__name__}: {e}")

    def _handle_submit_launch(self, req: dict, q, acct: dict = None,
                              deadline: Deadline = None, tracer=None):
        """LAUNCH phase (runs under the scheduler slot) → zero-arg FETCH
        closure the transport thread invokes after the slot is released.
        Segment refs, the latency timer, and the tracer span BOTH phases;
        cleanup lives in the closure's finally (launch failures clean up
        here and re-raise into the submit error path).

        The tracer is EXPLICIT (common/trace.py): it was minted in
        _submit_inner from the broker-stamped traceEnabled/traceId (or
        here, for direct submits whose SQL says SET trace=true) and rides
        by reference through the engine, the device launch handles, and
        the fetch closure — the PR-2 launch/fetch thread split and
        coalesced cohorts record onto the right query's trace."""
        import time as _time

        from pinot_tpu.common import trace
        from pinot_tpu.common.trace import span

        t_cpu = _time.thread_time_ns()
        # "queries" was already counted at receive time (_handle_submit),
        # before compile/admission
        timer = self.metrics.timed("query")
        timer.__enter__()
        if tracer is None and q.options_ci().get("trace"):
            tracer = trace.Tracer(req.get("traceId"))
        if tracer is not None and acct:
            # the scheduler published its admission wait before running
            # this fn — back-fill it as the queue phase
            tracer.add_ms("server.queue", acct.get("scheduler_wait_ms", 0.0))
        tdm, acquired = None, []

        def cleanup():
            if tdm is not None:
                tdm.release(acquired)
            timer.__exit__()

        try:
            q = _apply_request_overrides(q, req)
            tdm = self.engine.tables.get(q.table_name)
            wanted = set(req["segments"])
            acquired = [] if tdm is None else tdm.acquire()
            segments = [s for s in acquired if s.name in wanted]
            if not segments:
                # benign routing race (segments moved since the broker's
                # external-view read): broker skips this partial
                err = encode_error(
                    "no_segments",
                    f"server {self.instance_id} hosts none of the "
                    f"requested segments for table {q.table_name!r}",
                )

                def finish_missing():
                    try:
                        return err
                    finally:
                        cleanup()

                return finish_missing
            # requested-but-missing segments (assignment raced ahead of
            # loading) are simply absent from this partial, like the
            # reference's missing-segment accounting
            if faults.ACTIVE:
                # injected mid-query server crash: segments acquired, the
                # query is "executing" — the raise escapes in-band
                # handling (see _submit_inner) and kills the RPC at the
                # transport level; cleanup() still runs via the
                # BaseException path so the process itself stays sound
                faults.inject("server.crash", target=self.instance_id)
            from pinot_tpu.common import freshness

            # freshness snapshot BEFORE the scan: a mutation landing
            # mid-query must make the recorded epoch look stale to the
            # broker result cache (conservative re-scatter), never stamp
            # pre-mutation rows with the post-mutation epoch
            epoch_at_start = freshness.epoch(q.table_name)
            with span("server.execute", tracer):
                # the fetch-time host fallback (sorted-table overflow) is
                # heavy CPU work on a slot-free thread: re-admit it
                # through the scheduler so a fallback storm can't escape
                # the concurrency cap (saturation rejects it in-band);
                # the admission wait is bounded by the query's REMAINING
                # deadline at gate time, not the original budget
                gate = (lambda fn: self.scheduler.run(
                    fn, queue_timeout_s=None if deadline is None
                    else max(0.001, deadline.remaining_s()),
                    group=self._scheduler_group(q, req),
                    weight=self._scheduler_weight(q, req)))
                fetch_merged = self.engine.execute_segments_async(
                    q, segments, fallback_gate=gate, deadline=deadline,
                    tracer=tracer)
        except BaseException:
            cleanup()
            raise

        def finish() -> bytes:
            try:
                # the blocking link wait lives here, OUTSIDE the slot
                with span("server.fetch", tracer):
                    merged = fetch_merged()
                with span("server.trim", tracer):
                    merged = trim_group_by(q, merged, self.group_trim_size)
                # per-query resource accounting shipped in the partial's
                # stats (the reference's DataTable V3 threadCpuTimeNs
                # metadata); same transport thread runs both phases, so
                # thread_time spans launch + fetch
                merged.stats.thread_cpu_time_ns = \
                    _time.thread_time_ns() - t_cpu
                if acct:
                    merged.stats.scheduler_wait_ms = acct.get(
                        "scheduler_wait_ms", 0.0)
                # load + freshness piggyback (ISSUE 10): every response
                # carries this server's current pressure/in-flight depth
                # (the broker's load-aware replica-group pick) and the
                # table's freshness epoch as of scan START (the broker
                # result cache's staleness signal)
                merged.stats.server_pressure = self.scheduler.pressure()
                merged.stats.server_inflight = self._inflight_queries
                merged.stats.table_epoch = epoch_at_start
                self.queries_served += 1
                # segment-temperature telemetry (ISSUE 11): every routed
                # segment of this query heats up — bytes are the
                # rows x referenced-columns x 4 admission-cost proxy
                try:
                    ncols = max(1, len(q.columns()))
                    for s in segments:
                        self.heat.note(
                            q.table_name, s.name,
                            bytes_scanned=int(
                                getattr(s, "n_docs", 0)) * ncols * 4)
                except Exception:  # noqa: BLE001 — telemetry never fails a query
                    log.exception("segment heat accounting failed")
                if tracer is not None:
                    # encode itself can't appear in the trace: the spans
                    # are serialized INTO the payload encode produces.
                    # server.total is the reconciliation denominator —
                    # tracer birth (request entry) to now; the phase
                    # ladder's top-level spans must cover >=90% of it
                    tracer.add_ms("server.total", tracer.elapsed_ms())
                    merged.trace = tracer.to_json()
                return encode(merged)
            finally:
                cleanup()

        return finish

    # ---- distributed stage-2 exchange (ISSUE 16, mailbox leapfrog) -------
    def _peer_channel(self, endpoint: str):
        """One cached QueryRouterChannel per peer endpoint for
        ExchangeTransfer sends (the broker's per-instance pool pattern,
        server-side)."""
        with self._peer_lock:
            ch = self._peer_channels.get(endpoint)
            if ch is None:
                from pinot_tpu.transport.grpc_transport import (
                    QueryRouterChannel,
                )

                ch = QueryRouterChannel(endpoint, tls=self._tls)
                self._peer_channels[endpoint] = ch
            return ch

    def _handle_exchange_transfer(self, request: bytes) -> bytes:
        """Receive one exchange payload (or a sender's done marker) into
        the addressed mailbox. Errors answer in-band as {"ok": false} —
        the SENDING server converts that into a typed
        EXCHANGE_TRANSFER_FAILED with peer attribution, so the broker's
        retry can exclude the right instance."""
        import json as _json

        from pinot_tpu.query2 import exchange as ex

        try:
            msg = ex.decode_transfer(request)
            buf = self.exchanges.get_or_create(msg["id"])
            if msg["done"]:
                buf.mark_done(msg["sender"], msg.get("expected") or {})
                ack = {"ok": True, "spilled": False, "softLimit": False}
            else:
                ack = buf.offer(msg["sender"], msg["alias"],
                                msg["partition"], msg["cols"], msg["n"])
                self.metrics.count("exchangeTransfers")
                if ack.get("spilled"):
                    self.metrics.count("exchangeSpills")
            return _json.dumps(ack).encode("utf-8")
        except Exception as e:  # noqa: BLE001 — in-band, sender attributes
            self.metrics.count("exchangeTransferErrors")
            return _json.dumps(
                {"ok": False,
                 "error": f"{type(e).__name__}: {e}"}).encode("utf-8")

    def _handle_execute_stage(self, request: bytes) -> bytes:
        """Run this worker's slice of a DISTRIBUTED stage 2
        (query2/runner.run_exchange_stage): scan routed segments, ship
        hash partitions to their owners, join + partially aggregate the
        owned partitions, answer ONE mergeable DataTable. Same
        shutdown-drain/in-flight accounting and typed error ladder as
        the unary submit; no scheduler slot is held — the exchange
        barrier can wait on PEERS, and a fleet-wide stage parked on
        every server's scheduler would deadlock regular traffic behind
        a slow worker."""
        import json as _json

        from pinot_tpu.query2.exchange import ExchangeTransferError

        req = _json.loads(request.decode("utf-8"))
        with self._inflight_cond:
            if self._shutting_down:
                self.metrics.count("queriesRejected")
                return encode_error(
                    "server_shutting_down",
                    f"SERVER_SHUTTING_DOWN: {self.instance_id} is "
                    f"draining for shutdown")
            self._inflight_queries += 1
        try:
            self.metrics.count("exchangeStages")
            return self._execute_stage_inner(req)
        except faults.FaultInjected:
            # injected crash mode: die at the transport level, like a
            # process kill (matches the unary submit's contract)
            raise
        except QueryTimeout as e:
            self.metrics.count("queryTimeouts")
            return encode_error("query_timeout", str(e))
        except ExchangeTransferError as e:
            # typed with PEER attribution: the broker excludes the
            # implicated instance (not this healthy worker) on retry
            self.metrics.count("queryErrors")
            return encode_error(
                "query_error",
                f"EXCHANGE_TRANSFER_FAILED peer={e.peer}: {e}")
        except Exception as e:  # noqa: BLE001 — stage errors ship in-band
            self.metrics.count("queryErrors")
            return encode_error("query_error", f"{type(e).__name__}: {e}")
        finally:
            with self._inflight_cond:
                self._inflight_queries -= 1
                self._inflight_cond.notify_all()

    def _execute_stage_inner(self, req: dict) -> bytes:
        import json as _json

        from pinot_tpu.common import trace
        from pinot_tpu.query2 import exchange as ex
        from pinot_tpu.query2.logical import compile_plan
        from pinot_tpu.query2.runner import _tdm_for, run_exchange_stage
        from pinot_tpu.sql.parser import parse_sql

        deadline = self._request_deadline(req) or Deadline(30.0)
        tracer = trace.Tracer(req.get("traceId")) \
            if req.get("traceEnabled") else None
        exchange_id = req["exchangeId"]
        endpoints = req["endpoints"]
        owners = {int(p): o for p, o in req["partitionOwners"].items()}
        mailbox = self.exchanges.get_or_create(exchange_id)
        shipped = {"parts": 0, "bytes": 0}

        def send(owner: str, alias: str, partition: int, cols: dict,
                 n: int) -> None:
            if faults.ACTIVE:
                # exchange.transfer chaos seam: targets the RECEIVING
                # instance, so blackholing one server starves every
                # sender addressing it — including its own self-send —
                # and the typed failure names it for the broker's retry
                try:
                    faults.inject("exchange.transfer", target=owner,
                                  bound_ms=deadline.remaining_ms())
                except faults.FaultInjected as e:
                    raise ex.ExchangeTransferError(
                        owner, f"injected transfer fault: {e}") from e
            if owner == self.instance_id:
                # self-offer straight into the local mailbox: no wire,
                # not counted as shipped
                mailbox.offer(self.instance_id, alias, partition, cols, n)
                return
            payload = ex.encode_transfer(
                exchange_id, self.instance_id, alias, partition, cols, n)
            try:
                ch = self._peer_channel(endpoints[owner])
                ack = _json.loads(ch.transfer(
                    payload, timeout_s=max(0.1, deadline.remaining_s())))
            except Exception as e:  # noqa: BLE001 — typed for the broker
                raise ex.ExchangeTransferError(
                    owner, f"transfer to {owner} failed: "
                           f"{type(e).__name__}: {e}") from e
            if not ack.get("ok"):
                raise ex.ExchangeTransferError(
                    owner, f"transfer to {owner} rejected: "
                           f"{ack.get('error')}")
            shipped["parts"] += 1
            shipped["bytes"] += len(payload)
            if ack.get("softLimit"):
                # receiver mailbox running hot: pace the pipe (bounded
                # backpressure, never past the budget)
                time.sleep(min(0.005, max(0.0, deadline.remaining_s())))

        def done() -> None:
            # unary transfers from this thread are ordered, so done-last
            # is a valid completeness marker; each sender ships exactly
            # ONE payload per (alias, partition) — empty included — so
            # the receiver's expected count per slot is always 1
            aliases = list(req["routing"])
            for receiver in sorted(set(owners.values())):
                owned = [p for p, o in owners.items() if o == receiver]
                expected = {a: {str(p): 1 for p in owned}
                            for a in aliases}
                if receiver == self.instance_id:
                    mailbox.mark_done(self.instance_id, expected)
                    continue
                payload = ex.encode_transfer(
                    exchange_id, self.instance_id, "", -1, {}, 0,
                    done=True, expected=expected)
                try:
                    ch = self._peer_channel(endpoints[receiver])
                    ack = _json.loads(ch.transfer(
                        payload,
                        timeout_s=max(0.1, deadline.remaining_s())))
                except Exception as e:  # noqa: BLE001
                    raise ex.ExchangeTransferError(
                        receiver, f"done marker to {receiver} failed: "
                                  f"{type(e).__name__}: {e}") from e
                if not ack.get("ok"):
                    raise ex.ExchangeTransferError(
                        receiver, f"done marker to {receiver} rejected: "
                                  f"{ack.get('error')}")

        def catalog(table: str):
            tdm = _tdm_for(self.engine, table)
            segs = tdm.acquire()
            try:
                if not segs:
                    raise ValueError(f"table {table!r} has no segments")
                cols = tuple(segs[0].column_names())
            finally:
                tdm.release(segs)
            return cols, bool(getattr(tdm, "is_dim_table", False))

        spec = {
            "partitions": int(req["partitions"]),
            "partitionOwners": req["partitionOwners"],
            "senders": list(req["senders"]),
            "selfId": self.instance_id,
            "routing": req["routing"],
        }
        timer = self.metrics.timed("exchangeStage")
        timer.__enter__()
        try:
            with trace.span("server.compile", tracer):
                plan = compile_plan(parse_sql(req["sql"]), catalog)
            with trace.span("server.exchange", tracer):
                merged = run_exchange_stage(
                    self.engine, plan, spec, mailbox, send, done,
                    deadline, device=self.engine.device)
            merged.stats.exchange_partitions_shipped = shipped["parts"]
            merged.stats.exchange_bytes_shipped = shipped["bytes"]
            merged.stats.exchange_spill_count = mailbox.spill_count
            merged.stats.server_pressure = self.scheduler.pressure()
            merged.stats.server_inflight = self._inflight_queries
            self.metrics.count("exchangeBytesShipped", shipped["bytes"])
            self.queries_served += 1
            if tracer is not None:
                tracer.add_ms("server.total", tracer.elapsed_ms())
                merged.trace = tracer.to_json()
            return encode(merged)
        finally:
            timer.__exit__()
            # the barrier guarantees every peer payload addressed to
            # this worker has arrived before the stage returns, so the
            # mailbox (and its spill files) can be reclaimed here; a
            # broker retry mints a fresh exchange id
            self.exchanges.release(exchange_id)

    # ---- streaming query path (GrpcQueryServer streaming Submit) ---------
    def _handle_submit_streaming(self, request: bytes):
        """Generator: one DataTable block per executed segment, so large
        selection results never materialize whole server-side (the
        reference's streaming operator + StreamingReduceService contract).
        The per-request row budget (offset+limit) stops segment execution
        early — selection without ORDER BY is any-subset semantics."""
        req = parse_instance_request(request)
        with self._inflight_cond:
            rejected = self._shutting_down
            if rejected:
                self.metrics.count("queriesRejected")
            else:
                self._inflight_queries += 1
        if rejected:
            # yield OUTSIDE the condition lock: the generator suspends at
            # the yield while gRPC writes the block, and a slow client
            # must not park the server-wide lock every submit acquires
            yield encode_error(
                "server_shutting_down",
                f"SERVER_SHUTTING_DOWN: {self.instance_id} is "
                f"draining for shutdown")
            return
        try:
            # count at receive time, pre-compile — same invariant as the
            # unary path: queryErrors <= queries even on parse errors;
            # compile rides the same pre-admission semaphore bound
            self.metrics.count("queries")
            deadline = self._request_deadline(req)
            q = self._compile_admitted(req["sql"], deadline)
            if deadline is None:
                deadline = self._request_deadline(req, q)
            yield from self.scheduler.run(
                lambda: self._stream_blocks(req, q, deadline),
                queue_timeout_s=None if deadline is None
                else max(0.001, deadline.remaining_s()),
                group=self._scheduler_group(q, req),
                weight=self._scheduler_weight(q, req),
            )
        except QueryTimeout as e:
            self.metrics.count("queryTimeouts")
            yield encode_error("query_timeout", str(e))
        except SchedulerSaturated as e:
            self.metrics.count("queriesRejected")
            yield encode_error("query_error", f"QUERY_SCHEDULING_TIMEOUT: {e}")
        except Exception as e:  # noqa: BLE001 — in-band, like unary
            self.metrics.count("queryErrors")
            yield encode_error("query_error", f"{type(e).__name__}: {e}")
        finally:
            with self._inflight_cond:
                self._inflight_queries -= 1
                self._inflight_cond.notify_all()

    def _stream_blocks(self, req: dict, q, deadline: Deadline = None):
        """Materialize the block list under the scheduler slot (bounded by
        the row budget), releasing the slot before slow network drain.
        Returning a LIST (not a generator) is load-bearing: the scheduler
        charges wall time and holds the concurrency slot for the duration
        of fn(), so block production stays inside both."""
        q = _apply_request_overrides(q, req)
        if q.aggregations() or q.distinct or q.order_by:
            raise ValueError(
                "streaming submit only serves selection-without-order queries"
            )
        tdm = self.engine.tables.get(q.table_name)
        wanted = set(req["segments"])
        acquired = [] if tdm is None else tdm.acquire()
        encoded = []
        # the most recent block stays UNENCODED until the next one arrives
        # (or the loop ends): the fleet-wide stats stamp lands on the LAST
        # block, and encoding eagerly lets each earlier block's column
        # arrays free as soon as its wire bytes exist — peak RSS is one
        # block's arrays + the encoded tail, not two copies of the result
        pending = None
        try:
            segments = [s for s in acquired if s.name in wanted]
            if not segments:
                return [encode_error(
                    "no_segments",
                    f"server {self.instance_id} hosts none of the requested "
                    f"segments for table {q.table_name!r}",
                )]
            q = self.engine._expand_star(q, segments[0])
            from pinot_tpu.common import freshness

            # pre-scan snapshot, same contract as the unary path
            epoch_at_start = freshness.epoch(q.table_name)
            budget = q.offset + q.limit
            produced = 0
            pruned = 0
            cold = 0
            unexecuted_docs = 0  # pruned/budget-skipped: count toward totalDocs
            remaining = list(segments)
            while remaining:
                if deadline is not None:
                    deadline.check("streaming segment scan")
                seg = remaining.pop(0)
                if getattr(seg, "is_cold", False):
                    # cold tier (ISSUE 12): honest in-flight partial —
                    # the touch schedules the deep-store hydration, the
                    # stream never blocks on a download
                    cold += 1
                    unexecuted_docs += seg.n_docs
                    touch = getattr(seg, "touch", None)
                    if touch is not None:
                        touch()
                    continue
                if self.engine.pruner.prune(q, seg):
                    pruned += 1
                    unexecuted_docs += seg.n_docs
                    continue
                r = self.engine.host.execute_segment(q, seg)
                r.stats.num_segments_queried = 0  # set once on the last block
                produced += len(next(iter(r.rows.values()))) if r.rows else 0
                if pending is not None:
                    encoded.append(encode(pending))
                pending = r
                if produced >= budget:
                    break  # row budget hit: remaining segments unprocessed
            if pending is None:
                from pinot_tpu.engine.engine import _impossible

                base = next((s for s in segments
                             if not getattr(s, "is_cold", False)), None)
                empty = self.engine.host.execute_segment(
                    _impossible(q),
                    base if base is not None
                    else segments[0].empty_view())  # every segment cold
                if base is None:
                    empty.stats.num_segments_processed = 0
                    empty.stats.num_segments_queried = 0
                pending = empty
            # same stats contract as execute_segments: every requested
            # segment counts toward numSegmentsQueried and totalDocs, even
            # when pruning or the row budget skipped its execution
            last = pending.stats
            last.num_segments_queried = len(segments)
            last.num_segments_pruned = pruned
            last.num_segments_cold = cold
            last.total_docs += unexecuted_docs + sum(
                s.n_docs for s in remaining)
            last.server_pressure = self.scheduler.pressure()
            last.server_inflight = self._inflight_queries
            last.table_epoch = epoch_at_start
            self.queries_served += 1
            try:
                ncols = max(1, len(q.columns()))
                for s in segments:
                    self.heat.note(
                        q.table_name, s.name,
                        bytes_scanned=int(
                            getattr(s, "n_docs", 0)) * ncols * 4)
            except Exception:  # noqa: BLE001 — telemetry never fails a query
                log.exception("segment heat accounting failed")
            encoded.append(encode(pending))
            return encoded
        finally:
            if tdm is not None:
                tdm.release(acquired)

    # registry sections whose change obligates a full _sync_once — NOT
    # instances (peer heartbeats), leases (controller HA renewals), or
    # external_view (peers' publishes, and our own): those churn
    # constantly in a healthy cluster without changing what THIS server
    # should host
    _SYNC_SECTIONS = ("tables", "schemas", "segments", "assignment",
                      "partition_assignment", "segment_lineage")

    def _serving_map(self) -> dict:
        return {
            table: list(tdm.segments)
            for table, tdm in self.engine.tables.items() if tdm.segments
        }

    # ---- segment sync (state model replacement) --------------------------
    def _sync_loop(self) -> None:
        from pinot_tpu.common import freshness

        last_hb = 0.0
        last_token = None
        while not self._stop.is_set():
            try:
                # a full reconcile tick is 7+ registry transactions; under
                # sandboxed kernels (gVisor-class gofer fs) each costs
                # ~10ms of open/stat/flock syscalls, which at a 200ms
                # cadence kept the sync thread nearly CONTINUOUSLY busy
                # and stole the query threads' cores (measured: 2-server
                # QPS flat vs 1 server until this skip). Poll only the
                # lock-free section-version token; reconcile when it (or
                # our own serving set) moved, or on the heartbeat cadence
                # as a self-heal backstop.
                now = time.time()
                hb_due = now - last_hb >= self.heartbeat_interval_s
                token = self.registry.sections_version(self._SYNC_SECTIONS)
                if hb_due or token != last_token \
                        or self._serving_map() != self._last_serving:
                    self._sync_once()
                    # re-read: _sync_once's own writes (segment state
                    # flips, seals) must not re-trigger next tick
                    last_token = self.registry.sections_version(
                        self._SYNC_SECTIONS)
                if hb_due:
                    # heartbeat carries the load + freshness view (ISSUE
                    # 10): brokers read pressure for load-aware routing
                    # when no fresher piggybacked response signal exists,
                    # and the table epochs keep their result caches honest
                    # even when no queries are flowing. Cadence is
                    # DECOUPLED from the sync tick: a heartbeat is a full
                    # locked read-modify-write of the registry file, and N
                    # servers writing it every 200ms serialize on the lock.
                    self.registry.heartbeat(
                        self.instance_id, pressure=self.scheduler.pressure(),
                        table_epochs=freshness.snapshot(),
                        # per-segment temperature snapshot (ISSUE 11),
                        # hottest-N per table so the payload stays
                        # bounded at million-segment scale
                        heat=self.heat.snapshot(
                            top_per_table=self.heat_top_per_table),
                        # per-segment tier map (ISSUE 12): the
                        # controller's tier-aware replica-group
                        # assignment reads it
                        tiers=(self.tiers.snapshot()
                               if self.tiers.enabled else None))
                    last_hb = now
                # tier lifecycle pass (interval-gated internally): heat
                # ranking, hot-budget admission, cold demotion
                self.tiers.maybe_tick(now)
            except Exception:
                log.exception("segment sync failed")
            self._stop.wait(self.sync_interval_s)

    def _local_segment_dir(self, table: str, name: str) -> str:
        return os.path.join(self.data_dir, "segments", table, name)

    def _download_segment(self, table: str, rec) -> str:
        """Deep store → local working copy before load, like the reference's
        BaseTableDataManager.downloadSegment: queries never mmap deep-store
        files that a controller delete (retention, minion swap) can rm mid-
        read. Paths already under this server's data_dir (own realtime
        seals) are served in place. Local copies are CRC-VERSIONED
        (``name__<crc>``): a refresh push lands in a fresh directory, and
        the old one is torn down through the refcounted unload path once
        the last in-flight query over it drains — never rmtree'd in place."""
        import shutil

        src = rec.location
        if os.path.commonpath([os.path.abspath(src),
                               os.path.abspath(self.data_dir)]) \
                == os.path.abspath(self.data_dir):
            return src
        dirname = rec.name if not rec.crc else f"{rec.name}__{rec.crc}"
        local = self._local_segment_dir(table, dirname)
        if os.path.isdir(local):
            return local
        os.makedirs(os.path.dirname(local), exist_ok=True)
        tmp = f"{local}.tmp{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)  # debris from a dead copy
        try:
            shutil.copytree(src, tmp)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            if os.path.isdir(src):
                # source readable → the failure is LOCAL (disk full,
                # permissions): surface it loudly instead of
                # misdiagnosing it as deep-store-down and re-failing
                # the same way after a network download
                raise
            # deep store unreachable: fall back to a serving replica
            # (PeerServerSegmentFinder role — server/peer.py); the peer's
            # tar lands in the same CRC-versioned dir the copy would have
            from pinot_tpu.server.peer import peer_download

            return peer_download(self.registry, table, rec.name, local,
                                 self.instance_id, tls=self._tls,
                                 timeout_s=self.peer_download_timeout_s)
        if os.path.isdir(local):  # another loader won the copy race
            shutil.rmtree(tmp, ignore_errors=True)
        else:
            os.replace(tmp, local)
        return local

    def _on_segment_unload(self, tdm, seg) -> None:
        """Last reference drained after an unload: drop the local copy
        (deferred teardown is what the refcount buys — an in-flight query
        finished with the mmap before the files went away). If the segment
        was REASSIGNED meanwhile and a live entry is serving from the same
        directory, the delete is skipped — removing it would orphan the
        re-added copy's lazily-mmap'd files."""
        import shutil

        local_root = os.path.abspath(os.path.join(self.data_dir, "segments"))
        seg_dir = os.path.abspath(seg.dir)
        if os.path.commonpath([seg_dir, local_root]) != local_root:
            return
        cur = tdm.segments.get(seg.name)
        if cur is not None and os.path.abspath(cur.dir) == seg_dir:
            return
        shutil.rmtree(seg_dir, ignore_errors=True)

    def _sync_once(self) -> None:
        assigned = self.registry.assigned_segments(self.instance_id)
        # load newly-assigned sealed segments (OFFLINE→ONLINE)
        for table, names in assigned.items():
            records = self.registry.segments(table)
            tdm = self.engine.table(table)
            if tdm.is_dim_table is None:
                cfg = self.registry.table_config(table)
                if cfg is not None:
                    tdm.is_dim_table = cfg.is_dim_table
            table_schema = self.registry.table_schema(table)
            if tdm.on_unload is None:
                tdm.on_unload = (
                    lambda seg, _tdm=tdm: self._on_segment_unload(_tdm, seg))
            for name in names:
                rec = records.get(name)
                if rec is None or rec.state != SegmentState.ONLINE:
                    continue
                cur = tdm.segments.get(name)
                if cur is not None:
                    # self-heal the unload/re-add race: if a deferred delete
                    # won and this entry's files vanished, drop it so the
                    # next tick re-downloads a fresh copy
                    if not os.path.isfile(os.path.join(cur.dir, "metadata.json")):
                        log.warning("segment %s lost its local files; "
                                    "reloading", name)
                        tdm.remove_segment(name)
                        continue
                    if rec.crc and cur.metadata.crc \
                            and cur.metadata.crc != rec.crc:
                        # refresh push: retire the old copy via the doomed/
                        # unload path and load the new CRC's dir this tick
                        tdm.remove_segment(name)
                    else:
                        continue
                try:
                    seg = ImmutableSegment(self._download_segment(table, rec))
                    if table_schema is not None:
                        seg.table_schema = table_schema
                    tdm.add_segment(seg)
                except Exception:
                    log.exception("failed to load segment %s from %s",
                                  name, rec.location)
        # schema evolution: EVERY hosted segment — offline downloads,
        # sealed realtime, and consuming mutables — carries the CURRENT
        # table schema so queries over columns added after a segment was
        # built synthesize default values (reference: segment reload after
        # a Schema REST update)
        for table, tdm in list(self.engine.tables.items()):
            table_schema = self.registry.table_schema(table)
            if table_schema is not None:
                for seg in list(tdm.segments.values()):
                    seg.table_schema = table_schema
        # unload segments no longer assigned (ONLINE→OFFLINE/DROPPED);
        # consuming (mutable) segments belong to the realtime managers
        for table, tdm in list(self.engine.tables.items()):
            keep = set(assigned.get(table, ()))
            for name, seg in list(tdm.segments.items()):
                if name not in keep and not getattr(seg, "is_mutable", False):
                    tdm.remove_segment(name)
        self._sync_realtime()
        # publish what this instance can actually answer for (ExternalView)
        serving = self._serving_map()
        self.registry.update_external_view(self.instance_id, serving)
        self._last_serving = serving

    def _sync_realtime(self) -> None:
        """Reconcile stream consumers against the (multi-replica) partition
        assignment: start consumers for newly-assigned partitions, stop
        reassigned ones (CONSUMING state analog, level-triggered)."""
        for table in self.registry.tables():
            pa = self.registry.partition_assignment(table)
            mine = sorted(
                int(p) for p, insts in pa.items() if self.instance_id in insts
            )
            mgr = self._realtime_managers.get(table)
            if mgr is None:
                if not mine:
                    continue
                cfg = self.registry.table_config(table)
                schema = self.registry.table_schema(table)
                if cfg is None or cfg.stream is None:
                    continue
                from pinot_tpu.realtime.completion import SegmentCompletionClient
                from pinot_tpu.realtime.manager import RealtimeTableDataManager

                mgr = RealtimeTableDataManager(
                    schema, cfg, self.engine.table(table),
                    os.path.join(self.data_dir, f"rt_{table}"),
                    completion_client=SegmentCompletionClient(
                        self.registry, table, self.instance_id
                    ),
                    peer_fetch=lambda seg, dest, _t=table:
                        self._peer_fetch(_t, seg, dest),
                )
                # callbacks publish under the PHYSICAL registry key
                # (clicks_REALTIME), not the raw table name the manager carries
                mgr.start(
                    partitions=mine,
                    on_commit=lambda _t, p, seg, _k=table: self._publish_committed(_k, p, seg),
                    on_consuming=lambda _t, p, seg, _k=table: self._publish_consuming(_k, p, seg),
                )
                self._realtime_managers[table] = mgr
            else:
                current = set(mgr.partition_managers)
                for p in mine:
                    if p not in current:
                        mgr.add_partition(p)
                for p in current - set(mine):
                    mgr.stop_partition(p)

    def _peer_fetch(self, table: str, segment_name: str, dest_dir: str) -> str:
        """Adopt-path fallback when the winner's published location is
        unreachable: download from a serving replica. Retries briefly —
        the external view can lag the winner's publish by a sync tick.
        The retry window is config-driven
        (``pinot.server.segment.peer.retry.timeout.ms``; was a hardcoded
        10 s) and the SAME Deadline bounds every per-replica stream
        inside peer_download, so a hung peer can't hold the consume loop
        past the window."""
        from pinot_tpu.server.peer import peer_download

        deadline = Deadline(self.peer_retry_timeout_s)
        while True:
            try:
                return peer_download(self.registry, table, segment_name,
                                     dest_dir, self.instance_id,
                                     tls=self._tls,
                                     timeout_s=self.peer_download_timeout_s,
                                     deadline=deadline)
            except Exception:
                if deadline.expired():
                    raise
                time.sleep(0.3)

    def _publish_consuming(self, table: str, partition: int, segment) -> None:
        """Consuming segments are routable (brokers send them queries while
        rows stream in — RealtimeSegmentSelector analog)."""
        self.registry.add_segment(
            SegmentRecord(
                name=segment.name, table=table, n_docs=0,
                location="", state=SegmentState.CONSUMING,
            ),
            [self.instance_id],
            merge_instances=True,
        )

    def _publish_committed(self, table: str, partition: int, sealed) -> None:
        """Committed realtime segments become cluster-visible (the
        Server2Controller commit → ZK metadata step)."""
        meta = sealed.metadata
        from pinot_tpu.controller.controller import (
            _column_stats_fields,
            _partition_record_fields,
        )

        self.registry.add_segment(
            SegmentRecord(
                name=sealed.name, table=table, n_docs=sealed.n_docs,
                location=sealed.dir, state=SegmentState.ONLINE,
                start_time=meta.start_time, end_time=meta.end_time,
                **_partition_record_fields(meta),
                **_column_stats_fields(meta),
            ),
            [self.instance_id],
            merge_instances=True,
        )
