"""Peer segment download: fetch a segment from a serving replica when the
deep-store copy is unreachable.

Reference: PeerServerSegmentFinder
(pinot-core/.../util/PeerServerSegmentFinder.java:1) — on download
failure, the reference resolves ONLINE replicas from the external view
and fetches the segment over the data plane instead of the deep store
(exercised by PeerDownloadLLCRealtimeClusterIntegrationTest). Here the
fetch rides a FetchSegment gRPC method on the existing query transport:
the serving peer streams a tar of the segment dir.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import tarfile

log = logging.getLogger("pinot_tpu.server.peer")

_CHUNK = 256 * 1024


def serve_segment_tar(server, request: bytes):
    """Server-side FetchSegment handler: stream a tar of a segment this
    instance serves. The refcount acquire keeps the dir alive for the
    duration (a concurrent unload defers its teardown past the stream)."""
    req = json.loads(request.decode("utf-8"))
    table, name = req["table"], req["segment"]
    tdm = server.engine.tables.get(table)
    if tdm is None:
        raise KeyError(f"table {table!r} not hosted")
    acquired = tdm.acquire()
    try:
        seg = next((s for s in acquired if s.name == name), None)
        if seg is None or getattr(seg, "is_mutable", False) \
                or getattr(seg, "is_cold", False):
            # a cold-tier placeholder has no plane files to serve — a
            # peer must fall through to a replica that still holds them
            raise KeyError(f"segment {name!r} not served here")
        # spool to a temp FILE, not RAM: a multi-GB segment tar held on
        # heap while also serving queries is an OOM hazard exactly when
        # many replicas fall back at once (deep-store outage)
        import tempfile

        with tempfile.TemporaryFile(prefix="peer_tar_") as spool:
            with tarfile.open(fileobj=spool, mode="w") as tar:
                tar.add(seg.dir, arcname=name)
            spool.seek(0)
            while True:
                chunk = spool.read(_CHUNK)
                if not chunk:
                    break
                yield chunk
    finally:
        tdm.release(acquired)


def peer_download(registry, table: str, name: str, dest_dir: str,
                  self_id: str, tls=None, timeout_s: float = 60.0,
                  deadline=None) -> str:
    """Try every ONLINE replica of (table, segment) from the external view
    (excluding ``self_id``); untar the first successful stream into
    ``dest_dir`` (the caller's final path — may carry a CRC-versioned
    dirname). Returns ``dest_dir``; raises RuntimeError when no peer can
    serve it.

    ``deadline`` (common/deadline.py Deadline, optional): the CALLER's
    budget — each replica attempt's stream timeout is clamped to the
    remaining window (previously a fixed 60 s per replica, so a hung
    peer chain could stall a caller for minutes), and no further replica
    is tried once it expires. A mid-stream timeout cleans up the
    partially-written download the same way the ``os.replace``-failure
    path does: the extraction dir is removed in the per-candidate
    ``finally`` and the spool is a TemporaryFile that never survives the
    attempt."""
    from pinot_tpu.common import faults
    from pinot_tpu.transport.grpc_transport import QueryRouterChannel

    ev = registry.external_view(table)
    candidates = [i for i in ev.get(name, ()) if i != self_id]
    infos = {i.instance_id: i for i in registry.instances()}
    req = json.dumps({"table": table, "segment": name}).encode("utf-8")
    errors = []
    for inst_id in candidates:
        if deadline is not None and deadline.expired():
            errors.append("deadline expired before trying remaining "
                          f"replicas {candidates[candidates.index(inst_id):]}")
            break
        attempt_timeout_s = timeout_s if deadline is None \
            else max(0.001, deadline.clamp(timeout_s))
        info = infos.get(inst_id)
        if info is None or not getattr(info, "grpc_port", None):
            continue
        ch = QueryRouterChannel(f"{info.host}:{info.grpc_port}", tls=tls)
        tmp = f"{dest_dir}.peer{os.getpid()}"
        try:
            if faults.ACTIVE:
                faults.inject("peer.fetch", target=inst_id)
            import tempfile

            with tempfile.TemporaryFile(prefix="peer_dl_") as spool:
                for chunk in ch.fetch_segment(
                        req, timeout_s=attempt_timeout_s):
                    spool.write(chunk)
                spool.seek(0)
                shutil.rmtree(tmp, ignore_errors=True)
                with tarfile.open(fileobj=spool, mode="r") as tar:
                    # filter="data" rejects symlink/hardlink/absolute
                    # members — a malicious peer must not write outside
                    # the target dir (hand-rolled name checks miss
                    # symlink-then-write-through sequences)
                    tar.extractall(tmp, filter="data")
            src = os.path.join(tmp, name)  # arcname was the segment name
            if os.path.isdir(dest_dir):
                # a concurrent loader finished first: keep its copy (same
                # keep-existing race semantics as _download_segment)
                return dest_dir
            os.makedirs(os.path.dirname(dest_dir), exist_ok=True)
            os.replace(src, dest_dir)
            log.info("segment %s/%s peer-downloaded from %s",
                     table, name, inst_id)
            return dest_dir
        except Exception as e:  # noqa: BLE001 — try the next replica
            errors.append(f"{inst_id}: {type(e).__name__}: {e}")
        finally:
            # the extraction dir is removed on EVERY exit — including an
            # os.replace failure after extractall, which used to leak it
            # (only the success paths cleaned up)
            shutil.rmtree(tmp, ignore_errors=True)
            ch.close()
    raise RuntimeError(
        f"peer download of {table}/{name} failed "
        f"(candidates={candidates}, errors={errors})")
