"""Tiered segment lifecycle: temperature-driven hot/warm/cold storage.

ISSUE 12's tentpole — the storage tier's missing half (ROADMAP 3). Every
segment a server hosts lives in exactly one of three tiers:

- **hot**   — current behavior: host-resident working copy, eligible for
  the device ``BatchContext`` path (columns uploaded to HBM, batch LRU,
  partials cache). The capacity tier the PR-5 narrow-width planning and
  PR-9 sub-RTT machinery serve from.
- **warm**  — local working copy on disk, columns lazily mmap'd PER QUERY
  through :class:`LazySegmentView` (only the ``.npy`` planes a query
  touches are mapped — ``PinotDataBuffer.mapFile`` semantics, PAPER.md
  layer 1). Warm segments run on the host scan path and never occupy HBM.
- **cold**  — deep-store only (the PinotFS SPI, PAPER.md layer 7): the
  local plane files are evicted (``metadata.json`` stays so the sync loop
  and schema surface keep working) and ``SegmentRecord.location`` is the
  source of truth. A query that routes a cold segment gets an HONEST
  in-flight partial (``numSegmentsCold`` counter) while the touch kicks
  off an asynchronous re-download (PinotFS with the PR-6 deadline/retry
  contract, peer-download fallback) — the scheduler slot is never blocked
  on a deep-store fetch.

The :class:`TierManager` drives promotion/demotion from the PR-11
``SegmentHeatTracker`` decayed rates plus the PR-5 ``hbm_stats`` batch
hit/miss counters, with NARROW-WIDTH-AWARE admission cost: a segment's
hot-tier charge is its modeled ColPlan bytes (``segment_plan_bytes``) —
a uint8 dict-id plane costs 4x less than the int32 the legacy LRU
implicitly assumed — so the hot set holds what actually fits in HBM.

Divergence from the reference: Pinot tiers by TIME (TierConfig
``segment_age_ms`` + ``RealtimeToOfflineSegmentsTask``); this lifecycle
tiers by measured TEMPERATURE, with the controller's tier-aware
replica-group assignment (controller.py ``rebalance_tiered``) shrinking
cold segments to a single copy behind the object store.
"""

from __future__ import annotations

import logging
import os
import queue
import shutil
import threading
import time
from typing import Optional
from urllib.parse import urlparse

import numpy as np

from pinot_tpu.common.config import Configuration
from pinot_tpu.common.deadline import Deadline
from pinot_tpu.storage.segment import (
    METADATA_FILE,
    Encoding,
    ImmutableSegment,
    SegmentMetadata,
)

log = logging.getLogger("pinot_tpu.server.tiering")


class Tier:
    HOT = "hot"
    WARM = "warm"
    COLD = "cold"


_TIER_RANK = {Tier.HOT: 0, Tier.WARM: 1, Tier.COLD: 2}


def segment_plan_bytes(seg) -> int:
    """Modeled DEVICE bytes of a segment's column planes — the hot-tier
    admission charge. Mirrors the ColPlan width rules (engine/params.py)
    without importing jax: dict-id planes at uint8/uint16/int32 by
    cardinality, raw integer planes at the frame-of-reference width their
    metadata bounds allow, floats at the device f32 width, MV id blocks
    at int32 x entries. Zone maps (~1/4096 of a plane) and the opt-in
    sub-byte tier are ignored — this is an admission COST MODEL, not an
    allocator; what matters is that a narrow segment charges what it
    actually occupies (4-8x less than logical width) so the hot budget
    admits 4-8x more of them."""
    total = 0
    n = int(seg.n_docs)
    for m in seg.metadata.columns.values():
        entries = int(m.total_number_of_entries or n) if not m.single_value \
            else n
        if m.encoding == Encoding.DICT:
            if not m.single_value:
                total += 4 * entries  # MV (S, L, K) blocks stay int32
                continue
            c = max(1, int(m.cardinality))
            total += entries * (1 if c <= 255 else 2 if c <= 65535 else 4)
            continue
        dt = m.data_type.np_dtype
        if dt.kind == "f":
            total += entries * 4  # device float space is f32
            continue
        if dt.kind in ("i", "u") and isinstance(m.min_value, (int, np.integer)) \
                and isinstance(m.max_value, (int, np.integer)):
            lo, hi = int(m.min_value), int(m.max_value)
            rng = hi - lo
            if rng < (1 << 8) and dt.itemsize > 1:
                total += entries
            elif rng < (1 << 16) and dt.itemsize > 2:
                total += entries * 2
            elif rng < (1 << 32) and dt.itemsize > 4:
                total += entries * 4
            else:
                total += entries * dt.itemsize
            continue
        total += entries * max(1, dt.itemsize)
    return total


class LazySegmentView(ImmutableSegment):
    """Warm-tier reader: an ImmutableSegment whose plane loads are
    OBSERVED (the ``plane_load_hook`` seam in storage/segment.py) so the
    warm contract — a query touching 2 of 20 columns maps only those
    planes — is assertable, and whose decoded caches can be released
    (``release_planes``) without tearing the segment down. The mmaps
    themselves are page-cache-backed, so released planes cost a re-map,
    not a re-read."""

    def __init__(self, segment_dir: str):
        super().__init__(segment_dir)
        self.tier = Tier.WARM
        self.planes_loaded: set = set()
        self.plane_loads = 0
        self.plane_load_hook = self._on_plane_load

    def _on_plane_load(self, fname: str) -> None:
        self.planes_loaded.add(fname)
        self.plane_loads += 1

    def release_planes(self) -> None:
        """Drop every cached plane handle (decoded packed/compressed
        columns included) — the warm tier's host-RAM bound."""
        self._fwd_cache.clear()
        self._dict_cache.clear()
        self._json_cache.clear()
        self._text_cache.clear()
        for attr in ("_fst_cache", "_geo_cache"):
            if hasattr(self, attr):
                getattr(self, attr).clear()


class _EmptyColdView:
    """Zero-doc reader over a cold segment's METADATA — the schema donor
    for synthesizing an empty partial when EVERY routed segment is cold
    (the host executor needs a segment to shape the empty result by, and
    a cold segment's plane files are gone)."""

    is_mutable = False
    valid_docs_mask = None
    n_docs = 0

    def __init__(self, ref: "ColdSegmentRef"):
        self.metadata = ref.metadata
        self.dir = ref.dir
        self.table_schema = getattr(ref, "table_schema", None)

    @property
    def name(self) -> str:
        return self.metadata.segment_name

    def column_names(self) -> list:
        return list(self.metadata.columns)

    def column_metadata(self, col: str):
        return self.metadata.columns[col]

    def values(self, col: str) -> np.ndarray:
        return np.empty(0, dtype=self.metadata.columns[col].data_type.np_dtype)

    def flat_values(self, col: str) -> np.ndarray:
        return self.values(col)

    def forward(self, col: str) -> np.ndarray:
        return np.empty(0, dtype=np.int32)

    def mv_offsets(self, col: str):
        if self.metadata.columns[col].single_value:
            return None
        return np.zeros(1, dtype=np.int64)

    def dictionary(self, col: str):
        return None

    def inverted(self, col: str):
        return None

    def bloom(self, col: str):
        return None

    def zone_map(self, col: str):
        return None

    def range_index(self, col: str):
        return None

    def json_index(self, col: str):
        return None

    def text_index(self, col: str):
        return None

    def fst_index(self, col: str):
        return None

    def geo_index(self, col: str):
        return None

    def null_vector(self, col: str):
        return None

    def has_star_tree(self) -> bool:
        return False


class ColdSegmentRef:
    """Cold-tier placeholder hosted in the TableDataManager: keeps the
    segment ROUTABLE (external view, broker fan-out) and its metadata
    queryable while the plane files live only in the deep store. The
    engine splits these out at ``execute_segments_async`` — they count as
    ``numSegmentsCold`` in the partial and their ``touch()`` enqueues an
    asynchronous hydration, so a query never blocks its scheduler slot on
    a deep-store download."""

    is_mutable = False
    valid_docs_mask = None
    is_cold = True
    tier = Tier.COLD

    def __init__(self, table: str, metadata: SegmentMetadata, seg_dir: str,
                 manager: Optional["TierManager"] = None):
        self.table = table
        self.metadata = metadata
        self.dir = seg_dir
        self.manager = manager
        self.table_schema = None

    @property
    def name(self) -> str:
        return self.metadata.segment_name

    @property
    def n_docs(self) -> int:
        return self.metadata.n_docs

    def column_names(self) -> list:
        return list(self.metadata.columns)

    def column_metadata(self, col: str):
        return self.metadata.columns[col]

    def has_star_tree(self) -> bool:
        return False

    def touch(self) -> None:
        """A query routed this cold segment: schedule its re-download
        (never blocks the caller)."""
        if self.manager is not None:
            self.manager.request_hydration(self.table, self.name)

    def empty_view(self) -> _EmptyColdView:
        return _EmptyColdView(self)


# plane files that survive a cold demotion: the metadata keeps the sync
# loop / schema surface honest, creation meta is a few bytes of provenance
_COLD_KEEP = (METADATA_FILE, "creation.meta.json")


class TierManager:
    """Per-server tier lifecycle driver.

    Inputs: the PR-11 ``SegmentHeatTracker``'s decayed per-segment rates
    (``iter_all`` — the UNCAPPED export, demotion needs the cold tail the
    heartbeat's top-N drops) and the device executor's batch hit/miss
    counters. Each ``tick``:

    1. Ranks sealed segments by decayed rate and admits the hottest into
       the hot tier until the NARROW-WIDTH-AWARE byte budget
       (``segment_plan_bytes``) is spent; the rest demote to warm.
    2. Scales the effective hot budget by the observed batch-cache hit
       ratio: a miss-dominated window means the hot set thrashes the LRU
       (shrink toward 0.25x), a hit-dominated one recovers toward 1x.
    3. Demotes warm segments idle past ``cold.idle.ms`` with rate under
       ``cold.max.rate`` to cold — ONLY when the registry's
       ``SegmentRecord.location`` is a durable copy outside this server's
       data dir (own realtime seals never demote their only copy).
    4. Hydrates requested cold segments on a background worker (PinotFS
       download bounded by the PR-6 deadline contract, peer-download
       fallback), landing them WARM.

    Config (``pinot.server.tier.*``): ``enabled`` (default off),
    ``interval.ms``, ``hot.bytes`` (default: the device executor's byte
    budget), ``hot.min.rate``, ``cold.max.rate``, ``cold.idle.ms``,
    ``download.timeout.ms``.
    """

    def __init__(self, server, overrides: Optional[dict] = None):
        self.server = server
        conf = Configuration(overrides=overrides)
        self.enabled = conf.get_bool("pinot.server.tier.enabled", False)
        self.interval_s = conf.get_float(
            "pinot.server.tier.interval.ms", 5_000.0) / 1e3
        dev = getattr(server.engine, "device", None)
        default_budget = getattr(dev, "MAX_CACHED_BYTES", 0) if dev is not None \
            else 0
        self.hot_budget_bytes = int(conf.get_float(
            "pinot.server.tier.hot.bytes", float(default_budget)))
        # minimum decayed rate for hot admission: segments colder than
        # this stay warm even when the budget has room (uploading a
        # never-queried segment to HBM is pure waste)
        self.hot_min_rate = conf.get_float(
            "pinot.server.tier.hot.min.rate", 0.05)
        self.cold_max_rate = conf.get_float(
            "pinot.server.tier.cold.max.rate", 0.01)
        self.cold_idle_s = conf.get_float(
            "pinot.server.tier.cold.idle.ms", 600_000.0) / 1e3
        self.download_timeout_s = conf.get_float(
            "pinot.server.tier.download.timeout.ms", 60_000.0) / 1e3
        self._budget_scale = 1.0
        self._last_hits = self._last_misses = 0
        self._last_tick = 0.0
        self._lock = threading.Lock()
        self._cold: dict = {}        # (table, name) -> ColdSegmentRef
        # (table, name) -> (seg dir, modeled device bytes): the dir keys
        # refresh pushes (same name, new CRC dir) to a re-model
        self._plan_bytes: dict = {}
        # when the lifecycle first saw a segment: a never-queried segment
        # idles from its LOAD, not from the epoch — without this, freshly
        # assigned segments (no heat entry yet) would demote to cold on
        # the first tick
        self._first_seen: dict = {}
        self._hydrate_q: "queue.Queue" = queue.Queue()
        self._hydrating: set = set()
        self._hydrator: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # lifecycle counters (bench detail.tiering + tests)
        self.demotions_warm = 0
        self.demotions_cold = 0
        self.promotions_hot = 0
        self.hydrations = 0
        self.hydration_failures = 0

    # ---- observability ---------------------------------------------------
    def snapshot(self) -> dict:
        """{table: {segment: tier}} — the per-segment tier map the
        heartbeat piggybacks (cluster/registry.py InstanceInfo.tiers) and
        the controller's tier-aware assignment consumes."""
        out: dict = {}
        for table, tdm in list(self.server.engine.tables.items()):
            for name, seg in list(tdm.segments.items()):
                if getattr(seg, "is_mutable", False):
                    continue  # consuming segments live outside the lifecycle
                out.setdefault(table, {})[name] = getattr(
                    seg, "tier", None) or Tier.HOT
        return out

    def stats(self) -> dict:
        return {
            "demotions_warm": self.demotions_warm,
            "demotions_cold": self.demotions_cold,
            "promotions_hot": self.promotions_hot,
            "hydrations": self.hydrations,
            "hydration_failures": self.hydration_failures,
            "cold_segments": len(self._cold),
            "budget_scale": round(self._budget_scale, 3),
            "hot_budget_bytes": self.hot_budget_bytes,
        }

    def cold_segments(self, table: str) -> set:
        with self._lock:
            return {n for (t, n) in self._cold if t == table}

    # ---- tick ------------------------------------------------------------
    def maybe_tick(self, now: Optional[float] = None) -> bool:
        """Interval-gated tick for the server's sync loop."""
        if not self.enabled:
            return False
        now = time.time() if now is None else now
        if now - self._last_tick < self.interval_s:
            return False
        self._last_tick = now
        try:
            self.tick(now=now)
        except Exception:  # noqa: BLE001 — lifecycle must never kill the sync loop
            log.exception("tier tick failed")
        return True

    def _effective_budget(self) -> int:
        """Hot budget scaled by batch-cache behavior (the PR-5 hbm_stats
        half of the policy): a tick window dominated by batch MISSES means
        the admitted hot set is churning the device LRU — what we called
        hot does not fit — so the effective budget contracts until the
        re-launch traffic calms; hit-dominated windows recover it."""
        dev = getattr(self.server.engine, "device", None)
        if dev is None:
            return 0
        hits, misses = dev.batch_hits, dev.batch_misses
        dh, dm = hits - self._last_hits, misses - self._last_misses
        self._last_hits, self._last_misses = hits, misses
        if dh + dm >= 4:  # ignore idle / tiny windows
            if dm > dh:
                self._budget_scale = max(0.25, self._budget_scale * 0.8)
            elif dh >= 4 * dm:
                # hit-dominated window (a trickle of natural churn misses
                # must not pin the scale at the floor forever): recover
                self._budget_scale = min(1.0, self._budget_scale * 1.1)
        return int(self.hot_budget_bytes * self._budget_scale)

    def _records(self, table: str) -> dict:
        try:
            return self.server.registry.segments(table)
        except Exception:  # noqa: BLE001 — registry hiccups skip a tick
            return {}

    def tick(self, now: Optional[float] = None) -> dict:
        """One full promotion/demotion pass; returns {edge: [names]} of
        the transitions applied (bench/test visibility)."""
        now = time.time() if now is None else now
        heat = {}
        for t, s, rec in self.server.heat.iter_all(now=now):
            heat[(t, s)] = rec
        # prune cold entries the sync loop unloaded (segment unassigned
        # while cold): a later hydration must not resurrect them
        with self._lock:
            for key in list(self._cold):
                tdm = self.server.engine.tables.get(key[0])
                if tdm is None or \
                        tdm.segments.get(key[1]) is not self._cold[key]:
                    del self._cold[key]
        budget = self._effective_budget()
        applied = {"to_hot": [], "to_warm": [], "to_cold": []}
        seen_keys: set = set()
        # rank GLOBALLY across tables: the hot budget models the one
        # device LRU every table shares — a per-table pass would admit
        # N tables x budget and thrash exactly the cache it protects
        candidates = []
        for table, tdm in list(self.server.engine.tables.items()):
            for name, seg in list(tdm.segments.items()):
                if getattr(seg, "is_mutable", False) \
                        or getattr(seg, "is_cold", False):
                    continue
                candidates.append(
                    (float(heat.get((table, name), {}).get("rate", 0.0)),
                     table, name, seg))
        candidates.sort(key=lambda c: (-c[0], c[1], c[2]))
        records_cache: dict = {}
        spent = 0
        for rate, table, name, seg in candidates:
            rec = heat.get((table, name), {})
            last = float(rec.get("lastAccessTs", 0.0))
            seen_keys.add((table, name))
            first = self._first_seen.setdefault((table, name), now)
            cost = self._plan_cost(table, name, seg)
            cur = getattr(seg, "tier", None) or Tier.HOT
            want_hot = (budget > 0 and rate >= self.hot_min_rate
                        and spent + cost <= budget)
            if want_hot:
                spent += cost
                if cur != Tier.HOT:
                    if self.promote_to_hot(table, name):
                        applied["to_hot"].append(name)
                continue
            idle_s = now - max(last, first)
            if rate <= self.cold_max_rate and idle_s >= self.cold_idle_s:
                if table not in records_cache:
                    records_cache[table] = self._records(table)
                if self.demote_to_cold(table, name,
                                       rec=records_cache[table].get(name)):
                    applied["to_cold"].append(name)
                    continue
            if cur == Tier.HOT:
                if self.demote_to_warm(table, name):
                    applied["to_warm"].append(name)
            elif isinstance(seg, LazySegmentView) \
                    and idle_s >= self.cold_idle_s:
                # cold-ineligible (no durable copy) or cold-refused warm
                # segments still shed their decoded plane caches — the
                # warm tier's host-RAM bound is enforced here, not just
                # at tier-transition swaps
                seg.release_planes()
        # forget unloaded segments so the first-seen map stays bounded
        for key in [k for k in self._first_seen if k not in seen_keys]:
            del self._first_seen[key]
        for key in [k for k in self._plan_bytes if k not in seen_keys]:
            del self._plan_bytes[key]
        return applied

    def _plan_cost(self, table: str, name: str, seg) -> int:
        key = (table, name)
        seg_dir = getattr(seg, "dir", "")
        cached = self._plan_bytes.get(key)
        if cached is not None and cached[0] == seg_dir:
            return cached[1]
        # (re)model on first sight AND on a refresh push (same name, new
        # CRC-versioned dir — widths/cardinalities may have changed)
        try:
            cost = segment_plan_bytes(seg)
        except Exception:  # noqa: BLE001 — stats-less segments charge raw
            cost = int(seg.n_docs) * 4 * max(
                1, len(seg.metadata.columns))
        self._plan_bytes[key] = (seg_dir, cost)
        return cost

    # ---- transitions -----------------------------------------------------
    def _tdm(self, table: str):
        return self.server.engine.tables.get(table)

    def demote_to_warm(self, table: str, name: str) -> bool:
        """hot → warm: swap in a fresh LazySegmentView (drops any decoded
        host caches) and evict the segment's device batches so its HBM
        frees NOW, not at LRU depth. Refuses while a query holds the
        segment (retried next tick)."""
        tdm = self._tdm(table)
        if tdm is None:
            return False
        seg = tdm.segments.get(name)
        if seg is None or getattr(seg, "is_mutable", False) \
                or getattr(seg, "is_cold", False):
            return False
        try:
            view = LazySegmentView(seg.dir)
        except Exception:  # noqa: BLE001 — unreadable dir: leave as-is
            log.exception("warm demotion of %s/%s failed to open",
                          table, name)
            return False
        view.table_schema = getattr(seg, "table_schema", None)
        if not tdm.replace_if_idle(name, view):
            return False
        self._evict_device(seg.dir)
        self.demotions_warm += 1
        return True

    def promote_to_hot(self, table: str, name: str) -> bool:
        """warm → hot: flip the routing flag — the next device launch
        re-admits the segment's planes at their ColPlan widths (the
        admission charge ``tick`` already accounted)."""
        tdm = self._tdm(table)
        seg = tdm.segments.get(name) if tdm is not None else None
        if seg is None or getattr(seg, "is_cold", False) \
                or getattr(seg, "is_mutable", False):
            return False
        if (getattr(seg, "tier", None) or Tier.HOT) == Tier.HOT:
            return False
        seg.tier = Tier.HOT
        self.promotions_hot += 1
        return True

    def demote_to_cold(self, table: str, name: str, rec=None) -> bool:
        """warm/hot → cold: evict the local plane files (metadata stays),
        host a ColdSegmentRef so the segment remains routable, deep store
        becomes the only copy. Refuses when the registry record's
        ``location`` is missing or IS this server's working copy (own
        realtime seals: evicting would delete the only copy), or while a
        query holds the segment."""
        tdm = self._tdm(table)
        if tdm is None:
            return False
        seg = tdm.segments.get(name)
        if seg is None or getattr(seg, "is_mutable", False) \
                or getattr(seg, "is_cold", False):
            return False
        if rec is None:
            rec = self._records(table).get(name)
        location = getattr(rec, "location", "") if rec is not None else ""
        if not location:
            return False
        seg_dir = os.path.abspath(seg.dir)
        data_root = os.path.abspath(self.server.data_dir)
        # path-shaped locations (bare paths AND file:// URIs) must point
        # at a copy OUTSIDE this server before the local planes may go —
        # a record whose location IS the working copy (own realtime
        # seals) would otherwise lose its only copy
        local_like = "://" not in location or location.startswith("file://")
        if local_like:
            loc_path = os.path.abspath(
                urlparse(location).path if location.startswith("file://")
                else location)
            if loc_path == seg_dir:
                return False  # the local copy IS the record's location
            if os.path.commonpath([loc_path, data_root]) == data_root:
                return False  # durability would point back into this server
        ref = ColdSegmentRef(table, seg.metadata, seg.dir, manager=self)
        ref.table_schema = getattr(seg, "table_schema", None)
        if not tdm.replace_if_idle(name, ref):
            return False
        with self._lock:
            self._cold[(table, name)] = ref
        self._evict_device(seg.dir)
        # planes go, metadata stays (sync loop + schema surface): only
        # files inside the local working copy are ever deleted
        if os.path.commonpath([seg_dir, data_root]) == data_root:
            for fname in os.listdir(seg.dir):
                if fname in _COLD_KEEP:
                    continue
                p = os.path.join(seg.dir, fname)
                try:
                    if os.path.isdir(p):
                        shutil.rmtree(p, ignore_errors=True)
                    else:
                        os.unlink(p)
                except OSError:
                    pass
        self.demotions_cold += 1
        return True

    def _evict_device(self, seg_dir: str) -> None:
        dev = getattr(self.server.engine, "device", None)
        if dev is not None:
            try:
                dev.evict_segment_dir(seg_dir)
            except Exception:  # noqa: BLE001 — eviction is best-effort
                log.exception("device eviction for %s failed", seg_dir)

    # ---- hydration (cold → warm) -----------------------------------------
    def request_hydration(self, table: str, name: str) -> bool:
        """Enqueue an async re-download of a cold segment (deduped); the
        query that touched it proceeds with an honest partial."""
        key = (table, name)
        with self._lock:
            if key not in self._cold or key in self._hydrating:
                return False
            self._hydrating.add(key)
        if self._hydrator is None or not self._hydrator.is_alive():
            self._hydrator = threading.Thread(
                target=self._hydrate_loop,
                name=f"tier-hydrate-{self.server.instance_id}", daemon=True)
            self._hydrator.start()
        self._hydrate_q.put(key)
        return True

    def _hydrate_loop(self) -> None:
        while not self._stop.is_set():
            try:
                key = self._hydrate_q.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                self._hydrate_one(*key)
            except Exception:  # noqa: BLE001 — one failed download ≠ dead worker
                self.hydration_failures += 1
                log.exception("hydration of %s/%s failed", *key)
            finally:
                with self._lock:
                    self._hydrating.discard(key)

    def _hydrate_one(self, table: str, name: str) -> None:
        """Deep-store download → local planes → re-host WARM. Bounded by
        the PR-6 deadline contract; falls back to a serving peer when the
        deep store is unreachable (server/peer.py)."""
        with self._lock:
            ref = self._cold.get((table, name))
        if ref is None:
            return
        rec = self._records(table).get(name)
        location = getattr(rec, "location", "") if rec is not None else ""
        local = ref.dir
        tmp = f"{local}.hydrate{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        deadline = Deadline(self.download_timeout_s)
        try:
            try:
                if not location:
                    raise FileNotFoundError(
                        f"segment {table}/{name} has no deep-store location")
                from pinot_tpu.storage.fs import create_fs

                create_fs(location).copy(location, tmp)
                deadline.check("deep-store hydration")
            except Exception:
                shutil.rmtree(tmp, ignore_errors=True)
                if deadline.expired():
                    raise
                # deep store unreachable: a serving replica may still hold
                # the planes (PeerServerSegmentFinder role)
                from pinot_tpu.server.peer import peer_download

                peer_download(self.server.registry, table, name, tmp,
                              self.server.instance_id,
                              tls=self.server._tls,
                              timeout_s=self.download_timeout_s,
                              deadline=deadline)
            # move plane files INTO the cold dir one rename at a time —
            # metadata.json is replaced last-wins and the dir never loses
            # it, so the sync loop's lost-files self-heal can't misfire
            os.makedirs(local, exist_ok=True)
            for fname in os.listdir(tmp):
                os.replace(os.path.join(tmp, fname),
                           os.path.join(local, fname))
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        view = LazySegmentView(local)
        view.table_schema = getattr(ref, "table_schema", None)
        tdm = self._tdm(table)
        if tdm is None or tdm.segments.get(name) is not ref:
            # unassigned (or replaced) while downloading: don't resurrect
            with self._lock:
                self._cold.pop((table, name), None)
            return
        # the cold ref holds no file handles: a plain add replaces it even
        # under in-flight references
        tdm.add_segment(view)
        with self._lock:
            self._cold.pop((table, name), None)
        self.hydrations += 1
        log.info("segment %s/%s hydrated cold->warm", table, name)

    def wait_hydrated(self, table: str, name: str, timeout_s: float = 10.0) -> bool:
        """Test/bench helper: block until a requested hydration lands."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            with self._lock:
                if (table, name) not in self._cold:
                    return True
            time.sleep(0.02)
        return False

    def stop(self) -> None:
        self._stop.set()
        if self._hydrator is not None:
            self._hydrator.join(2)
