"""Segment uploader SPI
(pinot-plugins/pinot-segment-uploader/pinot-segment-uploader-default
analog): the pluggable push step between a built segment and the cluster,
with bounded retry — transient deep-store/controller hiccups during a
batch job must not fail the whole job on the first blip
(SegmentUploaderDefault wraps the same retry-and-report loop around the
controller push).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

log = logging.getLogger("pinot_tpu.ingestion.uploader")


class SegmentUploader:
    """SPI surface (SegmentUploader.java role)."""

    def upload(self, table: str, segment_dir: str) -> str:
        """Push one built segment dir; returns the segment name."""
        raise NotImplementedError


class ControllerSegmentUploader(SegmentUploader):
    """Default uploader: the controller push path with exponential-backoff
    retries."""

    def __init__(self, controller, max_attempts: int = 3,
                 backoff_s: float = 0.5):
        self.controller = controller
        self.max_attempts = max(1, max_attempts)
        self.backoff_s = backoff_s

    def upload(self, table: str, segment_dir: str) -> str:
        import random

        last: Optional[Exception] = None
        for attempt in range(self.max_attempts):
            try:
                return self.controller.upload_segment(table, segment_dir)
            except Exception as e:  # noqa: BLE001 — retried, then surfaced
                last = e
                if attempt + 1 < self.max_attempts:
                    # jittered exponential backoff (0.5x-1.0x of the
                    # step): a batch job's N workers failing on the same
                    # controller blip must not retry in lockstep and
                    # re-stampede it at exactly backoff*2^k
                    sleep = self.backoff_s * (2 ** attempt) \
                        * (0.5 + random.random() * 0.5)
                    log.warning(
                        "segment upload %s/%s attempt %d failed (%s); "
                        "retrying in %.1fs", table, segment_dir,
                        attempt + 1, e, sleep)
                    time.sleep(sleep)
        raise RuntimeError(
            f"segment upload {table}/{segment_dir} failed after "
            f"{self.max_attempts} attempts") from last


_UPLOADERS: dict[str, Callable] = {"default": ControllerSegmentUploader}


def register_uploader(name: str, factory: Callable) -> None:
    _UPLOADERS[name] = factory


def create_uploader(name: str, controller, **kwargs) -> SegmentUploader:
    try:
        return _UPLOADERS[name](controller, **kwargs)
    except KeyError:
        raise KeyError(f"unknown segment uploader {name!r}") from None
