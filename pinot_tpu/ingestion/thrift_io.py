"""Thrift input format (pinot-plugins/pinot-input-format/pinot-thrift
analog): TBinaryProtocol struct records → row dicts.

The reference's ThriftRecordReader deserializes through a GENERATED thrift
class (thriftClass config) and maps field ids to names via its metadata
map. A Python build has no generated classes, so the decoder here speaks
the TBinaryProtocol WIRE FORMAT directly — field headers are
self-describing (type byte + int16 field id) — and maps field ids to
column names through the reader config (``thrift.field.map``:
``"1:name,2:age"``), the role the generated class's FieldMetaData plays.
Strict protocol framing (versioned or unversioned struct encoding), no
external thrift dependency.

Supported field types cover FieldSpec's data model: BOOL, BYTE, I16, I32,
I64, DOUBLE, STRING/BINARY, and LIST thereof (multi-value columns).
Nested STRUCT/MAP/SET fields are skipped field-accurately (their bytes
are consumed) — the reference flattens only declared fields too.
"""

from __future__ import annotations

import struct
from typing import Optional

# TType codes (thrift protocol constants)
T_STOP, T_BOOL, T_BYTE, T_DOUBLE = 0, 2, 3, 4
T_I16, T_I32, T_I64 = 6, 8, 10
T_STRING, T_STRUCT, T_MAP, T_SET, T_LIST = 11, 12, 13, 14, 15


class _Buf:
    __slots__ = ("b", "o")

    def __init__(self, b: bytes):
        self.b = b
        self.o = 0

    def take(self, n: int) -> bytes:
        if self.o + n > len(self.b):
            raise EOFError("truncated thrift record")
        out = self.b[self.o: self.o + n]
        self.o += n
        return out

    def u8(self) -> int:
        return self.take(1)[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.take(8))[0]

    def f64(self) -> float:
        return struct.unpack(">d", self.take(8))[0]


def _read_value(buf: _Buf, ttype: int, binary: bool = False):
    if ttype == T_BOOL:
        return buf.u8() != 0
    if ttype == T_BYTE:
        return struct.unpack(">b", buf.take(1))[0]
    if ttype == T_DOUBLE:
        return buf.f64()
    if ttype == T_I16:
        return buf.i16()
    if ttype == T_I32:
        return buf.i32()
    if ttype == T_I64:
        return buf.i64()
    if ttype == T_STRING:
        n = buf.i32()
        raw = buf.take(n)
        if binary:
            return raw  # declared BINARY: bytes, always
        # declared STRING: str, always — the wire type (11) doesn't
        # distinguish string/binary, so the field map's annotation does;
        # content-dependent str-or-bytes would be type-unstable per column
        return raw.decode("utf-8")
    if ttype in (T_LIST, T_SET):
        et = buf.u8()
        n = buf.i32()
        return [_read_value(buf, et, binary) for _ in range(n)]
    if ttype == T_MAP:
        kt, vt = buf.u8(), buf.u8()
        n = buf.i32()
        return {_read_value(buf, kt): _read_value(buf, vt) for _ in range(n)}
    if ttype == T_STRUCT:
        return _read_struct(buf, None)
    raise ValueError(f"unsupported thrift type {ttype}")


def _read_struct(buf: _Buf, field_names: Optional[dict]):
    """One struct's fields; ``field_names`` maps field-id →
    (column name, is_binary) (None → id-keyed dict for nested structs)."""
    out: dict = {}
    while True:
        ftype = buf.u8()
        if ftype == T_STOP:
            return out
        fid = buf.i16()
        decl = field_names.get(fid) if field_names is not None else None
        val = _read_value(buf, ftype,
                          binary=bool(decl and decl[1]))
        if field_names is None:
            out[fid] = val
        elif decl is not None:
            out[decl[0]] = val
        # undeclared fields: bytes consumed, value dropped (reference
        # reads only the thrift class's declared fields)


def parse_field_map(spec: str) -> dict:
    """'1:name,2:age,3:blob#bytes' → {1: ('name', False), 2: ('age',
    False), 3: ('blob', True)}. The ``#bytes`` annotation marks a BINARY
    field (thrift's wire type 11 covers both string and binary; the
    generated class's metadata makes the call in the reference — the
    annotation plays that role here, keeping each column type-stable)."""
    out = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        fid, name = part.split(":", 1)
        name = name.strip()
        binary = name.endswith("#bytes")
        if binary:
            name = name[: -len("#bytes")].strip()
        out[int(fid)] = (name, binary)
    if not out:
        raise ValueError(
            "thrift decoder needs a field map ('thrift.field.map' = "
            "'1:col,2:col2') — the role the generated class plays in the "
            "reference's ThriftRecordReader")
    return out


def decode_record(payload: bytes, field_names: dict) -> dict:
    """One TBinaryProtocol struct → row dict. Accepts both the bare struct
    encoding and the versioned strict framing some serializers emit."""
    buf = _Buf(payload)
    # strict framing starts with a negative i32 version word; the bare
    # struct encoding starts with a field-type byte (< 16)
    if len(payload) >= 4 and payload[0] & 0x80:
        buf.i32()  # VERSION_1 | message type
        name_len = buf.i32()
        buf.take(name_len)
        buf.i32()  # seqid
    return _read_struct(buf, field_names)


def binary_decoder_for(field_map_spec: str):
    names = parse_field_map(field_map_spec)

    def decode(payload: bytes) -> dict:
        return decode_record(payload, names)

    return decode


def encode_record(row: dict, field_map: dict) -> bytes:
    """Row → TBinaryProtocol struct bytes (test fixture / writer utility;
    field_map: id → name). Types are inferred: bool, int (i64), float
    (double), str, bytes, list thereof."""
    out = bytearray()

    def w_value(v):
        if isinstance(v, bool):
            return T_BOOL, bytes([1 if v else 0])
        if isinstance(v, int):
            return T_I64, struct.pack(">q", v)
        if isinstance(v, float):
            return T_DOUBLE, struct.pack(">d", v)
        if isinstance(v, str):
            b = v.encode("utf-8")
            return T_STRING, struct.pack(">i", len(b)) + b
        if isinstance(v, (bytes, bytearray)):
            return T_STRING, struct.pack(">i", len(v)) + bytes(v)
        if isinstance(v, (list, tuple)):
            if not v:
                return T_LIST, bytes([T_STRING]) + struct.pack(">i", 0)
            et, _ = w_value(v[0])
            body = b"".join(w_value(x)[1] for x in v)
            return T_LIST, bytes([et]) + struct.pack(">i", len(v)) + body
        raise TypeError(f"unsupported thrift test value {type(v)}")

    for fid, name in sorted(field_map.items()):
        if isinstance(name, tuple):  # parse_field_map form (name, binary)
            name = name[0]
        if name not in row:
            continue
        ttype, body = w_value(row[name])
        out += bytes([ttype]) + struct.pack(">h", fid) + body
    out += bytes([T_STOP])
    return bytes(out)
