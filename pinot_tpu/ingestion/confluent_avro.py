"""Confluent-Avro stream decoder
(pinot-plugins/pinot-input-format/pinot-confluent-avro analog:
KafkaConfluentSchemaRegistryAvroMessageDecoder).

Wire format: 1 magic byte (0) + 4-byte big-endian schema id + the avro
binary record. The writer schema resolves through a Confluent Schema
Registry (``schema.registry.url``, fetched over plain HTTP with urllib —
no extra dependency) or through inline config
(``schema.registry.schemas`` = {id: schema-json}) for air-gapped /
test deployments. Resolved schemas cache per decoder (the reference
caches via CachedSchemaRegistryClient).
"""

from __future__ import annotations

import io
import json
import struct

from pinot_tpu.ingestion.avro_io import _norm_schema, decode_value

MAGIC = 0


class ConfluentAvroDecoder:
    def __init__(self, registry_url: str = "",
                 inline_schemas: dict | None = None,
                 timeout_s: float = 10.0):
        if not registry_url and not inline_schemas:
            raise KeyError(
                "confluent-avro decoder needs 'schema.registry.url' or "
                "inline 'schema.registry.schemas' in stream properties")
        self.registry_url = registry_url.rstrip("/")
        self.timeout_s = timeout_s
        self._cache: dict[int, dict] = {}
        for sid, sj in (inline_schemas or {}).items():
            self._cache[int(sid)] = _norm_schema(
                json.loads(sj) if isinstance(sj, str) else sj)

    def _schema(self, schema_id: int) -> dict:
        hit = self._cache.get(schema_id)
        if hit is not None:
            return hit
        if not self.registry_url:
            raise KeyError(
                f"schema id {schema_id} not in inline schemas and no "
                f"registry url configured")
        import urllib.request

        with urllib.request.urlopen(
                f"{self.registry_url}/schemas/ids/{schema_id}",
                timeout=self.timeout_s) as resp:
            body = json.loads(resp.read().decode("utf-8"))
        schema = _norm_schema(json.loads(body["schema"]))
        self._cache[schema_id] = schema
        return schema

    def __call__(self, payload: bytes) -> dict:
        if len(payload) < 5 or payload[0] != MAGIC:
            raise ValueError(
                "not a Confluent-framed message (magic byte 0 + schema id)")
        schema_id = struct.unpack(">I", payload[1:5])[0]
        return decode_value(io.BytesIO(payload[5:]),
                            self._schema(schema_id))


def encode_confluent(schema_id: int, schema, record: dict) -> bytes:
    """Producer/test helper: frame one record the Confluent way."""
    from pinot_tpu.ingestion.avro_io import encode_record

    return bytes([MAGIC]) + struct.pack(">I", schema_id) \
        + encode_record(schema, record)
