"""Protobuf input format (pinot-plugins/pinot-input-format/pinot-protobuf
analog), gated on the google.protobuf runtime.

Mirrors the reference's configuration shape: a compiled descriptor set
(``protoc --descriptor_set_out``) names the schema and ``message_name``
picks the record type (ProtoBufRecordReaderConfig: descriptorFile +
protoClassName). Batch files hold length-delimited messages (varint length
prefix, the standard delimited framing the reference reader consumes);
stream payloads are single serialized messages
(ProtoBufMessageDecoder analog).

Records decode to plain dicts with original field names; nested messages
become nested dicts, repeated fields lists — the GenericRow shape.
"""

from __future__ import annotations

import io


def _protobuf():
    try:
        from google.protobuf import (  # type: ignore
            descriptor_pb2,
            json_format,
            message_factory,
        )

        return descriptor_pb2, message_factory, json_format
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "protobuf input requires the google.protobuf runtime; "
            "install protobuf or use csv/json/avro") from e


def load_message_class(descriptor_file: str, message_name: str):
    """Message class from a compiled FileDescriptorSet."""
    descriptor_pb2, message_factory, _ = _protobuf()
    fds = descriptor_pb2.FileDescriptorSet()
    with open(descriptor_file, "rb") as f:
        fds.ParseFromString(f.read())
    classes = message_factory.GetMessages(list(fds.file))
    try:
        return classes[message_name]
    except KeyError:
        raise ValueError(
            f"message {message_name!r} not in descriptor set "
            f"(available: {sorted(classes)})") from None


def message_to_row(msg) -> dict:
    _, _, json_format = _protobuf()
    try:
        return json_format.MessageToDict(
            msg, preserving_proto_field_name=True,
            always_print_fields_with_no_presence=True)
    except TypeError:
        # protobuf < 5.26 names the option differently
        return json_format.MessageToDict(
            msg, preserving_proto_field_name=True,
            including_default_value_fields=True)


def _read_varint(buf: io.BytesIO):
    """None at a clean record boundary; raises on EOF mid-varint (a
    truncated length prefix must not silently drop the partial record)."""
    shift = acc = 0
    first = True
    while True:
        b = buf.read(1)
        if not b:
            if first:
                return None
            raise ValueError("truncated varint length prefix")
        first = False
        acc |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            return acc
        shift += 7


def read_delimited(path: str, descriptor_file: str, message_name: str) -> list:
    """Length-delimited message file → list of row dicts."""
    cls = load_message_class(descriptor_file, message_name)
    rows = []
    with open(path, "rb") as f:
        buf = io.BytesIO(f.read())
    while True:
        n = _read_varint(buf)
        if n is None:
            return rows
        payload = buf.read(n)
        if len(payload) != n:
            raise ValueError(f"{path}: truncated delimited message")
        msg = cls()
        msg.ParseFromString(payload)
        rows.append(message_to_row(msg))


def write_delimited(path: str, messages) -> None:
    """Test/producer helper: serialize messages with varint framing."""
    with open(path, "wb") as f:
        for m in messages:
            payload = m.SerializeToString()
            n = len(payload)
            while True:
                b = n & 0x7F
                n >>= 7
                f.write(bytes([b | 0x80] if n else [b]))
                if not n:
                    break
            f.write(payload)


def binary_decoder_for(descriptor_file: str, message_name: str):
    """Schemaful stream decoder (ProtoBufMessageDecoder analog): each
    message is one serialized record, no framing."""
    cls = load_message_class(descriptor_file, message_name)

    def decode(payload: bytes) -> dict:
        msg = cls()
        msg.ParseFromString(payload)
        return message_to_row(msg)

    return decode
