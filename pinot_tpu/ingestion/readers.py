"""Batch record readers: input files -> schema-coerced column arrays.

Equivalent of the reference's RecordReader SPI + input-format plugins
(pinot-spi/.../data/readers/RecordReader.java,
pinot-plugins/pinot-input-format/pinot-csv/.../CSVRecordReader.java,
pinot-json/.../JSONRecordReader.java), re-shaped column-first: instead of a
row iterator feeding a row-by-row segment creator, a reader returns whole
columns (the creator is vectorized numpy — storage/creator.py fuses stats +
write in column space, so materializing columns is the natural unit).

Formats are a plugin registry keyed by name; AVRO/Parquet register lazily
and raise a clear error when their optional deps are absent.
"""

from __future__ import annotations

import csv
import glob
import json
import os
from typing import Optional

from pinot_tpu.common.schema import Schema


class RecordReader:
    """SPI: subclass and register. ``read_columns`` returns
    {column: list} with values coerced to the schema's types; multi-value
    columns yield a list per row."""

    def __init__(self, **props):
        self.props = props

    def read_rows(self, path: str) -> list:
        """Format-specific: file -> list of {column: raw value} dicts."""
        raise NotImplementedError

    def read_columns(self, path: str, schema: Schema) -> dict:
        return rows_to_columns(self.read_rows(path), schema,
                               mv_delimiter=self.props.get("mv_delimiter", ";"))


def rows_to_columns(rows: list, schema: Schema, mv_delimiter: str = ";") -> dict:
    """Row dicts -> coerced columns. Missing/empty/JSON-null values stay
    ``None`` — the segment creator substitutes the field's default null AND
    records the doc in the column's null vector (CSVRecordReader treats
    empty cells as null the same way). MV cells accept lists or
    delimiter-joined strings (CSV multiValueDelimiter); an explicitly null
    MV ROW is null, an empty string is an empty row."""
    out: dict = {}
    for name in schema.column_names():
        spec = schema.field(name)
        dt = spec.data_type
        col = []
        for row in rows:
            v = row.get(name)
            if spec.single_value:
                col.append(None if v is None or v == "" else dt.convert(v))
            else:
                if v is None:
                    col.append(None)
                    continue
                if v == "":
                    vals = []
                elif isinstance(v, str):
                    vals = v.split(mv_delimiter)
                elif isinstance(v, (list, tuple)):
                    vals = list(v)
                else:
                    vals = [v]
                col.append([dt.convert(x) for x in vals])
        out[name] = col
    return out


class CSVRecordReader(RecordReader):
    """Header-row CSV (CSVRecordReader.java analog). Props: ``delimiter``
    (default ','), ``mv_delimiter`` (default ';')."""

    def read_rows(self, path: str) -> list:
        with open(path, newline="") as f:
            return list(csv.DictReader(f, delimiter=self.props.get("delimiter", ",")))


class JSONRecordReader(RecordReader):
    """JSON lines, or a single top-level JSON array of objects."""

    def read_rows(self, path: str) -> list:
        with open(path) as f:
            text = f.read()
        stripped = text.lstrip()
        if stripped.startswith("["):
            return json.loads(stripped)
        return [json.loads(line) for line in text.splitlines() if line.strip()]


class ParquetRecordReader(RecordReader):
    """Columnar Parquet via pyarrow (pinot-parquet analog); gated on the
    optional pyarrow dependency."""

    def read_rows(self, path: str) -> list:
        try:
            import pyarrow.parquet as pq
        except ImportError as e:
            raise RuntimeError(
                "parquet input requires pyarrow; convert to CSV/JSON or "
                "install pyarrow") from e
        return pq.read_table(path).to_pylist()


class ORCRecordReader(RecordReader):
    """ORC via pyarrow (pinot-orc analog); gated like Parquet."""

    def read_rows(self, path: str) -> list:
        try:
            import pyarrow.orc as orc
        except ImportError as e:
            raise RuntimeError(
                "orc input requires pyarrow; convert to CSV/JSON or "
                "install pyarrow") from e
        return orc.ORCFile(path).read().to_pylist()


class AvroRecordReader(RecordReader):
    """Avro Object Container Files (pinot-avro AvroRecordReader analog) —
    decoded by the in-tree pure-python codec (ingestion/avro_io.py), so no
    external avro dependency gates the canonical Pinot ingestion format."""

    def read_rows(self, path: str) -> list:
        from pinot_tpu.ingestion.avro_io import read_container

        return read_container(path)


class ProtobufRecordReader(RecordReader):
    """Length-delimited protobuf files (pinot-protobuf analog), gated on
    the google.protobuf runtime. Props: ``descriptor_file`` (compiled
    FileDescriptorSet from protoc --descriptor_set_out) and
    ``message_name``."""

    def read_rows(self, path: str) -> list:
        from pinot_tpu.ingestion.protobuf_io import read_delimited

        desc = self.props.get("descriptor_file", "")
        msg = self.props.get("message_name", "")
        if not desc or not msg:
            raise ValueError(
                "protobuf input needs descriptor_file + message_name props")
        return read_delimited(path, desc, msg)


_READERS = {
    "csv": CSVRecordReader,
    "json": JSONRecordReader,
    "parquet": ParquetRecordReader,
    "orc": ORCRecordReader,
    "avro": AvroRecordReader,
    "protobuf": ProtobufRecordReader,
}


def register_record_reader(fmt: str, cls) -> None:
    _READERS[fmt.lower()] = cls


def create_record_reader(fmt: str, **props) -> RecordReader:
    try:
        return _READERS[fmt.lower()](**props)
    except KeyError:
        raise ValueError(
            f"unknown input format {fmt!r}; registered: {sorted(_READERS)}"
        ) from None


def resolve_input_files(input_dir: str, include_pattern: str) -> list:
    """Expand the job's input glob, sorted for deterministic segment names
    (SegmentGenerationJobUtils#listMatchedFilesWithRecursiveOption)."""
    files = sorted(glob.glob(os.path.join(input_dir, include_pattern),
                             recursive=True))
    return [f for f in files if os.path.isfile(f)]
