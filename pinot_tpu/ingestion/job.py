"""Batch ingestion job: spec -> read -> build segments -> push.

Equivalent of the reference's standalone ingestion job
(pinot-spi/.../ingestion/batch/IngestionJobLauncher.java +
SegmentGenerationJobSpec + pinot-batch-ingestion-standalone's
SegmentGenerationJobRunner/SegmentTarPushJobRunner), collapsed to one
runner: each matched input file becomes one segment (the reference's
sequence-id naming), built with the vectorized creator and pushed to the
controller, which assigns replicas and records cluster metadata.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from pinot_tpu.ingestion.readers import create_record_reader, resolve_input_files
from pinot_tpu.storage.creator import build_segment


@dataclasses.dataclass
class IngestionJobSpec:
    """The honored subset of SegmentGenerationJobSpec's YAML surface."""

    table_name: str                 # raw or physical name; controller resolves
    input_dir: str
    include_pattern: str = "*.csv"
    format: str = "csv"             # record reader plugin key
    reader_props: dict = dataclasses.field(default_factory=dict)
    output_dir: Optional[str] = None  # staging dir (default: alongside input)
    segment_name_prefix: Optional[str] = None  # default: table name
    push: bool = True               # False: build segments, don't push
    # >1: per-file segment builds fan out to spawned worker processes —
    # the standalone analog of the hadoop/spark batch runners' distribution
    parallelism: int = 1

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, obj: dict | str) -> "IngestionJobSpec":
        if isinstance(obj, str):
            obj = json.loads(obj)
        return cls(**obj)

    @classmethod
    def load(cls, path: str) -> "IngestionJobSpec":
        with open(path) as f:
            return cls.from_json(json.load(f))


def _build_segment_file(schema, table_cfg, reader, transformer,
                        reader_props, path, name, out_root) -> str:
    """One input file → one built segment dir (shared by the in-process
    loop and the spawned workers)."""
    from pinot_tpu.ingestion.readers import rows_to_columns

    if transformer.active:
        try:
            rows = reader.read_rows(path)
        except NotImplementedError:
            # column-only RecordReader plugins (the SPI's minimum
            # surface): reconstruct rows from the schema columns —
            # transforms then can't see source-only fields, which such
            # a reader could never expose anyway
            raw_cols = reader.read_columns(path, schema)
            names = list(raw_cols)
            rows = [dict(zip(names, vals))
                    for vals in zip(*raw_cols.values())] if names else []
        rows = transformer.apply_rows(rows)
        columns = rows_to_columns(
            rows, schema, mv_delimiter=reader_props.get("mv_delimiter", ";"))
    else:
        columns = reader.read_columns(path, schema)
    seg_dir = os.path.join(out_root, name)
    build_segment(schema, columns, seg_dir, table_cfg, name)
    return seg_dir


def _build_one_spawned(args) -> str:
    """Spawn-context worker: reconstruct job state from picklable pieces.
    The reader travels as its CLASS (pickled by reference), not a registry
    key — a custom reader registered only in the parent would not exist in
    the worker's freshly imported registry."""
    (schema_json, cfg_json, reader_cls, reader_props, path, name,
     out_root) = args
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.ingestion.transform import RecordTransformer

    schema = Schema.from_json(schema_json)
    table_cfg = TableConfig.from_json(cfg_json)
    reader = reader_cls(**reader_props)
    transformer = RecordTransformer(table_cfg)
    return _build_segment_file(schema, table_cfg, reader, transformer,
                               reader_props, path, name, out_root)


def run_ingestion_job(spec: IngestionJobSpec, controller) -> list:
    """Execute the job against a live controller; returns the built segment
    directories (and pushes each unless ``spec.push`` is False).

    ``spec.parallelism > 1`` runs the per-file builds in SPAWNED worker
    processes — the standalone analog of the reference's hadoop/spark
    batch runners (pinot-batch-ingestion-hadoop/-spark distribute exactly
    this per-input-file segment build; here the fan-out is a process pool
    on one host). Pushes stay in the parent, sequential through the
    uploader SPI, exactly like the runners' collect-and-push step."""
    table = controller.resolve(spec.table_name)
    schema = controller.registry.table_schema(table)
    table_cfg = controller.registry.table_config(table)
    if schema is None or table_cfg is None:
        raise KeyError(f"table {spec.table_name!r} not registered")
    files = resolve_input_files(spec.input_dir, spec.include_pattern)
    if not files:
        raise FileNotFoundError(
            f"no input files match {spec.include_pattern!r} in {spec.input_dir}"
        )
    reader = create_record_reader(spec.format, **spec.reader_props)
    out_root = spec.output_dir or os.path.join(spec.input_dir, "_segments")
    prefix = spec.segment_name_prefix or table_cfg.table_name
    names = [f"{prefix}_{seq}" for seq in range(len(files))]
    if spec.parallelism > 1 and len(files) > 1:
        import multiprocessing as mp
        from concurrent.futures import ProcessPoolExecutor

        work = [
            (schema.to_json(), table_cfg.to_json(), type(reader),
             spec.reader_props, path, name, out_root)
            for path, name in zip(files, names)
        ]
        # spawn, not fork: the parent may hold a live JAX/TPU runtime that
        # must not be duplicated into build workers
        with ProcessPoolExecutor(
                max_workers=min(spec.parallelism, len(work)),
                mp_context=mp.get_context("spawn")) as pool:
            built = list(pool.map(_build_one_spawned, work))
    else:
        from pinot_tpu.ingestion.transform import RecordTransformer

        transformer = RecordTransformer(table_cfg)
        built = [
            _build_segment_file(schema, table_cfg, reader, transformer,
                                spec.reader_props, path, name, out_root)
            for path, name in zip(files, names)
        ]
    if spec.push:
        # uploader SPI (segment-uploader-default role): retried with
        # backoff, pluggable via reader_props
        from pinot_tpu.ingestion.uploader import create_uploader

        uploader = create_uploader(
            spec.reader_props.get("segment.uploader", "default"), controller)
        for seg_dir in built:
            uploader.upload(table, seg_dir)
    return built
