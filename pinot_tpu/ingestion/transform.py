"""Ingest-time record transforms + filtering.

Equivalent of the reference's record-transformer chain
(pinot-segment-local/.../recordtransformer/ExpressionTransformer +
FilterTransformer, driven by TransformConfig/FilterConfig): derived
columns compute from source record fields BEFORE schema coercion — so a
transform may read fields that are not schema columns — and rows matching
``filter_function`` are dropped. Expressions are the engine's own SQL
surface (parser + function registry) instead of Groovy.

Evaluation notes:
- String inputs that parse as numbers coerce to numbers before numeric
  ops (CSV readers hand every value over as str; numpy would otherwise
  concatenate '1'+'2' into '12' or crash comparisons).
- IN / NOT IN / BETWEEN / LIKE / IS [NOT] NULL are comparison forms the
  parser lowers to function nodes outside the ops registry; they are
  evaluated here directly.
- Errors raise ``TransformError`` — a CONFIG bug, which ingest paths must
  fail loudly on, never lump in with undecodable (poison) messages.
- Batch files evaluate column-vectorized (the np_fns are vectorized
  already); realtime evaluates per record.
"""

from __future__ import annotations

import re
from typing import Optional

import numpy as np

from pinot_tpu.ops.transform import get_function
from pinot_tpu.query.context import Expression
from pinot_tpu.sql.parser import Parser


class TransformError(Exception):
    """A transform/filter expression failed: misconfiguration, not bad data."""


def _parse(expr_text: str) -> Expression:
    try:
        return Parser(expr_text).parse_expr()
    except Exception as e:  # noqa: BLE001
        raise TransformError(f"bad transform expression {expr_text!r}: {e}") from e


def _maybe_number(v):
    """CSV sources are all-string: numeric-looking operands coerce so
    arithmetic is arithmetic (numpy would silently concatenate)."""
    if isinstance(v, str):
        s = v.strip()
        try:
            return int(s)
        except ValueError:
            try:
                return float(s)
            except ValueError:
                return v
    return v


_LIKE_CACHE: dict = {}


def _like_regex(pattern: str):
    rx = _LIKE_CACHE.get(pattern)
    if rx is None:
        from pinot_tpu.engine.host import like_to_regex

        rx = re.compile(like_to_regex(pattern))
        _LIKE_CACHE[pattern] = rx
    return rx


# ---------------------------------------------------------------------------
# scalar (per-record) evaluation — the realtime path
# ---------------------------------------------------------------------------

def _eval_row(expr: Expression, row: dict):
    """Scalar evaluation over one record; None propagates (a transform
    over an absent/null field yields null, like the reference's
    ExpressionTransformer on null inputs)."""
    if expr.is_literal:
        return expr.value
    if expr.is_identifier:
        return _maybe_number(row.get(expr.name))
    name = expr.name
    if name in ("in", "not_in"):
        lhs = _eval_row(expr.args[0], row)
        if lhs is None:
            return None
        vals = {_eval_row(a, row) for a in expr.args[1:]}
        return (lhs in vals) if name == "in" else (lhs not in vals)
    if name == "between":
        lhs = _eval_row(expr.args[0], row)
        if lhs is None:
            return None
        lo = _eval_row(expr.args[1], row)
        hi = _eval_row(expr.args[2], row)
        return lo <= lhs <= hi
    if name == "like":
        lhs = _eval_row(expr.args[0], row)
        if lhs is None:
            return None
        return bool(_like_regex(str(expr.args[1].value)).match(str(lhs)))
    if name == "is_null":
        return _eval_row(expr.args[0], row) is None
    if name == "is_not_null":
        return _eval_row(expr.args[0], row) is not None
    if name == "cast":
        arg = _eval_row(expr.args[0], row)
        if arg is None:
            return None
        return get_function("cast").np_fn(np.asarray(arg),
                                          expr.args[1].value).item()
    try:
        fn = get_function(name)
    except KeyError as e:
        raise TransformError(f"unknown function {name!r} in transform") from e
    args = [_eval_row(a, row) for a in expr.args]
    if any(a is None for a in args):
        return None
    out = fn.np_fn(*[np.asarray(a) for a in args])
    arr = np.asarray(out)
    return arr.item() if arr.ndim == 0 else arr.tolist()


# ---------------------------------------------------------------------------
# vectorized (per-file) evaluation — the batch path
# ---------------------------------------------------------------------------

class _Cols:
    """Lazy column view over raw row dicts: (values array, none mask)."""

    def __init__(self, rows: list):
        self.rows = rows
        self._cache: dict = {}

    def get(self, name: str):
        if name in self._cache:
            return self._cache[name]
        raw = [r.get(name) for r in self.rows]
        none = np.fromiter((v is None for v in raw), dtype=bool,
                           count=len(raw))
        coerced = [None if v is None else _maybe_number(v) for v in raw]
        numeric = all(isinstance(v, (int, float, bool))
                      for v in coerced if v is not None)
        if numeric:
            arr = np.asarray([0 if v is None else v for v in coerced])
        else:
            arr = np.asarray(["" if v is None else str(v) for v in coerced])
        out = (arr, none)
        self._cache[name] = out
        return out


def _eval_vec(expr: Expression, cols: _Cols, n: int):
    """(values array, none mask) over all rows."""
    if expr.is_literal:
        if expr.value is None:
            return np.zeros(n), np.ones(n, dtype=bool)
        return np.broadcast_to(np.asarray(expr.value), (n,)), \
            np.zeros(n, dtype=bool)
    if expr.is_identifier:
        return cols.get(expr.name)
    name = expr.name
    if name in ("in", "not_in"):
        v, none = _eval_vec(expr.args[0], cols, n)
        vals = [a.value for a in expr.args[1:]]
        if v.dtype.kind in ("U", "S"):
            vals = [str(x) for x in vals]
        m = np.isin(v, np.asarray(vals))
        return (m if name == "in" else ~m), none
    if name == "between":
        v, none = _eval_vec(expr.args[0], cols, n)
        lo, hi = expr.args[1].value, expr.args[2].value
        return (v >= lo) & (v <= hi), none
    if name == "like":
        v, none = _eval_vec(expr.args[0], cols, n)
        rx = _like_regex(str(expr.args[1].value))
        m = np.fromiter((bool(rx.match(str(s))) for s in v), dtype=bool,
                        count=n)
        return m, none
    if name == "is_null":
        _, none = _eval_vec(expr.args[0], cols, n)
        return none.copy(), np.zeros(n, dtype=bool)
    if name == "is_not_null":
        _, none = _eval_vec(expr.args[0], cols, n)
        return ~none, np.zeros(n, dtype=bool)
    try:
        fn = get_function(name)
    except KeyError as e:
        raise TransformError(f"unknown function {name!r} in transform") from e
    if name == "cast":
        v, none = _eval_vec(expr.args[0], cols, n)
        return fn.np_fn(v, expr.args[1].value), none
    parts = [_eval_vec(a, cols, n) for a in expr.args]
    none = np.zeros(n, dtype=bool)
    for _, m in parts:
        none |= m
    return fn.np_fn(*[p[0] for p in parts]), none


class RecordTransformer:
    """Applies a table's IngestionConfig to records (rows)."""

    def __init__(self, table_config):
        ing = getattr(table_config, "ingestion", None)
        self._transforms = []
        self._filter: Optional[Expression] = None
        if ing is None:
            return
        for t in ing.transform_configs:
            self._transforms.append((t.column_name,
                                     _parse(t.transform_function)))
        if ing.filter_function:
            self._filter = _parse(ing.filter_function)

    @property
    def active(self) -> bool:
        return bool(self._transforms) or self._filter is not None

    # ---- realtime: one record at a time ---------------------------------
    def apply_row(self, row: dict) -> Optional[dict]:
        """Transformed record, or None when the filter drops it. Raises
        TransformError on expression failure (config bug — callers must
        NOT treat it as a poison message)."""
        if not self.active:
            return row
        out = dict(row)
        try:
            for col, expr in self._transforms:
                out[col] = _eval_row(expr, out)
            if self._filter is not None and \
                    bool(_eval_row(self._filter, out)):
                return None
        except TransformError:
            raise
        except Exception as e:  # noqa: BLE001 — surface as config failure
            raise TransformError(f"transform failed: {e}") from e
        return out

    # ---- batch: vectorized over a whole file ----------------------------
    def apply_rows(self, rows: list) -> list:
        if not self.active or not rows:
            return rows
        n = len(rows)
        try:
            cols = _Cols(rows)
            derived = {}
            for col, expr in self._transforms:
                vals, none = _eval_vec(expr, cols, n)
                derived[col] = (np.asarray(vals), none)
                # chained transforms see prior outputs
                cols._cache[col] = derived[col]
            keep = np.ones(n, dtype=bool)
            if self._filter is not None:
                m, none = _eval_vec(self._filter, cols, n)
                keep = ~(np.asarray(m, dtype=bool) & ~none)
        except TransformError:
            raise
        except Exception as e:  # noqa: BLE001
            raise TransformError(f"transform failed: {e}") from e
        out = []
        for i in np.nonzero(keep)[0]:
            r = dict(rows[i])
            for col, (vals, none) in derived.items():
                v = vals[i]
                r[col] = None if none[i] else \
                    (v.item() if isinstance(v, np.generic) else v)
            out.append(r)
        return out
