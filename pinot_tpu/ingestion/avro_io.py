"""Pure-python Apache Avro codec: binary records + Object Container Files.

The build image ships no avro library, and Avro is the canonical Pinot
ingestion payload (pinot-plugins/pinot-input-format/pinot-avro/ for batch,
SimpleAvroMessageDecoder / KafkaConfluentSchemaRegistryAvroMessageDecoder
for realtime) — so the format is implemented here from the Avro 1.11 spec:

- binary encoding: zigzag-varint longs, little-endian IEEE float/double,
  length-prefixed bytes/UTF-8 strings, block-encoded arrays/maps,
  union-index-prefixed unions, enums as index, fixed as raw bytes;
- Object Container Files: magic ``Obj\\x01``, metadata map carrying
  ``avro.schema`` + ``avro.codec`` (null and deflate supported), 16-byte
  sync marker, blocks of (record count, byte length, payload, sync).

A writer is included (the reference only reads Avro, but test fixtures and
the quickstart need files to exist without an external library).

Logical types are passed through as their underlying primitives, matching
the reference's GenericRow handling.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib


MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# binary primitives
# ---------------------------------------------------------------------------


def _read_long(buf: io.BytesIO) -> int:
    """Zigzag varint (Avro int and long share the encoding)."""
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated varint")
        byte = b[0]
        acc |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
        if shift > 63:
            # Avro longs are 64-bit: an endless 0x80 run in a corrupt file
            # must fail fast, not grow a bigint unboundedly
            raise ValueError("varint exceeds 64 bits (corrupt avro data)")
    return (acc >> 1) ^ -(acc & 1)


def _write_long(out: io.BytesIO, n: int) -> None:
    n = (n << 1) ^ (n >> 63) if n < 0 else (n << 1)
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            break


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise EOFError("truncated bytes")
    return data


def _write_bytes(out: io.BytesIO, data: bytes) -> None:
    _write_long(out, len(data))
    out.write(data)


# ---------------------------------------------------------------------------
# schema-driven decode / encode
# ---------------------------------------------------------------------------


def _norm_schema(schema, names=None):
    """Resolve named-type references and normalize to dict/list/str form."""
    if names is None:
        names = {}
    if isinstance(schema, str):
        if schema in names:
            return names[schema]
        return schema
    if isinstance(schema, list):
        return [_norm_schema(s, names) for s in schema]
    t = schema.get("type")
    if t in ("record", "enum", "fixed"):
        names[schema["name"]] = schema
        if t == "record":
            for f in schema["fields"]:
                f["type"] = _norm_schema(f["type"], names)
    elif t == "array":
        schema["items"] = _norm_schema(schema["items"], names)
    elif t == "map":
        schema["values"] = _norm_schema(schema["values"], names)
    return schema


def decode_value(buf: io.BytesIO, schema):
    if isinstance(schema, list):  # union: index then value
        idx = _read_long(buf)
        return decode_value(buf, schema[idx])
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {f["name"]: decode_value(buf, f["type"])
                    for f in schema["fields"]}
        if t == "array":
            out = []
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:  # block with byte-size prefix
                    _read_long(buf)
                    n = -n
                for _ in range(n):
                    out.append(decode_value(buf, schema["items"]))
            return out
        if t == "map":
            out = {}
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:
                    _read_long(buf)
                    n = -n
                for _ in range(n):
                    k = _read_bytes(buf).decode("utf-8")
                    out[k] = decode_value(buf, schema["values"])
            return out
        if t == "enum":
            return schema["symbols"][_read_long(buf)]
        if t == "fixed":
            return buf.read(schema["size"])
        return decode_value(buf, t)  # {"type": "long", ...} primitive form
    if schema == "null":
        return None
    if schema == "boolean":
        return buf.read(1) == b"\x01"
    if schema in ("int", "long"):
        return _read_long(buf)
    if schema == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if schema == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if schema == "bytes":
        return _read_bytes(buf)
    if schema == "string":
        return _read_bytes(buf).decode("utf-8")
    raise ValueError(f"unsupported avro schema {schema!r}")


def encode_value(out: io.BytesIO, schema, value) -> None:
    if isinstance(schema, list):  # union: pick the first matching branch
        for i, s in enumerate(schema):
            if _matches(s, value):
                _write_long(out, i)
                encode_value(out, s, value)
                return
        raise ValueError(f"value {value!r} matches no union branch {schema}")
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            for f in schema["fields"]:
                v = value.get(f["name"])
                if v is None and not _nullable(f["type"]):
                    # str(None)/int(None) would silently corrupt the file
                    # or raise a context-free TypeError rows later
                    raise ValueError(
                        f"missing required avro field {f['name']!r} "
                        f"(schema {schema.get('name', '?')})")
                encode_value(out, f["type"], v)
            return
        if t == "array":
            if value:
                _write_long(out, len(value))
                for v in value:
                    encode_value(out, schema["items"], v)
            _write_long(out, 0)
            return
        if t == "map":
            if value:
                _write_long(out, len(value))
                for k, v in value.items():
                    _write_bytes(out, str(k).encode("utf-8"))
                    encode_value(out, schema["values"], v)
            _write_long(out, 0)
            return
        if t == "enum":
            _write_long(out, schema["symbols"].index(value))
            return
        if t == "fixed":
            out.write(value)
            return
        encode_value(out, t, value)
        return
    if schema == "null":
        return
    if schema == "boolean":
        out.write(b"\x01" if value else b"\x00")
    elif schema in ("int", "long"):
        _write_long(out, int(value))
    elif schema == "float":
        out.write(struct.pack("<f", float(value)))
    elif schema == "double":
        out.write(struct.pack("<d", float(value)))
    elif schema == "bytes":
        _write_bytes(out, bytes(value))
    elif schema == "string":
        _write_bytes(out, str(value).encode("utf-8"))
    else:
        raise ValueError(f"unsupported avro schema {schema!r}")


def _nullable(schema) -> bool:
    if schema == "null":
        return True
    if isinstance(schema, list):
        return any(_nullable(s) for s in schema)
    return isinstance(schema, dict) and schema.get("type") == "null"


def _matches(schema, value) -> bool:
    t = schema["type"] if isinstance(schema, dict) else schema
    if t == "null":
        return value is None
    if value is None:
        return False
    if t == "boolean":
        return isinstance(value, bool)
    if t in ("int", "long"):
        return isinstance(value, int) and not isinstance(value, bool)
    if t in ("float", "double"):
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if t in ("bytes", "fixed"):
        return isinstance(value, (bytes, bytearray))
    if t in ("string", "enum"):
        return isinstance(value, str)
    if t == "array":
        return isinstance(value, (list, tuple))
    if t == "map":
        return isinstance(value, dict)
    if t == "record":
        return isinstance(value, dict)
    return False


# ---------------------------------------------------------------------------
# Object Container Files
# ---------------------------------------------------------------------------


def read_container(path: str) -> list:
    """[(record dict), ...] from an Avro Object Container File."""
    with open(path, "rb") as f:
        data = f.read()
    buf = io.BytesIO(data)
    if buf.read(4) != MAGIC:
        raise ValueError(f"{path} is not an Avro container file")
    meta = {}
    while True:
        n = _read_long(buf)
        if n == 0:
            break
        if n < 0:
            _read_long(buf)
            n = -n
        for _ in range(n):
            k = _read_bytes(buf).decode("utf-8")
            meta[k] = _read_bytes(buf)
    schema = _norm_schema(json.loads(meta["avro.schema"].decode("utf-8")))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    sync = buf.read(16)
    rows = []
    while buf.tell() < len(data):
        count = _read_long(buf)
        block = _read_bytes(buf)
        if codec == "deflate":
            block = zlib.decompress(block, -15)  # raw deflate per spec
        elif codec != "null":
            raise ValueError(f"unsupported avro codec {codec!r}")
        bbuf = io.BytesIO(block)
        for _ in range(count):
            rows.append(decode_value(bbuf, schema))
        if buf.read(16) != sync:
            raise ValueError("avro sync marker mismatch (corrupt file)")
    return rows


def write_container(path: str, schema: dict, rows: list,
                    codec: str = "null", sync: bytes = b"\x07" * 16) -> None:
    schema = _norm_schema(dict(schema))
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode("utf-8"),
            "avro.codec": codec.encode("utf-8")}
    _write_long(out, len(meta))
    for k, v in meta.items():
        _write_bytes(out, k.encode("utf-8"))
        _write_bytes(out, v)
    _write_long(out, 0)
    out.write(sync)
    block = io.BytesIO()
    for r in rows:
        encode_value(block, schema, r)
    payload = block.getvalue()
    if codec == "deflate":
        comp = zlib.compressobj(wbits=-15)
        payload = comp.compress(payload) + comp.flush()
    elif codec != "null":
        raise ValueError(f"unsupported avro codec {codec!r}")
    _write_long(out, len(rows))
    _write_bytes(out, payload)
    out.write(sync)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(out.getvalue())


def schema_for_pinot(schema) -> dict:
    """Avro record schema matching a pinot_tpu Schema (test/demo helper)."""
    fields = []
    for name, spec in schema.fields.items():
        dt = spec.data_type.name
        base = {"INT": "int", "LONG": "long", "FLOAT": "float",
                "DOUBLE": "double", "BOOLEAN": "boolean", "STRING": "string",
                "BYTES": "bytes", "TIMESTAMP": "long", "JSON": "string",
                "BIG_DECIMAL": "string"}.get(dt, "string")
        t = base if spec.single_value else {"type": "array", "items": base}
        fields.append({"name": name, "type": t})
    return {"type": "record", "name": schema.name or "row", "fields": fields}


def binary_decoder_for(schema_json: str):
    """Schemaful payload decoder for realtime streams
    (SimpleAvroMessageDecoder analog): each message is one binary-encoded
    record with no container framing."""
    schema = _norm_schema(json.loads(schema_json))

    def decode(payload: bytes) -> dict:
        return decode_value(io.BytesIO(payload), schema)

    return decode


def encode_record(schema, record: dict) -> bytes:
    """One binary record (test/producer helper for the stream decoder)."""
    schema = _norm_schema(schema if isinstance(schema, dict)
                          else json.loads(schema))
    out = io.BytesIO()
    encode_value(out, schema, record)
    return out.getvalue()
