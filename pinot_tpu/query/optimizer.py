"""Filter optimizer: rewrite the FilterNode tree before planning.

Equivalent of pinot-core/.../query/optimizer/filter/:
``FlattenAndOrFilterOptimizer``, ``MergeEqInFilterOptimizer``,
``MergeRangeFilterOptimizer``, plus constant folding
(``NumericalFilterOptimizer``'s always-true/false collapse).
"""

from __future__ import annotations

from typing import Optional

from pinot_tpu.query.context import (
    FilterNode,
    FilterNodeType,
    Predicate,
    PredicateType,
    QueryContext,
)


def optimize_query(q: QueryContext) -> QueryContext:
    if q.filter is None:
        return q
    f = optimize_filter(q.filter)
    if f is q.filter:
        return q
    import dataclasses

    return dataclasses.replace(q, filter=f)


def optimize_filter(f: FilterNode) -> FilterNode:
    f = _flatten(f)
    f = _merge_eq_in(f)
    f = _merge_ranges(f)
    f = _fold_constants(f)
    return f


# ---------------------------------------------------------------------------


def _flatten(f: FilterNode) -> FilterNode:
    """AND(AND(a,b),c) → AND(a,b,c); same for OR; NOT(NOT(x)) → x."""
    if f.type is FilterNodeType.PREDICATE or f.type in (
        FilterNodeType.CONSTANT_TRUE,
        FilterNodeType.CONSTANT_FALSE,
    ):
        return f
    children = [_flatten(c) for c in f.children]
    if f.type is FilterNodeType.NOT:
        c = children[0]
        if c.type is FilterNodeType.NOT:
            return c.children[0]
        return FilterNode(FilterNodeType.NOT, children=(c,))
    out = []
    for c in children:
        if c.type is f.type:
            out.extend(c.children)
        else:
            out.append(c)
    if len(out) == 1:
        return out[0]
    return FilterNode(f.type, children=tuple(out))


def _merge_eq_in(f: FilterNode) -> FilterNode:
    """Under OR: EQ/IN predicates on the same expression merge into one IN
    (MergeEqInFilterOptimizer). Under AND the dual (intersection) applies."""
    if f.type not in (FilterNodeType.AND, FilterNodeType.OR):
        if f.type is FilterNodeType.NOT:
            return FilterNode.not_(_merge_eq_in(f.children[0]))
        return f
    children = [_merge_eq_in(c) for c in f.children]
    mergeable: dict = {}  # lhs -> set of values
    rest = []
    kinds = (PredicateType.EQ, PredicateType.IN)
    for c in children:
        if c.type is FilterNodeType.PREDICATE and c.predicate.type in kinds:
            p = c.predicate
            vals = {p.value} if p.type is PredicateType.EQ else set(p.values)
            if p.lhs in mergeable:
                if f.type is FilterNodeType.OR:
                    mergeable[p.lhs] |= vals
                else:
                    mergeable[p.lhs] &= vals
            else:
                mergeable[p.lhs] = vals
        else:
            rest.append(c)
    for lhs, vals in mergeable.items():
        if len(vals) == 0:
            rest.append(FilterNode.FALSE)
        elif len(vals) == 1:
            rest.append(
                FilterNode.pred(Predicate(PredicateType.EQ, lhs, value=next(iter(vals))))
            )
        else:
            rest.append(
                FilterNode.pred(
                    Predicate(PredicateType.IN, lhs, values=tuple(sorted(vals, key=repr)))
                )
            )
    if len(rest) == 1:
        return rest[0]
    return FilterNode(f.type, children=tuple(rest))


def _merge_ranges(f: FilterNode) -> FilterNode:
    """Under AND: multiple RANGE predicates on the same expression intersect
    into one (MergeRangeFilterOptimizer)."""
    if f.type is FilterNodeType.NOT:
        return FilterNode.not_(_merge_ranges(f.children[0]))
    if f.type is FilterNodeType.OR:
        children = tuple(_merge_ranges(c) for c in f.children)
        return FilterNode(FilterNodeType.OR, children=children)
    if f.type is not FilterNodeType.AND:
        return f
    children = [_merge_ranges(c) for c in f.children]
    ranges: dict = {}
    rest = []
    for c in children:
        if (
            c.type is FilterNodeType.PREDICATE
            and c.predicate.type is PredicateType.RANGE
        ):
            p = c.predicate
            if p.lhs in ranges:
                # an already-empty intersection (None) stays empty — a third
                # range on the same column must not resurrect it
                if ranges[p.lhs] is not None:
                    ranges[p.lhs] = _intersect(ranges[p.lhs], p)
            else:
                ranges[p.lhs] = p
        else:
            rest.append(c)
    for p in ranges.values():
        rest.append(FilterNode.pred(p) if p is not None else FilterNode.FALSE)
    if len(rest) == 1:
        return rest[0]
    return FilterNode(FilterNodeType.AND, children=tuple(rest))


def _intersect(a: Predicate, b: Predicate) -> Optional[Predicate]:
    lower, lower_inc = a.lower, a.lower_inclusive
    if b.lower is not None and (lower is None or b.lower > lower or (b.lower == lower and not b.lower_inclusive)):
        lower, lower_inc = b.lower, b.lower_inclusive
    upper, upper_inc = a.upper, a.upper_inclusive
    if b.upper is not None and (upper is None or b.upper < upper or (b.upper == upper and not b.upper_inclusive)):
        upper, upper_inc = b.upper, b.upper_inclusive
    if lower is not None and upper is not None:
        if lower > upper or (lower == upper and not (lower_inc and upper_inc)):
            return None  # empty range
    return Predicate(
        PredicateType.RANGE,
        a.lhs,
        lower=lower,
        upper=upper,
        lower_inclusive=lower_inc,
        upper_inclusive=upper_inc,
    )


def _fold_constants(f: FilterNode) -> FilterNode:
    if f.type is FilterNodeType.NOT:
        c = _fold_constants(f.children[0])
        if c.type is FilterNodeType.CONSTANT_TRUE:
            return FilterNode.FALSE
        if c.type is FilterNodeType.CONSTANT_FALSE:
            return FilterNode.TRUE
        return FilterNode.not_(c)
    if f.type not in (FilterNodeType.AND, FilterNodeType.OR):
        return f
    children = [_fold_constants(c) for c in f.children]
    out = []
    for c in children:
        if f.type is FilterNodeType.AND:
            if c.type is FilterNodeType.CONSTANT_FALSE:
                return FilterNode.FALSE
            if c.type is FilterNodeType.CONSTANT_TRUE:
                continue
        else:
            if c.type is FilterNodeType.CONSTANT_TRUE:
                return FilterNode.TRUE
            if c.type is FilterNodeType.CONSTANT_FALSE:
                continue
        out.append(c)
    if not out:
        return FilterNode.TRUE if f.type is FilterNodeType.AND else FilterNode.FALSE
    if len(out) == 1:
        return out[0]
    return FilterNode(f.type, children=tuple(out))
