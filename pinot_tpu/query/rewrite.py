"""Query rewrite helpers shared by engine and broker."""

from __future__ import annotations

import dataclasses

from pinot_tpu.query.context import Expression, QueryContext


def expand_star(q: QueryContext, column_names) -> QueryContext:
    """SELECT * → explicit schema columns (CalciteSqlParser star expansion);
    both the in-process engine and the broker reduce need identical select
    positions."""
    if not any(e.is_identifier and e.name == "*" for e in q.select_expressions):
        return q
    cols = [Expression.identifier(c) for c in column_names]
    select, aliases = [], []
    for e, a in zip(q.select_expressions, q.aliases or [None] * len(q.select_expressions)):
        if e.is_identifier and e.name == "*":
            select.extend(cols)
            aliases.extend([None] * len(cols))
        else:
            select.append(e)
            aliases.append(a)
    return dataclasses.replace(
        q, select_expressions=tuple(select), aliases=tuple(aliases)
    )
