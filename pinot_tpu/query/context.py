"""Engine-internal query IR.

Equivalent of the reference's request-context layer
(pinot-common/.../common/request/context/: ``ExpressionContext``,
``FilterContext``, ``predicate/*``, and pinot-core's ``QueryContext``,
query/request/context/QueryContext.java): the SQL front-end compiles the AST
into this IR, and the plan maker dispatches on it.

TPU-first deviation: every node here is a frozen, hashable dataclass built
from tuples. The executor keys its jit cache on the *structural template* of
a QueryContext (literals parameterized out), so two queries differing only in
literal values reuse one compiled kernel pipeline — the moral equivalent of
the reference compiling per query shape in
``InstancePlanMakerImplV2.makeSegmentPlanNode`` (:237-252) but with explicit
compile-once-per-template semantics that XLA requires.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Union

# ---------------------------------------------------------------------------
# Expressions
# ---------------------------------------------------------------------------


class ExpressionType(enum.Enum):
    LITERAL = "LITERAL"
    IDENTIFIER = "IDENTIFIER"
    FUNCTION = "FUNCTION"


@dataclasses.dataclass(frozen=True)
class Expression:
    """One node of an expression tree (ExpressionContext.java analog)."""

    type: ExpressionType
    # exactly one of the below is meaningful, per `type`
    value: object = None          # LITERAL: python scalar (str/int/float/bool/None)
    name: str = ""                # IDENTIFIER: column name; FUNCTION: canonical fn name
    args: tuple = ()  # FUNCTION args: tuple[Expression, ...]

    # ---- constructors ----------------------------------------------------
    @staticmethod
    def literal(value) -> "Expression":
        return Expression(ExpressionType.LITERAL, value=value)

    @staticmethod
    def identifier(name: str) -> "Expression":
        return Expression(ExpressionType.IDENTIFIER, name=name)

    @staticmethod
    def function(name: str, *args: "Expression") -> "Expression":
        return Expression(ExpressionType.FUNCTION, name=name.lower(), args=tuple(args))

    # ---- helpers ---------------------------------------------------------
    @property
    def is_literal(self) -> bool:
        return self.type is ExpressionType.LITERAL

    @property
    def is_identifier(self) -> bool:
        return self.type is ExpressionType.IDENTIFIER

    @property
    def is_function(self) -> bool:
        return self.type is ExpressionType.FUNCTION

    def columns(self) -> set[str]:
        """All identifier names referenced under this expression."""
        if self.is_identifier:
            return {self.name} if self.name != "*" else set()
        if self.is_function:
            out: set[str] = set()
            for a in self.args:
                out |= a.columns()
            return out
        return set()

    def __str__(self) -> str:  # EXPLAIN / debugging
        if self.is_literal:
            return repr(self.value) if isinstance(self.value, str) else str(self.value)
        if self.is_identifier:
            return self.name
        return f"{self.name}({','.join(str(a) for a in self.args)})"


STAR = Expression.identifier("*")

# Aggregation function names the engine understands (reference:
# pinot-core/.../query/aggregation/function/AggregationFunctionFactory.java).
AGGREGATION_FUNCTIONS = frozenset(
    {
        "count",
        "sum",
        "min",
        "max",
        "avg",
        "minmaxrange",
        "sumprecision",
        "distinctcount",
        "distinctcountbitmap",
        "distinctcounthll",
        "distinctcountthetasketch",
        "distinctcountrawthetasketch",
        "distinctcountsmarthll",
        "distinctcountrawhll",
        "fasthll",
        "segmentpartitioneddistinctcount",
        "percentile",
        "percentileest",
        "percentilerawest",
        "percentiletdigest",
        "percentilerawtdigest",
        "percentilesmarttdigest",
        "mode",
        "firstwithtime",
        "lastwithtime",
        "idset",
        "stunion",
        "st_union",
        # MV variants
        "countmv",
        "summv",
        "minmv",
        "maxmv",
        "avgmv",
        "minmaxrangemv",
        "distinctcountmv",
        "distinctcountbitmapmv",
        "distinctcounthllmv",
        "distinctcountrawhllmv",
        "percentilemv",
        "percentileestmv",
        "percentiletdigestmv",
        "percentilerawestmv",
        "percentilerawtdigestmv",
        # internal: star-tree sketch-state re-merges (engine/startree_exec.py)
        "hllmerge",
        "tdigestmerge",
        "bitmapmerge",
        "sumprecisionmerge",
    }
)


def is_aggregation(expr: Expression) -> bool:
    return expr.is_function and expr.name in AGGREGATION_FUNCTIONS


def find_aggregations(expr: Expression) -> list[Expression]:
    """All aggregation sub-expressions, depth-first (dedup preserved later).
    ``__window__`` nodes are opaque: SUM(x) OVER (...) is a window function
    owned by the multi-stage runner, not a mergeable aggregation."""
    if not expr.is_function or expr.name == "__window__":
        return []
    if is_aggregation(expr):
        return [expr]
    out = []
    for a in expr.args:
        out.extend(find_aggregations(a))
    return out


# ---------------------------------------------------------------------------
# Predicates
# ---------------------------------------------------------------------------


class PredicateType(enum.Enum):
    EQ = "EQ"
    NOT_EQ = "NOT_EQ"
    IN = "IN"
    NOT_IN = "NOT_IN"
    RANGE = "RANGE"
    REGEXP_LIKE = "REGEXP_LIKE"
    LIKE = "LIKE"
    TEXT_MATCH = "TEXT_MATCH"
    JSON_MATCH = "JSON_MATCH"
    IS_NULL = "IS_NULL"
    IS_NOT_NULL = "IS_NOT_NULL"


@dataclasses.dataclass(frozen=True)
class Predicate:
    """A leaf predicate over one expression (predicate/*.java analog).

    RANGE uses ``lower``/``upper`` (None = unbounded) with inclusivity flags,
    like the reference's RangePredicate string form ``(lo\x00hi]``.
    """

    type: PredicateType
    lhs: Expression
    # EQ/NOT_EQ: value in `value`; IN/NOT_IN: tuple in `values`;
    # RANGE: lower/upper; LIKE/REGEXP_LIKE/TEXT_MATCH/JSON_MATCH: pattern in `value`
    value: object = None
    values: tuple = ()
    lower: object = None
    upper: object = None
    lower_inclusive: bool = True
    upper_inclusive: bool = True

    def __str__(self) -> str:
        t = self.type
        if t is PredicateType.EQ:
            return f"{self.lhs} = {self.value!r}"
        if t is PredicateType.NOT_EQ:
            return f"{self.lhs} != {self.value!r}"
        if t in (PredicateType.IN, PredicateType.NOT_IN):
            op = "IN" if t is PredicateType.IN else "NOT IN"
            return f"{self.lhs} {op} ({','.join(map(repr, self.values))})"
        if t is PredicateType.RANGE:
            lo = "(" if not self.lower_inclusive else "["
            hi = ")" if not self.upper_inclusive else "]"
            return f"{self.lhs} {lo}{self.lower},{self.upper}{hi}"
        if t is PredicateType.IS_NULL:
            return f"{self.lhs} IS NULL"
        if t is PredicateType.IS_NOT_NULL:
            return f"{self.lhs} IS NOT NULL"
        return f"{t.value}({self.lhs},{self.value!r})"


# ---------------------------------------------------------------------------
# Filter tree
# ---------------------------------------------------------------------------


class FilterNodeType(enum.Enum):
    AND = "AND"
    OR = "OR"
    NOT = "NOT"
    PREDICATE = "PREDICATE"
    # constant filters produced by the optimizer (e.g. 1 != 1)
    CONSTANT_TRUE = "TRUE"
    CONSTANT_FALSE = "FALSE"


@dataclasses.dataclass(frozen=True)
class FilterNode:
    """Filter tree node (FilterContext.java analog)."""

    type: FilterNodeType
    children: tuple = ()  # tuple[FilterNode, ...] for AND/OR/NOT
    predicate: Optional[Predicate] = None

    @staticmethod
    def and_(*children: "FilterNode") -> "FilterNode":
        return FilterNode(FilterNodeType.AND, children=tuple(children))

    @staticmethod
    def or_(*children: "FilterNode") -> "FilterNode":
        return FilterNode(FilterNodeType.OR, children=tuple(children))

    @staticmethod
    def not_(child: "FilterNode") -> "FilterNode":
        return FilterNode(FilterNodeType.NOT, children=(child,))

    @staticmethod
    def pred(p: Predicate) -> "FilterNode":
        return FilterNode(FilterNodeType.PREDICATE, predicate=p)

    TRUE = None  # type: ignore  # filled in below
    FALSE = None  # type: ignore

    def columns(self) -> set[str]:
        if self.type is FilterNodeType.PREDICATE:
            return self.predicate.lhs.columns()
        out: set[str] = set()
        for c in self.children:
            out |= c.columns()
        return out

    def __str__(self) -> str:
        if self.type is FilterNodeType.PREDICATE:
            return str(self.predicate)
        if self.type is FilterNodeType.NOT:
            return f"NOT({self.children[0]})"
        if self.type in (FilterNodeType.CONSTANT_TRUE, FilterNodeType.CONSTANT_FALSE):
            return self.type.value
        sep = f" {self.type.value} "
        return "(" + sep.join(str(c) for c in self.children) + ")"


FilterNode.TRUE = FilterNode(FilterNodeType.CONSTANT_TRUE)
FilterNode.FALSE = FilterNode(FilterNodeType.CONSTANT_FALSE)


# ---------------------------------------------------------------------------
# Order-by / query context
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class OrderByExpression:
    expression: Expression
    ascending: bool = True

    def __str__(self) -> str:
        return f"{self.expression} {'ASC' if self.ascending else 'DESC'}"


@dataclasses.dataclass(frozen=True)
class QueryContext:
    """Compiled query (QueryContext.java analog). Hashable; used as part of
    the executor's jit-cache key after literal parameterization."""

    table_name: str
    select_expressions: tuple  # tuple[Expression, ...]
    aliases: tuple = ()        # tuple[Optional[str], ...] parallel to select
    distinct: bool = False
    filter: Optional[FilterNode] = None
    group_by: tuple = ()       # tuple[Expression, ...]
    having: Optional[FilterNode] = None
    order_by: tuple = ()       # tuple[OrderByExpression, ...]
    limit: int = 10            # reference default LIMIT 10 (CalciteSqlParser)
    offset: int = 0
    options: tuple = ()        # tuple[(key, value), ...] from SET statements
    explain: bool = False
    # EXPLAIN ANALYZE (ISSUE 11): execute for real + annotate the plan
    analyze: bool = False

    # ---- derived ---------------------------------------------------------
    def aggregations(self) -> list[Expression]:
        """Deduplicated aggregation expressions across select/having/order-by
        (QueryContext.getAggregationFunctions analog)."""
        seen: dict[Expression, None] = {}
        sources = list(self.select_expressions)
        if self.having is not None:
            sources.extend(_filter_expressions(self.having))
        for ob in self.order_by:
            sources.append(ob.expression)
        for e in sources:
            for a in find_aggregations(e):
                seen.setdefault(a)
        return list(seen)

    @property
    def is_aggregation_query(self) -> bool:
        return bool(self.aggregations())

    @property
    def is_group_by(self) -> bool:
        return bool(self.group_by)

    def columns(self) -> set[str]:
        out: set[str] = set()
        for e in self.select_expressions:
            out |= e.columns()
        if self.filter is not None:
            out |= self.filter.columns()
        for e in self.group_by:
            out |= e.columns()
        if self.having is not None:
            out |= self.having.columns()
        for ob in self.order_by:
            out |= ob.expression.columns()
        return out

    def options_dict(self) -> dict:
        return dict(self.options)

    def options_ci(self) -> dict:
        """SET options with case-insensitive keys (the reference treats
        query-option names case-insensitively, QueryOptionsUtils)."""
        return {str(k).lower(): v for k, v in self.options}

    def column_name(self, i: int) -> str:
        """Result column header for select position i (alias or expr string)."""
        if i < len(self.aliases) and self.aliases[i]:
            return self.aliases[i]
        return str(self.select_expressions[i])


def _filter_expressions(f: FilterNode) -> list[Expression]:
    if f.type is FilterNodeType.PREDICATE:
        return [f.predicate.lhs]
    out = []
    for c in f.children:
        out.extend(_filter_expressions(c))
    return out
