"""Native runtime pieces: on-demand-compiled C++ with numpy fallbacks.

The compute path is JAX/XLA; the runtime around it uses native code where
the reference does (here: the bit-packing codec backing
``<col>.fwdpacked.bin``, the FixedBitSVForwardIndexWriter/PinotDataBitSet
analog). The shared library is compiled once per checkout with the system
``g++`` (no pip/pybind11 — plain ``extern "C"`` + ctypes) and cached next
to the source; when no toolchain is available the vectorized numpy
fallback serves the same format, so segments stay portable either way.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger("pinot_tpu.native")

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "packer.cpp")
_LIB = os.path.join(_HERE, "_libpinot_packer.so")

_lock = threading.Lock()
_lib = None
_lib_tried = False


def _compile() -> bool:
    # compile to a pid-suffixed temp then os.replace: concurrent processes
    # racing through a fresh checkout must never dlopen a half-written .so
    tmp = f"{_LIB}.{os.getpid()}"
    base = ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC]
    # no zlib dev headers must not cost the bit-packing codec its native
    # path: retry without the inflate section (python stdlib zlib covers
    # decompression of the same bytes)
    for cmd in (base + ["-lz"], base + ["-DPINOT_NO_ZLIB"]):
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, _LIB)
            return True
        except Exception as e:  # noqa: BLE001 — try next variant / fall back
            log.warning("native packer build failed (%s) with %s", e, cmd[-1])
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return False


def _load():
    """ctypes handle on the packer library, or None (numpy fallback)."""
    global _lib, _lib_tried
    with _lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        try:
            if (not os.path.exists(_LIB)
                    or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                if not _compile():
                    return None
            lib = ctypes.CDLL(_LIB)
            lib.pack_bits.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
                ctypes.c_int, ctypes.POINTER(ctypes.c_uint8),
            ]
            lib.unpack_bits.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
            ]
            if hasattr(lib, "inflate_chunks"):  # absent under PINOT_NO_ZLIB
                lib.inflate_chunks.argtypes = [
                    ctypes.POINTER(ctypes.c_uint8),
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
                    ctypes.POINTER(ctypes.c_int64),
                ]
                lib.inflate_chunks.restype = ctypes.c_int
            _lib = lib
        except Exception as e:  # noqa: BLE001
            log.warning("native packer load failed (%s); numpy fallback", e)
            _lib = None
        return _lib


def native_available() -> bool:
    return _load() is not None


def bits_needed(cardinality: int) -> int:
    """Bits per dict id (>=1), PinotDataBitSet.getNumBitsPerValue analog."""
    if cardinality <= 1:
        return 1
    return int(cardinality - 1).bit_length()


def packed_size(n: int, bits: int) -> int:
    return (n * bits + 7) // 8


def pack(ids: np.ndarray, bits: int) -> np.ndarray:
    """int32 dict ids -> packed uint8 buffer (little-endian bit order)."""
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    n = len(ids)
    out = np.zeros(packed_size(n, bits), dtype=np.uint8)
    if n == 0:
        return out
    lib = _load()
    if lib is not None:
        lib.pack_bits(
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int64(n), ctypes.c_int(bits),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        return out
    return _pack_np(ids, bits, out)


def unpack(buf: np.ndarray, n: int, bits: int) -> np.ndarray:
    """Packed uint8 buffer -> int32 dict ids."""
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    out = np.empty(n, dtype=np.int32)
    if n == 0:
        return out
    lib = _load()
    if lib is not None:
        lib.unpack_bits(
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(n), ctypes.c_int(bits),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return out
    return _unpack_np(buf, n, bits)


# ---------------------------------------------------------------------------
# Chunked zlib compression for raw forward indexes (io/compression analog:
# the reference's per-chunk LZ4/Snappy/zstd compressors behind
# Fixed/VarByteChunkSVForwardIndex). zlib so the C++ decoder and the
# stdlib-zlib fallback read the same bytes.
# ---------------------------------------------------------------------------

CHUNK_BYTES = 1 << 18  # 256 KiB uncompressed per chunk


def compress_chunks(data: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Raw little-endian bytes -> (concatenated compressed chunks,
    offsets[n_chunks+1]). Build path: stdlib zlib (cold, simple)."""
    import zlib

    data = np.ascontiguousarray(data).view(np.uint8).reshape(-1)
    raw = data.tobytes()
    chunks = [zlib.compress(raw[i: i + CHUNK_BYTES], 6)
              for i in range(0, len(raw), CHUNK_BYTES)] or [zlib.compress(b"")]
    offsets = np.zeros(len(chunks) + 1, dtype=np.int64)
    np.cumsum([len(c) for c in chunks], out=offsets[1:])
    return np.frombuffer(b"".join(chunks), dtype=np.uint8), offsets


def decompress_chunks(blob: np.ndarray, offsets: np.ndarray,
                      total_bytes: int) -> np.ndarray:
    """(compressed chunks, offsets) -> uncompressed uint8 array of
    total_bytes. Load path: native inflate loop, stdlib zlib fallback."""
    blob = np.ascontiguousarray(blob, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n_chunks = len(offsets) - 1
    out = np.empty(total_bytes, dtype=np.uint8)
    if total_bytes == 0:
        return out
    dst_off = np.minimum(
        np.arange(n_chunks + 1, dtype=np.int64) * CHUNK_BYTES, total_bytes)
    lib = _load()
    if lib is not None and hasattr(lib, "inflate_chunks"):
        rc = lib.inflate_chunks(
            blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(n_chunks),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            dst_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        if rc != 0:
            raise ValueError(f"corrupt compressed forward index (zlib rc={rc})")
        return out
    import zlib

    buf = blob.tobytes()
    pos = 0
    for c in range(n_chunks):
        chunk = zlib.decompress(buf[offsets[c]: offsets[c + 1]])
        out[pos: pos + len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
        pos += len(chunk)
    if pos != total_bytes:
        raise ValueError(f"corrupt compressed forward index "
                         f"({pos} bytes, expected {total_bytes})")
    return out


# ---------------------------------------------------------------------------
# numpy fallback (same byte format, vectorized via a per-value bit matrix)
# ---------------------------------------------------------------------------


def _pack_np(ids: np.ndarray, bits: int, out: np.ndarray) -> np.ndarray:
    n = len(ids)
    # (n, bits) value bits, little-endian per value, flattened to the
    # global little-endian bitstream then repacked 8 at a time
    shifts = np.arange(bits, dtype=np.uint32)
    bitmat = ((ids.astype(np.uint32)[:, None] >> shifts) & 1).astype(np.uint8)
    stream = bitmat.reshape(-1)
    pad = (-len(stream)) % 8
    if pad:
        stream = np.concatenate([stream, np.zeros(pad, dtype=np.uint8)])
    out[:] = np.packbits(stream.reshape(-1, 8), axis=1, bitorder="little").reshape(-1)
    return out


def _unpack_np(buf: np.ndarray, n: int, bits: int) -> np.ndarray:
    stream = np.unpackbits(buf, bitorder="little")[: n * bits]
    bitmat = stream.reshape(n, bits).astype(np.uint32)
    shifts = np.arange(bits, dtype=np.uint32)
    return (bitmat << shifts).sum(axis=1).astype(np.int32)
