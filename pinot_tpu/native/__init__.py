"""Native runtime pieces: on-demand-compiled C++ with numpy fallbacks.

The compute path is JAX/XLA; the runtime around it uses native code where
the reference does (here: the bit-packing codec backing
``<col>.fwdpacked.bin``, the FixedBitSVForwardIndexWriter/PinotDataBitSet
analog). The shared library is compiled once per checkout with the system
``g++`` (no pip/pybind11 — plain ``extern "C"`` + ctypes) and cached next
to the source; when no toolchain is available the vectorized numpy
fallback serves the same format, so segments stay portable either way.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger("pinot_tpu.native")

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "packer.cpp")
_LIB = os.path.join(_HERE, "_libpinot_packer.so")

_lock = threading.Lock()
_lib = None
_lib_tried = False


def _compile() -> bool:
    # compile to a pid-suffixed temp then os.replace: concurrent processes
    # racing through a fresh checkout must never dlopen a half-written .so
    tmp = f"{_LIB}.{os.getpid()}"
    base = ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC]
    # degrade codec by codec: a host missing one dev header/library must
    # not cost the others their native path (python fallbacks read the
    # same bytes, slower). liblz4 often ships only the versioned .so.
    # probe each codec independently, then compile once with exactly the
    # available set — a host missing one dev header/library must not cost
    # the OTHERS their native path
    probes = {
        "zlib": (["-lz"], "#include <zlib.h>\nint main(){return 0;}"),
        "zstd": (["-lzstd"], "#include <zstd.h>\nint main(){return 0;}"),
        # liblz4 often ships only the versioned .so and no header; the
        # packer declares the stable ABI itself, so probe link-only
        "lz4": (["-l:liblz4.so.1"],
                "extern \"C\" int LZ4_compressBound(int);\n"
                "int main(){return LZ4_compressBound(1) > 0 ? 0 : 1;}"),
        "lz4alt": (["-llz4"],
                   "extern \"C\" int LZ4_compressBound(int);\n"
                   "int main(){return LZ4_compressBound(1) > 0 ? 0 : 1;}"),
    }
    import tempfile

    def _probe(flags, src_text) -> bool:
        with tempfile.TemporaryDirectory() as td:
            src = os.path.join(td, "probe.cpp")
            with open(src, "w") as f:
                f.write(src_text)
            try:
                subprocess.run(
                    ["g++", "-o", os.path.join(td, "probe"), src] + flags,
                    check=True, capture_output=True, timeout=60)
                return True
            except Exception:  # noqa: BLE001 — feature probe
                return False

    extra = []
    for name, define in (("zlib", "PINOT_NO_ZLIB"),
                         ("zstd", "PINOT_NO_ZSTD")):
        flags, src_text = probes[name]
        if _probe(flags, src_text):
            extra += flags
        else:
            extra.append(f"-D{define}")
    if _probe(*probes["lz4"]):
        extra.append("-l:liblz4.so.1")
    elif _probe(*probes["lz4alt"]):
        extra.append("-llz4")
    else:
        extra.append("-DPINOT_NO_LZ4")
    try:
        subprocess.run(base + extra, check=True, capture_output=True,
                       timeout=120)
        os.replace(tmp, _LIB)
        return True
    except Exception as e:  # noqa: BLE001 — numpy/python fallbacks serve
        log.warning("native packer build failed (%s) with %s", e, extra)
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return False


_FORCE_NUMPY_ENV = "PINOT_TPU_NO_NATIVE"


def _load():
    """ctypes handle on the packer library, or None (numpy fallback).

    Every failure mode — no toolchain, a failed compile, a corrupt or
    unloadable ``_libpinot_packer.so`` — degrades to the pure-numpy codec
    (`_pack_np`/`_unpack_np`, same byte format), so ``<col>.fwdpacked.bin``
    segments stay readable on any host. ``PINOT_TPU_NO_NATIVE=1`` forces
    the numpy path outright (checked per call, ahead of the cached
    handle, so tests and constrained deployments can flip it without
    reloading the module)."""
    global _lib, _lib_tried
    if os.environ.get(_FORCE_NUMPY_ENV, "") not in ("", "0"):
        return None
    with _lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        try:
            if (not os.path.exists(_LIB)
                    or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                if not _compile():
                    return None
            lib = ctypes.CDLL(_LIB)
            lib.pack_bits.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
                ctypes.c_int, ctypes.POINTER(ctypes.c_uint8),
            ]
            lib.unpack_bits.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
            ]
            if hasattr(lib, "inflate_chunks"):  # absent under PINOT_NO_ZLIB
                lib.inflate_chunks.argtypes = [
                    ctypes.POINTER(ctypes.c_uint8),
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
                    ctypes.POINTER(ctypes.c_int64),
                ]
                lib.inflate_chunks.restype = ctypes.c_int
            _chunk_args = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_int64),
            ]
            for fn in ("zstd_decompress_chunks", "lz4_decompress_chunks"):
                if hasattr(lib, fn):  # absent under PINOT_NO_ZSTD/_LZ4
                    getattr(lib, fn).argtypes = _chunk_args
                    getattr(lib, fn).restype = ctypes.c_int
            if hasattr(lib, "zstd_compress_chunk"):
                lib.zstd_compress_chunk.argtypes = [
                    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                    ctypes.c_int,
                ]
                lib.zstd_compress_chunk.restype = ctypes.c_int64
                lib.zstd_bound.argtypes = [ctypes.c_int64]
                lib.zstd_bound.restype = ctypes.c_int64
            if hasattr(lib, "lz4_compress_chunk"):
                lib.lz4_compress_chunk.argtypes = [
                    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ]
                lib.lz4_compress_chunk.restype = ctypes.c_int64
                lib.lz4_bound.argtypes = [ctypes.c_int64]
                lib.lz4_bound.restype = ctypes.c_int64
            _lib = lib
        except Exception as e:  # noqa: BLE001
            log.warning("native packer load failed (%s); numpy fallback", e)
            _lib = None
        return _lib


def native_available() -> bool:
    return _load() is not None


def bits_needed(cardinality: int) -> int:
    """Bits per dict id (>=1), PinotDataBitSet.getNumBitsPerValue analog."""
    if cardinality <= 1:
        return 1
    return int(cardinality - 1).bit_length()


def packed_size(n: int, bits: int) -> int:
    return (n * bits + 7) // 8


def pack(ids: np.ndarray, bits: int) -> np.ndarray:
    """int32 dict ids -> packed uint8 buffer (little-endian bit order)."""
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    n = len(ids)
    out = np.zeros(packed_size(n, bits), dtype=np.uint8)
    if n == 0:
        return out
    lib = _load()
    if lib is not None:
        lib.pack_bits(
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int64(n), ctypes.c_int(bits),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        return out
    return _pack_np(ids, bits, out)


def unpack(buf: np.ndarray, n: int, bits: int) -> np.ndarray:
    """Packed uint8 buffer -> int32 dict ids."""
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    out = np.empty(n, dtype=np.int32)
    if n == 0:
        return out
    lib = _load()
    if lib is not None:
        lib.unpack_bits(
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(n), ctypes.c_int(bits),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return out
    return _unpack_np(buf, n, bits)


# ---------------------------------------------------------------------------
# Chunked compression for raw forward indexes (io/compression analog: the
# reference's per-chunk compressors behind Fixed/VarByteChunkSVForwardIndex,
# ChunkCompressionType = PASS_THROUGH | SNAPPY | ZSTANDARD | LZ4; here
# zlib | zstd | lz4, selectable per column via IndexingConfig). Each codec
# has a native C++ loop and a pure-python fallback reading the same bytes.
# ---------------------------------------------------------------------------

CHUNK_BYTES = 1 << 18  # 256 KiB uncompressed per chunk

CHUNK_CODECS = ("zlib", "zstd", "lz4")


def _lz4_compress_py(src: bytes) -> bytes:
    """Literal-only LZ4 block (valid format, no matches) — the build-path
    fallback when the native library is absent: round-trips correctly at
    roughly pass-through size."""
    out = bytearray()
    L = len(src)
    token_lit = min(L, 15)
    out.append(token_lit << 4)
    if token_lit == 15:
        rem = L - 15
        while rem >= 255:
            out.append(255)
            rem -= 255
        out.append(rem)
    out += src
    return bytes(out)


def _lz4_decompress_py(src: bytes, expected: int) -> bytes:
    """Pure-python LZ4 block decoder (load-path fallback)."""
    out = bytearray()
    i, n = 0, len(src)
    while i < n:
        token = src[i]
        i += 1
        lit = token >> 4
        if lit == 15:
            while True:
                b = src[i]
                i += 1
                lit += b
                if b != 255:
                    break
        out += src[i: i + lit]
        i += lit
        if i >= n:
            break  # last sequence carries no match
        off = src[i] | (src[i + 1] << 8)
        i += 2
        ml = token & 15
        if ml == 15:
            while True:
                b = src[i]
                i += 1
                ml += b
                if b != 255:
                    break
        ml += 4
        start = len(out) - off
        if start < 0:
            raise ValueError("corrupt LZ4 block (offset before start)")
        for _ in range(ml):  # byte-wise: matches may overlap themselves
            out.append(out[start])
            start += 1
    if len(out) != expected:
        raise ValueError(
            f"corrupt LZ4 block ({len(out)} bytes, expected {expected})")
    return bytes(out)


def _compress_chunk(raw: bytes, codec: str, lib) -> bytes:
    if codec == "zlib":
        import zlib

        return zlib.compress(raw, 6)
    if codec == "zstd":
        try:
            import zstandard

            return zstandard.ZstdCompressor(level=3).compress(raw)
        except ImportError:
            pass
        if lib is not None and hasattr(lib, "zstd_compress_chunk"):
            cap = int(lib.zstd_bound(len(raw)))
            dst = np.empty(max(cap, 64), dtype=np.uint8)
            src = np.frombuffer(raw, dtype=np.uint8)
            n = lib.zstd_compress_chunk(
                src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.c_int64(len(raw)),
                dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.c_int64(len(dst)), ctypes.c_int(3))
            if n < 0:
                raise ValueError("zstd compression failed")
            return dst[:n].tobytes()
        raise RuntimeError(
            "zstd codec needs the zstandard package or the native library")
    if codec == "lz4":
        if lib is not None and hasattr(lib, "lz4_compress_chunk"):
            cap = int(lib.lz4_bound(len(raw))) if len(raw) else 64
            dst = np.empty(max(cap, 64), dtype=np.uint8)
            src = np.frombuffer(raw, dtype=np.uint8) if raw else \
                np.empty(0, dtype=np.uint8)
            n = lib.lz4_compress_chunk(
                src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.c_int64(len(raw)),
                dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.c_int64(len(dst)))
            if n > 0:
                return dst[:n].tobytes()
        return _lz4_compress_py(raw)
    raise ValueError(f"unknown chunk codec {codec!r} (use {CHUNK_CODECS})")


def compress_chunks(data: np.ndarray,
                    codec: str = "zlib") -> tuple[np.ndarray, np.ndarray]:
    """Raw little-endian bytes -> (concatenated compressed chunks,
    offsets[n_chunks+1]). Build path (cold)."""
    lib = _load()
    data = np.ascontiguousarray(data).view(np.uint8).reshape(-1)
    raw = data.tobytes()
    pieces = [raw[i: i + CHUNK_BYTES]
              for i in range(0, len(raw), CHUNK_BYTES)] or [b""]
    chunks = [_compress_chunk(p, codec, lib) for p in pieces]
    offsets = np.zeros(len(chunks) + 1, dtype=np.int64)
    np.cumsum([len(c) for c in chunks], out=offsets[1:])
    return np.frombuffer(b"".join(chunks), dtype=np.uint8), offsets


_NATIVE_DECOMPRESS = {
    "zlib": "inflate_chunks",
    "zstd": "zstd_decompress_chunks",
    "lz4": "lz4_decompress_chunks",
}


def _decompress_chunk_py(buf: bytes, codec: str, expected: int) -> bytes:
    if codec == "zlib":
        import zlib

        return zlib.decompress(buf)
    if codec == "zstd":
        try:
            import zstandard
        except ImportError as e:
            raise RuntimeError(
                "loading a zstd-compressed segment needs the zstandard "
                "package or the native library") from e
        return zstandard.ZstdDecompressor().decompress(
            buf, max_output_size=max(expected, 1))
    if codec == "lz4":
        return _lz4_decompress_py(buf, expected)
    raise ValueError(f"unknown chunk codec {codec!r} (use {CHUNK_CODECS})")


def decompress_chunks(blob: np.ndarray, offsets: np.ndarray,
                      total_bytes: int, codec: str = "zlib") -> np.ndarray:
    """(compressed chunks, offsets) -> uncompressed uint8 array of
    total_bytes. Load path: native per-chunk loop, python fallback."""
    blob = np.ascontiguousarray(blob, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n_chunks = len(offsets) - 1
    out = np.empty(total_bytes, dtype=np.uint8)
    if total_bytes == 0:
        return out
    dst_off = np.minimum(
        np.arange(n_chunks + 1, dtype=np.int64) * CHUNK_BYTES, total_bytes)
    lib = _load()
    fn_name = _NATIVE_DECOMPRESS.get(codec)
    if fn_name is None:
        raise ValueError(f"unknown chunk codec {codec!r} (use {CHUNK_CODECS})")
    if lib is not None and hasattr(lib, fn_name):
        rc = getattr(lib, fn_name)(
            blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(n_chunks),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            dst_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        if rc != 0:
            raise ValueError(
                f"corrupt compressed forward index ({codec} rc={rc})")
        return out
    buf = blob.tobytes()
    pos = 0
    for c in range(n_chunks):
        expected = int(dst_off[c + 1] - dst_off[c])
        chunk = _decompress_chunk_py(
            buf[offsets[c]: offsets[c + 1]], codec, expected)
        out[pos: pos + len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
        pos += len(chunk)
    if pos != total_bytes:
        raise ValueError(f"corrupt compressed forward index "
                         f"({pos} bytes, expected {total_bytes})")
    return out


# ---------------------------------------------------------------------------
# numpy fallback (same byte format, vectorized via a per-value bit matrix)
# ---------------------------------------------------------------------------


def _pack_np(ids: np.ndarray, bits: int, out: np.ndarray) -> np.ndarray:
    n = len(ids)
    # (n, bits) value bits, little-endian per value, flattened to the
    # global little-endian bitstream then repacked 8 at a time
    shifts = np.arange(bits, dtype=np.uint32)
    bitmat = ((ids.astype(np.uint32)[:, None] >> shifts) & 1).astype(np.uint8)
    stream = bitmat.reshape(-1)
    pad = (-len(stream)) % 8
    if pad:
        stream = np.concatenate([stream, np.zeros(pad, dtype=np.uint8)])
    out[:] = np.packbits(stream.reshape(-1, 8), axis=1, bitorder="little").reshape(-1)
    return out


def _unpack_np(buf: np.ndarray, n: int, bits: int) -> np.ndarray:
    stream = np.unpackbits(buf, bitorder="little")[: n * bits]
    bitmat = stream.reshape(n, bits).astype(np.uint32)
    shifts = np.arange(bits, dtype=np.uint32)
    return (bitmat << shifts).sum(axis=1).astype(np.int32)
