// Native bit-packing codec for dictionary-encoded forward indexes.
//
// The role of the reference's FixedBitSVForwardIndexWriter/Reader +
// PinotDataBitSet (pinot-segment-local/.../io/writer/impl/, util/
// PinotDataBitSet.java), as a small C shared library: dict ids need only
// ceil(log2(cardinality)) bits, so packing cuts forward-index disk/IO by
// 4-32x vs int32. Packing is little-endian within a 64-bit accumulator;
// unpack reproduces int32 ids ready for the straight HBM upload.
//
// Built on demand by pinot_tpu/native/__init__.py with the system g++;
// a vectorized numpy fallback keeps environments without a toolchain
// working (slower, same format).

#include <cstdint>
#include <cstring>

extern "C" {

// out must hold (n * bits + 7) / 8 bytes, zero-initialized by the caller.
void pack_bits(const int32_t* in, int64_t n, int bits, uint8_t* out) {
    uint64_t acc = 0;
    int acc_bits = 0;
    int64_t out_pos = 0;
    const uint64_t mask = (bits >= 64) ? ~0ULL : ((1ULL << bits) - 1);
    for (int64_t i = 0; i < n; ++i) {
        acc |= (static_cast<uint64_t>(static_cast<uint32_t>(in[i])) & mask)
               << acc_bits;
        acc_bits += bits;
        while (acc_bits >= 8) {
            out[out_pos++] = static_cast<uint8_t>(acc & 0xFF);
            acc >>= 8;
            acc_bits -= 8;
        }
    }
    if (acc_bits > 0) {
        out[out_pos++] = static_cast<uint8_t>(acc & 0xFF);
    }
}

// in holds (n * bits + 7) / 8 bytes; out receives n int32 values.
void unpack_bits(const uint8_t* in, int64_t n, int bits, int32_t* out) {
    uint64_t acc = 0;
    int acc_bits = 0;
    int64_t in_pos = 0;
    const uint64_t mask = (bits >= 64) ? ~0ULL : ((1ULL << bits) - 1);
    for (int64_t i = 0; i < n; ++i) {
        while (acc_bits < bits) {
            acc |= static_cast<uint64_t>(in[in_pos++]) << acc_bits;
            acc_bits += 8;
        }
        out[i] = static_cast<int32_t>(acc & mask);
        acc >>= bits;
        acc_bits -= bits;
    }
}

}  // extern "C"
