// Native bit-packing codec for dictionary-encoded forward indexes.
//
// The role of the reference's FixedBitSVForwardIndexWriter/Reader +
// PinotDataBitSet (pinot-segment-local/.../io/writer/impl/, util/
// PinotDataBitSet.java), as a small C shared library: dict ids need only
// ceil(log2(cardinality)) bits, so packing cuts forward-index disk/IO by
// 4-32x vs int32. Packing is little-endian within a 64-bit accumulator;
// unpack reproduces int32 ids ready for the straight HBM upload.
//
// Built on demand by pinot_tpu/native/__init__.py with the system g++;
// a vectorized numpy fallback keeps environments without a toolchain
// working (slower, same format).

#include <cstdint>
#include <cstring>

extern "C" {

// out must hold (n * bits + 7) / 8 bytes, zero-initialized by the caller.
void pack_bits(const int32_t* in, int64_t n, int bits, uint8_t* out) {
    uint64_t acc = 0;
    int acc_bits = 0;
    int64_t out_pos = 0;
    const uint64_t mask = (bits >= 64) ? ~0ULL : ((1ULL << bits) - 1);
    for (int64_t i = 0; i < n; ++i) {
        acc |= (static_cast<uint64_t>(static_cast<uint32_t>(in[i])) & mask)
               << acc_bits;
        acc_bits += bits;
        while (acc_bits >= 8) {
            out[out_pos++] = static_cast<uint8_t>(acc & 0xFF);
            acc >>= 8;
            acc_bits -= 8;
        }
    }
    if (acc_bits > 0) {
        out[out_pos++] = static_cast<uint8_t>(acc & 0xFF);
    }
}

// in holds (n * bits + 7) / 8 bytes; out receives n int32 values.
void unpack_bits(const uint8_t* in, int64_t n, int bits, int32_t* out) {
    uint64_t acc = 0;
    int acc_bits = 0;
    int64_t in_pos = 0;
    const uint64_t mask = (bits >= 64) ? ~0ULL : ((1ULL << bits) - 1);
    for (int64_t i = 0; i < n; ++i) {
        while (acc_bits < bits) {
            acc |= static_cast<uint64_t>(in[in_pos++]) << acc_bits;
            acc_bits += 8;
        }
        out[i] = static_cast<int32_t>(acc & mask);
        acc >>= bits;
        acc_bits -= bits;
    }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Chunked zlib decompression for compressed raw forward indexes — the
// reference's chunk-decompressor role (segment/local/io/compression/,
// e.g. ZstandardCompressor/LZ4Compressor behind VarByteChunkSVForwardIndex).
// zlib keeps the format readable by the pure-Python fallback (stdlib zlib).
//
// Compiled out with -DPINOT_NO_ZLIB on hosts without zlib dev headers, so
// the bit-packing codec keeps its native path there; Python's stdlib zlib
// serves decompression instead (same bytes, slower).
// ---------------------------------------------------------------------------

#ifndef PINOT_NO_ZLIB
#include <zlib.h>

extern "C" {

// src: concatenated compressed chunks; offsets[n_chunks+1]: byte offsets of
// each chunk in src; dst_offsets[n_chunks+1]: uncompressed byte offsets.
// Returns 0 on success, the zlib error code of the first failing chunk
// otherwise.
int inflate_chunks(const uint8_t* src, const int64_t* offsets,
                   int64_t n_chunks, uint8_t* dst,
                   const int64_t* dst_offsets) {
    for (int64_t c = 0; c < n_chunks; ++c) {
        uLongf dst_len = static_cast<uLongf>(dst_offsets[c + 1] - dst_offsets[c]);
        const uLong src_len = static_cast<uLong>(offsets[c + 1] - offsets[c]);
        int rc = uncompress(dst + dst_offsets[c], &dst_len,
                            src + offsets[c], src_len);
        if (rc != Z_OK ||
            dst_len != static_cast<uLongf>(dst_offsets[c + 1] - dst_offsets[c])) {
            return rc != Z_OK ? rc : Z_DATA_ERROR;
        }
    }
    return 0;
}

}  // extern "C"
#endif  // PINOT_NO_ZLIB

// ---------------------------------------------------------------------------
// zstd chunk codec (reference ChunkCompressionType.ZSTANDARD,
// io/compression/ZstandardCompressor). System libzstd; compiled out with
// -DPINOT_NO_ZSTD where the dev header is absent (python `zstandard`
// serves the same frames).
// ---------------------------------------------------------------------------

#ifndef PINOT_NO_ZSTD
#include <zstd.h>

extern "C" {

int zstd_decompress_chunks(const uint8_t* src, const int64_t* offsets,
                           int64_t n_chunks, uint8_t* dst,
                           const int64_t* dst_offsets) {
    for (int64_t c = 0; c < n_chunks; ++c) {
        const size_t cap = static_cast<size_t>(dst_offsets[c + 1] - dst_offsets[c]);
        size_t rc = ZSTD_decompress(dst + dst_offsets[c], cap,
                                    src + offsets[c],
                                    static_cast<size_t>(offsets[c + 1] - offsets[c]));
        if (ZSTD_isError(rc) || rc != cap) return -1;
    }
    return 0;
}

int64_t zstd_compress_chunk(const uint8_t* src, int64_t src_len,
                            uint8_t* dst, int64_t cap, int level) {
    size_t rc = ZSTD_compress(dst, static_cast<size_t>(cap), src,
                              static_cast<size_t>(src_len), level);
    return ZSTD_isError(rc) ? -1 : static_cast<int64_t>(rc);
}

int64_t zstd_bound(int64_t n) {
    return static_cast<int64_t>(ZSTD_compressBound(static_cast<size_t>(n)));
}

}  // extern "C"
#endif  // PINOT_NO_ZSTD

// ---------------------------------------------------------------------------
// LZ4 block chunk codec (reference ChunkCompressionType.LZ4,
// io/compression/LZ4Compressor). The build image ships liblz4.so.1 but no
// header, so the stable liblz4 ABI is declared here; compiled out with
// -DPINOT_NO_LZ4 where the library is absent (a pure-python block decoder
// in native/__init__.py reads the same bytes).
// ---------------------------------------------------------------------------

#ifndef PINOT_NO_LZ4
extern "C" {
int LZ4_compress_default(const char* src, char* dst, int srcSize, int dstCap);
int LZ4_decompress_safe(const char* src, char* dst, int srcSize, int dstCap);
int LZ4_compressBound(int inputSize);
}

extern "C" {

int lz4_decompress_chunks(const uint8_t* src, const int64_t* offsets,
                          int64_t n_chunks, uint8_t* dst,
                          const int64_t* dst_offsets) {
    for (int64_t c = 0; c < n_chunks; ++c) {
        const int cap = static_cast<int>(dst_offsets[c + 1] - dst_offsets[c]);
        int rc = LZ4_decompress_safe(
            reinterpret_cast<const char*>(src + offsets[c]),
            reinterpret_cast<char*>(dst + dst_offsets[c]),
            static_cast<int>(offsets[c + 1] - offsets[c]), cap);
        if (rc != cap) return -1;
    }
    return 0;
}

int64_t lz4_compress_chunk(const uint8_t* src, int64_t src_len,
                           uint8_t* dst, int64_t cap) {
    int rc = LZ4_compress_default(reinterpret_cast<const char*>(src),
                                  reinterpret_cast<char*>(dst),
                                  static_cast<int>(src_len),
                                  static_cast<int>(cap));
    return rc <= 0 ? -1 : static_cast<int64_t>(rc);
}

int64_t lz4_bound(int64_t n) {
    return static_cast<int64_t>(LZ4_compressBound(static_cast<int>(n)));
}

}  // extern "C"
#endif  // PINOT_NO_LZ4
