// Native bit-packing codec for dictionary-encoded forward indexes.
//
// The role of the reference's FixedBitSVForwardIndexWriter/Reader +
// PinotDataBitSet (pinot-segment-local/.../io/writer/impl/, util/
// PinotDataBitSet.java), as a small C shared library: dict ids need only
// ceil(log2(cardinality)) bits, so packing cuts forward-index disk/IO by
// 4-32x vs int32. Packing is little-endian within a 64-bit accumulator;
// unpack reproduces int32 ids ready for the straight HBM upload.
//
// Built on demand by pinot_tpu/native/__init__.py with the system g++;
// a vectorized numpy fallback keeps environments without a toolchain
// working (slower, same format).

#include <cstdint>
#include <cstring>

extern "C" {

// out must hold (n * bits + 7) / 8 bytes, zero-initialized by the caller.
void pack_bits(const int32_t* in, int64_t n, int bits, uint8_t* out) {
    uint64_t acc = 0;
    int acc_bits = 0;
    int64_t out_pos = 0;
    const uint64_t mask = (bits >= 64) ? ~0ULL : ((1ULL << bits) - 1);
    for (int64_t i = 0; i < n; ++i) {
        acc |= (static_cast<uint64_t>(static_cast<uint32_t>(in[i])) & mask)
               << acc_bits;
        acc_bits += bits;
        while (acc_bits >= 8) {
            out[out_pos++] = static_cast<uint8_t>(acc & 0xFF);
            acc >>= 8;
            acc_bits -= 8;
        }
    }
    if (acc_bits > 0) {
        out[out_pos++] = static_cast<uint8_t>(acc & 0xFF);
    }
}

// in holds (n * bits + 7) / 8 bytes; out receives n int32 values.
void unpack_bits(const uint8_t* in, int64_t n, int bits, int32_t* out) {
    uint64_t acc = 0;
    int acc_bits = 0;
    int64_t in_pos = 0;
    const uint64_t mask = (bits >= 64) ? ~0ULL : ((1ULL << bits) - 1);
    for (int64_t i = 0; i < n; ++i) {
        while (acc_bits < bits) {
            acc |= static_cast<uint64_t>(in[in_pos++]) << acc_bits;
            acc_bits += 8;
        }
        out[i] = static_cast<int32_t>(acc & mask);
        acc >>= bits;
        acc_bits -= bits;
    }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Chunked zlib decompression for compressed raw forward indexes — the
// reference's chunk-decompressor role (segment/local/io/compression/,
// e.g. ZstandardCompressor/LZ4Compressor behind VarByteChunkSVForwardIndex).
// zlib keeps the format readable by the pure-Python fallback (stdlib zlib).
//
// Compiled out with -DPINOT_NO_ZLIB on hosts without zlib dev headers, so
// the bit-packing codec keeps its native path there; Python's stdlib zlib
// serves decompression instead (same bytes, slower).
// ---------------------------------------------------------------------------

#ifndef PINOT_NO_ZLIB
#include <zlib.h>

extern "C" {

// src: concatenated compressed chunks; offsets[n_chunks+1]: byte offsets of
// each chunk in src; dst_offsets[n_chunks+1]: uncompressed byte offsets.
// Returns 0 on success, the zlib error code of the first failing chunk
// otherwise.
int inflate_chunks(const uint8_t* src, const int64_t* offsets,
                   int64_t n_chunks, uint8_t* dst,
                   const int64_t* dst_offsets) {
    for (int64_t c = 0; c < n_chunks; ++c) {
        uLongf dst_len = static_cast<uLongf>(dst_offsets[c + 1] - dst_offsets[c]);
        const uLong src_len = static_cast<uLong>(offsets[c + 1] - offsets[c]);
        int rc = uncompress(dst + dst_offsets[c], &dst_len,
                            src + offsets[c], src_len);
        if (rc != Z_OK ||
            dst_len != static_cast<uLongf>(dst_offsets[c + 1] - dst_offsets[c])) {
            return rc != Z_OK ? rc : Z_DATA_ERROR;
        }
    }
    return 0;
}

}  // extern "C"
#endif  // PINOT_NO_ZLIB
