"""Minion worker: claims queued tasks and runs their executors.

Equivalent of the reference's ``MinionStarter`` + ``TaskFactoryRegistry`` +
``TaskExecutorFactoryRegistry``
(pinot-minion/src/main/java/org/apache/pinot/minion/MinionStarter.java):
a stateless worker role that polls the registry task queue (replacing the
Helix task framework's assignment push), CAS-claims one task at a time,
and reports DONE/FAILED with an output message.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from pinot_tpu.cluster.registry import ClusterRegistry, InstanceInfo, Role
from pinot_tpu.minion.tasks import TASK_EXECUTORS, TaskContext

log = logging.getLogger("pinot_tpu.minion")


class MinionWorker:
    def __init__(self, registry: ClusterRegistry, controller, work_dir: str,
                 instance_id: str = "minion_0", poll_interval_s: float = 0.2,
                 touch_interval_s: float = 5.0,
                 executors: Optional[dict] = None):
        self.instance_id = instance_id
        self.registry = registry
        self.ctx = TaskContext(registry, controller, work_dir)
        self.poll_interval_s = poll_interval_s
        self.touch_interval_s = touch_interval_s
        self.executors = dict(TASK_EXECUTORS if executors is None else executors)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.tasks_run = 0

    def start(self) -> None:
        self.registry.register_instance(InstanceInfo(self.instance_id, Role.MINION))
        self._thread = threading.Thread(
            target=self._loop, name=f"minion-{self.instance_id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(10)
        self.registry.drop_instance(self.instance_id)

    def run_one(self) -> Optional[dict]:
        """Claim and execute a single task synchronously; returns the
        finished task dict (with output) or None if the queue is empty."""
        task = self.registry.claim_task(self.instance_id,
                                        list(self.executors))
        if task is None:
            return None
        # heartbeat the claim while executing so the controller's stale-task
        # sweep never requeues live work (only genuinely dead claims age out)
        stop_touch = threading.Event()

        def _toucher():
            while not stop_touch.wait(self.touch_interval_s):
                self.registry.touch_task(task["id"])

        toucher = threading.Thread(
            target=_toucher, name=f"touch-{task['id']}", daemon=True
        )
        toucher.start()
        try:
            output = self.executors[task["type"]](self.ctx, task)
            ok = True
        except Exception as e:  # noqa: BLE001 — task failures are data
            log.exception("task %s failed", task["id"])
            output = f"{type(e).__name__}: {e}"
            ok = False
        finally:
            stop_touch.set()
            toucher.join(1)
        self.registry.finish_task(task["id"], ok, output)
        from pinot_tpu.common.metrics import get_metrics

        get_metrics("minion").count(
            "tasksCompleted" if ok else "tasksFailed", tag=task["type"])
        self.tasks_run += 1
        task.update(state="DONE" if ok else "FAILED", output=output)
        return task

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self.run_one() is not None:
                    continue  # drain the queue without sleeping
            except Exception:
                log.exception("minion loop error")
            self.registry.heartbeat(self.instance_id)
            self._stop.wait(self.poll_interval_s)
