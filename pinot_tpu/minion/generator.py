"""Minion task generation: table task configs -> concrete queued tasks.

Equivalent of the reference's ``PinotTaskManager`` + per-type task
generators (pinot-controller/.../core/minion/PinotTaskManager.java,
pinot-plugins/.../tasks/*/…TaskGenerator.java), driven by
``TableConfig.task_configs`` and the registry task queue instead of the
Helix task framework.

Divergence worth noting: RealtimeToOffline window-readiness here is "sealed
data exists past the window end" rather than the reference's per-partition
consuming-state check — the registry's completion FSM seals partitions
independently, and the buffer_ms guard covers stragglers the same way the
reference's bufferTimePeriod does.
"""

from __future__ import annotations

import logging
import time

log = logging.getLogger("pinot_tpu.minion")

_ACTIVE = ("PENDING", "RUNNING")


def _busy_segments(registry, table: str) -> set:
    """Segments referenced by queued/running tasks or active lineage — not
    eligible for new tasks (no two tasks may rewrite the same segment)."""
    busy: set = set()
    for t in registry.tasks(table=table):
        if t["state"] in _ACTIVE:
            busy.update(t["config"].get("segments", ()))
    for entry in registry.lineage(table).values():
        # Mid-swap (IN_PROGRESS/ABORTING): both sides are locked.
        # COMPLETED: the from-set is awaiting deletion, but the to-set is a
        # live segment — eligible for new tasks.
        busy.update(entry["from"])
        if entry["state"] != "COMPLETED":
            busy.update(entry["to"])
    return busy


def _has_active_task(registry, table: str, task_type: str) -> bool:
    return any(
        t["type"] == task_type and t["state"] in _ACTIVE
        for t in registry.tasks(table=table)
    )


def generate_merge_rollup_tasks(registry, table: str, cfg: dict) -> list:
    """Small ONLINE segments -> merge buckets up to max_docs_per_segment
    (MergeRollupTaskGenerator, simplified to a single merge level)."""
    table_cfg = registry.table_config(table)
    if table_cfg is not None and table_cfg.upsert.mode != "NONE":
        return []  # validDocIds are server-local; compaction handles upsert
    if _has_active_task(registry, table, "RealtimeToOfflineSegmentsTask"):
        # an RTO task reads whichever ONLINE segments overlap its window at
        # EXECUTION time (its config carries no segment list), so no swap
        # may run concurrently with it
        return []
    max_docs = int(cfg.get("max_docs_per_segment", 5_000_000))
    min_inputs = int(cfg.get("min_input_segments", 2))
    busy = _busy_segments(registry, table)
    candidates = sorted(
        (r for r in registry.segments(table).values()
         if r.state == "ONLINE" and r.location and r.name not in busy
         and r.n_docs < max_docs),
        key=lambda r: r.name,
    )
    out = []
    bucket, bucket_docs = [], 0
    for rec in candidates:
        if bucket_docs + rec.n_docs > max_docs and bucket:
            if len(bucket) >= min_inputs:
                out.append(bucket)
            bucket, bucket_docs = [], 0
        bucket.append(rec.name)
        bucket_docs += rec.n_docs
    if len(bucket) >= min_inputs:
        out.append(bucket)
    ids = []
    for names in out:
        ids.append(registry.submit_task("MergeRollupTask", table, {
            "segments": names,
            "mode": cfg.get("mode", "concat"),
            "rollup_aggregates": cfg.get("rollup_aggregates", {}),
        }))
    return ids


def generate_realtime_to_offline_tasks(registry, table: str, cfg: dict,
                                       now_ms: int) -> list:
    """One time-bucket window per invocation, watermark-driven
    (RealtimeToOfflineSegmentsTaskGenerator)."""
    if not table.endswith("_REALTIME"):
        return []
    raw = table[: -len("_REALTIME")]
    if registry.table_config(f"{raw}_OFFLINE") is None:
        return []
    table_cfg = registry.table_config(table)
    if table_cfg is None or table_cfg.time_column is None:
        return []
    if any(t["state"] in _ACTIVE for t in registry.tasks(table=table)) \
            or registry.lineage(table):
        return []  # exclusive with swaps: RTO reads live ONLINE segments
    bucket_ms = int(cfg.get("bucket_ms", 86_400_000))
    # Reference default bufferTimePeriod=2d: the window must be well past
    # "now" before moving — the guard against a slow partition whose
    # in-window rows are still CONSUMING (we only read sealed segments, and
    # consuming segments carry no time metadata to check directly).
    buffer_ms = int(cfg.get("buffer_ms", 2 * 86_400_000))
    sealed = [r for r in registry.segments(table).values()
              if r.state == "ONLINE" and r.start_time is not None]
    if not sealed:
        return []
    meta = registry.task_metadata_get(table, "RealtimeToOfflineSegmentsTask")
    wm = meta.get("watermark_ms")
    if wm is None:
        wm = (min(r.start_time for r in sealed) // bucket_ms) * bucket_ms
    we = wm + bucket_ms
    max_end = max(r.end_time for r in sealed if r.end_time is not None)
    if we > now_ms - buffer_ms or max_end < we:
        return []  # window not yet complete
    return [registry.submit_task("RealtimeToOfflineSegmentsTask", table, {
        "window_start_ms": int(wm), "window_end_ms": int(we),
        "bucket_ms": bucket_ms,
    })]


def generate_purge_tasks(registry, table: str, cfg: dict) -> list:
    """Segments not yet purged under the current filter (PurgeTaskGenerator
    tracks last-purge time in segment metadata; here a task-metadata map)."""
    if not cfg.get("filter"):
        return []
    table_cfg = registry.table_config(table)
    if table_cfg is not None and table_cfg.upsert.mode != "NONE":
        return []
    if _has_active_task(registry, table, "RealtimeToOfflineSegmentsTask"):
        return []
    busy = _busy_segments(registry, table)
    meta = registry.task_metadata_get(table, "PurgeTask")
    # a changed filter is a new purge request: prior markers don't apply
    done = meta.get("purged", {}) if meta.get("filter") == cfg["filter"] else {}
    names = [r.name for r in registry.segments(table).values()
             if r.state == "ONLINE" and r.location
             and r.name not in busy and r.name not in done]
    if not names:
        return []
    return [registry.submit_task("PurgeTask", table, {
        "segments": sorted(names), "filter": cfg["filter"],
    })]


def _index_mismatch(meta, idx_cfg) -> bool:
    """True when a segment's on-disk indexes don't reflect the CURRENT
    IndexingConfig (the reload-needed check the reference surfaces through
    needReload/table reload status). Only indexes the BUILDER can actually
    create count — an unachievable config entry (inverted on a RAW column,
    range on a RAW MV column) must not flag forever, or generation would
    rebuild-and-swap the same segment in an infinite loop."""
    cols = meta.columns
    for c in idx_cfg.inverted_index_columns:
        if c in cols and cols[c].has_dictionary and not cols[c].has_inverted:
            return True
    for c in idx_cfg.bloom_filter_columns:
        if c in cols and not cols[c].has_bloom:
            return True
    for c in getattr(idx_cfg, "json_index_columns", ()):
        if c in cols and cols[c].single_value and \
                cols[c].data_type.is_string_like and not cols[c].has_json_index:
            return True
    for c in getattr(idx_cfg, "text_index_columns", ()):
        if c in cols and cols[c].single_value and \
                cols[c].data_type.is_string_like and not cols[c].has_text_index:
            return True
    for c in idx_cfg.range_index_columns:
        if c in cols and not cols[c].has_range and (
            cols[c].encoding == "DICT"
            or (cols[c].encoding == "RAW" and cols[c].single_value)
        ):
            return True
    for c in getattr(idx_cfg, "compressed_columns", ()):
        if c in cols and cols[c].encoding == "RAW" and \
                cols[c].single_value and cols[c].compression is None:
            return True
    return False


def generate_refresh_tasks(registry, table: str, cfg: dict) -> list:
    """Segments whose index set lags the current IndexingConfig get a
    rebuild task (the reference's segment reload, as a minion swap)."""
    import json as _json
    import os as _os

    from pinot_tpu.storage.segment import METADATA_FILE, SegmentMetadata

    table_cfg = registry.table_config(table)
    if table_cfg is None:
        return []
    if table_cfg.upsert.mode != "NONE":
        # validDocIds are server-local in-memory state: a rebuilt copy
        # would resurrect superseded rows (same reason merge/purge skip)
        return []
    if _has_active_task(registry, table, "RealtimeToOfflineSegmentsTask"):
        # an RTO task reads whichever ONLINE segments overlap its window
        # at EXECUTION time; no swap may run concurrently with it
        return []
    busy = _busy_segments(registry, table)
    # segments are immutable: once a segment checked clean under THIS
    # indexing config, skip re-parsing its metadata on every cycle
    fp = _json.dumps(table_cfg.indexing.__dict__, sort_keys=True, default=str)
    meta_state = registry.task_metadata_get(table, "RefreshSegmentsTask")
    clean = set(meta_state.get("clean", ())) \
        if meta_state.get("config_fp") == fp else set()
    stale = []
    for r in registry.segments(table).values():
        if r.state != "ONLINE" or not r.location or r.name in busy \
                or r.name in clean:
            continue
        meta_path = _os.path.join(r.location, METADATA_FILE)
        try:
            with open(meta_path) as f:
                meta = SegmentMetadata.from_json(_json.load(f))
        except (OSError, ValueError, KeyError):
            continue  # unreadable metadata: leave the segment alone
        if _index_mismatch(meta, table_cfg.indexing):
            stale.append(r.name)
        else:
            clean.add(r.name)
    live = set(registry.segments(table))
    registry.task_metadata_set(table, "RefreshSegmentsTask", {
        "config_fp": fp, "clean": sorted(clean & live),
    })
    if not stale:
        return []
    return [registry.submit_task("RefreshSegmentsTask", table,
                                 {"segments": sorted(stale)})]


def generate_tasks(registry, now_ms=None) -> list:
    """Scan every table's task_configs and enqueue what is due."""
    now_ms = now_ms or int(time.time() * 1000)
    registry.prune_terminal_tasks()
    ids = []
    for table in registry.tables():
        table_cfg = registry.table_config(table)
        if table_cfg is None or not table_cfg.task_configs:
            continue
        registry.prune_lineage(table)
        for task_type, cfg in table_cfg.task_configs.items():
            if task_type == "MergeRollupTask":
                ids += generate_merge_rollup_tasks(registry, table, cfg)
            elif task_type == "RealtimeToOfflineSegmentsTask":
                ids += generate_realtime_to_offline_tasks(
                    registry, table, cfg, now_ms
                )
            elif task_type == "PurgeTask":
                ids += generate_purge_tasks(registry, table, cfg)
            elif task_type == "RefreshSegmentsTask":
                ids += generate_refresh_tasks(registry, table, cfg)
            else:
                log.warning("unknown task type %s on table %s", task_type, table)
    return ids
