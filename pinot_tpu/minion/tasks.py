"""Minion task executors: mergeRollup, realtimeToOffline, purge.

Equivalent of the reference's built-in minion tasks
(pinot-plugins/pinot-minion-tasks/pinot-minion-builtin-tasks/.../tasks/:
MergeRollupTaskExecutor, RealtimeToOfflineSegmentsTaskExecutor,
PurgeTaskExecutor), re-shaped for this runtime:

- Segment replace is made atomic to queries via registry segment lineage
  (SegmentLineage analog): brokers route the FROM set while the replace is
  IN_PROGRESS and flip to the TO set on the single-tx COMPLETED flip.
- Record reading is whole-column vectorized numpy over the mmap'd segment
  (not row-by-row GenericRow transforms): merges concatenate column arrays,
  rollup groups via np.unique over factorized dimension ids, and purge
  reuses the host engine's vectorized filter evaluator as its RecordPurger.
"""

from __future__ import annotations

import logging
import os
import time

import numpy as np

from pinot_tpu.common.datatypes import FieldRole
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.segment import ImmutableSegment

log = logging.getLogger("pinot_tpu.minion")


class TaskContext:
    """What an executor needs: cluster state, segment push/delete, scratch."""

    def __init__(self, registry, controller, work_dir: str):
        self.registry = registry
        self.controller = controller
        self.work_dir = work_dir
        os.makedirs(work_dir, exist_ok=True)

    def scratch(self, task_id: str) -> str:
        d = os.path.join(self.work_dir, task_id)
        os.makedirs(d, exist_ok=True)
        return d


def _wait_until(cond, timeout_s: float = 30.0, interval_s: float = 0.05) -> bool:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return False


def _read_columns(segments: list, schema, row_masks=None) -> tuple:
    """Concatenate decoded columns across segments (optionally row-masked).
    SV columns come back as typed arrays, MV columns as lists of per-row
    value arrays (what ``build_segment`` expects). Returns
    ``(columns, null_masks)`` — nullness lives only in the per-column null
    vectors (the forward index stores substituted defaults), so a rebuild
    that dropped them would silently un-null every row."""
    out: dict = {}
    null_out: dict = {}
    for name in schema.column_names():
        spec = schema.field(name)
        parts = []
        null_parts = []
        for i, seg in enumerate(segments):
            mask = None if row_masks is None else row_masks[i]
            nv = seg.null_vector(name) if hasattr(seg, "null_vector") else None
            nulls = (np.zeros(seg.n_docs, dtype=bool) if nv is None
                     else np.asarray(nv, dtype=bool)[: seg.n_docs])
            null_parts.append(nulls if mask is None else nulls[mask])
            if spec.single_value:
                vals = np.asarray(seg.flat_values(name))
                parts.append(vals if mask is None else vals[mask])
            else:
                vals = seg.values(name)
                if mask is not None:
                    vals = vals[mask]
                parts.extend(list(vals))
        if spec.single_value:
            out[name] = np.concatenate(parts) if parts else np.array([])
        else:
            out[name] = parts
        combined = np.concatenate(null_parts) if null_parts else np.empty(0, bool)
        if combined.any():
            null_out[name] = combined
    return out, null_out or None


def _rollup(columns: dict, schema, aggregates: dict) -> dict:
    """Group identical dimension/datetime rows, aggregating metric columns
    (MergeRollupTask rollup mode; default aggregation SUM, per
    MergeRollupTaskUtils). MV dimension cells participate as tuples."""
    dim_cols = [n for n in schema.column_names()
                if schema.field(n).role is not FieldRole.METRIC]
    metric_cols = [n for n in schema.column_names()
                   if schema.field(n).role is FieldRole.METRIC]
    n_rows = None
    ids = []
    for name in dim_cols:
        col = columns[name]
        if isinstance(col, list):  # MV: factorize via hashable tuples
            keys = [tuple(np.asarray(v).tolist()) for v in col]
            lut: dict = {}
            arr = np.fromiter((lut.setdefault(k, len(lut)) for k in keys),
                              dtype=np.int64, count=len(keys))
        else:
            _, arr = np.unique(np.asarray(col), return_inverse=True)
        ids.append(arr)
        n_rows = len(arr)
    if not ids:  # no dimensions: single output row
        gid = np.zeros(len(next(iter(columns.values()))), dtype=np.int64)
        first = np.array([0])
        n_groups = 1
    else:
        stacked = np.stack(ids, axis=1)
        uniq, first, gid = np.unique(
            stacked, axis=0, return_index=True, return_inverse=True
        )
        gid = gid.reshape(-1)
        n_groups = len(uniq)
    out: dict = {}
    for name in dim_cols:
        col = columns[name]
        if isinstance(col, list):
            out[name] = [col[i] for i in first]
        else:
            out[name] = np.asarray(col)[first]
    for name in metric_cols:
        vals = np.asarray(columns[name])
        agg = aggregates.get(name, "SUM").upper()
        if agg == "SUM":
            if vals.dtype.kind in "iu":
                # exact integer accumulation — float64 bincount weights lose
                # bits past 2^53
                merged = np.zeros(n_groups, dtype=np.int64)
                np.add.at(merged, gid, vals.astype(np.int64))
                info = np.iinfo(vals.dtype)
                if len(merged) and (merged.max() > info.max
                                    or merged.min() < info.min):
                    raise ValueError(
                        f"rollup SUM of {name} overflows {vals.dtype}; "
                        f"widen the schema column to LONG"
                    )
                merged = merged.astype(vals.dtype)
            else:
                merged = np.bincount(gid, weights=vals.astype(np.float64),
                                     minlength=n_groups)
        elif agg == "MIN":
            merged = np.full(n_groups, np.iinfo(vals.dtype).max
                             if vals.dtype.kind in "iu" else np.inf, dtype=vals.dtype)
            np.minimum.at(merged, gid, vals)
        elif agg == "MAX":
            merged = np.full(n_groups, np.iinfo(vals.dtype).min
                             if vals.dtype.kind in "iu" else -np.inf, dtype=vals.dtype)
            np.maximum.at(merged, gid, vals)
        else:
            raise ValueError(f"unsupported rollup aggregate {agg!r} for {name}")
        out[name] = merged
    return out


def _lineage_swap(ctx: TaskContext, table: str, input_names: list,
                  out_dir: str, merged_name: str) -> None:
    """Upload ``out_dir`` as the replacement for ``input_names`` with
    query-atomic cutover, then delete the inputs."""
    lid = ctx.registry.start_lineage(table, input_names, [merged_name])
    try:
        ctx.controller.upload_segment(table, out_dir)
        # Wait for a server to actually serve the replacement before the
        # flip — completing early would leave queries seeing neither set.
        if not _wait_until(
            lambda: merged_name in ctx.registry.external_view(table)
        ):
            raise TimeoutError(
                f"replacement segment {merged_name} never reached the "
                f"external view of {table}"
            )
        if not ctx.registry.complete_lineage(table, lid):
            # controller repair claimed the entry while we were uploading
            # (we looked dead); abandoning keeps the FROM set authoritative
            raise RuntimeError(
                f"lineage {lid} flip lost to concurrent repair; "
                f"abandoning replace of {input_names}"
            )
    except Exception:
        # Unwind BEFORE dropping the lineage entry: while IN_PROGRESS the
        # replacement is routing-excluded, so deleting it first can never
        # expose a double-counting window (to + from both routed).
        try:
            ctx.controller.delete_segment(table, merged_name)
        except Exception:  # noqa: BLE001 — best-effort unwind
            log.exception("failed to unwind replacement segment %s", merged_name)
        ctx.registry.revert_lineage(table, lid)
        raise
    for name in input_names:
        ctx.controller.delete_segment(table, name)
    ctx.registry.prune_lineage(table)


def execute_merge_rollup(ctx: TaskContext, task: dict) -> str:
    """MergeRollupTaskExecutor analog: N small segments -> one, optionally
    rolling up duplicate dimension rows. Star-trees are rebuilt implicitly:
    ``build_segment`` re-runs the star-tree builder from the table config."""
    table = task["table"]
    cfg = task["config"]
    schema = ctx.registry.table_schema(table)
    table_cfg = ctx.registry.table_config(table)
    records = ctx.registry.segments(table)
    # Requeued-attempt idempotency: if a previous attempt already flipped a
    # COMPLETED lineage over (some of) these inputs, the replacement is the
    # live copy — re-merging would shadow it. Finish that attempt's cleanup
    # (delete the leftover FROM segments) instead of redoing the merge.
    input_set = set(cfg["segments"])
    for entry in ctx.registry.lineage(table).values():
        if entry["state"] == "COMPLETED" and input_set & set(entry["from"]):
            for name in entry["from"]:
                if name in records:
                    ctx.controller.delete_segment(table, name)
            ctx.registry.prune_lineage(table)
            return (f"previous attempt already committed "
                    f"{entry['to']}; cleaned up leftover inputs")
    names = [n for n in cfg["segments"] if n in records]
    if len(names) < 2:
        return f"skipped: only {len(names)} input segments still exist"
    segments = [ImmutableSegment(records[n].location) for n in names]
    columns, null_masks = _read_columns(segments, schema)
    if cfg.get("mode", "concat") == "rollup":
        columns = _rollup(columns, schema, cfg.get("rollup_aggregates", {}))
        # rollup re-groups rows: per-row nullness no longer maps through
        # (aggregated metrics are non-null; dims grouped by substituted
        # value). Matches the reference, where rollup drops null vectors.
        null_masks = None
    # name is unique per task AND per attempt: a requeued re-run must never
    # collide with a half-dead prior attempt's upload
    merged_name = (f"merged_{table}_"
                   + "_".join(task["id"].split("_")[-2:])
                   + f"_a{task.get('attempts', 1)}")
    out_dir = os.path.join(ctx.scratch(task["id"]), merged_name)
    build_segment(schema, columns, out_dir, table_cfg, merged_name,
                  null_masks=null_masks)
    _lineage_swap(ctx, table, names, out_dir, merged_name)
    n_docs = len(next(iter(columns.values())))
    return f"merged {len(names)} segments -> {merged_name} ({n_docs} docs)"


def execute_realtime_to_offline(ctx: TaskContext, task: dict) -> str:
    """RealtimeToOfflineSegmentsTaskExecutor analog: extract the
    [window_start, window_end) time slice from sealed realtime segments into
    a segment pushed to the OFFLINE table, then advance the watermark. The
    hybrid broker's time boundary moves with the new offline max end time,
    which is what hides the realtime copies of the moved rows."""
    rt_table = task["table"]
    cfg = task["config"]
    ws, we = cfg["window_start_ms"], cfg["window_end_ms"]
    raw = rt_table[: -len("_REALTIME")]
    off_table = f"{raw}_OFFLINE"
    rt_cfg = ctx.registry.table_config(rt_table)
    schema = ctx.registry.table_schema(rt_table)
    off_cfg = ctx.registry.table_config(off_table)
    if off_cfg is None:
        raise KeyError(f"no offline table {off_table} to move data into")
    time_col = rt_cfg.time_column
    records = ctx.registry.segments(rt_table)
    segs, masks = [], []
    for rec in records.values():
        if rec.state != "ONLINE" or not rec.location:
            continue
        if rec.start_time is not None and rec.start_time >= we:
            continue
        if rec.end_time is not None and rec.end_time < ws:
            continue
        seg = ImmutableSegment(rec.location)
        tvals = np.asarray(seg.flat_values(time_col))
        mask = (tvals >= ws) & (tvals < we)
        if mask.any():
            segs.append(seg)
            masks.append(mask)
    moved = 0
    if segs:
        columns, null_masks = _read_columns(segs, schema, masks)
        moved = len(next(iter(columns.values())))
        name = f"{raw}_{ws}_{we}"
        out_dir = os.path.join(ctx.scratch(task["id"]), name)
        build_segment(schema, columns, out_dir, off_cfg, name,
                      null_masks=null_masks)
        ctx.controller.upload_segment(off_table, out_dir)
        # Gate on a server actually serving the pushed segment before
        # advancing the watermark: the hybrid time boundary only moves for
        # externally-visible offline segments (broker._physical_tables), so
        # the window never goes dark between push and load. On timeout the
        # push is unwound and the watermark stays put for a retry.
        if not _wait_until(
            lambda: name in ctx.registry.external_view(off_table)
        ):
            ctx.controller.delete_segment(off_table, name)
            raise TimeoutError(
                f"offline segment {name} never reached the external view "
                f"of {off_table}; watermark not advanced"
            )
    meta = ctx.registry.task_metadata_get(rt_table, "RealtimeToOfflineSegmentsTask")
    meta["watermark_ms"] = we
    ctx.registry.task_metadata_set(rt_table, "RealtimeToOfflineSegmentsTask", meta)
    return f"moved {moved} docs in [{ws}, {we}) to {off_table}"


def execute_purge(ctx: TaskContext, task: dict) -> str:
    """PurgeTaskExecutor analog. The RecordPurger is a SQL boolean
    expression from the task config (rows MATCHING it are dropped),
    evaluated with the host engine's vectorized filter path instead of a
    per-row Java predicate."""
    from pinot_tpu.engine.host import SegmentEvaluator
    from pinot_tpu.query.optimizer import optimize_query
    from pinot_tpu.sql.compiler import compile_query

    table = task["table"]
    cfg = task["config"]
    schema = ctx.registry.table_schema(table)
    table_cfg = ctx.registry.table_config(table)
    filter_node = optimize_query(
        compile_query(f"SELECT COUNT(*) FROM {table} WHERE {cfg['filter']}")
    ).filter
    records = ctx.registry.segments(table)
    purged_meta = ctx.registry.task_metadata_get(table, "PurgeTask")
    # the purged map is only valid for the filter it was built under
    if purged_meta.get("filter") != cfg["filter"]:
        purged_meta = {"filter": cfg["filter"], "purged": {}}
    done = dict(purged_meta.get("purged", {}))
    out_msgs = []
    for name in cfg["segments"]:
        rec = records.get(name)
        if rec is None:
            continue
        seg = ImmutableSegment(rec.location)
        drop = SegmentEvaluator(seg).filter_mask(filter_node)
        n_drop = int(drop.sum())
        if n_drop == 0:
            out_msgs.append(f"{name}: clean")
        elif n_drop == seg.n_docs:
            ctx.controller.delete_segment(table, name)
            out_msgs.append(f"{name}: fully purged ({n_drop} docs), deleted")
        else:
            keep = ~drop
            columns, null_masks = _read_columns([seg], schema, [keep])
            new_name = f"{name}_purged_{int(time.time() * 1000)}"
            out_dir = os.path.join(ctx.scratch(task["id"]), new_name)
            build_segment(schema, columns, out_dir, table_cfg, new_name,
                          null_masks=null_masks)
            _lineage_swap(ctx, table, [name], out_dir, new_name)
            done[new_name] = int(time.time() * 1000)
            out_msgs.append(f"{name}: purged {n_drop} docs -> {new_name}")
        done[name] = int(time.time() * 1000)
    purged_meta["purged"] = done
    ctx.registry.task_metadata_set(table, "PurgeTask", purged_meta)
    return "; ".join(out_msgs) if out_msgs else "nothing to purge"


def execute_refresh_segments(ctx: TaskContext, task: dict) -> str:
    """Rebuild segments under the CURRENT IndexingConfig — the reference's
    segment reload (needReload -> reload) expressed as a lineage-atomic
    minion swap: each input rebuilds 1:1 under a fresh name so queries
    never see a half-indexed copy."""
    table = task["table"]
    cfg = task["config"]
    schema = ctx.registry.table_schema(table)
    table_cfg = ctx.registry.table_config(table)
    records = ctx.registry.segments(table)
    # requeued-attempt idempotency (same contract as merge): a COMPLETED
    # lineage over an input means a prior attempt already swapped it —
    # finish that attempt's cleanup (delete the leftover FROM copy) so the
    # lineage entry can prune and the segment stops being busy forever
    done_lineage = {
        f for e in ctx.registry.lineage(table).values()
        if e["state"] == "COMPLETED" for f in e["from"]
    }
    out_msgs = []
    attempt = task.get("attempts", 1)
    suffix = "_".join(task["id"].split("_")[-2:])
    for name in cfg["segments"]:
        rec = records.get(name)
        if name in done_lineage:
            if rec is not None:
                ctx.controller.delete_segment(table, name)
            ctx.registry.prune_lineage(table)
            out_msgs.append(f"{name}: already swapped; cleaned up leftover")
            continue
        if rec is None:
            out_msgs.append(f"{name}: gone")
            continue
        seg = ImmutableSegment(rec.location)
        columns, null_masks = _read_columns([seg], schema)
        new_name = f"refreshed_{name}_{suffix}_a{attempt}"
        out_dir = os.path.join(ctx.scratch(task["id"]), new_name)
        build_segment(schema, columns, out_dir, table_cfg, new_name,
                      null_masks=null_masks)
        _lineage_swap(ctx, table, [name], out_dir, new_name)
        out_msgs.append(f"{name} -> {new_name}")
    return "; ".join(out_msgs) or "nothing to refresh"


TASK_EXECUTORS = {
    "MergeRollupTask": execute_merge_rollup,
    "RealtimeToOfflineSegmentsTask": execute_realtime_to_offline,
    "PurgeTask": execute_purge,
    "RefreshSegmentsTask": execute_refresh_segments,
}
