"""Multi-stage logical planner: SqlSelect with joins/windows → MultiStagePlan.

Role-equivalent of the reference's pinot-query-planner (Calcite logical
plan → dispatchable stage plan), scoped to the shapes engine v2 executes:

- left-deep INNER / LEFT equi-join chains over a probe (fact) table and
  one build table per join,
- window functions over ``OVER (PARTITION BY ... ORDER BY ...)``,
- a stage-2 GROUP BY ... HAVING / ORDER BY / LIMIT over the joined rows,
  reusing the single-stage QueryContext IR so engine/reduce.py finalizes
  the result unchanged.

Name resolution rewrites every identifier to a canonical ``alias.column``
form against the catalog (the per-alias column sets) and raises the typed
``SqlAnalysisError`` — naming the alias and the candidate columns — for
unknown or ambiguous references, instead of letting a raw KeyError escape
the compiler. WHERE conjuncts referencing a single table push down into
that table's stage-1 scan when semantics allow (always for the probe
side; build side only under INNER joins — a LEFT join's build filter must
see the type-default fill of unmatched rows, so it stays post-join).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Optional

from pinot_tpu.query.context import (
    Expression,
    ExpressionType,
    OrderByExpression,
    QueryContext,
    is_aggregation,
)
from pinot_tpu.sql.compiler import (
    DEFAULT_LIMIT,
    _to_filter,
    contains_window,
    is_multistage,  # noqa: F401  (re-exported: the routing predicate)
)
from pinot_tpu.sql.parser import SqlAnalysisError, SqlSelect

WINDOW_FUNCTIONS = {
    "row_number": 0,
    "rank": 0,
    "dense_rank": 0,
    "count": None,  # COUNT(*) or COUNT(x)
    "sum": 1,
    "avg": 1,
    "min": 1,
    "max": 1,
}

BROADCAST_MAX_BUILD_ROWS = 1 << 20  # build side bigger than this shuffles


# ---------------------------------------------------------------------------
# plan IR
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TableSource:
    table: str       # table name as written in the SQL
    alias: str       # alias (defaults to the table name)
    columns: tuple   # column names from the catalog
    is_dim: bool = False


@dataclasses.dataclass(frozen=True)
class JoinStep:
    kind: str        # "INNER" | "LEFT"
    build: TableSource
    left_keys: tuple    # canonical Expressions over the accumulated left side
    right_keys: tuple   # canonical Expressions over the build table
    residual: Optional[Expression] = None  # extra ON conjuncts, post-match


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    fn: str
    expr: Expression      # the canonical __window__ node (runner env key)
    args: tuple           # canonical argument expressions of fn
    partition_by: tuple   # canonical Expressions
    order_by: tuple       # tuple[(Expression, ascending: bool)]

    def describe(self) -> str:
        arg = ",".join(str(a) for a in self.args)
        part = ",".join(str(p) for p in self.partition_by)
        order = ",".join(f"{e} {'ASC' if asc else 'DESC'}"
                         for e, asc in self.order_by)
        spec = []
        if part:
            spec.append(f"PARTITION BY {part}")
        if order:
            spec.append(f"ORDER BY {order}")
        return f"{self.fn}({arg}) OVER ({' '.join(spec)})"


@dataclasses.dataclass
class MultiStagePlan:
    """The compiled two-stage plan. ``stage2`` is a plain QueryContext over
    the canonical joined namespace (table_name = the probe table), so the
    single-stage reduce machinery finalizes it unchanged."""

    sources: tuple            # TableSource..., [0] = probe side
    joins: tuple              # JoinStep...
    pushdown: dict            # alias -> Expression (BARE column names) | None
    post_filter: Optional[Expression]  # canonical; applied to joined rows
    windows: tuple            # WindowSpec...
    stage2: QueryContext
    strategy: str             # "BROADCAST" | "SHUFFLE" | "DISTRIBUTED"
    # True when SET joinStrategy forced it: the runner honors a forced
    # BROADCAST even past BROADCAST_MAX_BUILD_ROWS (a heuristic pick
    # demotes to SHUFFLE there instead of replicating a huge build table)
    strategy_forced: bool = False
    explain: bool = False
    analyze: bool = False  # EXPLAIN ANALYZE (ISSUE 11)

    @property
    def probe(self) -> TableSource:
        return self.sources[0]

    @property
    def table_name(self) -> str:
        """Primary (probe) table — routing / logging identity."""
        return self.sources[0].table

    def options_ci(self) -> dict:
        return self.stage2.options_ci()


# ---------------------------------------------------------------------------
# compilation
# ---------------------------------------------------------------------------


def compile_plan(stmt: SqlSelect,
                 catalog: Callable[[str], tuple]) -> MultiStagePlan:
    """``catalog(table_name)`` → (column name tuple, is_dim_table bool);
    raises KeyError for an unknown table."""
    sources: list[TableSource] = []
    by_alias: dict[str, TableSource] = {}
    for table, alias in [(stmt.table, stmt.table_alias)] + [
            (j.table, j.alias) for j in stmt.joins]:
        alias = alias or table
        if alias in by_alias:
            raise SqlAnalysisError(
                f"duplicate table alias {alias!r}; every joined table "
                f"needs a distinct alias")
        try:
            columns, is_dim = catalog(table)
        except KeyError:
            raise SqlAnalysisError(f"table {table!r} not found") from None
        src = TableSource(table=table, alias=alias,
                          columns=tuple(columns), is_dim=bool(is_dim))
        sources.append(src)
        by_alias[alias] = src

    res = _Resolver(sources)

    # ---- select list (with * expansion over all sources, in order) ------
    select: list[Expression] = []
    aliases: list[Optional[str]] = []
    for e, a in stmt.select:
        if e.is_identifier and e.name == "*":
            for src in sources:
                for c in src.columns:
                    select.append(Expression.identifier(f"{src.alias}.{c}"))
                    aliases.append(c if len(sources) == 1 else None)
            continue
        select.append(res.resolve(e))
        aliases.append(a)

    group_by = tuple(res.resolve(e) for e in stmt.group_by)
    order_by_resolved = tuple(
        (res.resolve(e), asc) for e, asc in stmt.order_by)
    having_expr = None if stmt.having is None else res.resolve(stmt.having)

    # ---- WHERE split: per-alias pushdown vs post-join residual ----------
    pushdown: dict[str, Optional[Expression]] = {
        s.alias: None for s in sources}
    post: list[Expression] = []
    left_kinds = {s.alias: "PROBE" for s in sources[:1]}
    for j, src in zip(stmt.joins, sources[1:]):
        left_kinds[src.alias] = j.kind
    if stmt.where is not None:
        for conj in _conjuncts(res.resolve(stmt.where)):
            refs = _aliases_of(conj)
            if len(refs) == 1:
                a = next(iter(refs))
                # probe-side filters always commute with the join; a LEFT
                # join's build-side filter must observe default-filled
                # unmatched rows, so it cannot push below the join
                if left_kinds.get(a) in ("PROBE", "INNER"):
                    pushdown[a] = _and(pushdown[a], _unqualify(conj, a))
                    continue
            post.append(conj)

    # ---- joins: equi-key extraction from ON ------------------------------
    joins: list[JoinStep] = []
    seen = {sources[0].alias}
    for clause, build in zip(stmt.joins, sources[1:]):
        on = res.resolve(clause.on)
        keys_l: list[Expression] = []
        keys_r: list[Expression] = []
        residual: list[Expression] = []
        for conj in _conjuncts(on):
            pair = _equi_pair(conj, seen, build.alias)
            if pair is not None:
                keys_l.append(pair[0])
                keys_r.append(pair[1])
                continue
            refs = _aliases_of(conj)
            if clause.kind == "INNER" and len(refs) == 1 \
                    and next(iter(refs)) == build.alias:
                # an INNER join's build-only ON conjunct is equivalent to a
                # WHERE filter on the build table: push it into the scan
                pushdown[build.alias] = _and(
                    pushdown[build.alias], _unqualify(conj, build.alias))
                continue
            residual.append(conj)
        if not keys_l:
            raise SqlAnalysisError(
                f"join ON {build.alias!r} needs at least one equality "
                f"between the joined tables (equi-join); got: {on}")
        joins.append(JoinStep(
            kind=clause.kind, build=build,
            left_keys=tuple(keys_l), right_keys=tuple(keys_r),
            residual=_and_all(residual)))
        seen.add(build.alias)

    # ---- windows ---------------------------------------------------------
    windows = _collect_windows(
        list(select) + [e for e, _ in order_by_resolved])
    if windows and (group_by or stmt.distinct
                    or any(_has_aggregation(e) for e in select)):
        raise SqlAnalysisError(
            "window functions cannot be combined with GROUP BY, DISTINCT "
            "or plain aggregations in the same query")
    if having_expr is not None and contains_window(having_expr):
        raise SqlAnalysisError("window functions are not allowed in HAVING")
    if stmt.where is not None and contains_window(res.resolve(stmt.where)):
        raise SqlAnalysisError("window functions are not allowed in WHERE")

    stage2 = QueryContext(
        table_name=sources[0].table,
        select_expressions=tuple(select),
        aliases=tuple(aliases),
        distinct=stmt.distinct,
        filter=None,
        group_by=group_by,
        having=None if having_expr is None else _to_filter(having_expr),
        order_by=tuple(OrderByExpression(e, asc)
                       for e, asc in order_by_resolved),
        limit=stmt.limit if stmt.limit is not None else DEFAULT_LIMIT,
        offset=stmt.offset,
        options=tuple(sorted(stmt.options.items())),
        explain=stmt.explain,
        analyze=stmt.analyze,
    )

    opts_ci = stage2.options_ci()
    strategy = _pick_strategy(opts_ci, sources[1:])
    return MultiStagePlan(
        sources=tuple(sources), joins=tuple(joins), pushdown=pushdown,
        post_filter=_and_all(post), windows=windows, stage2=stage2,
        strategy=strategy,
        strategy_forced="joinstrategy" in opts_ci,
        explain=stmt.explain, analyze=stmt.analyze)


def _pick_strategy(opts: dict, builds) -> str:
    forced = opts.get("joinstrategy")
    if forced is not None:
        forced = str(forced).upper()
        if forced not in ("BROADCAST", "SHUFFLE", "DISTRIBUTED"):
            raise SqlAnalysisError(
                f"SET joinStrategy must be 'broadcast', 'shuffle' or "
                f"'distributed', got {forced!r}")
        return forced
    # dimension tables are replicated and cheap to broadcast (narrow
    # planes); anything else defaults to the partitioned shuffle join
    if builds and all(b.is_dim for b in builds):
        return "BROADCAST"
    return "SHUFFLE"


# ---------------------------------------------------------------------------
# name resolution
# ---------------------------------------------------------------------------


class _Resolver:
    def __init__(self, sources):
        self.sources = sources
        self.by_alias = {s.alias: s for s in sources}

    def _describe(self) -> str:
        return "; ".join(
            f"{s.alias}({', '.join(s.columns[:8])}"
            f"{', ...' if len(s.columns) > 8 else ''})"
            for s in self.sources)

    def resolve_name(self, name: str) -> str:
        if "." in name:
            alias, col = name.split(".", 1)
            src = self.by_alias.get(alias)
            if src is None:
                raise SqlAnalysisError(
                    f"unknown table alias {alias!r} in column reference "
                    f"{name!r}; tables: {self._describe()}",
                    column=name,
                    candidates=tuple(self.by_alias))
            if col not in src.columns:
                raise SqlAnalysisError(
                    f"column {col!r} not found in table {src.table!r} "
                    f"(alias {alias!r}); its columns: "
                    f"{', '.join(src.columns)}",
                    column=name, candidates=src.columns)
            return name
        hits = [s for s in self.sources if name in s.columns]
        if not hits:
            raise SqlAnalysisError(
                f"column {name!r} not found in any joined table; "
                f"tables: {self._describe()}",
                column=name,
                candidates=tuple(c for s in self.sources for c in s.columns))
        if len(hits) > 1:
            opts = " or ".join(f"{s.alias}.{name}" for s in hits)
            raise SqlAnalysisError(
                f"ambiguous column {name!r}: present in "
                f"{', '.join(repr(s.alias) for s in hits)} — qualify it "
                f"as {opts}",
                column=name, candidates=tuple(s.alias for s in hits))
        return f"{hits[0].alias}.{name}"

    def resolve(self, e: Expression) -> Expression:
        if e.is_identifier:
            if e.name == "*":
                return e  # COUNT(*) operand
            if e.name.startswith("$"):
                raise SqlAnalysisError(
                    f"virtual column {e.name!r} is not supported in "
                    f"multi-stage queries")
            return Expression.identifier(self.resolve_name(e.name))
        if e.is_function:
            return Expression(
                ExpressionType.FUNCTION, name=e.name,
                args=tuple(self.resolve(a) for a in e.args))
        return e


# ---------------------------------------------------------------------------
# expression utilities
# ---------------------------------------------------------------------------


def _conjuncts(e: Expression) -> list:
    if e.is_function and e.name == "and":
        out = []
        for a in e.args:
            out.extend(_conjuncts(a))
        return out
    return [e]


def _and(a: Optional[Expression], b: Expression) -> Expression:
    return b if a is None else Expression.function("and", a, b)


def _and_all(conjs: list) -> Optional[Expression]:
    out = None
    for c in conjs:
        out = _and(out, c)
    return out


def _aliases_of(e: Expression) -> set:
    return {name.split(".", 1)[0] for name in e.columns() if "." in name}


def _unqualify(e: Expression, alias: str) -> Expression:
    """Canonical ``alias.col`` identifiers → bare ``col`` for a pushed-down
    single-table filter (evaluated against that table's own scan)."""
    if e.is_identifier and e.name.startswith(alias + "."):
        return Expression.identifier(e.name[len(alias) + 1:])
    if e.is_function:
        return Expression(
            ExpressionType.FUNCTION, name=e.name,
            args=tuple(_unqualify(a, alias) for a in e.args))
    return e


def _equi_pair(conj: Expression, left_aliases: set, build_alias: str):
    """``equals(a, b)`` with one side referencing only already-joined
    aliases and the other only the build alias → (left_expr, right_expr)."""
    if not (conj.is_function and conj.name == "equals"
            and len(conj.args) == 2):
        return None
    a, b = conj.args
    ra, rb = _aliases_of(a), _aliases_of(b)
    if ra and ra <= left_aliases and rb == {build_alias}:
        return a, b
    if rb and rb <= left_aliases and ra == {build_alias}:
        return b, a
    return None


def _has_aggregation(e: Expression) -> bool:
    if is_aggregation(e):
        return True
    if e.is_function and e.name != "__window__":
        return any(_has_aggregation(a) for a in e.args)
    return False


def _collect_windows(exprs: list) -> tuple:
    found: dict[Expression, WindowSpec] = {}

    def walk(e: Expression):
        if not e.is_function:
            return
        if e.name == "__window__":
            fn, part, order = e.args
            if not fn.is_function or fn.name not in WINDOW_FUNCTIONS:
                raise SqlAnalysisError(
                    f"{fn.name if fn.is_function else fn}() is not a "
                    f"window function; supported: "
                    f"{', '.join(sorted(WINDOW_FUNCTIONS))}")
            arity = WINDOW_FUNCTIONS[fn.name]
            args = tuple(a for a in fn.args
                         if not (a.is_identifier and a.name == "*"))
            if arity is not None and len(args) != arity:
                raise SqlAnalysisError(
                    f"window function {fn.name}() takes {arity} "
                    f"argument(s), got {len(args)}")
            for sub in args + part.args + tuple(
                    o.args[0] for o in order.args):
                if contains_window(sub):
                    raise SqlAnalysisError(
                        "nested window functions are not supported")
            found.setdefault(e, WindowSpec(
                fn=fn.name, expr=e, args=args,
                partition_by=part.args,
                order_by=tuple((o.args[0], o.name == "__asc__")
                               for o in order.args)))
            return
        for a in e.args:
            walk(a)

    for e in exprs:
        walk(e)
    return tuple(found.values())


# ---------------------------------------------------------------------------
# SQL rendering (broker leaf queries + EXPLAIN)
# ---------------------------------------------------------------------------

_OP_BIN = {
    "equals": "=", "not_equals": "<>",
    "greater_than": ">", "greater_than_or_equal": ">=",
    "less_than": "<", "less_than_or_equal": "<=",
    "plus": "+", "minus": "-", "times": "*", "divide": "/", "mod": "%",
}

_IDENT_RE = re.compile(r"[A-Za-z_$][A-Za-z0-9_$]*")


def _sql_ident(name: str) -> str:
    if _IDENT_RE.fullmatch(name):
        return name
    return '"' + name.replace('"', '""') + '"'


def to_sql(e: Expression) -> str:
    """Render an expression back to parseable SQL (broker leaf scans ship
    pushdown filters to servers as text; EXPLAIN renders plans with it)."""
    if e.is_literal:
        v = e.value
        if v is None:
            return "NULL"
        if isinstance(v, bool):
            return "TRUE" if v else "FALSE"
        if isinstance(v, str):
            return "'" + v.replace("'", "''") + "'"
        return str(v)
    if e.is_identifier:
        return e.name if e.name == "*" else _sql_ident(e.name)
    name = e.name
    if name in _OP_BIN and len(e.args) == 2:
        return f"({to_sql(e.args[0])} {_OP_BIN[name]} {to_sql(e.args[1])})"
    if name in ("and", "or"):
        op = f" {name.upper()} "
        return "(" + op.join(to_sql(a) for a in e.args) + ")"
    if name == "not":
        return f"NOT ({to_sql(e.args[0])})"
    if name in ("in", "not_in"):
        vals = ", ".join(to_sql(a) for a in e.args[1:])
        op = "IN" if name == "in" else "NOT IN"
        return f"{to_sql(e.args[0])} {op} ({vals})"
    if name == "between":
        return (f"{to_sql(e.args[0])} BETWEEN {to_sql(e.args[1])} "
                f"AND {to_sql(e.args[2])}")
    if name == "like":
        return f"{to_sql(e.args[0])} LIKE {to_sql(e.args[1])}"
    if name == "is_null":
        return f"{to_sql(e.args[0])} IS NULL"
    if name == "is_not_null":
        return f"{to_sql(e.args[0])} IS NOT NULL"
    if name == "cast":
        return f"CAST({to_sql(e.args[0])} AS {e.args[1].value})"
    return f"{name}({', '.join(to_sql(a) for a in e.args)})"
