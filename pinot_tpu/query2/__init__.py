"""Multi-stage query engine (engine v2): logical planner + stage runner.

The reference snapshot predates Pinot's multi-stage engine — PAPER.md is
explicit that it carries "no pinot-query-planner/pinot-query-runtime; the
only query engine is single-stage scatter-gather". This package leapfrogs
that gap (ROADMAP item 2): ``logical.py`` compiles JOIN / window queries
into a two-stage plan, ``runner.py`` executes it — stage 1 leaf scans ride
the existing single-stage machinery, the join runs on device hash-join
kernels (ops/join.py, radix key packing + static-bound pair expansion,
broadcast or shuffle across the mesh), window functions ride one sorted
pass (ops/window.py), and stage 2 reuses engine/reduce.py's merge /
HAVING / ORDER BY / finalize wholesale. Plain single-table queries never
enter this package.
"""

from pinot_tpu.query2.logical import (  # noqa: F401
    MultiStagePlan,
    compile_plan,
    is_multistage,
)
