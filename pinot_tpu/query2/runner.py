"""Multi-stage stage runner: execute a MultiStagePlan.

Two stages over the existing partial-result machinery:

- **Stage 1** — leaf scans. Embedded/server-local execution rides the same
  SegmentEvaluator the host executor uses (pushdown filters lowered
  through the single-stage FilterNode path, upsert validDocIds honored,
  consuming segments scanned through their mutable reader), producing
  columnar row sets per table. The broker gathers the same row sets by
  scatter-gathering plain single-stage SELECT leaf queries instead
  (broker/broker.py) — stage 1 IS the existing engine either way.
- **Join** — packed int64 key codes (both sides factorized into one shared
  code space, multi-column keys combined with the radix cartesian
  arithmetic) drive the device hash-join kernels (ops/join.py): sort the
  build side, probe with binary search, expand matched pairs under a
  static bound. BROADCAST replicates the sorted build table across the
  mesh and shards the probe axis; SHUFFLE partitions both sides by key
  radix with one bucket per device, all inside one shard_map. A host
  (numpy) mirror covers engines without a device executor — the
  differential reference.
- **Windows** — ops/window.py's one-sort segmented-scan kernel, with a
  per-partition numpy mirror as the host path.
- **Stage 2** — the joined row set feeds the SAME aggregation specs,
  factorize/merge, HAVING, ORDER BY and finalize code the single-stage
  engine uses (engine/aggspec.py + engine/reduce.py): the stage-2
  QueryContext is a plain QueryContext over the canonical joined
  namespace.

LEFT JOIN misses fill build columns with the column TYPE's default ("" /
0), matching the LOOKUP transform's miss semantics — the broadcast join
is a strict superset of LOOKUP and tests pin the two bit-identical.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from pinot_tpu.engine import aggspec
from pinot_tpu.engine.host import (
    SegmentEvaluator,
    factorize_multi,
    like_to_regex,
)
from pinot_tpu.engine.reduce import finalize, merge_intermediates
from pinot_tpu.engine.result import ExecutionStats, IntermediateResult
from pinot_tpu.ops.transform import get_function
from pinot_tpu.query.context import (
    Expression,
    FilterNode,
    FilterNodeType,
    PredicateType,
)
from pinot_tpu.query.optimizer import optimize_filter
from pinot_tpu.query2.logical import (
    BROADCAST_MAX_BUILD_ROWS,
    MultiStagePlan,
    compile_plan,
)
from pinot_tpu.sql.compiler import _to_filter
from pinot_tpu.sql.parser import SqlAnalysisError

MAX_STAGE1_ROWS = int(os.environ.get("PINOT_TPU_MAX_JOIN_ROWS", 4_000_000))
MAX_JOIN_PAIRS = int(os.environ.get("PINOT_TPU_MAX_JOIN_PAIRS", 16_000_000))

# combined key-code space guard: the cartesian pack must stay in int64
_MAX_KEYSPACE = 1 << 62


# ---------------------------------------------------------------------------
# expression evaluation over a columnar row set
# ---------------------------------------------------------------------------


def _eval(cols: dict, expr: Expression, env: Optional[dict] = None,
          n: Optional[int] = None):
    """Evaluate an expression over canonical joined columns. ``env`` maps
    precomputed expressions (window results) to value arrays."""
    if env and expr in env:
        return env[expr]
    if expr.is_literal:
        return np.asarray(expr.value)
    if expr.is_identifier:
        if expr.name not in cols:
            raise KeyError(f"column {expr.name!r} not in joined row set")
        return cols[expr.name]
    if expr.name == "__window__":
        raise SqlAnalysisError(
            "window expression evaluated outside its stage")
    fn = get_function(expr.name)
    if expr.name == "cast":
        return fn.np_fn(_eval(cols, expr.args[0], env, n),
                        expr.args[1].value)
    args = [_eval(cols, a, env, n) for a in expr.args]
    return fn.np_fn(*args)


def _eval_rows(cols: dict, expr: Expression, env: Optional[dict],
               n: int) -> np.ndarray:
    v = np.asarray(_eval(cols, expr, env, n))
    if v.ndim == 0:
        return np.broadcast_to(v, (n,))
    return v


def _predicate_mask(v: np.ndarray, p) -> np.ndarray:
    import re as _re

    t = p.type
    if t is PredicateType.EQ:
        return v == _coerce(p.value, v)
    if t is PredicateType.NOT_EQ:
        return v != _coerce(p.value, v)
    if t is PredicateType.IN:
        return np.isin(v, _coerce_list(p.values, v))
    if t is PredicateType.NOT_IN:
        return ~np.isin(v, _coerce_list(p.values, v))
    if t is PredicateType.RANGE:
        m = np.ones(len(v), dtype=bool)
        if p.lower is not None:
            lo = _coerce(p.lower, v)
            m &= (v >= lo) if p.lower_inclusive else (v > lo)
        if p.upper is not None:
            hi = _coerce(p.upper, v)
            m &= (v <= hi) if p.upper_inclusive else (v < hi)
        return m
    if t in (PredicateType.LIKE, PredicateType.REGEXP_LIKE):
        pat = p.value if t is not PredicateType.LIKE \
            else like_to_regex(p.value)
        rx = _re.compile(pat)
        search = rx.search if t is not PredicateType.LIKE else rx.match
        return np.fromiter((bool(search(s)) for s in v.astype(str)),
                           dtype=bool, count=len(v))
    raise SqlAnalysisError(f"predicate {t.value} is not supported on "
                           f"joined rows")


def _coerce(value, v: np.ndarray):
    return str(value) if v.dtype.kind in ("U", "S") else value


def _coerce_list(values, v: np.ndarray):
    if v.dtype.kind in ("U", "S"):
        return np.asarray([str(x) for x in values])
    return np.asarray(list(values))


def _filter_mask(cols: dict, f: FilterNode, env, n: int) -> np.ndarray:
    t = f.type
    if t is FilterNodeType.CONSTANT_TRUE:
        return np.ones(n, dtype=bool)
    if t is FilterNodeType.CONSTANT_FALSE:
        return np.zeros(n, dtype=bool)
    if t is FilterNodeType.AND:
        m = _filter_mask(cols, f.children[0], env, n)
        for c in f.children[1:]:
            m = m & _filter_mask(cols, c, env, n)
        return m
    if t is FilterNodeType.OR:
        m = _filter_mask(cols, f.children[0], env, n)
        for c in f.children[1:]:
            m = m | _filter_mask(cols, c, env, n)
        return m
    if t is FilterNodeType.NOT:
        return ~_filter_mask(cols, f.children[0], env, n)
    return _predicate_mask(_eval_rows(cols, f.predicate.lhs, env, n),
                           f.predicate)


def _expr_mask(cols: dict, expr: Expression, env, n: int) -> np.ndarray:
    """Boolean expression → row mask, through the single-stage filter
    lowering so predicate semantics are identical to stage 1."""
    return _filter_mask(cols, optimize_filter(_to_filter(expr)), env, n)


# ---------------------------------------------------------------------------
# stage 1: local leaf scans (embedded / server-local execution)
# ---------------------------------------------------------------------------


def _tdm_for(engine, table: str):
    for key in (table, f"{table}_OFFLINE", f"{table}_REALTIME"):
        tdm = engine.tables.get(key)
        if tdm is not None:
            return tdm
    raise KeyError(f"table {table!r} not found")


def scan_local_rows(engine, table: str, filter_expr: Optional[Expression],
                    need_cols: tuple, stats: ExecutionStats,
                    segments: Optional[list] = None) -> dict:
    """Matched rows of one table over all locally hosted segments →
    {bare column -> np array}. Pushdown filters lower through the SAME
    FilterNode path as single-stage queries; upsert validDocIds and
    consuming (mutable) segments behave exactly like the host executor.
    ``segments`` restricts the scan to the named segments — the
    distributed exchange ships each worker its routed slice so two
    replicas of one segment never both scan it."""
    seg_filter = None if segments is None else set(segments)
    tdm = _tdm_for(engine, table)
    hosted = tdm.acquire()
    try:
        if not hosted:
            raise ValueError(f"table {table!r} has no segments")
        fnode = None if filter_expr is None \
            else optimize_filter(_to_filter(filter_expr))
        parts: dict[str, list] = {c: [] for c in need_cols}
        total = 0
        for seg in hosted:
            if seg_filter is not None and \
                    getattr(seg, "name", None) not in seg_filter:
                continue
            if getattr(seg, "is_cold", False):
                # cold tier (server/tiering.py): planes live only in the
                # deep store — honest in-flight partial, the touch
                # schedules the async hydration
                stats.num_segments_queried += 1
                stats.num_segments_cold += 1
                stats.total_docs += seg.n_docs
                touch = getattr(seg, "touch", None)
                if touch is not None:
                    touch()
                continue
            ev = SegmentEvaluator(
                seg, lookup_resolver=getattr(engine.host, "lookup_resolver",
                                             None))
            vd = getattr(seg, "valid_docs_mask", None)
            if vd is not None:
                vd = np.asarray(vd)[: ev.n].copy()
            elif hasattr(seg, "valid_docs"):
                m = seg.valid_docs(ev.n)
                vd = None if m is None else np.asarray(m).copy()
            mask = ev.filter_mask(fnode) if fnode is not None \
                else np.ones(ev.n, dtype=bool)
            if vd is not None:
                mask = mask & vd
            doc_idx = np.nonzero(mask)[0]
            stats.num_segments_queried += 1
            stats.num_segments_processed += 1
            stats.num_docs_scanned += int(len(doc_idx))
            stats.num_entries_scanned_in_filter += ev.entries_scanned_in_filter
            stats.num_entries_scanned_post_filter += \
                len(doc_idx) * len(need_cols)
            stats.total_docs += ev.n
            if len(doc_idx):
                stats.num_segments_matched += 1
            total += len(doc_idx)
            if total > MAX_STAGE1_ROWS:
                raise SqlAnalysisError(
                    f"stage-1 row set for table {table!r} exceeds "
                    f"{MAX_STAGE1_ROWS} rows; add a more selective filter "
                    f"(PINOT_TPU_MAX_JOIN_ROWS overrides)")
            for c in need_cols:
                parts[c].append(
                    np.asarray(ev.eval(Expression.identifier(c), doc_idx)))
        return {
            c: (np.concatenate(parts[c]) if parts[c]
                else np.empty(0)) for c in need_cols
        }
    finally:
        tdm.release(hosted)


def needed_columns(plan: MultiStagePlan) -> dict:
    """alias → tuple of bare columns the post-scan stages reference."""
    names: set[str] = set()
    q = plan.stage2
    for e in q.select_expressions:
        names |= e.columns()
    for g in q.group_by:
        names |= g.columns()
    if q.having is not None:
        names |= q.having.columns()
    for ob in q.order_by:
        names |= ob.expression.columns()
    for j in plan.joins:
        for k in j.left_keys + j.right_keys:
            names |= k.columns()
        if j.residual is not None:
            names |= j.residual.columns()
    if plan.post_filter is not None:
        names |= plan.post_filter.columns()
    for w in plan.windows:
        for e in w.args + w.partition_by + tuple(e for e, _ in w.order_by):
            names |= e.columns()
    out: dict[str, list] = {s.alias: [] for s in plan.sources}
    for name in sorted(names):
        if "." not in name:
            continue
        alias, col = name.split(".", 1)
        if alias in out and col not in out[alias]:
            out[alias].append(col)
    # a table joined purely for existence still needs its key columns,
    # which the loop above covers; guarantee at least one column per
    # source so empty projections keep a row count
    for s in plan.sources:
        if not out[s.alias]:
            out[s.alias].append(s.columns[0])
    return {a: tuple(c) for a, c in out.items()}


# ---------------------------------------------------------------------------
# join execution
# ---------------------------------------------------------------------------


def _factorize_codes(left_vals: list, right_vals: list):
    """Shared-code-space factorization: per key column, both sides'
    values unify into one np.unique code table; multi-column keys combine
    with the radix cartesian arithmetic (ops/radix_groupby.pack_keys'
    scheme, host-side). Returns (codes_l, codes_r, impossible):
    ``impossible`` is True when a key pair mixes string and numeric
    operands — strict typing means such an equi-key can never match (the
    sqlite oracle's int = text is always false), so the caller skips the
    match phase entirely."""
    n_l = len(left_vals[0]) if left_vals else 0
    n_r = len(right_vals[0]) if right_vals else 0
    codes_l = np.zeros(n_l, dtype=np.int64)
    codes_r = np.zeros(n_r, dtype=np.int64)
    space = 1
    for lv, rv in zip(left_vals, right_vals):
        lv, rv = np.asarray(lv), np.asarray(rv)
        if (lv.dtype.kind in ("U", "S", "O")) != \
                (rv.dtype.kind in ("U", "S", "O")):
            return codes_l, codes_r, True
        u, inv = np.unique(np.concatenate([lv, rv]), return_inverse=True)
        c = max(len(u), 1)
        if space > _MAX_KEYSPACE // c:
            raise SqlAnalysisError(
                "join key space too wide to pack into int64; reduce the "
                "number of join key columns")
        space *= c
        codes_l = codes_l * c + inv[:n_l]
        codes_r = codes_r * c + inv[n_l:]
    return codes_l, codes_r, False


def _match_pairs_host(probe: np.ndarray, build: np.ndarray):
    """numpy mirror of the device sort/probe/expand pipeline."""
    from pinot_tpu.engine.host import concat_ranges

    order = np.argsort(build, kind="stable")
    sk = build[order]
    lo = np.searchsorted(sk, probe, side="left")
    hi = np.searchsorted(sk, probe, side="right")
    counts = hi - lo
    total = int(counts.sum())
    if total > MAX_JOIN_PAIRS:
        raise SqlAnalysisError(
            f"join produces more than {MAX_JOIN_PAIRS} matched pairs")
    probe_idx = np.repeat(np.arange(len(probe), dtype=np.int64), counts)
    build_pos = concat_ranges(lo.astype(np.int64), counts.astype(np.int64))
    return probe_idx, order[build_pos]


def _match_pairs_device(probe: np.ndarray, build: np.ndarray, mesh,
                        strategy: str):
    """Device pipeline: ops/join.py kernels, solo or across the mesh."""
    import jax.numpy as jnp

    from pinot_tpu.ops import join as join_ops

    if mesh is not None and strategy == "SHUFFLE":
        return _match_pairs_mesh_shuffle(probe, build, mesh)
    unique_build = len(np.unique(build)) == len(build)
    if mesh is not None:
        return _match_pairs_mesh_broadcast(probe, build, mesh,
                                           unique_build)
    jp = jnp.asarray(probe)
    sk, perm = join_ops.sort_build(jnp.asarray(build))
    if unique_build:
        # dim-table pk probe (the LOOKUP shape): 1:1, no pair expansion
        found, build_row = join_ops.probe_unique(sk, perm, jp)
        found = np.asarray(found)
        build_row = np.asarray(build_row)
        probe_idx = np.nonzero(found)[0]
        return probe_idx, build_row[probe_idx]
    lo, counts = join_ops.probe_ranges(sk, jp)
    total = int(np.asarray(counts).sum())
    if total > MAX_JOIN_PAIRS:
        raise SqlAnalysisError(
            f"join produces more than {MAX_JOIN_PAIRS} matched pairs")
    if total == 0:
        return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64))
    bound = join_ops.next_pow2(total)
    pr, bp, valid = join_ops.expand_pairs(
        lo.astype(jnp.int64), counts.astype(jnp.int64), bound)
    pr, bp, valid = np.asarray(pr), np.asarray(bp), np.asarray(valid)
    perm = np.asarray(perm)
    return pr[valid], perm[bp[valid]]


def _match_pairs_mesh_broadcast(probe: np.ndarray, build: np.ndarray, mesh,
                                unique_build: bool = False):
    """BROADCAST on the mesh: replicated sorted build table, probe axis
    sharded inside one shard_map (ops/join.py mesh_probe_ranges;
    mesh_probe_unique for the 1:1 dim-table pk shape)."""
    import jax.numpy as jnp

    from pinot_tpu.ops import join as join_ops

    D = mesh.devices.size
    n = len(probe)
    pad = (-n) % D
    probe_p = np.concatenate(
        [probe, np.full(pad, join_ops.PROBE_PAD, dtype=np.int64)]) \
        if pad else probe
    sk, perm = join_ops.sort_build(jnp.asarray(build))
    if unique_build:
        found, build_row = join_ops.mesh_probe_unique(
            mesh, sk, perm, jnp.asarray(probe_p))
        found = np.asarray(found)[:n]
        build_row = np.asarray(build_row)[:n]
        probe_idx = np.nonzero(found)[0]
        return probe_idx, build_row[probe_idx]
    lo, counts = join_ops.mesh_probe_ranges(mesh, sk, jnp.asarray(probe_p))
    lo = np.asarray(lo)[:n].astype(np.int64)
    counts = np.asarray(counts)[:n].astype(np.int64)
    total = int(counts.sum())
    if total > MAX_JOIN_PAIRS:
        raise SqlAnalysisError(
            f"join produces more than {MAX_JOIN_PAIRS} matched pairs")
    if total == 0:
        return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64))
    bound = join_ops.next_pow2(total)
    pr, bp, valid = join_ops.expand_pairs(
        jnp.asarray(lo), jnp.asarray(counts), bound)
    pr, bp, valid = np.asarray(pr), np.asarray(bp), np.asarray(valid)
    perm = np.asarray(perm)
    return pr[valid], perm[bp[valid]]


def _match_pairs_mesh_shuffle(probe: np.ndarray, build: np.ndarray, mesh):
    """SHUFFLE on the mesh: both sides partitioned by key radix, one
    bucket per device, every bucket's sort+probe in ONE shard_map; pair
    expansion rides a vmapped static-bound kernel per bucket."""
    import jax.numpy as jnp

    from pinot_tpu.ops import join as join_ops

    D = mesh.devices.size
    bkeys, brows = join_ops.partition_by_key(build, D, join_ops.BUILD_PAD)
    pkeys, prows = join_ops.partition_by_key(probe, D, join_ops.PROBE_PAD)
    lo, counts, perm = join_ops.mesh_bucket_ranges(
        mesh, jnp.asarray(bkeys), jnp.asarray(pkeys))
    counts_np = np.asarray(counts).astype(np.int64)
    total = int(counts_np.sum())
    if total > MAX_JOIN_PAIRS:
        raise SqlAnalysisError(
            f"join produces more than {MAX_JOIN_PAIRS} matched pairs")
    if total == 0:
        return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64))
    bound = join_ops.next_pow2(int(counts_np.sum(axis=1).max()))
    pr, bp, valid = join_ops.expand_pairs_buckets(
        jnp.asarray(np.asarray(lo).astype(np.int64)),
        jnp.asarray(counts_np), bound)
    pr, bp, valid = np.asarray(pr), np.asarray(bp), np.asarray(valid)
    perm = np.asarray(perm)
    out_probe, out_build = [], []
    for d in range(D):
        v = valid[d]
        if not v.any():
            continue
        local_pr = pr[d][v]
        local_bp = bp[d][v]
        out_probe.append(prows[d][local_pr])
        out_build.append(brows[d][perm[d][local_bp]])
    return (np.concatenate(out_probe), np.concatenate(out_build))


def _default_fill(arr: np.ndarray, k: int) -> np.ndarray:
    """LEFT-join miss fill: the build column TYPE's default — identical to
    the LOOKUP transform's miss semantics ("" for strings, 0 for
    numbers)."""
    kind = arr.dtype.kind
    if kind in ("U", "S"):
        return np.zeros(k, dtype=arr.dtype)  # empty strings
    if kind == "O":
        return np.full(k, "", dtype=object)
    if kind == "b":
        return np.zeros(k, dtype=bool)
    return np.zeros(k, dtype=arr.dtype)


def execute_join_step(left_cols: dict, n_left: int, step, build_cols: dict,
                      device, mesh, strategy: str):
    """One join: match, expand, gather, residual-filter, LEFT-append.
    Returns (joined cols dict, new row count)."""
    lkeys = [_eval_rows(left_cols, k, None, n_left) for k in step.left_keys]
    n_build = len(next(iter(build_cols.values()))) if build_cols else 0
    rkeys = [_eval_rows(build_cols, k, None, n_build)
             for k in step.right_keys]
    pc, bc, impossible = _factorize_codes(lkeys, rkeys)

    if n_left == 0 or n_build == 0 or impossible:
        probe_idx = np.empty(0, dtype=np.int64)
        build_idx = np.empty(0, dtype=np.int64)
    elif device is not None:
        probe_idx, build_idx = _match_pairs_device(pc, bc, mesh, strategy)
    else:
        probe_idx, build_idx = _match_pairs_host(pc, bc)

    joined = {name: np.asarray(arr)[probe_idx]
              for name, arr in left_cols.items()}
    for name, arr in build_cols.items():
        joined[name] = np.asarray(arr)[build_idx]

    if step.residual is not None and len(probe_idx):
        m = _expr_mask(joined, step.residual, None, len(probe_idx))
        probe_idx = probe_idx[m]
        joined = {k: v[m] for k, v in joined.items()}

    n = len(probe_idx)
    if step.kind == "LEFT":
        matched = np.zeros(n_left, dtype=bool)
        matched[probe_idx] = True
        miss = np.nonzero(~matched)[0]
        if len(miss):
            for name, arr in left_cols.items():
                joined[name] = np.concatenate(
                    [joined[name], np.asarray(arr)[miss]])
            for name, arr in build_cols.items():
                joined[name] = np.concatenate(
                    [joined[name], _default_fill(np.asarray(arr),
                                                 len(miss))])
            n += len(miss)
    return joined, n


# ---------------------------------------------------------------------------
# window execution
# ---------------------------------------------------------------------------


def _partition_codes(cols: dict, exprs: tuple, n: int) -> np.ndarray:
    if not exprs:
        return np.zeros(n, dtype=np.int64)
    vals = [_eval_rows(cols, e, None, n) for e in exprs]
    _, ginv = factorize_multi(vals)
    return ginv.astype(np.int64)


def _order_codes(cols: dict, order_by: tuple, n: int) -> np.ndarray:
    """Dense lexicographic rank codes over (expr, asc) keys: peer rows
    (equal tuples) share a code; descending keys negate their per-column
    rank so one combined int64 preserves the full ordering."""
    if not order_by:
        return np.zeros(n, dtype=np.int64)
    keys = []
    for e, asc in order_by:
        v = _eval_rows(cols, e, None, n)
        u, inv = np.unique(np.asarray(v), return_inverse=True)
        code = inv.astype(np.int64)
        if not asc:
            code = (len(u) - 1) - code
        keys.append(code)
    # combined dense rank via one more factorize pass (no overflow: the
    # pairwise-chained combine re-densifies at every step)
    combined = keys[0]
    for c in keys[1:]:
        u, inv = np.unique(combined * (c.max() + 1 if len(c) else 1) + c,
                           return_inverse=True)
        combined = inv.astype(np.int64)
    return combined


def _windows_host(part: np.ndarray, order: np.ndarray, specs: list,
                  values: list, n: int) -> list:
    """Per-partition numpy mirror of ops/window.window_eval (engines
    without a device executor — the differential reference)."""
    sorter = np.lexsort((np.arange(n), order, part))
    p, o = part[sorter], order[sorter]
    bounds = np.nonzero(np.concatenate(
        [[True], p[1:] != p[:-1]]))[0].tolist() + [n]
    outs = [np.zeros(n, dtype=np.int64 if fn in
                     ("row_number", "rank", "dense_rank", "count")
                     else np.float64) for fn, _ in specs]
    for s, e in zip(bounds[:-1], bounds[1:]):
        po = o[s:e]
        peer_start = np.concatenate([[True], po[1:] != po[:-1]])
        peer_id = np.cumsum(peer_start) - 1
        rn = np.arange(1, e - s + 1, dtype=np.int64)
        first_of_peer = np.nonzero(peer_start)[0]
        last_of_peer = np.concatenate([first_of_peer[1:] - 1, [e - s - 1]])
        for oi, (fn, vi) in enumerate(specs):
            if fn == "row_number":
                res = rn
            elif fn == "rank":
                res = rn[first_of_peer][peer_id]
            elif fn == "dense_rank":
                res = peer_id + 1
            elif fn == "count":
                res = rn[last_of_peer][peer_id]
            else:
                v = values[vi][sorter][s:e].astype(np.float64)
                if fn == "sum":
                    run = np.cumsum(v)
                elif fn == "avg":
                    run = np.cumsum(v)
                elif fn == "min":
                    run = np.minimum.accumulate(v)
                else:
                    run = np.maximum.accumulate(v)
                res = run[last_of_peer][peer_id]
                if fn == "avg":
                    res = res / rn[last_of_peer][peer_id]
            outs[oi][sorter[s:e]] = res
    return outs


def apply_windows(cols: dict, windows: tuple, n: int, device) -> dict:
    """Compute every WindowSpec → {window Expression: value array}.
    Specs sharing a (PARTITION BY, ORDER BY) pair share one sort."""
    env: dict = {}
    groups: dict = {}
    for w in windows:
        groups.setdefault((w.partition_by, w.order_by), []).append(w)
    for (part_by, order_by), ws in groups.items():
        part = _partition_codes(cols, part_by, n)
        order = _order_codes(cols, order_by, n)
        values: list = []
        val_index: dict = {}
        specs = []
        for w in ws:
            if w.args:
                key = w.args[0]
                if key not in val_index:
                    val_index[key] = len(values)
                    values.append(
                        _eval_rows(cols, key, None, n).astype(np.float64))
                vi = val_index[key]
            else:
                # COUNT(*) / rank family need no operand; COUNT rides the
                # row counter inside the kernel
                vi = -1
            specs.append((w.fn, vi))
        if n == 0:
            for w, (fn, _) in zip(ws, specs):
                dt = np.int64 if fn in ("row_number", "rank", "dense_rank",
                                        "count") else np.float64
                env[w.expr] = np.empty(0, dtype=dt)
            continue
        if device is not None:
            import jax.numpy as jnp

            from pinot_tpu.ops import window as window_ops

            pp, oo, rr, vv = window_ops.pad_inputs(
                part, order, np.arange(n, dtype=np.int64), tuple(values))
            outs = window_ops.window_eval(
                jnp.asarray(pp), jnp.asarray(oo), jnp.asarray(rr),
                tuple(jnp.asarray(v) for v in vv), tuple(specs))
            outs = [np.asarray(o)[:n] for o in outs]
        else:
            outs = _windows_host(part, order, specs, values, n)
        for w, out in zip(ws, outs):
            env[w.expr] = out
    return env


# ---------------------------------------------------------------------------
# stage 2: aggregate / having / order / finalize (engine/reduce.py reuse)
# ---------------------------------------------------------------------------


def _pallas_groupby_partials(aggs, specs, cols, env, ginv, n_groups: int,
                             n: int, device) -> dict:
    """Route COUNT + integer SUM/AVG stage-2 group partials through the
    PR-14 Pallas tiled local-accumulate scatter (ops/pallas_scatter.py
    plane_group_sums), mirroring device.py's ``_try_mm_groupby``
    channel-planning: each eligible agg contributes byte-plane bf16
    channels, the ones channel carries the per-group count, and
    ``recombine_int`` reassembles EXACT int64 sums (converted to the
    canonical float64 ``{"sum"}`` partial — exact for in-range ints, so
    results stay bit-identical to the host scatter). Float sums keep the
    host path: f32 plane recombination can round differently from the
    float64 ``np.add.at`` accumulator and stage-2 parity is pinned
    bit-exact. Returns {agg index: partial dict}; {} when the tier is
    off or out of regime, and the caller falls back per-agg."""
    mode = device._resolve_pallas({}) if device is not None else "off"
    if mode == "off" or n == 0 or n_groups == 0:
        return {}
    try:
        import jax.numpy as jnp

        from pinot_tpu.ops import groupby_mm as mm
        from pinot_tpu.ops import pallas_scatter as ps
    except Exception:  # noqa: BLE001 — tier is an optimization, not a dep
        return {}

    count_idx = [i for i, s in enumerate(specs) if s.name == "count"]
    plans = []  # (i, int64 values, offset, nplanes)
    total_ch = 1  # ones channel
    for i, spec in enumerate(specs):
        if spec.name not in ("sum", "avg") or spec.mv or not spec.args:
            continue
        v = np.asarray(_eval_rows(cols, spec.args[0], env, n))
        if v.dtype.kind not in ("i", "u", "b"):
            continue
        lo, hi = int(v.min()), int(v.max())
        nplanes = mm.int_planes_needed(lo, hi)
        if total_ch + nplanes > mm.MAX_CHANNELS + 1:
            continue
        plans.append((i, v.astype(np.int64), lo, nplanes))
        total_ch += nplanes
    if not plans and not count_idx:
        return {}
    if not (ps.sums_supported(n_groups, total_ch)
            and (mode == "interpret" or n >= ps.PALLAS_MIN_ROWS)):
        return {}

    channels = [jnp.ones(n, dtype=jnp.bfloat16)]
    for _, v, off, nplanes in plans:
        channels.extend(mm.int_planes(jnp.asarray(v), off, nplanes))
    sums = ps.plane_group_sums(
        jnp.asarray(np.asarray(ginv, dtype=np.int64)),
        jnp.stack(channels), n_groups,
        interpret=(mode == "interpret"), first_channel_ones=True)
    gcount = jnp.round(sums[0]).astype(jnp.int64)
    gcount_np = np.asarray(gcount)
    out = {}
    row = 1
    for i, _, off, nplanes in plans:
        planes = [sums[j] for j in range(row, row + nplanes)]
        row += nplanes
        s = np.asarray(mm.recombine_int(planes, gcount, off)) \
            .astype(np.float64)
        out[i] = ({"sum": s, "count": gcount_np.copy()}
                  if specs[i].name == "avg" else {"sum": s})
    for i in count_idx:
        out[i] = {"count": gcount_np.copy()}
    return out


def stage2_partial(plan: MultiStagePlan, cols: dict, n: int, env: dict,
                   device=None) -> IntermediateResult:
    """Joined rows → one MERGEABLE IntermediateResult (the canonical
    partial engine/reduce.py merges). The distributed exchange runs this
    per owned partition on each server — partials ship back as
    DataTables and the broker's merge_intermediates + finalize is the
    only stage-2 work left above the fleet. ``device`` routes eligible
    group-bys through the Pallas scatter tier."""
    q = plan.stage2
    stats = ExecutionStats(num_docs_scanned=n)
    aggs = q.aggregations()

    if q.distinct:
        key_cols = [_eval_rows(cols, e, env, n) for e in
                    q.select_expressions]
        if n == 0:
            keys = tuple(np.asarray(k)[:0] for k in key_cols)
        else:
            keys, _ = factorize_multi(key_cols)
        return IntermediateResult("distinct", group_keys=keys, stats=stats)

    if aggs and q.group_by:
        key_cols = [_eval_rows(cols, g, env, n) for g in q.group_by]
        specs = [aggspec.make_spec(a) for a in aggs]
        if n == 0:
            return IntermediateResult(
                "group_by",
                group_keys=tuple(np.asarray(k)[:0] for k in key_cols),
                agg_partials=[s.empty(0) for s in specs], stats=stats)
        keys, ginv = factorize_multi(key_cols)
        n_groups = len(keys[0])
        for a, spec in zip(aggs, specs):
            if spec.mv:
                raise SqlAnalysisError(
                    f"multi-value aggregation {a.name}() is not supported "
                    f"over joined rows")
        fast = _pallas_groupby_partials(aggs, specs, cols, env, ginv,
                                        n_groups, n, device)
        partials = []
        for i, (a, spec) in enumerate(zip(aggs, specs)):
            if i in fast:
                partials.append(fast[i])
                continue
            arg_values = [_eval_rows(cols, arg, env, n)
                          for arg in spec.args]
            partials.append(spec.host_groups(arg_values, ginv, n_groups))
        return IntermediateResult("group_by", group_keys=keys,
                                  agg_partials=partials, stats=stats)

    if aggs:
        specs = [aggspec.make_spec(a) for a in aggs]
        zero = np.zeros(n, dtype=np.int64)
        partials = []
        for a, spec in zip(aggs, specs):
            if spec.mv:
                raise SqlAnalysisError(
                    f"multi-value aggregation {a.name}() is not supported "
                    f"over joined rows")
            arg_values = [_eval_rows(cols, arg, env, n)
                          for arg in spec.args]
            partials.append(spec.host_groups(arg_values, zero, 1))
        return IntermediateResult("aggregation", agg_partials=partials,
                                  stats=stats)

    # selection: evaluate select + order-by columns, let finalize trim
    rows: dict = {}
    for i, e in enumerate(q.select_expressions):
        rows[i] = _eval_rows(cols, e, env, n)
    for j, ob in enumerate(q.order_by):
        rows[f"__ob{j}"] = _eval_rows(cols, ob.expression, env, n)
    return IntermediateResult("selection", rows=rows, stats=stats)


def run_stage2(plan: MultiStagePlan, cols: dict, n: int, env: dict,
               device=None):
    """Joined rows → ResultTable through the single-stage reduce path."""
    return finalize(plan.stage2, stage2_partial(plan, cols, n, env, device))


# ---------------------------------------------------------------------------
# plan execution over materialized stage-1 row sets (engine + broker shared)
# ---------------------------------------------------------------------------


def run_plan(plan: MultiStagePlan, table_rows: dict, device=None,
             advisor=None, advisor_key=None):
    """table_rows: alias → {bare column: np array}. Returns (ResultTable,
    meta dict with join/window execution facts).

    ``advisor``/``advisor_key`` (ISSUE 17): the plan advisor's memo for
    this template feeds the join-strategy pick (measured build-side rows
    from prior executions beat the catalog's dim-table heuristic), and
    every step's ACTUAL build rows + effective strategy are observed
    back. Overrides land in meta["advisorDecisions"]."""
    mesh = getattr(device, "mesh", None) if device is not None else None
    probe = plan.probe
    left_cols = {f"{probe.alias}.{c}": np.asarray(v)
                 for c, v in table_rows[probe.alias].items()}
    n = len(next(iter(left_cols.values()))) if left_cols else 0

    strategies = []
    roofline_recs = []
    adv_notes = []
    for step in plan.joins:
        build_cols = {f"{step.build.alias}.{c}": np.asarray(v)
                      for c, v in table_rows[step.build.alias].items()}
        n_build = len(next(iter(build_cols.values()))) if build_cols else 0
        strat = plan.strategy
        if strat == "DISTRIBUTED":
            # the wire exchange lives in the broker's orchestration
            # (broker/broker.py _execute_distributed); when the plan
            # reaches THIS runner — embedded engine, or a broker that
            # found the plan ineligible — the local execution form of a
            # distributed join IS the shuffle mirror
            strat = "SHUFFLE"
        if advisor is not None and advisor_key \
                and not plan.strategy_forced:
            # measured build rows beat the static dim-table heuristic:
            # a fact build that filters down tiny broadcasts, a dim
            # build that grew past the threshold shuffles. Both sides
            # compute identical joined rows — strategy is pure perf.
            strat2, note = advisor.advise_join_strategy(
                advisor_key, strat, step.build.alias,
                BROADCAST_MAX_BUILD_ROWS)
            if note:
                strat = strat2
                adv_notes.append(note)
        if strat == "BROADCAST" and not plan.strategy_forced \
                and n_build > BROADCAST_MAX_BUILD_ROWS:
            # a heuristic BROADCAST must not replicate a huge build table
            # to every device; SET joinStrategy='broadcast' overrides
            strat = "SHUFFLE"
        bytes_in = sum(int(v.nbytes) for v in left_cols.values()) \
            + sum(int(v.nbytes) for v in build_cols.values())
        t_join = time.perf_counter()
        left_cols, n = execute_join_step(
            left_cols, n, step, build_cols, device, mesh, strat)
        join_ms = (time.perf_counter() - t_join) * 1e3
        strategies.append(strat)
        if advisor is not None and advisor_key:
            advisor.observe(advisor_key,
                            build_rows={step.build.alias: n_build},
                            join_strategy=strat)
        # roofline record for the join step (ISSUE 11): probe+build
        # bytes in, expanded pairs out, over the step's wall — a coarser
        # model than the leaf-scan kernels' (host glue is inside the
        # wall), but it makes EXPLAIN ANALYZE on a join render the same
        # per-kernel GB/s line the single-stage path gets
        roofline_recs.append(_join_roofline_record(
            step, strat, bytes_in, left_cols, join_ms, device))

    if plan.post_filter is not None and n:
        m = _expr_mask(left_cols, plan.post_filter, None, n)
        left_cols = {k: v[m] for k, v in left_cols.items()}
        n = int(m.sum())

    env = apply_windows(left_cols, plan.windows, n, device) \
        if plan.windows else {}

    result = run_stage2(plan, left_cols, n, env, device)
    effective = None
    if strategies:
        effective = strategies[0] if len(set(strategies)) == 1 else "MIXED"
    meta = {
        "numStages": 2 if (plan.joins or plan.windows) else 1,
        "joinStrategy": effective,
        "numJoinedRows": n,
        "backend": "device" if device is not None else "host",
        "mesh": mesh is not None,
        "roofline": roofline_recs,
        # partition fan-out of the executed join (the broker-local
        # SHUFFLE baseline column vs the distributed exchange's
        # numPartitions): one bucket per mesh device, 1 when solo/host
        "joinFanout": (mesh.devices.size
                       if (mesh is not None and effective == "SHUFFLE")
                       else 1) if strategies else 0,
    }
    if adv_notes:
        meta["advisorDecisions"] = adv_notes
    return result, meta


def _join_roofline_record(step, strat: str, bytes_in: int, out_cols: dict,
                          join_ms: float, device) -> dict:
    """Roofline flight record for one executed join step."""
    import sys

    from pinot_tpu.ops import roofline as rl

    bytes_out = sum(int(v.nbytes) for v in out_cols.values())
    bytes_moved = bytes_in + bytes_out
    rec = {"kernel": f"join_{step.kind.lower()}+{strat.lower()}",
           "bytesMoved": bytes_moved, "bytesFetched": bytes_out,
           "kernelMs": round(join_ms, 3), "linkMs": 0.0,
           "cacheHit": False}
    if join_ms > 0:
        gbps = bytes_moved / (join_ms / 1e3) / 1e9
        rec["gbps"] = round(gbps, 3)
        # only probe when a device executor is attached or jax is already
        # resident — a jax-free broker process must stay jax-free
        peak = rl.hbm_peak_gbps() \
            if (device is not None or "jax" in sys.modules) \
            else (rl.peak_if_probed() or 0.0)
        pct = rl.pct_of_peak(gbps, peak)
        if pct is not None:
            rec["peakGbps"] = round(peak, 1)
            rec["pctOfPeak"] = pct
    return rec


def run_local(engine, plan: MultiStagePlan):
    """Embedded / server-local execution: stage-1 scans over the engine's
    hosted segments, then the shared plan runner."""
    from pinot_tpu.common.trace import span

    stats = ExecutionStats()
    need = needed_columns(plan)
    table_rows = {}
    # spans are no-ops untraced; under EXPLAIN ANALYZE's thread-local
    # tracer they fill the embedded waterfall (scan_local_rows drives
    # SegmentEvaluator directly, below the engine's instrumented paths)
    for src in plan.sources:
        with span("host_scan"):
            table_rows[src.alias] = scan_local_rows(
                engine, src.table, plan.pushdown.get(src.alias),
                need[src.alias], stats)
    # plan-advisor hookup (ISSUE 17): the device executor's advisor (one
    # per process) also memoizes multi-stage templates — join-strategy
    # advice from measured build rows. SET useAdvisor=false bypasses.
    advisor = getattr(engine.device, "advisor", None) \
        if engine.device is not None else None
    adv_key = None
    if advisor is not None:
        from pinot_tpu.engine.advisor import advisor_enabled

        try:
            opts = plan.stage2.options_ci()
        except Exception:  # noqa: BLE001 — advice is optional
            opts = {}
        if advisor_enabled(opts):
            from pinot_tpu.broker.querylog import template_key

            adv_key = template_key(plan)
    with span("stage2"):
        result, meta = run_plan(plan, table_rows, device=engine.device,
                                advisor=advisor, advisor_key=adv_key)
    meta["leafRows"] = {
        alias: (len(next(iter(cols.values()))) if cols else 0)
        for alias, cols in table_rows.items()
    }
    return result, stats, meta


def run_exchange_stage(engine, plan: MultiStagePlan, spec: dict, mailbox,
                       send, done, deadline, device=None):
    """One worker's slice of DISTRIBUTED stage 2 (the mailbox-exchange
    tentpole, ISSUE 16): scan the locally routed stage-1 segments, hash-
    partition every row set by join key (``exchange.stable_hash64`` —
    data-independent, so all workers agree without coordination), hand
    each partition to ``send`` (the server routes it to its owner: a
    self-offer or an ExchangeTransfer RPC), then join + partially
    aggregate every OWNED partition locally and merge those partials
    into the one IntermediateResult the broker's final merge consumes —
    stage 2 runs on the fleet, the broker only merges, exactly like
    stage 1.

    ``spec``: {"partitions": P, "partitionOwners": {str(p): instance},
    "senders": [instances], "selfId": str, "routing": {alias: {"table",
    "segments", optional "dtypes"}}}. The broker gates this path to
    single-join, window-free plans.
    """
    from pinot_tpu.common.trace import span
    from pinot_tpu.ops import join as join_ops
    from pinot_tpu.query2 import exchange

    if len(plan.joins) != 1 or plan.windows:
        raise SqlAnalysisError(
            "distributed exchange supports exactly one join and no "
            "window functions")
    step = plan.joins[0]
    probe_alias = plan.probe.alias
    build_alias = step.build.alias
    P = int(spec["partitions"])
    owners = {int(p): o for p, o in spec["partitionOwners"].items()}
    self_id = spec["selfId"]
    mesh = getattr(device, "mesh", None) if device is not None else None

    stats = ExecutionStats()
    need = needed_columns(plan)
    key_exprs = {probe_alias: step.left_keys, build_alias: step.right_keys}

    # ---- stage 1 + scatter: scan routed segments, partition, ship ----
    for src in plan.sources:
        route = spec["routing"].get(src.alias) or {}
        segs = route.get("segments")
        with span(f"exchange_scan:{src.alias}"):
            if segs:
                cols = scan_local_rows(
                    engine, src.table, plan.pushdown.get(src.alias),
                    need[src.alias], stats, segments=segs)
            else:
                cols = {c: np.empty(0) for c in need[src.alias]}
        # empty scans surface float64-empty arrays; the broker ships the
        # schema dtypes so a worker with zero routed rows still sends
        # correctly-typed (empty) payloads — the empty-leaf dtype guard
        dtypes = route.get("dtypes") or {}
        cols = {c: (v.astype(dtypes[c]) if len(v) == 0 and c in dtypes
                    else v) for c, v in cols.items()}
        n_rows = len(next(iter(cols.values()))) if cols else 0
        stats.leaf_rows[src.alias] = \
            stats.leaf_rows.get(src.alias, 0) + n_rows
        prefixed = {f"{src.alias}.{c}": v for c, v in cols.items()}
        key_vals = [_eval_rows(prefixed, k, None, n_rows)
                    for k in key_exprs[src.alias]]
        part = exchange.stable_hash64(key_vals, n_rows) % P
        deadline.check("exchange.partition")
        with span(f"exchange_send:{src.alias}"):
            for p, rows in enumerate(
                    join_ops.hash_partition_rows(part, P)):
                # EVERY partition ships, empty included: the owner's
                # gather then always sees dtyped arrays for both sides
                send(owners[p], src.alias, p,
                     {c: np.asarray(v)[rows] for c, v in cols.items()},
                     len(rows))
    done()

    # ---- barrier: all senders done, all announced payloads arrived ----
    with span("exchange_barrier"):
        mailbox.wait_ready(spec["senders"], deadline)

    # ---- stage 2 per owned partition: build+probe join + partials ----
    owned = sorted(p for p, o in owners.items() if o == self_id)
    partials = []
    total_joined = 0
    for p in owned:
        deadline.check("exchange.stage2")
        probe_cols, n_probe = mailbox.gather(probe_alias, p)
        build_cols, n_build = mailbox.gather(build_alias, p)
        if not probe_cols:
            probe_cols = {c: np.empty(0) for c in need[probe_alias]}
        if not build_cols:
            build_cols = {c: np.empty(0) for c in need[build_alias]}
        left = {f"{probe_alias}.{c}": np.asarray(v)
                for c, v in probe_cols.items()}
        build = {f"{build_alias}.{c}": np.asarray(v)
                 for c, v in build_cols.items()}
        with span(f"exchange_join:p{p}"):
            joined, n_j = execute_join_step(
                left, n_probe, step, build, device, mesh, "SHUFFLE")
        if plan.post_filter is not None and n_j:
            m = _expr_mask(joined, plan.post_filter, None, n_j)
            joined = {k: v[m] for k, v in joined.items()}
            n_j = int(m.sum())
        total_joined += n_j
        with span(f"exchange_stage2:p{p}"):
            partials.append(stage2_partial(plan, joined, n_j, {}, device))
    if partials:
        merged = merge_intermediates(plan.stage2, partials)
    else:
        # belt-and-braces: a worker that owns no partition still returns
        # a well-typed empty partial over the canonical joined namespace
        empty = {f"{a}.{c}": np.empty(0)
                 for a, cs in need.items() for c in cs}
        merged = stage2_partial(plan, empty, 0, {}, device)
    stats.stage2_rows = total_joined
    merged.stats = stats
    return merged


def execute_multistage(engine, stmt, t0: Optional[float] = None) -> dict:
    """Full embedded path: parsed multi-stage statement → broker-style
    response dict (the engine.execute integration point)."""
    t0 = time.time() if t0 is None else t0

    def catalog(table: str):
        tdm = _tdm_for(engine, table)
        segs = tdm.acquire()
        try:
            if not segs:
                raise ValueError(f"table {table!r} has no segments")
            cols = tuple(segs[0].column_names())
        finally:
            tdm.release(segs)
        return cols, bool(getattr(tdm, "is_dim_table", False))

    plan = compile_plan(stmt, catalog)
    analyze = plan.explain and getattr(plan, "analyze", False)
    if plan.explain and not analyze:
        from pinot_tpu.engine.explain import explain_multistage

        return explain_multistage(engine, plan)
    tracer = None
    if analyze:
        # EXPLAIN ANALYZE needs the phase waterfall: install a
        # thread-local tracer so the leaf scans' span() sites (same
        # thread on the embedded path) fill the ladder — matching the
        # broker EA paths, which force SET trace = true
        from pinot_tpu.common import trace as _trace

        tracer = _trace.start_trace("analyze")
    try:
        result, stats, meta = run_local(engine, plan)
    finally:
        if tracer is not None:
            from pinot_tpu.common import trace as _trace

            _trace.end_trace()
    resp = result.to_json()
    resp.update({
        "exceptions": [],
        "numDocsScanned": stats.num_docs_scanned,
        "numEntriesScannedInFilter": stats.num_entries_scanned_in_filter,
        "numEntriesScannedPostFilter": stats.num_entries_scanned_post_filter,
        "numSegmentsQueried": stats.num_segments_queried,
        "numSegmentsProcessed": stats.num_segments_processed,
        "numSegmentsMatched": stats.num_segments_matched,
        "numSegmentsPrunedByServer": stats.num_segments_pruned,
        "numBlocksPruned": stats.num_blocks_pruned,
        "numSegmentsCold": stats.num_segments_cold,
        # cold leaves answered honestly-partial rows: the joined result
        # is partial too (matches the broker multistage path)
        "partialResult": stats.num_segments_cold > 0,
        "numGroupsLimitReached": stats.num_groups_limit_reached,
        "totalDocs": stats.total_docs,
        "numStages": meta["numStages"],
        "numJoinedRows": meta["numJoinedRows"],
        "leafRows": meta.get("leafRows") or {},
        "timeUsedMs": round((time.time() - t0) * 1000, 3),
    })
    if meta.get("roofline"):
        resp["roofline"] = meta["roofline"]
    if meta["joinStrategy"]:
        resp["joinStrategy"] = meta["joinStrategy"]
    # plan-advisor stamps (ISSUE 17): stage-2 strategy overrides from
    # the plan runner + leaf-scan overrides the stats carried up
    adv_lines = list(meta.get("advisorDecisions") or [])
    for line in (stats.advisor_decisions or []):
        if line not in adv_lines:
            adv_lines.append(line)
    if adv_lines:
        resp["advisorDecisions"] = adv_lines
    if analyze:
        # EXPLAIN ANALYZE (ISSUE 11): the plan ran for real above —
        # annotate the static tree with its actuals; the executed
        # response rides as analyzedResponse (bit-identical contract)
        from pinot_tpu.engine.explain import (
            annotate_analyze,
            explain_multistage,
        )

        if tracer is not None and tracer.spans:
            resp["traceInfo"] = {"server": tracer.to_json()}
        out = annotate_analyze(explain_multistage(engine, plan), resp)
        out["analyzedResponse"] = resp
        return out
    return resp
