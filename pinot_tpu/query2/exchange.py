"""Distributed stage-2 exchange: partition hashing, wire codec, mailbox.

The reference snapshot has NO multi-stage runtime (no
``pinot-query-runtime``; PAPER.md) — modern Pinot's equivalent is the
mailbox service (``GrpcMailboxServer`` / ``MailboxSendOperator`` /
``MailboxReceiveOperator``) that ships shuffled blocks between stage
workers. This module is our leapfrog of that machinery, shaped for the
existing transport: each participating server radix-partitions its
stage-1 rows by join-key hash, ships every partition to its owner over
the gRPC wire (``transport/grpc_transport.py`` ExchangeTransfer), and
the owner's ``ExchangeBuffer`` — the mailbox — buffers payloads until
the barrier releases the per-partition build+probe join.

Design points:

- **numpy-pure.** The broker imports ``query2/`` and must stay jax-free
  (jax-free broker is a repo invariant); everything here is numpy +
  stdlib. Device work stays in ``ops/join.py`` / ``engine/device.py``.
- **Data-independent hashing.** Broker-local SHUFFLE partitions by
  factorized key codes — codes are DATA-dependent, so two servers would
  disagree on them. ``stable_hash64`` hashes raw key VALUES with a
  fixed splitmix64 mix so every sender routes the same key to the same
  owner without coordination. Numerics canonicalize through float64
  (matching ``np.concatenate``'s dtype unification in the runner's
  factorizer, so cross-dtype equi-keys land together); collisions are
  harmless — the owner re-factorizes per partition.
- **Empty partitions ship too.** A zero-row payload still carries dtyped
  arrays, so the receiver's gather never has to invent a schema for an
  empty side (the empty-leaf dtype bug class).
- **Bounded memory.** Payloads past ``spill_limit_bytes`` spill column
  arrays to ``.npy`` files under the warm tier's spill dir and gather
  back via ``np.load(mmap_mode="r")`` — an oversized build side degrades
  to mmap'd files (PR-12's tier idea) instead of OOM. ``offer`` returns
  a ``softLimit`` flag the sender uses as backpressure.
"""

from __future__ import annotations

import io
import json
import os
import struct
import threading
import time
import zlib

import numpy as np

from pinot_tpu.common.deadline import Deadline

__all__ = [
    "stable_hash64", "encode_transfer", "decode_transfer",
    "ExchangeTransferError", "ExchangeBuffer", "ExchangeRegistry",
]


class ExchangeTransferError(RuntimeError):
    """A partition transfer to a peer failed. ``peer`` names the
    instance so the broker's retry can exclude it from the next
    attempt's worker set (failure attribution, PR-6 style)."""

    def __init__(self, peer: str, message: str):
        super().__init__(message)
        self.peer = peer


# ---------------------------------------------------------------------------
# partition hashing
# ---------------------------------------------------------------------------

_FNV_PRIME = np.uint64(0x100000001B3)
_FNV_OFFSET = np.uint64(0xCBF29CE484222325)


def _splitmix64(v: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer: uint64 → well-mixed uint64."""
    with np.errstate(over="ignore"):
        v = v ^ (v >> np.uint64(30))
        v = v * np.uint64(0xBF58476D1CE4E5B9)
        v = v ^ (v >> np.uint64(27))
        v = v * np.uint64(0x94D049BB133111EB)
        v = v ^ (v >> np.uint64(31))
    return v


def stable_hash64(columns, n: int) -> np.ndarray:
    """Data-independent per-row hash of one or more key columns →
    non-negative (n,) int64. Every sender computes identical hashes for
    equal key values regardless of which rows it holds, so
    ``hash % n_partitions`` is a coordination-free routing function.

    Numeric columns canonicalize through float64 (−0.0 folded into
    +0.0) before hashing — the same unification ``np.concatenate``
    applies when the runner factorizes mixed-dtype equi-keys, so an
    int32 key equals its float64 twin here exactly when the join's
    comparator says they are equal. Strings hash per-value via crc32."""
    h = np.full(max(n, 0), _FNV_OFFSET, dtype=np.uint64)
    for col in columns:
        col = np.asarray(col)
        if col.dtype.kind in ("U", "S", "O"):
            vals = np.fromiter(
                (zlib.crc32(str(v).encode("utf-8")) for v in col),
                dtype=np.uint64, count=len(col))
        else:
            canon = col.astype(np.float64)
            # -0.0 == 0.0 must hash equal
            canon = np.where(canon == 0.0, 0.0, canon)
            vals = canon.view(np.uint64)
        with np.errstate(over="ignore"):
            h = h * _FNV_PRIME + _splitmix64(vals)
    return (_splitmix64(h) >> np.uint64(1)).astype(np.int64)


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------

_MAGIC = b"PXP1"


def _wire_array(col: np.ndarray) -> np.ndarray:
    """Object-dtype columns (python strings) can't ride npz without
    pickle; normalize to a fixed-width unicode array."""
    col = np.asarray(col)
    if col.dtype.kind == "O":
        return col.astype(str) if len(col) else col.astype("U1")
    return col


def encode_transfer(exchange_id: str, sender: str, alias: str,
                    partition: int, cols: dict, n: int, *,
                    done: bool = False, expected=None) -> bytes:
    """One exchange payload: magic + 4-byte header length + JSON header
    + npz column payload. ``done=True`` marks the sender's LAST message
    to this receiver; ``expected`` then carries
    ``{alias: {partition: payload_count}}`` so the receiver's barrier
    knows exactly how many payloads to wait for (unary RPCs from one
    sender thread are ordered, so done-last is a valid completeness
    marker)."""
    names = list(cols)
    header = {
        "id": exchange_id, "sender": sender, "alias": alias,
        "partition": int(partition), "n": int(n), "names": names,
        "done": bool(done), "expected": expected,
    }
    buf = io.BytesIO()
    np.savez(buf, **{f"c{i}": _wire_array(cols[name])
                     for i, name in enumerate(names)})
    hb = json.dumps(header).encode("utf-8")
    return _MAGIC + struct.pack("<I", len(hb)) + hb + buf.getvalue()


def decode_transfer(payload: bytes) -> dict:
    """Inverse of ``encode_transfer``: header dict with ``cols`` mapped
    back to {name: ndarray}."""
    if payload[:4] != _MAGIC:
        raise ValueError("bad exchange payload magic")
    (hlen,) = struct.unpack("<I", payload[4:8])
    header = json.loads(payload[8: 8 + hlen].decode("utf-8"))
    with np.load(io.BytesIO(payload[8 + hlen:])) as z:
        header["cols"] = {name: z[f"c{i}"]
                          for i, name in enumerate(header["names"])}
    return header


# ---------------------------------------------------------------------------
# mailbox buffer
# ---------------------------------------------------------------------------


class ExchangeBuffer:
    """One receiving server's mailbox for one exchange: accepts offered
    partition payloads (in memory, or spilled to ``.npy`` past the byte
    limit), tracks per-sender done markers, and releases ``gather`` once
    the barrier — every sender done AND every announced payload arrived
    — is met."""

    def __init__(self, exchange_id: str, spill_dir: str,
                 spill_limit_bytes: int):
        self.exchange_id = exchange_id
        self.spill_dir = spill_dir
        self.spill_limit_bytes = int(spill_limit_bytes)
        self.created_at = time.monotonic()
        self.buffered_bytes = 0
        self.spill_count = 0
        self.spilled_bytes = 0
        self._cv = threading.Condition()
        self._seq = 0
        # (alias, partition) -> [(sender, seq, kind, payload...)]
        self._slots: dict = {}
        # sender -> expected {alias: {str(partition): count}}
        self._done: dict = {}
        # (sender, alias, partition) -> payloads received
        self._counts: dict = {}
        self._spill_files: list = []
        self._closed = False

    # ---- sender side -----------------------------------------------------
    def offer(self, sender: str, alias: str, partition: int,
              cols: dict, n: int) -> dict:
        """Accept one payload. Returns backpressure/accounting flags:
        ``spilled`` when this payload went to disk, ``softLimit`` when
        the in-memory pool is running hot (sender should pace itself)."""
        norm = {}
        nbytes = 0
        for name, col in cols.items():
            col = _wire_array(col)
            norm[name] = col
            nbytes += int(col.nbytes)
        with self._cv:
            if self._closed:
                raise ExchangeTransferError(
                    "", f"exchange {self.exchange_id} already closed")
            seq = self._seq
            self._seq += 1
            spilled = (nbytes > 0 and
                       self.buffered_bytes + nbytes > self.spill_limit_bytes)
            if spilled:
                entry = ("spill", self._spill(sender, alias, partition,
                                              seq, norm), int(n))
                self.spill_count += 1
                self.spilled_bytes += nbytes
            else:
                entry = ("mem", norm, int(n))
                self.buffered_bytes += nbytes
            key = (alias, int(partition))
            self._slots.setdefault(key, []).append((sender, seq) + entry)
            ck = (sender, alias, int(partition))
            self._counts[ck] = self._counts.get(ck, 0) + 1
            soft = self.buffered_bytes >= 0.75 * self.spill_limit_bytes
            self._cv.notify_all()
        return {"ok": True, "spilled": spilled, "softLimit": soft}

    def _spill(self, sender, alias, partition, seq, cols) -> list:
        os.makedirs(self.spill_dir, exist_ok=True)
        paths = []
        for i, (name, col) in enumerate(cols.items()):
            path = os.path.join(
                self.spill_dir,
                f"{self.exchange_id}_{sender}_{alias}_{partition}"
                f"_{seq}_{i}.npy")
            np.save(path, col)
            paths.append((name, path))
            self._spill_files.append(path)
        return paths

    def mark_done(self, sender: str, expected: dict) -> None:
        with self._cv:
            self._done[sender] = expected or {}
            self._cv.notify_all()

    # ---- receiver side ---------------------------------------------------
    def _barrier_met(self, senders) -> bool:
        for s in senders:
            if s not in self._done:
                return False
            for alias, parts in self._done[s].items():
                for part, count in parts.items():
                    if self._counts.get((s, alias, int(part)), 0) < count:
                        return False
        return True

    def wait_ready(self, senders, deadline: Deadline) -> None:
        """Block until every sender's done marker and all announced
        payloads have arrived; raises QueryTimeout past the deadline so
        a lost sender can never hang the stage."""
        senders = list(senders)
        with self._cv:
            while not self._barrier_met(senders):
                deadline.check("exchange.barrier")
                self._cv.wait(timeout=min(0.05, deadline.remaining_s()))

    def gather(self, alias: str, partition: int):
        """Deterministic concatenation of every payload for one
        (alias, partition): ordered by (sender, seq) so merges are
        reproducible run-to-run. Spilled columns come back mmap'd.
        Returns (cols, n); ({}, 0) when nothing arrived (e.g. a
        partition whose every sender held zero rows AND sent nothing —
        normal senders always send, so this is belt-and-braces)."""
        with self._cv:
            entries = sorted(self._slots.get((alias, int(partition)), []),
                             key=lambda e: (e[0], e[1]))
        if not entries:
            return {}, 0
        chunks = []  # list of (cols, n)
        for sender, seq, kind, payload, n in entries:
            if kind == "mem":
                chunks.append((payload, n))
            else:
                chunks.append(({name: np.load(path, mmap_mode="r")
                                for name, path in payload}, n))
        names = list(chunks[0][0])
        total = sum(c[1] for c in chunks)
        cols = {name: np.concatenate([np.asarray(c[0][name])
                                      for c in chunks])
                for name in names}
        return cols, total

    def close(self) -> None:
        with self._cv:
            self._closed = True
            files, self._spill_files = self._spill_files, []
            self._slots.clear()
            self.buffered_bytes = 0
        for path in files:
            try:
                os.remove(path)
            except OSError:
                pass


class ExchangeRegistry:
    """Per-server map of live exchanges. ``get_or_create`` races safely
    (transfers can land before the owning ExecuteStage request does);
    an age sweep reaps mailboxes orphaned by a sender that died after
    its first transfer."""

    SWEEP_AGE_S = 600.0

    def __init__(self, spill_dir: str, spill_limit_bytes: int):
        self.spill_dir = spill_dir
        self.spill_limit_bytes = int(spill_limit_bytes)
        self._lock = threading.Lock()
        self._exchanges: dict = {}

    def get_or_create(self, exchange_id: str) -> ExchangeBuffer:
        now = time.monotonic()
        with self._lock:
            for xid in [x for x, b in self._exchanges.items()
                        if now - b.created_at > self.SWEEP_AGE_S]:
                self._exchanges.pop(xid).close()
            buf = self._exchanges.get(exchange_id)
            if buf is None:
                buf = ExchangeBuffer(exchange_id, self.spill_dir,
                                     self.spill_limit_bytes)
                self._exchanges[exchange_id] = buf
            return buf

    def release(self, exchange_id: str) -> None:
        with self._lock:
            buf = self._exchanges.pop(exchange_id, None)
        if buf is not None:
            buf.close()

    def close(self) -> None:
        with self._lock:
            bufs = list(self._exchanges.values())
            self._exchanges.clear()
        for buf in bufs:
            buf.close()
