"""Broker result cache: serve repeat dashboard queries without a scatter.

ISSUE 10's third leg. Entries are keyed by ``(table, literal-free
template key, literal digest)`` — PR 4 made template keys literal-free,
so the template key attributes entries per dashboard panel while the
literal digest (a blake2b over the compiled QueryContext, literals
included) pins the exact query. Freshness is validated at GET time, not
TTL-guessed, against two tokens recorded when the entry was filled:

- the registry's ROUTING GENERATION (cluster/registry.py) — any segment
  add/remove/move, lineage flip, or replica-group change bumps it, so a
  cached answer computed over a different segment set never serves;
- the per-table EPOCH VIEW ``{instance: epoch}`` (common/freshness.py) —
  servers bump their table epoch on every in-place mutation (consuming
  appends, chunklet promotion, upsert invalidation, seal) and report it
  piggybacked in every DataTable partial plus the sync heartbeat; any
  drift between the recorded and current view invalidates the entry.

The reference has no broker result cache (its star-tree and segment
caches live server-side) — this is a leapfrog the literal-free template
keys and the PR-9 invalidation seams made nearly free.

LRU bounded by entries AND bytes (``pinot.broker.resultcache.max.entries``
/ ``.max.bytes``); per-query opt-out via ``SET useResultCache = false``.
Off by default (``pinot.broker.resultcache.enabled``): partial-result and
chaos semantics (deliberately repeated queries against faulted replicas)
must stay exact unless an operator opts the broker in.
"""

from __future__ import annotations

import collections
import copy
import hashlib
import json
import threading
import time


class BrokerResultCache:
    def __init__(self, max_entries: int = 512, max_bytes: int = 32 << 20,
                 stale_retention_s: float = 30.0):
        self.max_entries = max(1, int(max_entries))
        self.max_bytes = max(1, int(max_bytes))
        # how long a FRESHNESS-stale entry is kept for bounded-staleness
        # load shedding (ISSUE 14): get() used to drop stale entries on
        # sight, which would leave the shed path nothing to degrade to —
        # now a stale entry lingers this long for get_stale() before the
        # fresh path's drop-on-sight applies (0 restores the old drop)
        self.stale_retention_s = float(stale_retention_s)
        self._lock = threading.Lock()
        # key -> {resp, nbytes, epoch_view, routing_gen, ts}
        self._entries: "collections.OrderedDict" = collections.OrderedDict()
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        self.stale_hits = 0

    # ---- keying ----------------------------------------------------------
    # SET options that change WHO asks / HOW the broker admits, never
    # WHAT the rows are (ISSUE 14): stripped from the digest so tenant
    # admission's queue-jump memo and the bounded-staleness shed path
    # match the entry the same panel filled without them
    _NON_SEMANTIC_OPTIONS = frozenset(
        ("workloadname", "priorityclass", "maxstalenessms"))

    @classmethod
    def key_for(cls, q, template: str) -> tuple:
        """(table, template key, literal digest). The digest covers the
        WHOLE compiled context repr — filter literals, select/order
        shapes, limit/offset, and SET options (minus the admission-only
        options above) — so two queries share an entry only when a
        broker would answer them identically."""
        import dataclasses

        opts = tuple((k, v) for k, v in q.options
                     if str(k).lower() not in cls._NON_SEMANTIC_OPTIONS)
        canon = dataclasses.replace(q, explain=False, options=opts)
        digest = hashlib.blake2b(
            repr(canon).encode("utf-8"), digest_size=16).hexdigest()
        return (q.table_name, template, digest)

    # ---- lookup / fill ---------------------------------------------------
    def _fresh(self, ent: dict, epoch_view: dict, routing_gen: int) -> bool:
        return (ent["routing_gen"] == routing_gen
                and ent["epoch_view"] == epoch_view)

    def get(self, key: tuple, epoch_view: dict, routing_gen: int):
        """The cached response dict, or None. A stale entry (routing or
        epoch drift) is never served FRESH; it lingers for
        ``stale_retention_s`` AFTER FIRST BEING OBSERVED STALE (so the
        shed path's bounded-staleness ``get_stale`` has something to
        serve — an entry that was fresh for minutes before an epoch bump
        still earns its full linger window) and is dropped past that."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return None
            if not self._fresh(ent, epoch_view, routing_gen):
                now = time.time()
                stale_since = ent.setdefault("stale_since", now)
                if now - stale_since > self.stale_retention_s:
                    self._drop(key)
                self.invalidations += 1
                self.misses += 1
                return None
            # fresh again (e.g. the recorded epoch view re-validated):
            # the entry is not on the stale clock anymore
            ent.pop("stale_since", None)
            self._entries.move_to_end(key)
            self.hits += 1
            # deep copy both ways (here and in put): callers that post-
            # process a response in place (sorting rows, appending a
            # footer) must not poison the stored entry for later hits
            return copy.deepcopy(ent["resp"])

    def get_stale(self, key: tuple, max_age_s: float):
        """Bounded-staleness read for the load-shedding degradation path
        (ISSUE 14): ``(response copy, age_s)`` when an entry exists no
        older than ``max_age_s`` — REGARDLESS of epoch/routing freshness
        (that's the contract: the client opted into ``maxStalenessMs``-
        bounded data rather than a 429) — else ``(None, None)``. No LRU
        touch: a shed query must not keep pinning the stale entry past
        entries that still validate fresh."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return None, None
            age_s = max(0.0, time.time() - ent["ts"])
            if age_s > max(0.0, max_age_s):
                return None, None
            self.stale_hits += 1
            return copy.deepcopy(ent["resp"]), age_s

    def peek_fresh(self, key: tuple, epoch_view: dict,
                   routing_gen: int) -> bool:
        """EXPLAIN's view (CACHED_RESULT line): would this query serve
        from cache right now? No LRU touch, no counters."""
        with self._lock:
            ent = self._entries.get(key)
            return ent is not None and \
                self._fresh(ent, epoch_view, routing_gen)

    def put(self, key: tuple, resp: dict, epoch_view: dict,
            routing_gen: int) -> None:
        try:
            nbytes = len(json.dumps(resp, default=str))
        except (TypeError, ValueError):
            return  # unserializable response: not worth caching
        if nbytes > self.max_bytes:
            return  # one giant selection must not wipe the whole cache
        with self._lock:
            if key in self._entries:
                self._drop(key)
            self._entries[key] = {
                "resp": copy.deepcopy(resp), "nbytes": nbytes,
                "epoch_view": dict(epoch_view), "routing_gen": routing_gen,
                "ts": time.time(),
            }
            self.bytes += nbytes
            while (len(self._entries) > self.max_entries
                   or self.bytes > self.max_bytes):
                old_key = next(iter(self._entries))
                self._drop(old_key)
                self.evictions += 1

    def _drop(self, key: tuple) -> None:
        ent = self._entries.pop(key, None)
        if ent is not None:
            self.bytes -= ent["nbytes"]

    # ---- maintenance -----------------------------------------------------
    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.bytes = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries), "bytes": self.bytes,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "stale_hits": self.stale_hits,
            }
