"""Structured broker query log: rotating JSONL + in-memory ring buffer.

The third leg of the query-path flight recorder (ISSUE 7): every query
the broker decides is WORTH KEEPING — slow past a threshold, errored,
timed out, partial, or sampled — is appended as one JSON line carrying
the merged trace (broker + per-instance server spans), the
retry/hedge/pruning counters, and a literal-independent template key, so
an operator can answer "where did THAT query's 120 ms go" days later.
The reference ships this as the broker's query log
(BaseBrokerRequestHandler's ``QueryLogger`` with its ``maxRatePerSecond``
/ dropped-count semantics); ours trades the rate limiter for a
threshold + sample-rate pair plus always-on capture of anything
abnormal.

Config (common/config.py Configuration keys):

- ``pinot.broker.querylog.path``            — JSONL file; unset = ring only
- ``pinot.broker.querylog.slow.threshold.ms`` (default 500.0)
- ``pinot.broker.querylog.sample.rate``     — 0..1 of HEALTHY fast queries
  to keep anyway (default 0.0)
- ``pinot.broker.querylog.max.bytes``       — rotation size (default 16 MB;
  one rotated generation, ``<path>.1``)
- ``pinot.broker.querylog.ring.size``       — /debug/queries depth (128)

The ring buffer backs the broker's ``GET /debug/queries`` endpoint — the
last N kept entries, newest first, no file required.
"""

from __future__ import annotations

import collections
import json
import os
import random
import threading
import time
from typing import Optional


def template_key(q) -> str:
    """Literal-independent shape key for a compiled QueryContext — the
    same normalization that keeps device template/cohort keys stable
    under changing filter literals (PR 4): table + result shape + agg
    names + group-by columns + filter STRUCTURE (ops and columns, no
    values). Two dashboard queries differing only in literals share a
    key, so the summarizer can aggregate latency per template.

    Multi-stage plans (query2/ joins + windows) key on the stage-2 shape
    PLUS the join chain (kind, strategy, build alias, key columns — no
    literals) and the window function/partition signature, so two-stage
    dashboard queries group per template exactly like single-stage ones."""
    try:
        if hasattr(q, "stage2") and hasattr(q, "joins"):
            inner = template_key(q.stage2)
            joins = ";".join(
                f"{j.kind}:{q.strategy}:{j.build.alias}"
                f"({','.join(str(k) for k in j.left_keys)})"
                for j in q.joins)
            wins = ",".join(
                f"{w.fn}[{','.join(str(p) for p in w.partition_by)}]"
                for w in q.windows)
            parts = [inner]
            if joins:
                parts.append(f"joins[{joins}]")
            if wins:
                parts.append(f"windows[{wins}]")
            return "|".join(parts)
        aggs = ",".join(a.name for a in q.aggregations())
        group = ",".join(g.name if g.is_identifier else "expr"
                         for g in (q.group_by or ()))
        shape = ("distinct" if q.distinct
                 else "group_by" if q.group_by
                 else "aggregation" if q.aggregations()
                 else "selection")

        def _filter_sig(f) -> str:
            if f is None:
                return ""
            from pinot_tpu.query.context import FilterNodeType

            if f.type is FilterNodeType.PREDICATE:
                p = f.predicate
                col = p.lhs.name if p.lhs.is_identifier else "expr"
                return f"{p.type.name}({col})"
            kids = ",".join(_filter_sig(c) for c in (f.children or ()))
            return f"{f.type.name}[{kids}]"

        return f"{q.table_name}|{shape}|{aggs}|{group}|{_filter_sig(q.filter)}"
    except Exception:  # noqa: BLE001 — a log key must never fail a query
        return "unknown"


class QueryLogger:
    def __init__(self, path: Optional[str] = None,
                 slow_threshold_ms: float = 500.0,
                 sample_rate: float = 0.0,
                 max_bytes: int = 16 << 20,
                 ring_size: int = 128,
                 broker_id: Optional[str] = None):
        self.path = path
        # fleet attribution (ISSUE 18): when set, every kept entry stamps
        # which broker wrote it, so tools/querylog.py can merge JSONL
        # files from N brokers and still break stats down per broker
        self.broker_id = broker_id
        self.slow_threshold_ms = float(slow_threshold_ms)
        self.sample_rate = float(sample_rate)
        self.max_bytes = int(max_bytes)
        self.ring = collections.deque(maxlen=max(1, int(ring_size)))
        self._lock = threading.Lock()
        self.dropped = 0  # entries that failed to write (disk trouble)

    @classmethod
    def from_config(cls, conf=None) -> "QueryLogger":
        if conf is None:
            from pinot_tpu.common.config import Configuration

            conf = Configuration()
        return cls(
            path=conf.get("pinot.broker.querylog.path", None),
            slow_threshold_ms=conf.get_float(
                "pinot.broker.querylog.slow.threshold.ms", 500.0),
            sample_rate=conf.get_float(
                "pinot.broker.querylog.sample.rate", 0.0),
            max_bytes=int(conf.get_float(
                "pinot.broker.querylog.max.bytes", float(16 << 20))),
            ring_size=int(conf.get_float(
                "pinot.broker.querylog.ring.size", 128)),
        )

    # ---- capture policy --------------------------------------------------
    def should_log(self, time_used_ms: float, abnormal: bool) -> bool:
        """Timeouts/errors/partials ALWAYS log; healthy queries log past
        the slow threshold or with sample_rate probability."""
        if abnormal:
            return True
        if time_used_ms >= self.slow_threshold_ms:
            return True
        return self.sample_rate > 0 and random.random() < self.sample_rate

    def record(self, sql: str, resp: dict, time_used_ms: float,
               table: Optional[str] = None,
               template=None,
               extra: Optional[dict] = None) -> Optional[dict]:
        """Build + (maybe) keep one entry from a finished broker response.
        Returns the entry when it was kept, None when policy dropped it.
        ``template`` may be a zero-arg callable — resolved only AFTER the
        keep decision, so the default-policy hot path (healthy fast
        queries, dropped) never pays the template-key tree walk."""
        excs = resp.get("exceptions") or []
        # shed/degraded responses are always-log abnormal (ISSUE 14):
        # the typed sheddingReason contract includes the query log
        abnormal = bool(excs) or bool(resp.get("partialResult")) \
            or bool(resp.get("sheddingReason"))
        if not self.should_log(time_used_ms, abnormal):
            return None
        if callable(template):
            template = template()
        entry = {
            "ts": round(time.time(), 3),
            "brokerId": self.broker_id or resp.get("brokerId"),
            "requestId": resp.get("requestId"),
            "traceId": resp.get("traceId"),
            "table": table,
            "template": template,
            "sql": sql if len(sql) <= 2000 else sql[:2000] + "...",
            "timeUsedMs": round(float(time_used_ms), 3),
            "partialResult": bool(resp.get("partialResult")),
            "exceptions": excs,
            "counters": {
                k: resp.get(k) for k in (
                    "numServersQueried", "numServersResponded",
                    "numRetries", "numHedges",
                    "numSegmentsPrunedByBroker",
                    "numSegmentsPrunedByServer", "numBlocksPruned",
                    "numDocsScanned", "numGroupsLimitReached",
                    "partialsCacheHit",
                    # cluster-tier attribution (ISSUE 10): which replica
                    # group took the query at what load score, and whether
                    # the broker result cache answered without a scatter
                    "numReplicaGroupsQueried", "replicaGroup",
                    "loadScore", "resultCacheHit",
                    # multi-tenant admission (ISSUE 14): who asked, at
                    # what priority, and whether the overload loop shed
                    # or degraded the query (typed, never silent)
                    "tenant", "priorityClass", "sheddingReason",
                    "servedStale", "staleAgeMs",
                    # kernel roofline accounting (ISSUE 11): HBM bytes
                    # the device pipelines moved vs their kernel wall
                    "deviceBytesMoved", "deviceKernelMs", "deviceLinkMs",
                    # distributed stage-2 exchange (ISSUE 16): effective
                    # strategy (demotion included — the plan is mutated
                    # before logging), partition fan-out, wire volume,
                    # warm-tier spills
                    "joinStrategy", "joinStrategyDemoted", "joinFanout",
                    "numPartitionsShipped", "exchangeBytes",
                    "exchangeSpillCount",
                    # plan advisor (ISSUE 17): the measurement-driven
                    # overrides this execution ran with — the raw
                    # ADVISOR(...) stamps, aggregated per template by
                    # tools/querylog.py --per-template
                    "advisorDecisions",
                ) if resp.get(k) is not None
            },
        }
        roofline = resp.get("roofline")
        if roofline:
            # per-flight achieved-GB/s records, capped so one scattered
            # query over many servers can't bloat a log line
            entry["roofline"] = list(roofline)[:8]
        trace_info = resp.get("traceInfo")
        if trace_info:
            entry["traceInfo"] = trace_info
        if extra:
            entry.update(extra)
        with self._lock:
            self.ring.appendleft(entry)
        self._write(entry)
        return entry

    # ---- file backend ----------------------------------------------------
    def _write(self, entry: dict) -> None:
        if not self.path:
            return
        line = json.dumps(entry, default=str) + "\n"
        try:
            with self._lock:
                try:
                    if os.path.getsize(self.path) + len(line) > self.max_bytes:
                        # one rotated generation, replace-style (atomic on
                        # POSIX): bounded disk, never a mid-query stall
                        os.replace(self.path, self.path + ".1")
                except OSError:
                    pass  # no file yet
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line)
        except OSError:
            self.dropped += 1

    def recent(self, limit: int = 0) -> list:
        """Newest-first kept entries from the ring (the /debug/queries
        payload)."""
        with self._lock:
            out = list(self.ring)
        return out[:limit] if limit else out
