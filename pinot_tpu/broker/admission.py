"""Per-tenant admission control: priority-classed token buckets + shedding.

ISSUE 14's broker leg. The reference's only overload defenses are the
server-side ``QueryScheduler`` family (FCFS / resource-aware token
buckets) and the per-table QPS quota — neither knows WHO is asking, so a
single spiking tenant starves everyone behind one shared 429 wall. This
module puts a workload-isolation layer in FRONT of the
``QueryQuotaManager``:

- **Tenant resolution**: the authenticated principal (broker HTTP basic
  auth) wins; a query may also self-identify via ``SET workloadName =
  'dashboards'`` (the reference's ``workloadName`` query option); else
  the shared ``default`` bucket.
- **Priority classes**: ``interactive`` > ``dashboard`` > ``adhoc``
  (weights 4/2/1). The query's class (``SET priorityClass``, else the
  tenant's configured default) ships to the servers as the
  weighted-fair slot weight (engine/scheduler.py) and picks the
  load-shed rung; the tenant's CONFIGURED class
  (``pinot.broker.admission.tenant.<name>.priority``) scales its bucket
  refill — a client cannot self-upgrade its own refill budget with a
  per-query SET.
- **Token buckets**: one per tenant, class-scaled rate. A dry bucket
  does NOT immediately 429: the broker first tries a bounded-staleness
  result-cache read (``SET maxStalenessMs`` — broker/broker.py
  ``_shed_response``), and only rejects when no eligible entry exists,
  with ``retryAfterSeconds`` computed from THIS tenant's actual refill
  time (capped at 5 s), never the table-quota's fixed hint.
- **Queue jumping**: literal digests whose last execution was sub-RTT
  (broker result cache or device partials cache hit) are remembered;
  such queries admit at a fraction of a token and ride the
  ``interactive`` weight server-side — repeat dashboard panels never
  wait behind a cold scan's admission debt.
- **Load shedding**: the broker-wide decayed ``LoadTracker`` score
  (max across servers) crossing ``shed_threshold`` sheds ``adhoc``
  first, ``dashboard`` at 1.5x, ``interactive`` only at 2x — graceful
  brownout instead of a cliff.

Config (common/config.py keys, all ``pinot.broker.admission.*``):

    enabled (false), rate.qps (20), burst (40),
    default.priority (dashboard), shed.load.threshold (0 = off),
    tenant.<name>.rate / .burst / .priority

Chaos: the ``scheduler.admit`` fault point (common/faults.py, modes
error|delay) fires inside ``try_admit`` with the tenant as target, so
tests can starve admission deterministically and prove the typed
429/degraded contract.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Optional

from pinot_tpu.common import faults

# one notion of priority end to end: the SAME weights drive the tenant
# bucket's refill scaling here and the server scheduler's weighted-fair
# slot share (single-sourced in engine/scheduler.py)
from pinot_tpu.engine.scheduler import PRIORITY_WEIGHTS

RETRY_AFTER_CAP_S = 5.0

# sub-RTT queries (known cache-hit digests) charge this fraction of a
# token: serving them is two orders of magnitude cheaper than a cold
# scan, and charging full price would let admission starve exactly the
# traffic the caches made nearly free
SUBRTT_COST = 0.1


@dataclasses.dataclass
class AdmissionDecision:
    admitted: bool
    tenant: str
    priority: str
    # typed shed reason carried through responses + the query log
    # (None when admitted): tenant_bucket_dry | load_shed |
    # admission_fault
    reason: Optional[str] = None
    # seconds until this tenant's bucket refills one token (already
    # capped at RETRY_AFTER_CAP_S) — the 429 Retry-After basis
    retry_after_s: float = 0.0
    sub_rtt: bool = False


class _TenantBucket:
    __slots__ = ("tokens", "last", "rate", "burst", "admitted", "shed",
                 "spent")

    def __init__(self, rate: float, burst: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst  # cold tenants start with full burst
        self.last = time.monotonic()
        self.admitted = 0
        self.shed = 0
        # cumulative admitted token cost — the fleet-gossip counter
        # (ISSUE 18): peers read it from broker heartbeats and debit the
        # DELTA from their own bucket so N brokers share one logical
        # per-tenant budget
        self.spent = 0.0

    def refill(self, now: float) -> None:
        dt = now - self.last
        if dt > 0:
            self.tokens = min(self.burst, self.tokens + self.rate * dt)
            self.last = now


class TenantAdmissionController:
    MAX_TENANTS = 1024      # overflow tenants share one bucket
    MAX_SUBRTT_DIGESTS = 512

    def __init__(self, rate_qps: float = 20.0, burst: float = 40.0,
                 default_priority: str = "dashboard",
                 shed_load_threshold: float = 0.0,
                 tenant_overrides: Optional[dict] = None):
        if default_priority not in PRIORITY_WEIGHTS:
            raise ValueError(
                f"unknown priority class {default_priority!r} "
                f"({'|'.join(sorted(PRIORITY_WEIGHTS))})")
        self.rate_qps = float(rate_qps)
        self.burst = float(burst)
        self.default_priority = default_priority
        # broker-wide load score at which shedding begins (0 = load
        # shedding off; bucket admission still applies)
        self.shed_load_threshold = float(shed_load_threshold)
        # {tenant: {"rate": .., "burst": .., "priority": ..}}
        self.tenant_overrides = dict(tenant_overrides or {})
        self._lock = threading.Lock()
        self._buckets: dict[str, _TenantBucket] = {}
        # literal-digest -> last-seen ts for queries whose previous
        # execution was sub-RTT (result-cache or device-partials hit)
        self._subrtt: "collections.OrderedDict" = collections.OrderedDict()
        self.num_admitted = 0
        self.num_shed = 0
        self.num_shed_stale_served = 0  # bumped by the broker's shed path
        # fleet-gossip bookkeeping (ISSUE 18): last-seen cumulative spend
        # per peer broker, {peer_id: {tenant: cum_spend}} — deltas against
        # it are debited locally so the fleet shares one logical budget
        self._peer_spend_seen: dict = {}

    @classmethod
    def from_config(cls, conf) -> "TenantAdmissionController":
        # per-tenant overrides ride explicit config keys; the tenant list
        # itself comes from pinot.broker.admission.tenants (csv) since a
        # flat Configuration cannot enumerate key prefixes
        overrides: dict = {}
        names = str(conf.get("pinot.broker.admission.tenants", "") or "")
        for name in (n.strip() for n in names.split(",")):
            if not name:
                continue
            ent: dict = {}
            rate = conf.get(f"pinot.broker.admission.tenant.{name}.rate")
            if rate is not None:
                ent["rate"] = float(rate)
            burst = conf.get(f"pinot.broker.admission.tenant.{name}.burst")
            if burst is not None:
                ent["burst"] = float(burst)
            prio = conf.get(f"pinot.broker.admission.tenant.{name}.priority")
            if prio is not None:
                ent["priority"] = str(prio)
            overrides[name] = ent
        return cls(
            rate_qps=conf.get_float("pinot.broker.admission.rate.qps", 20.0),
            burst=conf.get_float("pinot.broker.admission.burst", 40.0),
            default_priority=str(conf.get(
                "pinot.broker.admission.default.priority", "dashboard")),
            shed_load_threshold=conf.get_float(
                "pinot.broker.admission.shed.load.threshold", 0.0),
            tenant_overrides=overrides,
        )

    # ---- tenant / priority resolution ------------------------------------
    def resolve(self, q, principal: Optional[str] = None) -> tuple:
        """(tenant, priority class) for a compiled query: the auth
        principal wins, then ``SET workloadName``, then ``default``;
        ``SET priorityClass`` overrides the tenant's configured default
        class. Unknown class names fall back to the controller default
        rather than erroring — a typo'd dashboard must not break."""
        opts = q.options_ci()
        tenant = principal or None
        if not tenant:
            wl = opts.get("workloadname")
            tenant = str(wl) if wl else "default"
        prio = opts.get("priorityclass")
        if prio is not None and str(prio) in PRIORITY_WEIGHTS:
            return tenant, str(prio)
        cfg = self.tenant_overrides.get(tenant, {})
        prio = cfg.get("priority")
        if prio in PRIORITY_WEIGHTS:
            return tenant, prio
        return tenant, self.default_priority

    def _bucket(self, tenant: str) -> _TenantBucket:
        b = self._buckets.get(tenant)
        if b is None:
            if len(self._buckets) >= self.MAX_TENANTS:
                tenant = "__overflow__"
                b = self._buckets.get(tenant)
                if b is not None:
                    return b
            cfg = self.tenant_overrides.get(tenant, {})
            # the bucket's refill scales by the TENANT'S CONFIGURED
            # class (override, else controller default) — never the
            # requesting query's class: a per-query SET priorityClass
            # must change slot weight and shed rung, not let a client
            # self-upgrade its own refill budget (and the first query's
            # class must not freeze the tenant's rate forever)
            prio = cfg.get("priority")
            if prio not in PRIORITY_WEIGHTS:
                prio = self.default_priority
            weight = PRIORITY_WEIGHTS[prio]
            rate = float(cfg.get("rate", self.rate_qps * weight /
                                 PRIORITY_WEIGHTS[self.default_priority]))
            burst = float(cfg.get("burst", max(1.0, self.burst)))
            b = self._buckets[tenant] = _TenantBucket(rate, burst)
        return b

    # ---- sub-RTT digest memo (queue jumping) -----------------------------
    def note_sub_rtt(self, digest) -> None:
        """Record a literal digest whose execution was sub-RTT (broker
        result-cache or device partials-cache hit): its repeats admit at
        SUBRTT_COST and ride the interactive slot weight server-side."""
        if digest is None:
            return
        with self._lock:
            self._subrtt[digest] = time.monotonic()
            self._subrtt.move_to_end(digest)
            while len(self._subrtt) > self.MAX_SUBRTT_DIGESTS:
                self._subrtt.popitem(last=False)

    def is_sub_rtt(self, digest) -> bool:
        if digest is None:
            return False
        with self._lock:
            return digest in self._subrtt

    # ---- the admission decision ------------------------------------------
    def try_admit(self, tenant: str, priority: str,
                  load_score: Optional[float] = None,
                  sub_rtt: bool = False) -> AdmissionDecision:
        """One non-blocking decision: charge the tenant's bucket, apply
        the load-shed ladder, fire the ``scheduler.admit`` chaos seam.
        Never waits (the broker has no admission queue — degrade-or-429
        IS the backpressure); ``delay``-mode faults sleep here to model a
        slow admission path deterministically."""
        if faults.ACTIVE:
            try:
                faults.inject("scheduler.admit", target=tenant)
            except faults.FaultInjected:
                with self._lock:
                    self.num_shed += 1
                return AdmissionDecision(
                    False, tenant, priority, reason="admission_fault",
                    retry_after_s=min(RETRY_AFTER_CAP_S, 1.0),
                    sub_rtt=sub_rtt)
        weight = PRIORITY_WEIGHTS.get(priority, 1.0)
        # load-shed ladder: adhoc sheds at the threshold, dashboard at
        # 1.5x, interactive at 2x; known-sub-RTT repeats are exempt
        # (they cost no server slot worth protecting)
        if (self.shed_load_threshold > 0 and load_score is not None
                and not sub_rtt):
            bar = self.shed_load_threshold * (
                2.0 if priority == "interactive"
                else 1.5 if priority == "dashboard" else 1.0)
            if load_score >= bar:
                with self._lock:
                    b = self._bucket(tenant)
                    b.shed += 1
                    self.num_shed += 1
                return AdmissionDecision(
                    False, tenant, priority, reason="load_shed",
                    retry_after_s=min(RETRY_AFTER_CAP_S, 1.0),
                    sub_rtt=sub_rtt)
        cost = SUBRTT_COST if sub_rtt else 1.0
        now = time.monotonic()
        with self._lock:
            b = self._bucket(tenant)
            b.refill(now)
            if b.tokens >= cost:
                b.tokens -= cost
                b.admitted += 1
                b.spent += cost
                self.num_admitted += 1
                return AdmissionDecision(True, tenant, priority,
                                         sub_rtt=sub_rtt)
            # dry: Retry-After from THIS bucket's actual refill time —
            # (cost - tokens) / rate seconds until the query could pass
            need = max(0.0, cost - b.tokens)
            retry = need / b.rate if b.rate > 0 else RETRY_AFTER_CAP_S
            b.shed += 1
            self.num_shed += 1
        return AdmissionDecision(
            False, tenant, priority, reason="tenant_bucket_dry",
            retry_after_s=min(RETRY_AFTER_CAP_S, retry), sub_rtt=sub_rtt)

    # ---- fleet spend gossip (ISSUE 18) -----------------------------------
    # Every broker keeps the tenant's FULL refill rate but debits what its
    # peers admitted since the last heartbeat: at equilibrium each broker
    # nets (rate − fleet_admit_rate_elsewhere) tokens/s, so the fleet as a
    # whole admits at ONE logical rate regardless of how a tenant sprays
    # its queries. The budget is eventual — a peer's spend lands one
    # heartbeat late — so the worst-case over-admit is bounded by one
    # heartbeat of refill (plus each broker's independent cold-start
    # burst, a one-time transient).

    def spend_snapshot(self) -> dict:
        """{tenant: cumulative admitted token cost} — published in the
        broker's fleet heartbeat for peers to diff against."""
        with self._lock:
            return {name: round(b.spent, 3)
                    for name, b in self._buckets.items() if b.spent > 0}

    def observe_peer_spend(self, peer_id: str, spend: dict) -> None:
        """Debit a peer broker's admitted spend since its last gossip.

        ``spend`` is the peer's cumulative {tenant: cost} snapshot; the
        delta vs the last-seen snapshot comes out of the local bucket's
        tokens (floored at -burst so a hot peer can dent but not
        permanently bankrupt this broker). A peer whose counter went
        BACKWARD restarted — treat its full counter as fresh spend once
        rather than ignoring it."""
        if not peer_id or not spend:
            return
        with self._lock:
            seen = self._peer_spend_seen.setdefault(peer_id, {})
            for tenant, cum in spend.items():
                try:
                    cum = float(cum)
                except (TypeError, ValueError):
                    continue
                last = seen.get(tenant, 0.0)
                delta = cum if cum < last else cum - last
                seen[tenant] = cum
                if delta <= 0:
                    continue
                b = self._bucket(tenant)
                b.refill(time.monotonic())
                b.tokens = max(-b.burst, b.tokens - delta)

    def forget_peer(self, peer_id: str) -> None:
        """Drop a departed peer's last-seen snapshot (a rejoining broker
        starts a fresh counter and must not be double-debited)."""
        with self._lock:
            self._peer_spend_seen.pop(peer_id, None)

    def stats(self) -> dict:
        with self._lock:
            return {
                "admitted": self.num_admitted,
                "shed": self.num_shed,
                "shed_stale_served": self.num_shed_stale_served,
                "tenants": {
                    name: {
                        "tokens": round(b.tokens, 2),
                        "rate": b.rate, "burst": b.burst,
                        "admitted": b.admitted, "shed": b.shed,
                        "spent": round(b.spent, 2),
                    } for name, b in self._buckets.items()
                },
            }
