"""Broker-side segment pruning: partition + time.

Equivalent of the reference's routing pruners
(pinot-broker/.../routing/segmentpruner/SinglePartitionColumnSegmentPruner.java,
TimeSegmentPruner.java + segmentpruner/interval/IntervalTree.java): before
scattering, drop segments whose recorded partition-id set or time range
provably cannot satisfy the query filter. Pruning is conservative — a segment
survives unless the filter *provably* excludes every one of its docs.

The evaluation walks the filter tree bottom-up with tri-state semantics
collapsed to "may match" booleans: AND may-match iff every child may match,
OR iff any child may match, NOT is always "may match" (the complement of a
partial exclusion proves nothing about the segment).
"""

from __future__ import annotations

from typing import Optional

from pinot_tpu.cluster.registry import SegmentRecord
from pinot_tpu.query.context import (
    FilterNode,
    FilterNodeType,
    Predicate,
    PredicateType,
    QueryContext,
)
from pinot_tpu.storage.partition import partition_of_value


def _value_in_time_range(v, lo, hi) -> bool:
    try:
        return not (v < lo or v > hi)
    except TypeError:
        return True  # incomparable literal: cannot prune


def _predicate_may_match(p: Predicate, rec: SegmentRecord,
                         time_column: Optional[str]) -> bool:
    if not p.lhs.is_identifier:
        return True
    col = p.lhs.name

    # ---- partition pruning (SinglePartitionColumnSegmentPruner) ----------
    if (
        rec.partition_column == col
        and rec.partition_ids
        and rec.partition_function
        and rec.num_partitions
    ):
        pids = set(rec.partition_ids)

        def pid(v) -> int:
            return partition_of_value(v, rec.partition_function, rec.num_partitions)

        try:
            if p.type is PredicateType.EQ:
                if pid(p.value) not in pids:
                    return False
            elif p.type is PredicateType.IN and p.values:
                if all(pid(v) not in pids for v in p.values):
                    return False
        except Exception:  # noqa: BLE001 — unhashable/odd literal: no pruning
            pass

    # ---- time pruning (TimeSegmentPruner) --------------------------------
    if (
        time_column is not None
        and col == time_column
        and rec.start_time is not None
        and rec.end_time is not None
    ):
        lo, hi = rec.start_time, rec.end_time
        try:
            if p.type is PredicateType.EQ:
                return _value_in_time_range(p.value, lo, hi)
            if p.type is PredicateType.IN and p.values:
                return any(_value_in_time_range(v, lo, hi) for v in p.values)
            if p.type is PredicateType.RANGE:
                if p.lower is not None:
                    if p.lower > hi or (p.lower == hi and not p.lower_inclusive):
                        return False
                if p.upper is not None:
                    if p.upper < lo or (p.upper == lo and not p.upper_inclusive):
                        return False
        except TypeError:
            return True
    return True


def _filter_may_match(f: FilterNode, rec: SegmentRecord,
                      time_column: Optional[str]) -> bool:
    if f.type is FilterNodeType.PREDICATE:
        return _predicate_may_match(f.predicate, rec, time_column)
    if f.type is FilterNodeType.AND:
        return all(_filter_may_match(c, rec, time_column) for c in f.children)
    if f.type is FilterNodeType.OR:
        if not f.children:
            return True  # degenerate OR: never prune on it
        return any(_filter_may_match(c, rec, time_column) for c in f.children)
    if f.type is FilterNodeType.CONSTANT_FALSE:
        return False
    # NOT / CONSTANT_TRUE: conservative
    return True


def _hybrid_boundary_filter(time_filter: Optional[dict]) -> Optional[FilterNode]:
    """The broker's hybrid time-boundary split (op le/gt) as a prunable
    RANGE predicate over the time column."""
    if not time_filter:
        return None
    from pinot_tpu.query.context import Expression

    col = Expression.identifier(time_filter["column"])
    if time_filter["op"] == "le":
        p = Predicate(PredicateType.RANGE, col, upper=time_filter["value"],
                      upper_inclusive=True)
    else:  # gt
        p = Predicate(PredicateType.RANGE, col, lower=time_filter["value"],
                      lower_inclusive=False)
    return FilterNode.pred(p)


def prune_segments(
    q: Optional[QueryContext],
    records: dict[str, SegmentRecord],
    segments: list[str],
    time_column: Optional[str],
    time_filter: Optional[dict] = None,
) -> tuple[list[str], int]:
    """Return (surviving segments, pruned count) for one routed instance."""
    filters = []
    if q is not None and q.filter is not None:
        filters.append(q.filter)
    bf = _hybrid_boundary_filter(time_filter)
    if bf is not None:
        filters.append(bf)
    if not filters:
        return segments, 0
    tree = filters[0] if len(filters) == 1 else FilterNode.and_(*filters)
    out = []
    for s in segments:
        rec = records.get(s)
        if rec is None or _filter_may_match(tree, rec, time_column):
            out.append(s)
    return out, len(segments) - len(out)
