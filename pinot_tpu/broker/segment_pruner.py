"""Broker-side segment pruning: partition + time.

Equivalent of the reference's routing pruners
(pinot-broker/.../routing/segmentpruner/SinglePartitionColumnSegmentPruner.java,
TimeSegmentPruner.java + segmentpruner/interval/IntervalTree.java): before
scattering, drop segments whose recorded partition-id set or time range
provably cannot satisfy the query filter. Pruning is conservative — a segment
survives unless the filter *provably* excludes every one of its docs.

The evaluation walks the filter tree bottom-up over a three-value verdict
lattice (structural-no-match < stats-no-match < may-match): AND takes the
minimum, OR the maximum, NOT is always "may match" (the complement of a
partial exclusion proves nothing about the segment). The middle value
attributes prunes that ONLY the per-column min/max stats produced
(numSegmentsPrunedByValue) in a single walk; partition/time/FALSE prunes
are structural. Value comparisons ride the shared interval algebra
(common/pruning.py) so broker and server can never drift.
"""

from __future__ import annotations

from typing import Optional

from pinot_tpu.cluster.registry import SegmentRecord
from pinot_tpu.common.pruning import interval_may_match
from pinot_tpu.query.context import (
    FilterNode,
    FilterNodeType,
    Predicate,
    PredicateType,
    QueryContext,
)
from pinot_tpu.storage.partition import partition_of_value


def _value_in_time_range(v, lo, hi) -> bool:
    try:
        return not (v < lo or v > hi)
    except TypeError:
        return True  # incomparable literal: cannot prune


def _stats_may_match(p: Predicate, rec: SegmentRecord) -> bool:
    """Per-column min/max pruning on ANY column the record carries stats
    for — the shared interval algebra (common/pruning.py), so broker and
    server can never drift on bound or coercion semantics."""
    stats = (rec.column_stats or {}).get(p.lhs.name)
    if not stats:
        return True
    return interval_may_match(p, stats.get("min"), stats.get("max"))


# prune verdicts form a lattice: AND takes the minimum, OR the maximum.
# STATS_NO separates "only the value stats excluded it" (the reference's
# numSegmentsPrunedByValue breakdown) from structural partition/time/FALSE
# prunes in ONE tree walk — under AND a structural exclusion wins (the
# prune would happen without stats), under OR a stats child keeps the
# whole disjunct attributable to stats.
_STRUCT_NO, _STATS_NO, _MAY = 0, 1, 2


def _predicate_verdict(p: Predicate, rec: SegmentRecord,
                       time_column: Optional[str]) -> int:
    if not p.lhs.is_identifier:
        return _MAY
    col = p.lhs.name

    # ---- partition pruning (SinglePartitionColumnSegmentPruner) ----------
    if (
        rec.partition_column == col
        and rec.partition_ids
        and rec.partition_function
        and rec.num_partitions
    ):
        pids = set(rec.partition_ids)

        def pid(v) -> int:
            return partition_of_value(v, rec.partition_function, rec.num_partitions)

        try:
            if p.type is PredicateType.EQ:
                if pid(p.value) not in pids:
                    return _STRUCT_NO
            elif p.type is PredicateType.IN and p.values:
                if all(pid(v) not in pids for v in p.values):
                    return _STRUCT_NO
        except Exception:  # noqa: BLE001 — unhashable/odd literal: no pruning
            pass

    # ---- time pruning (TimeSegmentPruner) --------------------------------
    if (
        time_column is not None
        and col == time_column
        and rec.start_time is not None
        and rec.end_time is not None
    ):
        lo, hi = rec.start_time, rec.end_time
        try:
            if p.type is PredicateType.EQ:
                if not _value_in_time_range(p.value, lo, hi):
                    return _STRUCT_NO
            elif p.type is PredicateType.IN and p.values:
                if not any(_value_in_time_range(v, lo, hi)
                           for v in p.values):
                    return _STRUCT_NO
            elif p.type is PredicateType.RANGE:
                if p.lower is not None:
                    if p.lower > hi or (p.lower == hi and not p.lower_inclusive):
                        return _STRUCT_NO
                if p.upper is not None:
                    if p.upper < lo or (p.upper == lo and not p.upper_inclusive):
                        return _STRUCT_NO
        except TypeError:
            pass  # incomparable: fall through to the stats check

    # ---- per-column value stats (min/max on any column) ------------------
    if not _stats_may_match(p, rec):
        return _STATS_NO
    return _MAY


def _filter_verdict(f: FilterNode, rec: SegmentRecord,
                    time_column: Optional[str]) -> int:
    if f.type is FilterNodeType.PREDICATE:
        return _predicate_verdict(f.predicate, rec, time_column)
    if f.type is FilterNodeType.AND:
        return min((_filter_verdict(c, rec, time_column)
                    for c in f.children), default=_MAY)
    if f.type is FilterNodeType.OR:
        if not f.children:
            return _MAY  # degenerate OR: never prune on it
        return max(_filter_verdict(c, rec, time_column)
                   for c in f.children)
    if f.type is FilterNodeType.CONSTANT_FALSE:
        return _STRUCT_NO
    # NOT / CONSTANT_TRUE: conservative
    return _MAY


def _hybrid_boundary_filter(time_filter: Optional[dict]) -> Optional[FilterNode]:
    """The broker's hybrid time-boundary split (op le/gt) as a prunable
    RANGE predicate over the time column."""
    if not time_filter:
        return None
    from pinot_tpu.query.context import Expression

    col = Expression.identifier(time_filter["column"])
    if time_filter["op"] == "le":
        p = Predicate(PredicateType.RANGE, col, upper=time_filter["value"],
                      upper_inclusive=True)
    else:  # gt
        p = Predicate(PredicateType.RANGE, col, lower=time_filter["value"],
                      lower_inclusive=False)
    return FilterNode.pred(p)


def prune_segments(
    q: Optional[QueryContext],
    records: dict[str, SegmentRecord],
    segments: list[str],
    time_column: Optional[str],
    time_filter: Optional[dict] = None,
) -> tuple[list[str], int, int]:
    """Return (surviving segments, pruned count, pruned-by-value count) for
    one routed instance. ``pruned-by-value`` counts the segments only the
    per-column min/max stats excluded (the reference's
    numSegmentsPrunedByValue breakdown) — partition/time prunes report in
    the total alone."""
    filters = []
    if q is not None and q.filter is not None:
        filters.append(q.filter)
    bf = _hybrid_boundary_filter(time_filter)
    if bf is not None:
        filters.append(bf)
    if not filters:
        return segments, 0, 0
    tree = filters[0] if len(filters) == 1 else FilterNode.and_(*filters)
    out = []
    by_value = 0
    for s in segments:
        rec = records.get(s)
        v = _MAY if rec is None else _filter_verdict(tree, rec, time_column)
        if v == _MAY:
            out.append(s)
        elif v == _STATS_NO:
            by_value += 1  # only the value stats excluded it
    return out, len(segments) - len(out), by_value
