"""Broker HTTP endpoint: POST /query/sql, the reference's public query API.

Equivalent of pinot-broker/.../api/resources/PinotClientRequest.java (the
jersey resource brokering HTTP to BaseBrokerRequestHandler) — stdlib
ThreadingHTTPServer; each request body is {"sql": "..."} and the response is
the BrokerResponse JSON. /health mirrors the reference's health resource.

Auth (BasicAuthAccessControlFactory analog): pass ``users`` as
{username: password} to require HTTP Basic credentials on the query
endpoints; /health stays open like the reference's health resource.
"""

from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class BrokerHttpServer:
    def __init__(self, broker, host: str = "127.0.0.1", port: int = 0,
                 users: Optional[dict] = None, tls="auto"):
        self.broker = broker
        self._users = dict(users) if users else None
        if tls == "auto":
            from pinot_tpu.common.tls import TlsConfig

            tls = TlsConfig.from_config()
        self.tls = tls
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._send(200, {"status": "OK"})
                    return
                # everything beyond /health requires credentials when auth
                # is enabled (metrics leak query/table statistics)
                if not self._authorized():
                    self._reject_unauthorized()
                    return
                if self.path == "/metrics":
                    from pinot_tpu.common.metrics import all_snapshots

                    self._send(200, all_snapshots())
                elif self.path == "/metrics/prometheus":
                    from pinot_tpu.common.metrics import all_prometheus_text

                    body = all_prometheus_text().encode("utf-8")
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._send(404, {"error": "not found"})

            def _authorized(self) -> bool:
                if outer._users is None:
                    return True
                header = self.headers.get("Authorization", "")
                if header.startswith("Basic "):
                    try:
                        raw = base64.b64decode(header[6:]).decode("utf-8")
                        user, _, pw = raw.partition(":")
                    except Exception:  # noqa: BLE001 — malformed header
                        return False
                    import hmac

                    # bytes-compare (str compare_digest rejects non-ASCII)
                    # against a dummy for unknown users so timing doesn't
                    # enumerate usernames
                    expected = outer._users.get(user)
                    known = expected is not None
                    ref = (expected if known else "\x00dummy").encode("utf-8")
                    return hmac.compare_digest(pw.encode("utf-8"), ref) and known
                return False

            def _reject_unauthorized(self) -> None:
                self.send_response(401)
                self.send_header("WWW-Authenticate", 'Basic realm="pinot-tpu"')
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_POST(self):
                if self.path not in ("/query/sql", "/query"):
                    self._send(404, {"error": "not found"})
                    return
                if not self._authorized():
                    self._reject_unauthorized()
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    sql = payload.get("sql", "")
                    self._send(200, outer.broker.execute(sql))
                except Exception as e:  # noqa: BLE001
                    self._send(
                        200,
                        {"exceptions": [{"errorCode": 450,
                                         "message": f"{type(e).__name__}: {e}"}]},
                    )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        if self.tls is not None:
            # HTTPS listener (reference: broker TLS via TlsConfig/Netty).
            # Defer the handshake off the accept loop: with
            # do_handshake_on_connect=True, SSLSocket.accept() handshakes
            # inside serve_forever's single accept thread, so one client
            # that connects and never sends a ClientHello would block ALL
            # broker HTTP traffic. Deferred, the handshake happens on the
            # handler thread's first recv.
            self._httpd.socket = self.tls.server_ssl_context().wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="broker-http", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        scheme = "https" if self.tls is not None else "http"
        return f"{scheme}://{self.host}:{self.port}"
