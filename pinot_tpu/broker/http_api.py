"""Broker HTTP endpoint: POST /query/sql, the reference's public query API.

Equivalent of pinot-broker/.../api/resources/PinotClientRequest.java (the
jersey resource brokering HTTP to BaseBrokerRequestHandler) — stdlib
ThreadingHTTPServer; each request body is {"sql": "..."} and the response is
the BrokerResponse JSON. /health mirrors the reference's health resource.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class BrokerHttpServer:
    def __init__(self, broker, host: str = "127.0.0.1", port: int = 0):
        self.broker = broker
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._send(200, {"status": "OK"})
                elif self.path == "/metrics":
                    from pinot_tpu.common.metrics import all_snapshots

                    self._send(200, all_snapshots())
                elif self.path == "/metrics/prometheus":
                    from pinot_tpu.common.metrics import all_prometheus_text

                    body = all_prometheus_text().encode("utf-8")
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                if self.path not in ("/query/sql", "/query"):
                    self._send(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    sql = payload.get("sql", "")
                    self._send(200, outer.broker.execute(sql))
                except Exception as e:  # noqa: BLE001
                    self._send(
                        200,
                        {"exceptions": [{"errorCode": 450,
                                         "message": f"{type(e).__name__}: {e}"}]},
                    )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="broker-http", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
