"""Broker HTTP endpoint: POST /query/sql, the reference's public query API.

Equivalent of pinot-broker/.../api/resources/PinotClientRequest.java (the
jersey resource brokering HTTP to BaseBrokerRequestHandler) — stdlib
ThreadingHTTPServer; each request body is {"sql": "..."} and the response is
the BrokerResponse JSON. /health mirrors the reference's health resource.

Auth (BasicAuthAccessControlFactory analog): pass ``users`` as
{username: password} to require HTTP Basic credentials on the query
endpoints; /health stays open like the reference's health resource.
``acls`` ({username: [table, ...]}) adds per-principal TABLE access
control (principals.<user>.tables= in config form): a query against a
table outside the principal's list answers 403 BEFORE any execution —
the reference's AccessControl.hasAccess check in
BaseBrokerRequestHandler.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from pinot_tpu.common.auth import BasicAuthAccessControl


class BrokerHttpServer:
    def __init__(self, broker, host: str = "127.0.0.1", port: int = 0,
                 users: Optional[dict] = None, tls="auto",
                 acls: Optional[dict] = None,
                 access_control: Optional[BasicAuthAccessControl] = None):
        self.broker = broker
        if access_control is None and users:
            access_control = BasicAuthAccessControl(users, acls)
        elif access_control is None and acls:
            # ACLs without credentials cannot be enforced — constructing an
            # open endpoint the operator believes is table-restricted is
            # the one wrong answer
            raise ValueError("table acls require users (or access_control)")
        self._access = access_control
        if tls == "auto":
            from pinot_tpu.common.tls import TlsConfig

            tls = TlsConfig.from_config()
        self.tls = tls
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 enables chunked transfer for the streaming result
            # path; safe for every other route because they all set
            # Content-Length (keep-alive framing stays intact)
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, payload: dict,
                      headers: dict = None) -> None:
                body = json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._send(200, {"status": "OK"})
                    return
                # everything beyond /health requires credentials when auth
                # is enabled (metrics leak query/table statistics)
                principal = self._authorized()
                if principal is None:
                    self._reject_unauthorized()
                    return
                if outer._access is not None and \
                        outer._access.is_restricted(principal):
                    # metrics aggregate across ALL tables: a principal with
                    # a table grant list must not read them
                    self._send(403, {"error": "Permission denied: metrics "
                                              "span tables outside this "
                                              "principal's grants"})
                    return
                if self.path == "/metrics":
                    from pinot_tpu.common.metrics import all_snapshots

                    self._send(200, all_snapshots())
                elif self.path.startswith("/debug/queries"):
                    # flight-recorder ring: the last N logged queries
                    # (slow/errored/sampled — broker/querylog.py policy),
                    # newest first, each with its merged trace attached
                    try:
                        from urllib.parse import parse_qs, urlparse

                        qs = parse_qs(urlparse(self.path).query)
                        limit = int(qs.get("limit", ["0"])[0])
                    except (ValueError, IndexError):
                        limit = 0
                    self._send(200, {
                        "queries": outer.broker.querylog.recent(limit)})
                elif self.path == "/metrics/prometheus":
                    from pinot_tpu.common.metrics import all_prometheus_text

                    body = all_prometheus_text().encode("utf-8")
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._send(404, {"error": "not found"})

            def _authorized(self):
                """Principal name, "" when auth is disabled, None when
                rejected."""
                if outer._access is None:
                    return ""
                return outer._access.authenticate(
                    self.headers.get("Authorization"))

            def _reject_unauthorized(self) -> None:
                self.send_response(401)
                self.send_header("WWW-Authenticate", 'Basic realm="pinot-tpu"')
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_POST(self):
                if self.path not in ("/query/sql", "/query",
                                     "/query/sql/stream"):
                    self._send(404, {"error": "not found"})
                    return
                principal = self._authorized()
                if principal is None:
                    self._reject_unauthorized()
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    sql = payload.get("sql", "")
                    if outer.broker.draining:
                        # fleet drain (ISSUE 18): a REAL 503 before any
                        # execution — rotating clients move to a peer
                        self._send(503, outer.broker.drain_response(),
                                   headers={"Retry-After": "1"})
                        return
                    denied = outer._denied_table(principal, sql)
                    if denied is not None:
                        # per-principal table ACL: reject BEFORE execution
                        # (BaseBrokerRequestHandler access-control ordering)
                        self._send(403, {"exceptions": [{
                            "errorCode": 403,
                            "message": f"Permission denied on table "
                                       f"{denied!r} for principal "
                                       f"{principal!r}"}]})
                        return
                    if self.path == "/query/sql/stream":
                        self._stream_query(sql, principal)
                        return
                    # the authenticated principal is the tenant key for
                    # priority admission (ISSUE 14); "" (auth disabled)
                    # falls back to SET workloadName / 'default'
                    resp = outer.broker.execute(sql,
                                                principal=principal or None)
                    excs = resp.get("exceptions") or []
                    if excs and all(x.get("errorCode") == 429 for x in excs):
                        # over-quota: a real 429 status + Retry-After so
                        # standards clients (and our DB-API driver) can
                        # back off and retry instead of failing the call.
                        # The header derives from the broker's own pacing
                        # hint, ceiled to RFC delta-seconds (integers)
                        import math

                        after = math.ceil(float(
                            resp.get("retryAfterSeconds", 1.0)))
                        self._send(429, resp,
                                   headers={"Retry-After": str(max(1, after))})
                        return
                    self._send(200, resp)
                except Exception as e:  # noqa: BLE001
                    self._send(
                        200,
                        {"exceptions": [{"errorCode": 450,
                                         "message": f"{type(e).__name__}: {e}"}]},
                    )

            def _stream_query(self, sql: str, principal: str) -> None:
                """Chunked NDJSON result delivery (ISSUE 18): one JSON
                line per broker chunk (schema / rows / final), HTTP/1.1
                chunked transfer encoding written by hand so each chunk
                flushes as it is produced — client RTT-to-first-row is
                one block, broker RSS stays bounded. urllib/http.client
                decode the chunk framing transparently; consumers just
                readline NDJSON."""
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def write_chunk(obj: dict) -> None:
                    line = (json.dumps(obj) + "\n").encode("utf-8")
                    self.wfile.write(f"{len(line):X}\r\n".encode("ascii"))
                    self.wfile.write(line)
                    self.wfile.write(b"\r\n")

                try:
                    for chunk in outer.broker.execute_stream(
                            sql, principal=principal or None):
                        write_chunk(chunk)
                except BrokenPipeError:
                    return  # client went away: stop producing
                except Exception as e:  # noqa: BLE001 — in-band, typed
                    try:
                        write_chunk({"type": "final", "exceptions": [{
                            "errorCode": 450,
                            "message": f"{type(e).__name__}: {e}"}]})
                    except OSError:
                        return
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        if self.tls is not None:
            # HTTPS listener (reference: broker TLS via TlsConfig/Netty).
            # Defer the handshake off the accept loop: with
            # do_handshake_on_connect=True, SSLSocket.accept() handshakes
            # inside serve_forever's single accept thread, so one client
            # that connects and never sends a ClientHello would block ALL
            # broker HTTP traffic. Deferred, the handshake happens on the
            # handler thread's first recv.
            self._httpd.socket = self.tls.server_ssl_context().wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="broker-http", daemon=True
        )

    def _denied_table(self, principal: str, sql: str):
        """Table the principal may NOT query, or None when allowed.
        Unparseable SQL passes through — the broker's own compile error
        answers it in-band (no information leak: the table name in a
        broken query never resolves)."""
        if self._access is None or not self._access.restricts_tables:
            return None  # pure-auth setup: skip the extra SQL compile
        try:
            from pinot_tpu.sql.parser import parse_sql

            stmt = parse_sql(sql)
            # a multi-stage (join) query touches EVERY referenced table —
            # each one must pass the principal's ACL, or a restricted
            # principal could read a denied table through a join
            tables = [stmt.table] + [j.table for j in stmt.joins]
        except Exception:  # noqa: BLE001 — broker reports the parse error
            return None
        for table in tables:
            if table and not self._access.allows(principal, table):
                return table
        return None

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        scheme = "https" if self.tls is not None else "http"
        return f"{scheme}://{self.host}:{self.port}"
