"""Broker fleet membership: registration, liveness, drain, spend gossip.

ISSUE 18's front door. The reference's ``BrokerStarter`` registers every
broker as a Helix BROKER-resource participant so clients and the
controller discover the fleet through ZK; ours registers under the
registry's existing ``Role.BROKER`` with the same heartbeat plumbing the
servers use — no second channel. Each heartbeat piggybacks a ``stats``
dict on the broker's ``InstanceInfo``:

    {"url": "http://host:port",      # the query endpoint clients rotate over
     "draining": bool,               # drain state (typed 503s while set)
     "qps": float,                   # served QPS over the last interval
     "queries": int,                 # cumulative queries served
     "cacheHits"/"cacheMisses": int, # broker result-cache counters
     "cacheHitRate": float,          # hits / (hits + misses)
     "tenantSpend": {tenant: cum}}   # admission gossip (see below)

Three consumers ride that one dict: the DB-API client's registry
discovery (rotate across live, non-draining ``url``s), ``clusterstat
--brokers`` (fleet health at a glance), and the admission controllers'
**spend gossip** — each broker publishes its cumulative per-tenant
admitted cost and debits every peer's delta from its own buckets
(broker/admission.py ``observe_peer_spend``), so N brokers share ONE
logical per-tenant budget with over-admit bounded by one heartbeat of
refill. Gossip is symmetric and leaderless: there is no budget
coordinator to elect or lose.

Drain (``BrokerFleetMember.drain()``) flips the broker to typed 503s,
publishes ``draining: true`` immediately (not at the next tick), and
keeps heartbeating so peers see a LIVE-but-draining broker — rotation
skips it, in-flight queries finish, and ``stop()`` deregisters cleanly.

Config: ``pinot.broker.fleet.heartbeat.interval.ms`` (default 2000 —
the same cadence as server heartbeats, and the bound in "a stale cache
entry on broker B dies within one heartbeat of an ingest through A").
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from pinot_tpu.cluster.registry import HB_STALE_S, InstanceInfo, Role

log = logging.getLogger("pinot_tpu.broker.fleet")


def live_brokers(registry, include_draining: bool = False) -> list:
    """Live BROKER-role instances (heartbeat within HB_STALE_S), newest
    registration order as the registry returns them. ``include_draining``
    keeps draining members (they still answer /health, not queries)."""
    out = []
    for info in registry.instances(Role.BROKER,
                                   live_ttl_ms=int(HB_STALE_S * 1000)):
        if not include_draining and (info.stats or {}).get("draining"):
            continue
        out.append(info)
    return out


def discover_broker_urls(registry) -> list:
    """The rotation list a DB-API client builds from a registry: every
    live, non-draining broker's published query URL."""
    urls = []
    for info in live_brokers(registry):
        url = (info.stats or {}).get("url")
        if url:
            urls.append(url)
    return urls


class BrokerFleetMember:
    """One broker's fleet membership: registers the broker under
    Role.BROKER, heartbeats liveness + piggybacked stats, applies peer
    spend gossip to the local admission controller, and owns the drain
    lifecycle. Composition, not inheritance — the Broker object stays
    usable standalone (tests, embedded connections) and joins a fleet by
    being wrapped."""

    def __init__(self, registry, broker, http_url: Optional[str] = None,
                 heartbeat_interval_ms: Optional[float] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.registry = registry
        self.broker = broker
        self.http_url = http_url
        self.host = host
        self.port = int(port)
        if heartbeat_interval_ms is None:
            from pinot_tpu.common.config import Configuration

            heartbeat_interval_ms = Configuration().get_float(
                "pinot.broker.fleet.heartbeat.interval.ms", 2_000.0)
        self.heartbeat_interval_s = max(0.01, heartbeat_interval_ms / 1e3)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # last cumulative queries_served + wall clock → interval QPS
        self._last_queries = 0
        self._last_tick = time.monotonic()

    @property
    def instance_id(self) -> str:
        return self.broker.broker_id

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "BrokerFleetMember":
        self.registry.register_instance(InstanceInfo(
            instance_id=self.instance_id, role=Role.BROKER,
            host=self.host, grpc_port=self.port,
            stats=self._stats()))
        self._last_tick = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name=f"fleet-hb-{self.instance_id}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Deregister cleanly: peers re-discover without waiting out the
        liveness TTL, and their gossip last-seen snapshot for this broker
        is dropped on their next tick (a rejoin starts a fresh counter)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        try:
            self.registry.drop_instance(self.instance_id)
        except Exception:  # noqa: BLE001 — best-effort on teardown
            log.exception("fleet deregistration failed")

    # ---- drain -----------------------------------------------------------
    def drain(self) -> None:
        """Typed 503s from now on; the drain state publishes IMMEDIATELY
        (clients must stop landing here within one rotation, not one
        heartbeat)."""
        self.broker.draining = True
        self._beat()

    def undrain(self) -> None:
        self.broker.draining = False
        self._beat()

    # ---- heartbeat -------------------------------------------------------
    def _stats(self) -> dict:
        b = self.broker
        now = time.monotonic()
        queries = b.queries_served
        dt = max(1e-6, now - self._last_tick)
        qps = max(0, queries - self._last_queries) / dt
        self._last_queries = queries
        self._last_tick = now
        rc = b.result_cache
        hits, misses = rc.hits, rc.misses
        stats = {
            "url": self.http_url,
            "draining": bool(b.draining),
            "qps": round(qps, 3),
            "queries": queries,
            "cacheHits": hits,
            "cacheMisses": misses,
            "cacheHitRate": round(hits / (hits + misses), 4)
            if (hits + misses) else 0.0,
        }
        if b.admission is not None:
            spend = b.admission.spend_snapshot()
            if spend:
                stats["tenantSpend"] = spend
        return stats

    def _beat(self) -> None:
        """One tick: publish stats, ingest every live peer's gossip."""
        try:
            self.registry.heartbeat(self.instance_id, stats=self._stats())
        except Exception:  # noqa: BLE001 — a registry hiccup must not
            log.exception("fleet heartbeat failed")  # kill the loop
            return
        if self.broker.admission is None:
            return
        try:
            live_ids = set()
            for peer in live_brokers(self.registry, include_draining=True):
                if peer.instance_id == self.instance_id:
                    continue
                live_ids.add(peer.instance_id)
                spend = (peer.stats or {}).get("tenantSpend")
                if spend:
                    self.broker.admission.observe_peer_spend(
                        peer.instance_id, spend)
            # departed peers: drop their last-seen gossip snapshot so a
            # rejoin's fresh counter isn't diffed against the old one
            for gone in (set(self.broker.admission._peer_spend_seen)
                         - live_ids):
                self.broker.admission.forget_peer(gone)
        except Exception:  # noqa: BLE001
            log.exception("fleet gossip failed")

    def _run(self) -> None:
        while not self._stop.wait(self.heartbeat_interval_s):
            self._beat()
