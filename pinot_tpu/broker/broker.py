"""Broker role: SQL endpoint → route → scatter/gather → reduce.

Equivalent of the reference's broker stack (pinot-broker/:
BaseBrokerRequestHandler.java:169,194-400 parse→rewrite→route→scatter→reduce,
BrokerRoutingManager + instance selectors, failuredetector/ with exponential
backoff, SingleConnectionBrokerRequestHandler netty scatter-gather). The
scatter rides gRPC channels (transport/grpc_transport.py); the reduce is the
same value-space merge used in-process (engine/reduce.py), since servers ship
canonical DataTable partials.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from concurrent import futures
from typing import Optional

from pinot_tpu.broker.segment_pruner import prune_segments
from pinot_tpu.cluster.registry import ClusterRegistry, Role, SegmentState
from pinot_tpu.engine.datatable import decode
from pinot_tpu.engine.reduce import finalize, merge_intermediates
from pinot_tpu.engine.result import ExecutionStats, IntermediateResult
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.optimizer import optimize_query
from pinot_tpu.sql.compiler import compile_query
from pinot_tpu.transport.grpc_transport import QueryRouterChannel, make_instance_request

log = logging.getLogger("pinot_tpu.broker")


class QueryQuotaManager:
    """Per-table QPS token bucket
    (queryquota/HelixExternalViewBasedQueryQuotaManager analog). Rates come
    from TableConfig.quota.max_queries_per_second; the bucket holds up to
    one second of burst. Enforced per broker — the reference divides the
    table quota by the live-broker count, which a deployment can mirror by
    setting the per-table rate accordingly."""

    def __init__(self, registry):
        self.registry = registry
        self._buckets: dict = {}  # raw table -> [tokens, last_ts, rate]
        self._lock = threading.Lock()

    @staticmethod
    def _base_name(table: str) -> str:
        # one bucket per logical table: 'tbl', 'tbl_OFFLINE' and
        # 'tbl_REALTIME' must draw from the SAME quota
        for suffix in ("_OFFLINE", "_REALTIME"):
            if table.endswith(suffix):
                return table[: -len(suffix)]
        return table

    def _rate(self, base: str) -> Optional[float]:
        for key in (base, f"{base}_OFFLINE", f"{base}_REALTIME"):
            cfg = self.registry.table_config(key)
            if cfg is not None and \
                    cfg.quota.max_queries_per_second is not None:
                return float(cfg.quota.max_queries_per_second)
        return None

    def acquire(self, table: str) -> bool:
        """True = admit; False = over quota (HTTP 429-shaped rejection)."""
        base = self._base_name(table)
        rate = self._rate(base)
        if rate is None:
            return True
        now = time.time()
        with self._lock:
            tokens, last, _ = self._buckets.get(base, (rate, now, rate))
            tokens = min(rate, tokens + (now - last) * rate)
            if tokens < 1.0:
                self._buckets[base] = [tokens, now, rate]
                return False
            self._buckets[base] = [tokens - 1.0, now, rate]
            return True


class FailureDetector:
    """Connection-level failure detector with exponential backoff retry
    (pinot-broker/.../failuredetector/BaseExponentialBackoffRetryFailureDetector)."""

    def __init__(self, initial_backoff_s: float = 1.0, max_backoff_s: float = 30.0):
        self._unhealthy: dict[str, tuple[float, float]] = {}  # id -> (retry_at, backoff)
        self._initial = initial_backoff_s
        self._max = max_backoff_s
        self._lock = threading.Lock()

    def mark_failure(self, instance_id: str) -> None:
        with self._lock:
            _, backoff = self._unhealthy.get(instance_id, (0.0, self._initial / 2))
            backoff = min(backoff * 2, self._max)
            self._unhealthy[instance_id] = (time.time() + backoff, backoff)

    def mark_success(self, instance_id: str) -> None:
        with self._lock:
            self._unhealthy.pop(instance_id, None)

    def is_healthy(self, instance_id: str) -> bool:
        with self._lock:
            entry = self._unhealthy.get(instance_id)
            if entry is None:
                return True
            return time.time() >= entry[0]  # retry window open


class RoutingManager:
    """table → {instance: [segments]} from the registry's assignment
    (BrokerRoutingManager.java:87 + balanced instance selection: one replica
    per segment, round-robin across queries)."""

    def __init__(self, registry: ClusterRegistry, failure_detector: FailureDetector):
        self.registry = registry
        self.failures = failure_detector
        self._rr = itertools.count()

    def routing_table(self, table: str) -> Optional[dict]:
        # route on the EXTERNAL VIEW (what servers actually serve), not the
        # ideal-state assignment — assignment may race ahead of loading
        view, records, lineage = self.registry.routing_snapshot(table)
        if not view:
            return None
        # Segment-lineage filter (reference SegmentLineage +
        # SegmentLineageBasedSegmentPreSelector): an IN_PROGRESS replace
        # routes the FROM set (the TO segments are still loading); a
        # COMPLETED one routes the TO set even while the FROM segments
        # linger in the external view awaiting deletion. This is what makes
        # a minion merge swap atomic from the query path's point of view.
        excluded = set()
        for entry in lineage.values():
            excluded.update(
                entry["from"] if entry["state"] == "COMPLETED" else entry["to"]
            )
        offset = next(self._rr)
        out: dict[str, list] = {}
        for segment, instances in view.items():
            if segment in excluded:
                continue
            rec = records.get(segment)
            if rec is not None and rec.state == SegmentState.OFFLINE:
                continue
            candidates = [i for i in instances if self.failures.is_healthy(i)]
            if not candidates:
                candidates = instances  # all unhealthy: try anyway
            pick = candidates[offset % len(candidates)]
            out.setdefault(pick, []).append(segment)
        return out


class Broker:
    def __init__(self, registry: ClusterRegistry, broker_id: str = "broker_0",
                 timeout_s: float = 10.0, tls="auto"):
        self.registry = registry
        self.broker_id = broker_id
        self.timeout_s = timeout_s
        if tls == "auto":
            # layered config (pinot.tls.*) like the reference's TlsConfig
            from pinot_tpu.common.tls import TlsConfig

            tls = TlsConfig.from_config()
        self.tls = tls
        from pinot_tpu.common.metrics import get_metrics

        self.metrics = get_metrics("broker")
        self.quota = QueryQuotaManager(registry)
        self.failures = FailureDetector()
        self.routing = RoutingManager(registry, self.failures)
        self._channels: dict[str, QueryRouterChannel] = {}
        self._channels_lock = threading.Lock()
        self._request_id = itertools.count(1)
        self._pool = futures.ThreadPoolExecutor(max_workers=16)

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()
        self._pool.shutdown(wait=False)

    def _channel(self, instance_id: str) -> Optional[QueryRouterChannel]:
        info = {i.instance_id: i for i in self.registry.instances(Role.SERVER)}.get(
            instance_id
        )
        if info is None:
            return None
        with self._channels_lock:  # pool threads race per-instance channels
            ch = self._channels.get(instance_id)
            if ch is None or ch.endpoint != info.endpoint:
                if ch is not None:
                    ch.close()
                ch = QueryRouterChannel(info.endpoint, tls=self.tls)
                self._channels[instance_id] = ch
            return ch

    # ---- request handling ------------------------------------------------
    def execute(self, sql: str) -> dict:
        """HTTP POST /query/sql equivalent (PinotClientRequest →
        BaseBrokerRequestHandler.handleRequest)."""
        from pinot_tpu.common import trace

        t0 = time.time()
        self.metrics.count("queries")
        if sql.strip().rstrip(";").strip().upper() == "SHOW TABLES":
            # catalog surface for standards clients (the JDBC driver's
            # DatabaseMetaData.getTables role, backed by the controller's
            # /tables REST in the reference): logical names, type suffix
            # stripped, hybrid halves collapsed
            names = sorted({
                t[: -len(suffix)] if t.endswith(suffix) else t
                for t in self.registry.tables()
                for suffix in ("_OFFLINE", "_REALTIME")
                if t.endswith(suffix)
            } | {t for t in self.registry.tables()
                 if not t.endswith(("_OFFLINE", "_REALTIME"))})
            return {
                "resultTable": {
                    "dataSchema": {"columnNames": ["tableName"],
                                   "columnDataTypes": ["STRING"]},
                    "rows": [[n] for n in names],
                },
                "exceptions": [],
                "numDocsScanned": 0,
                "totalDocs": 0,
                "timeUsedMs": round((time.time() - t0) * 1000, 3),
            }
        tracer = None
        try:
            q = optimize_query(compile_query(sql))
            q = self._resolve_table_case(q)
            if q.explain:
                from pinot_tpu.engine.explain import explain_plan

                class _NoDevice:
                    # broker-side explain has no local executor or segments:
                    # filter lines show generic PREDICATE operators (index
                    # choice is per-segment, server-side)
                    device = None
                    tables: dict = {}

                return explain_plan(_NoDevice(), q)
            if not self.quota.acquire(q.table_name):
                # quota rejection before any fan-out
                # (BaseBrokerRequestHandler's quota check placement)
                self.metrics.count("queriesQuotaExceeded")
                return {"exceptions": [{
                    "errorCode": 429,
                    "message": f"query quota exceeded for table "
                               f"{q.table_name!r}"}]}
            if q.options_ci().get("trace"):
                tracer = trace.start_trace()
            resp = self._scatter_gather(q, sql)
            if tracer is not None:
                resp.setdefault("traceInfo", {})["broker"] = tracer.to_json()
        except Exception as e:  # noqa: BLE001 — in-band errors like the reference
            self.metrics.count("queryErrors")
            return {"exceptions": [{"errorCode": 450, "message": f"{type(e).__name__}: {e}"}]}
        finally:
            if tracer is not None:
                trace.end_trace()
        resp["timeUsedMs"] = round((time.time() - t0) * 1000, 3)
        self.metrics.time_ms("query", resp["timeUsedMs"])
        return resp

    def _resolve_table_case(self, q: QueryContext) -> QueryContext:
        """Case-insensitive table resolution against the registry
        (BaseBrokerRequestHandler.java:245-254 / TableCache's
        ignore-case lookup): FROM mytable matches a registered MyTable.
        Exact matches win; ambiguous case-folds keep the literal name."""
        raw = q.table_name
        names = set(self.registry.tables())
        candidates = {raw, f"{raw}_OFFLINE", f"{raw}_REALTIME"}
        if candidates & names:
            return q
        low = raw.lower()
        # physical-name fold first (FROM sAlEs_OFFLINE → sales_OFFLINE),
        # then the base-name fold (FROM SALES → sales)
        physical = {n for n in names if n.lower() == low}
        base = {QueryQuotaManager._base_name(n) for n in names}
        matches = physical or {b for b in base if b.lower() == low}
        if len(matches) != 1:
            return q
        return dataclasses.replace(q, table_name=matches.pop())

    def _expand_star(self, q: QueryContext) -> QueryContext:
        """SELECT * resolves against the registry schema (looked up via the
        physical table key) so the broker's reduce sees the same select
        positions the servers produced."""
        from pinot_tpu.query.rewrite import expand_star

        schema = None
        for key in (q.table_name, f"{q.table_name}_OFFLINE", f"{q.table_name}_REALTIME"):
            schema = self.registry.table_schema(key)
            if schema is not None:
                break
        if schema is None:
            return q
        return expand_star(q, schema.column_names())

    def _physical_tables(self, raw: str) -> list:
        """Raw table name → [(physical key, time filter or None)].

        A hybrid table (both _OFFLINE and _REALTIME registered) is split at
        the time boundary = max offline segment end time: offline answers
        time <= boundary, realtime answers time > boundary
        (routing/timeboundary/TimeBoundaryManager.java +
        BaseBrokerRequestHandler.java:387-395)."""
        tables = set(self.registry.tables())
        if raw in tables:
            return [(raw, None)]
        off, rt = f"{raw}_OFFLINE", f"{raw}_REALTIME"
        out = []
        boundary = None
        if off in tables and rt in tables:
            cfg = self.registry.table_config(off)
            if cfg is not None and cfg.time_column is not None:
                # boundary counts only SERVABLE offline segments: a freshly
                # pushed segment (e.g. a realtimeToOffline move) must not
                # advance the boundary before any server can answer for it,
                # or its window would transiently vanish from hybrid results
                view, records, _ = self.registry.routing_snapshot(off)
                ends = [
                    r.end_time
                    for name, r in records.items()
                    if r.end_time is not None and name in view
                ]
                if ends:
                    # TimeBoundaryManager semantics: back off one time unit
                    # from the max offline end time — realtime rows with
                    # ts <= maxEnd not yet pushed offline would otherwise be
                    # invisible to both sides (offline lacks them, gt filter
                    # excludes them).
                    bval = max(ends)
                    if isinstance(bval, int):
                        bval -= 1
                    else:
                        # float time columns: back off one ULP so ts == maxEnd
                        # rows route to realtime (same semantics as minus one
                        # unit at float resolution)
                        import math

                        bval = math.nextafter(float(bval), -math.inf)
                    boundary = (cfg.time_column, bval)
        if off in tables:
            tf = None if boundary is None else                 {"column": boundary[0], "op": "le", "value": boundary[1]}
            out.append((off, tf))
        if rt in tables:
            tf = None if boundary is None else                 {"column": boundary[0], "op": "gt", "value": boundary[1]}
            out.append((rt, tf))
        if not out:
            raise KeyError(f"table {raw!r} not found")
        return out

    def _scatter_gather(self, q: QueryContext, sql: str) -> dict:
        from pinot_tpu.common.trace import span

        q = self._expand_star(q)
        request_id = next(self._request_id)
        # per-query timeout override (SET timeoutMs = N — the reference's
        # timeoutMs query option)
        opts = q.options_ci()
        timeout_s = self.timeout_s
        if "timeoutms" in opts:
            timeout_s = max(0.001, float(opts["timeoutms"]) / 1000.0)

        scatter = []  # (instance, physical table, segments, time_filter)
        n_servers = set()
        num_pruned = 0
        num_pruned_value = 0  # excluded by per-column min/max stats alone
        fully_pruned = []  # fallback: keep one segment so reduce sees a shape
        for physical, time_filter in self._physical_tables(q.table_name):
            routing = self.routing.routing_table(physical)
            if not routing:
                continue
            records = self.registry.segments(physical)
            cfg = self.registry.table_config(physical)
            time_col = cfg.time_column if cfg is not None else None
            for inst, segs in routing.items():
                kept, pruned, by_value = prune_segments(
                    q, records, segs, time_col, time_filter)
                num_pruned += pruned
                num_pruned_value += by_value
                if kept:
                    scatter.append((inst, physical, kept, time_filter))
                    n_servers.add(inst)
                else:
                    fully_pruned.append((inst, physical, segs[:1], time_filter))
        if not scatter and fully_pruned:
            # every segment pruned: query one anyway — the server's min/max
            # pruner short-circuits it, and the reduce gets a typed empty
            # result instead of a synthesized one
            inst, phys, segs, tf = fully_pruned[0]
            num_pruned -= len(segs)
            # the re-queried segment no longer counts as pruned in EITHER
            # number; the clamp is exact — by-value can only exceed the new
            # total when the re-added segment itself was value-pruned
            num_pruned_value = min(num_pruned_value, max(0, num_pruned))
            scatter.append((inst, phys, segs, tf))
            n_servers.add(inst)
        if not scatter:
            raise KeyError(f"no routing entry for table {q.table_name!r}")

        # Streaming execution (StreamingReduceService analog): selection
        # without ORDER BY has any-subset semantics, so servers stream one
        # DataTable block per segment and the broker cancels every stream
        # as soon as offset+limit rows arrived — no full materialization on
        # either side. SET streaming = false forces the unary path.
        use_streaming = (
            not q.aggregations() and not q.distinct and not q.order_by
            and opts.get("streaming") is not False
            # tracing rides the unary DataTable header; streaming blocks
            # don't carry spans, so a traced query takes the unary path
            and not opts.get("trace")
        )
        row_budget = q.offset + q.limit
        rows_seen = [0]
        rows_lock = threading.Lock()

        def call(instance_id: str, physical: str, segments: list, time_filter):
            ch = self._channel(instance_id)
            if ch is None:
                raise ConnectionError(f"server {instance_id} not registered")
            payload = make_instance_request(
                sql, segments, request_id, self.broker_id,
                table=physical, time_filter=time_filter,
            )
            if not use_streaming:
                return [decode(ch.submit(payload, timeout_s))]
            stream = ch.submit_streaming(payload, timeout_s)
            parts = []
            for block in stream:
                r = decode(bytes(block))
                parts.append(r)
                n = len(next(iter(r.rows.values()))) if r.rows else 0
                with rows_lock:
                    rows_seen[0] += n
                    done = rows_seen[0] >= row_budget
                if done:
                    stream.cancel()
                    break
            return parts

        futs = {
            self._pool.submit(call, inst, phys, segs, tf): inst
            for inst, phys, segs, tf in scatter
        }
        from pinot_tpu.engine.datatable import NoSegmentsHosted, ServerQueryError

        results, exceptions = [], []
        query_errors = []
        server_traces = {}
        responded = set()  # instances, not blocks (streaming yields many)
        with span("broker.scatter_gather"):
            for fut, inst in futs.items():
                try:
                    for r in fut.result(timeout=timeout_s + 1):
                        if r.trace is not None:
                            server_traces[inst] = r.trace
                        results.append(r)
                    responded.add(inst)
                    self.failures.mark_success(inst)
                except NoSegmentsHosted:
                    # benign routing/sync race: segments moved between the
                    # external-view read and the RPC; not a server failure
                    self.failures.mark_success(inst)
                except ServerQueryError as e:
                    # query-level error (bad column etc.): the server is
                    # healthy; report in-band, don't poison the detector
                    self.failures.mark_success(inst)
                    query_errors.append(
                        {"errorCode": 200, "message": f"{inst}: {e}"}
                    )
                except Exception as e:  # noqa: BLE001 — transport failure
                    self.failures.mark_failure(inst)
                    exceptions.append(
                        {"errorCode": 427,
                         "message": f"SERVER_NOT_RESPONDING: {inst}: {e}"}
                    )
        if query_errors:
            return {"exceptions": query_errors}
        if not results:
            self.metrics.count("serverFailures", len(exceptions))
            raise ConnectionError(f"all servers failed: {exceptions}")

        with span("broker.reduce"):
            merged = merge_intermediates(q, results)
            table = finalize(q, merged)
        resp = table.to_json()
        if server_traces:
            resp["traceInfo"] = server_traces
        stats = merged.stats
        resp.update(
            {
                "exceptions": exceptions,
                "partialResult": bool(exceptions),
                "numServersQueried": len(n_servers),
                "numServersResponded": len(responded),
                "numDocsScanned": stats.num_docs_scanned,
                "numEntriesScannedInFilter": stats.num_entries_scanned_in_filter,
                "numEntriesScannedPostFilter": stats.num_entries_scanned_post_filter,
                "numSegmentsQueried": stats.num_segments_queried,
                "numSegmentsPrunedByBroker": num_pruned,
                "numSegmentsPrunedByValue": num_pruned_value,
                "numSegmentsPrunedByServer": stats.num_segments_pruned,
                "numBlocksPruned": stats.num_blocks_pruned,
                "numSegmentsProcessed": stats.num_segments_processed,
                "numSegmentsMatched": stats.num_segments_matched,
                "totalDocs": stats.total_docs,
                "numGroupsLimitReached": stats.num_groups_limit_reached,
                # summed across servers, like the reference's V3 metadata
                "threadCpuTimeNs": stats.thread_cpu_time_ns,
                "schedulerWaitMs": round(stats.scheduler_wait_ms, 3),
                "requestId": request_id,
            }
        )
        return resp
