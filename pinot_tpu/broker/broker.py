"""Broker role: SQL endpoint → route → scatter/gather → reduce.

Equivalent of the reference's broker stack (pinot-broker/:
BaseBrokerRequestHandler.java:169,194-400 parse→rewrite→route→scatter→reduce,
BrokerRoutingManager + instance selectors, failuredetector/ with exponential
backoff, SingleConnectionBrokerRequestHandler netty scatter-gather). The
scatter rides gRPC channels (transport/grpc_transport.py); the reduce is the
same value-space merge used in-process (engine/reduce.py), since servers ship
canonical DataTable partials.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from concurrent import futures
from typing import Optional

from pinot_tpu.broker.segment_pruner import prune_segments
from pinot_tpu.cluster.registry import (
    HB_STALE_S,
    ClusterRegistry,
    Role,
    SegmentState,
)
from pinot_tpu.common import faults
from pinot_tpu.common.deadline import Deadline
from pinot_tpu.common.options import bool_option
from pinot_tpu.engine.datatable import decode
from pinot_tpu.engine.reduce import finalize, merge_intermediates
from pinot_tpu.engine.result import ExecutionStats, IntermediateResult
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.optimizer import optimize_query
from pinot_tpu.transport.grpc_transport import QueryRouterChannel, make_instance_request

log = logging.getLogger("pinot_tpu.broker")


class QueryQuotaManager:
    """Per-table QPS token bucket
    (queryquota/HelixExternalViewBasedQueryQuotaManager analog). Rates come
    from TableConfig.quota.max_queries_per_second; the bucket holds up to
    one second of burst. Enforced per broker — the reference divides the
    table quota by the live-broker count, which a deployment can mirror by
    setting the per-table rate accordingly."""

    def __init__(self, registry):
        self.registry = registry
        self._buckets: dict = {}  # raw table -> [tokens, last_ts, rate]
        self._lock = threading.Lock()
        # rate lookups memoized per registry routing generation: config
        # changes ride the tables section (which bumps the generation), so
        # the memo is exact — and the steady-state query path stops paying
        # three registry reads per query (ISSUE 10 hit-latency budget)
        self._rates: dict = {}
        self._rates_gen = None

    @staticmethod
    def _base_name(table: str) -> str:
        # one bucket per logical table: 'tbl', 'tbl_OFFLINE' and
        # 'tbl_REALTIME' must draw from the SAME quota — the same fold
        # the freshness epochs use (single-sourced there; freshness is
        # the dependency-free module, so the broker delegates to it)
        from pinot_tpu.common import freshness

        return freshness.base_table(table)

    def _rate(self, base: str, gen=None) -> Optional[float]:
        if gen is None:
            gen = self.registry.routing_generation()
        with self._lock:
            if self._rates_gen == gen and base in self._rates:
                return self._rates[base]
        rate = None
        for key in (base, f"{base}_OFFLINE", f"{base}_REALTIME"):
            cfg = self.registry.table_config(key)
            if cfg is not None and \
                    cfg.quota.max_queries_per_second is not None:
                rate = float(cfg.quota.max_queries_per_second)
                break
        with self._lock:
            if self._rates_gen != gen:
                self._rates = {}
                self._rates_gen = gen
            self._rates[base] = rate
        return rate

    def acquire(self, table: str, gen=None) -> bool:
        """True = admit; False = over quota (HTTP 429-shaped rejection).
        ``gen``: the caller's already-read routing generation (the broker
        reads it ONCE per query and shares it across every memo)."""
        base = self._base_name(table)
        rate = self._rate(base, gen)
        if rate is None:
            return True
        now = time.time()
        with self._lock:
            tokens, last, _ = self._buckets.get(base, (rate, now, rate))
            tokens = min(rate, tokens + (now - last) * rate)
            if tokens < 1.0:
                self._buckets[base] = [tokens, now, rate]
                return False
            self._buckets[base] = [tokens - 1.0, now, rate]
            return True


class FailureDetector:
    """Connection-level failure detector: exponential backoff + half-open
    circuit-breaker probing
    (pinot-broker/.../failuredetector/BaseExponentialBackoffRetryFailureDetector).

    State machine per instance:

        HEALTHY --mark_failure--> OPEN (backoff window, no traffic)
        OPEN --window elapses--> HALF_OPEN (ONE probe query admitted)
        HALF_OPEN --probe mark_success--> HEALTHY (backoff forgotten)
        HALF_OPEN --probe mark_failure--> OPEN (backoff doubled)

    The probe IS a live query the router deliberately sends (try_probe
    consumes the slot only when the instance is actually picked); while a
    probe is outstanding, other queries keep routing to healthy replicas
    so a still-down server costs at most one query per backoff window."""

    ST_HEALTHY, ST_OPEN, ST_HALF_OPEN = "healthy", "open", "half_open"
    PROBE_TTL_S = 10.0  # a probe that never resolves frees the slot

    def __init__(self, initial_backoff_s: float = 1.0, max_backoff_s: float = 30.0):
        # id -> [retry_at, backoff, probe_started_or_None]
        self._unhealthy: dict[str, list] = {}
        self._initial = initial_backoff_s
        self._max = max_backoff_s
        self._lock = threading.Lock()

    def mark_failure(self, instance_id: str) -> None:
        with self._lock:
            entry = self._unhealthy.get(instance_id)
            backoff = self._initial / 2 if entry is None else entry[1]
            backoff = min(backoff * 2, self._max)
            self._unhealthy[instance_id] = [time.time() + backoff, backoff, None]

    def mark_success(self, instance_id: str) -> None:
        with self._lock:
            self._unhealthy.pop(instance_id, None)

    def state(self, instance_id: str) -> str:
        with self._lock:
            entry = self._unhealthy.get(instance_id)
            if entry is None:
                return self.ST_HEALTHY
            return self.ST_HALF_OPEN if time.time() >= entry[0] \
                else self.ST_OPEN

    def is_healthy(self, instance_id: str) -> bool:
        """Routable at all: healthy, or half-open (the backoff window
        elapsed — the routed query becomes the recovery probe)."""
        return self.state(instance_id) != self.ST_OPEN

    def try_probe(self, instance_id: str) -> bool:
        """Claim the half-open instance's single probe slot. True → the
        caller's query is the probe (its mark_success/mark_failure
        resolves the state); False → a probe is already in flight (or
        the window hasn't opened) and the caller should route elsewhere."""
        with self._lock:
            entry = self._unhealthy.get(instance_id)
            if entry is None:
                return True  # healthy: not a probe at all
            now = time.time()
            if now < entry[0]:
                return False  # still OPEN
            if entry[2] is not None and now - entry[2] < self.PROBE_TTL_S:
                return False  # probe outstanding
            entry[2] = now
            return True

    def release_probe(self, instance_id: str) -> None:
        """The probe query never actually ran (cancelled before start —
        e.g. its entry settled via a hedge): free the slot so the next
        query can probe instead of waiting out PROBE_TTL_S."""
        with self._lock:
            entry = self._unhealthy.get(instance_id)
            if entry is not None:
                entry[2] = None


class LatencyTracker:
    """Per-server latency view → the hedging trigger delay
    (AdaptiveServerSelector's latency EWMA role). Since ISSUE 7 this
    rides the SHARED metrics histogram machinery
    (``broker.serverLatencyMs.<instance>`` — common/metrics.py
    Histogram): every sample feeds the registry histogram (the
    /metrics p50/p90/p99 exposition and the query log read that one
    lifetime distribution), and the hedge trigger reads the SAME
    log-bucketed histogram over a two-generation rotating window —
    recency matters for hedging: a lifetime distribution with 100k fast
    samples would hold the trigger at the old p90 for tens of thousands
    of queries after a server degrades, hedging every request mid-
    incident. A server with no history hedges after the default —
    better to hedge a touch early than never."""

    METRIC = "serverLatencyMs"
    WINDOW_S = 30.0        # rotate generations at least this often...
    WINDOW_SAMPLES = 512   # ...or after this many samples, whichever first

    def __init__(self, default_s: float = 0.05, registry=None):
        self.default_s = default_s
        if registry is None:
            from pinot_tpu.common.metrics import get_metrics

            registry = get_metrics("broker")
        self.metrics = registry
        # instance -> [current Histogram, previous Histogram, rotated_at]
        self._windows: dict = {}
        self._lock = threading.Lock()

    def record(self, instance_id: str, seconds: float) -> None:
        from pinot_tpu.common.metrics import Histogram

        ms = seconds * 1e3
        self.metrics.time_ms(self.METRIC, ms, tag=instance_id)
        now = time.monotonic()
        with self._lock:
            w = self._windows.get(instance_id)
            if w is None:
                w = self._windows[instance_id] = [Histogram(), None, now]
            cur = w[0]
            if (cur.count >= self.WINDOW_SAMPLES
                    or now - w[2] >= self.WINDOW_S):
                w[1], w[0], w[2] = cur, Histogram(), now
                cur = w[0]
            cur.update(ms)

    def p90_s(self, instance_id: str) -> float:
        from pinot_tpu.common.metrics import Histogram

        with self._lock:
            w = self._windows.get(instance_id)
            if w is None:
                p90_ms = None
            else:
                # merge current + previous generations (shared global
                # bucket bounds make the merge a count add) so a fresh
                # rotation never empties the view
                merged = Histogram()
                for h in (w[0], w[1]):
                    if h is None:
                        continue
                    for i, c in enumerate(h.counts):
                        merged.counts[i] += c
                    merged.count += h.count
                    merged.min_ms = min(merged.min_ms, h.min_ms)
                    merged.max_ms = max(merged.max_ms, h.max_ms)
                p90_ms = merged.quantile(0.9) if merged.count else None
        if p90_ms is None:
            # no windowed samples yet (e.g. restarted tracker): fall back
            # to the lifetime histogram, then the default
            p90_ms = self.metrics.quantile(self.METRIC, 0.9,
                                           tag=instance_id)
        return self.default_s if p90_ms is None else p90_ms / 1e3


class LoadTracker:
    """Decayed per-instance load view feeding the replica-group pick
    (AdaptiveServerSelector's NumInFlightReqSelector + server-latency
    roles, ISSUE 10). Three signals fold into one score:

    - the server's scheduler ``pressure()`` + in-flight depth, piggybacked
      in every DataTable partial (freshest; observed at gather time);
    - the same pressure from the sync-loop heartbeat
      (``InstanceInfo.pressure``) when no queries are flowing;
    - this broker's own outstanding RPC count per instance (instant —
      covers the window before any response could report back).

    Reported observations EWMA-decay toward idle over ``DECAY_S`` so one
    busy moment doesn't blacklist a server; past ``STALE_S`` the score is
    None and the router falls back to rolling-p90 latency."""

    DECAY_S = 10.0
    STALE_S = 30.0
    # heartbeat-staleness cut (ISSUE 14 satellite, single-sourced in
    # cluster/registry.py): an instance that missed 3 heartbeat
    # intervals is presumed crashed/wedged — its last pressure sample
    # must DECAY OUT of scoring entirely, not sit there exponentially
    # decaying toward 0 and making a dead server look like the
    # cluster's idlest pick
    HB_STALE_S = HB_STALE_S

    def __init__(self):
        self._lock = threading.Lock()
        self._obs: dict = {}          # inst -> [ewma score, monotonic ts]
        self._outstanding: dict = {}  # inst -> this broker's in-flight RPCs

    def observe(self, instance_id: str, pressure, inflight=0,
                ts: float = None) -> None:
        import math

        load = max(float(pressure or 0), float(inflight or 0))
        now = time.monotonic() if ts is None else ts
        with self._lock:
            cur = self._obs.get(instance_id)
            if cur is None:
                self._obs[instance_id] = [load, now]
                return
            if cur[1] > now:
                return  # a fresher (piggybacked) observation already landed
            decayed = cur[0] * math.exp(-(now - cur[1]) / self.DECAY_S)
            self._obs[instance_id] = [0.5 * decayed + 0.5 * load, now]

    def expire_if_stale(self, instance_id: str, max_age_s: float) -> None:
        """Drop an instance's observation when the observation ITSELF is
        older than ``max_age_s`` — the heartbeat-stale fix: a crashed
        server stops both heartbeating and piggybacking, so its frozen
        sample would otherwise decay toward 0 and read as 'idle' to the
        least-loaded pick for the full STALE_S window. A fresher
        piggybacked observation (server alive, registry heartbeat merely
        delayed) keeps the entry."""
        now = time.monotonic()
        with self._lock:
            cur = self._obs.get(instance_id)
            if cur is not None and now - cur[1] > max_age_s:
                self._obs.pop(instance_id, None)

    def note_dispatch(self, instance_id: str) -> None:
        with self._lock:
            self._outstanding[instance_id] = \
                self._outstanding.get(instance_id, 0) + 1

    def note_done(self, instance_id: str) -> None:
        with self._lock:
            n = self._outstanding.get(instance_id, 0) - 1
            if n > 0:
                self._outstanding[instance_id] = n
            else:
                self._outstanding.pop(instance_id, None)

    def outstanding(self, instance_id: str) -> int:
        with self._lock:
            return self._outstanding.get(instance_id, 0)

    def score(self, instance_id: str):
        """Decayed reported load + this broker's own outstanding RPCs, or
        None when the last report went stale (router falls back to p90)."""
        import math

        now = time.monotonic()
        with self._lock:
            out = self._outstanding.get(instance_id, 0)
            cur = self._obs.get(instance_id)
            if cur is None or now - cur[1] > self.STALE_S:
                return None
            return cur[0] * math.exp(-(now - cur[1]) / self.DECAY_S) + out


class RoutingManager:
    """table → {instance: [segments]} from the registry's external view
    (BrokerRoutingManager.java:87 + instance selection).

    Since ISSUE 10 this routes at REPLICA-GROUP granularity when the
    controller has built a group map: the derived routing structures
    (lineage/offline-filtered replicas + per-group segment coverage) are
    cached per (table, registry routing generation) — rebuilt only when
    the cluster actually changed, not per query — and each query goes to
    ONE group's instances, picked least-loaded (decayed piggybacked
    pressure, falling back to rolling-p90 latency when pressure is
    stale). Tables without a group map keep the per-segment healthy-first
    round-robin."""

    # groups within this much of the best score share round-robin traffic
    # (a strict argmin would starve an equally-idle group on float noise)
    LOAD_TIE_EPS = 0.5

    def __init__(self, registry: ClusterRegistry,
                 failure_detector: FailureDetector, latency=None):
        self.registry = registry
        self.failures = failure_detector
        self.latency = latency  # LatencyTracker: stale-pressure fallback
        self.loads = LoadTracker()
        # optional memoized instances supplier (the Broker wires its 0.25s
        # _server_instances memo here) so the rate-limited heartbeat-load
        # refresh doesn't pay a registry read — file-backed registries
        # make that real I/O on the query path
        self.instances_fn = None
        self._rr = itertools.count()
        self._snap_lock = threading.Lock()
        self._snapshots: dict = {}  # table -> (routing generation, snapshot)
        self._last_hb_refresh = 0.0
        # serializes pick + reservation: without it a burst of concurrent
        # queries all read the same scores before any outstanding count
        # moves and herd onto one group (observed: 2 servers at 55%
        # utilization each, zero scaling)
        self._pick_lock = threading.Lock()

    def routing_table(self, table: str) -> Optional[dict]:
        routing, _, _ = self.routing_with_replicas(table)
        return routing

    # ---- cached derived routing state ------------------------------------
    def _snapshot(self, table: str, gen=None) -> dict:
        """The expensive derived structures, cached per (table, registry
        routing generation) — ISSUE 10 satellite: a steady cluster costs
        one dict lookup per query instead of a registry walk."""
        if gen is None:
            gen = self.registry.routing_generation()
        with self._snap_lock:
            ent = self._snapshots.get(table)
            if ent is not None and ent[0] == gen:
                return ent[1]
        snap = self._build_snapshot(table)
        with self._snap_lock:
            self._snapshots[table] = (gen, snap)
        return snap

    def _build_snapshot(self, table: str) -> dict:
        # route on the EXTERNAL VIEW (what servers actually serve), not the
        # ideal-state assignment — assignment may race ahead of loading.
        # Segment-lineage filter (reference SegmentLineage +
        # SegmentLineageBasedSegmentPreSelector): an IN_PROGRESS replace
        # routes the FROM set (the TO segments are still loading); a
        # COMPLETED one routes the TO set even while the FROM segments
        # linger in the external view awaiting deletion. This is what makes
        # a minion merge swap atomic from the query path's point of view.
        view, records, lineage = self.registry.routing_snapshot(table)
        excluded = set()
        for entry in lineage.values():
            excluded.update(
                entry["from"] if entry["state"] == "COMPLETED" else entry["to"]
            )
        replicas: dict[str, list] = {}
        for segment, instances in view.items():
            if segment in excluded:
                continue
            rec = records.get(segment)
            if rec is not None and rec.state == SegmentState.OFFLINE:
                continue
            replicas[segment] = list(instances)
        # per-group coverage: group -> {segment: [serving members]}; a
        # group missing ANY segment can't take whole queries and is left
        # out (its instances still serve as per-segment retry replicas)
        groups = self.registry.replica_groups(table)
        group_cover: dict = {}
        if replicas:
            for name, members in groups.items():
                mset = set(members)
                cover: Optional[dict] = {}
                for seg, insts in replicas.items():
                    within = [i for i in insts if i in mset]
                    if not within:
                        cover = None
                        break
                    cover[seg] = within
                if cover is not None:
                    group_cover[name] = cover
        return {"replicas": replicas, "groups": groups,
                "group_cover": group_cover}

    def _refresh_heartbeat_loads(self) -> None:
        """Fold sync-loop heartbeat pressure into the load view (rate
        limited — piggybacked response signals dominate under traffic)."""
        now = time.monotonic()
        if now - self._last_hb_refresh < 0.5:
            return
        self._last_hb_refresh = now
        now_ms = time.time() * 1000
        instances = (self.instances_fn() if self.instances_fn is not None
                     else self.registry.instances(Role.SERVER))
        for i in instances:
            age_s = max(0.0, (now_ms - i.last_heartbeat_ms) / 1e3)
            if age_s <= LoadTracker.HB_STALE_S:
                self.loads.observe(i.instance_id,
                                   getattr(i, "pressure", 0.0),
                                   ts=now - age_s)
            else:
                # no heartbeat within 3 intervals: the instance is
                # presumed down — expire its load sample (unless a
                # fresher piggybacked response observation proves it
                # alive) so the least-loaded pick stops seeing a
                # crashed server as permanently idle
                self.loads.expire_if_stale(i.instance_id,
                                           LoadTracker.HB_STALE_S)

    # ---- query-time selection --------------------------------------------
    def release(self, instances) -> None:
        """Release reservations taken by ``routing_with_replicas(...,
        reserve=True)`` — the broker calls this when the query's scatter
        completes (one release per reserved occurrence)."""
        for inst in instances:
            self.loads.note_done(inst)

    def routing_with_replicas(self, table: str, reserve: bool = False,
                              gen=None) -> tuple:
        """(routing {instance: [segments]},
            replicas {segment: [instances]},
            info {numReplicaGroupsQueried, replicaGroup, loadScore, ...}).

        The replicas map is what the scatter path's failure handling
        consumes: on a transport failure (or a hedge trigger) the broker
        re-sends the failed instance's segment list to another serving
        replica instead of immediately declaring ``partialResult``.

        ``reserve=True`` (the broker's scatter path) atomically bumps the
        picked instances' outstanding counts WITH the pick — concurrent
        arrivals see each other's placements instead of herding onto one
        group — and lists them under ``info["reserved"]``; the caller MUST
        ``release()`` them when the query settles."""
        snap = self._snapshot(table, gen)
        replicas = snap["replicas"]
        if not replicas:
            return None, {}, {}
        offset = next(self._rr)
        info: dict = {"numReplicaGroupsQueried": 0}
        if snap["group_cover"]:
            # registry/heartbeat I/O stays OUTSIDE the pick lock — a
            # file-backed refresh while holding it would serialize every
            # concurrent query's group pick behind the read
            self._refresh_heartbeat_loads()
            with self._pick_lock:
                routing, ginfo = self._route_via_group(snap, offset)
                if routing is not None and reserve:
                    reserved = []
                    for inst, segs in routing.items():
                        self.loads.note_dispatch(inst)
                        reserved.append(inst)
                    ginfo["reserved"] = reserved
            if routing is not None:
                info.update(ginfo)
                return routing, replicas, info
        out: dict[str, list] = {}
        for segment, instances in replicas.items():
            # healthy replicas take traffic; a half-open one (backoff
            # window elapsed) joins the pool and, when the round-robin
            # actually picks it, claims the single probe slot — its query
            # is the recovery probe. If the probe slot is taken, fall
            # back to a healthy replica.
            healthy, half_open = [], []
            for i in instances:
                st = self.failures.state(i)
                if st == FailureDetector.ST_HEALTHY:
                    healthy.append(i)
                elif st == FailureDetector.ST_HALF_OPEN:
                    half_open.append(i)
            pool = healthy + half_open
            if not pool:
                pool, half_open = list(instances), []  # all down: try anyway
            pick = pool[offset % len(pool)]
            if pick in half_open and not self.failures.try_probe(pick):
                pick = healthy[offset % len(healthy)] if healthy else pick
            out.setdefault(pick, []).append(segment)
        if reserve and out:
            reserved = []
            for inst in out:
                self.loads.note_dispatch(inst)
                reserved.append(inst)
            info["reserved"] = reserved
        return out, replicas, info

    def _route_via_group(self, snap: dict, offset: int) -> tuple:
        """Pick ONE replica group for the whole query: least-loaded by
        decayed piggybacked pressure across the group's serving members;
        when any candidate's pressure is stale, every group re-scores on
        rolling-p90 latency (one comparable basis). Near-tie groups share
        round-robin traffic. Returns (routing, info) or (None, {}) when
        no group has a routable replica for every segment (caller falls
        back to per-segment selection). Caller holds ``_pick_lock`` and
        has already refreshed heartbeat loads (outside the lock)."""
        cands = []  # (name, cover, members serving + routable)
        for name in sorted(snap["group_cover"]):
            cover = snap["group_cover"][name]
            members: set = set()
            ok = True
            for seg, within in cover.items():
                routable = [i for i in within if self.failures.is_healthy(i)]
                if not routable:
                    ok = False
                    break
                members.update(routable)
            if ok:
                cands.append((name, cover, sorted(members)))
        if not cands:
            return None, {}
        fresh = {name: [self.loads.score(i) for i in members]
                 for name, _c, members in cands}
        all_fresh = all(s is not None
                        for scores in fresh.values() for s in scores)
        scored = []
        for name, cover, members in cands:
            if all_fresh:
                score = max(fresh[name]) if fresh[name] else 0.0
            else:
                # stale pressure somewhere: rolling-p90 latency (ms) for
                # EVERY group so the comparison stays one-basis
                score = max((self.latency.p90_s(i) for i in members),
                            default=0.0) * 1e3 if self.latency is not None \
                    else 0.0
            scored.append((score, name, cover))
        best = min(s for s, _n, _c in scored)
        pool = [e for e in scored if e[0] <= best + self.LOAD_TIE_EPS]
        score, gname, cover = pool[offset % len(pool)]
        routing: dict = {}
        for seg, within in cover.items():
            routable = [i for i in within if self.failures.is_healthy(i)]
            healthy = [i for i in routable
                       if self.failures.state(i) == FailureDetector.ST_HEALTHY]
            ipool = healthy + [i for i in routable if i not in healthy]
            pick = ipool[offset % len(ipool)]
            if pick not in healthy and not self.failures.try_probe(pick):
                pick = healthy[offset % len(healthy)] if healthy else pick
            routing.setdefault(pick, []).append(seg)
        return routing, {
            "numReplicaGroupsQueried": 1,
            "replicaGroup": gname,
            "loadScore": round(float(score), 3),
            "loadBasis": "pressure" if all_fresh else "latency_p90",
        }


class _NoEngine:
    """Broker-side EXPLAIN stand-in: no local executor or segments —
    filter lines show generic PREDICATE operators (index choice is
    per-segment, server-side)."""

    device = None
    tables: dict = {}


class Broker:
    def __init__(self, registry: ClusterRegistry, broker_id: str = "broker_0",
                 timeout_s: float = 10.0, tls="auto", result_cache=None,
                 admission=None):
        self.registry = registry
        self.broker_id = broker_id
        self.timeout_s = timeout_s
        if tls == "auto":
            # layered config (pinot.tls.*) like the reference's TlsConfig
            from pinot_tpu.common.tls import TlsConfig

            tls = TlsConfig.from_config()
        self.tls = tls
        from pinot_tpu.common.metrics import get_metrics

        self.metrics = get_metrics("broker")
        self.quota = QueryQuotaManager(registry)
        self.failures = FailureDetector()
        # hedge-delay percentiles come from the SHARED metrics histogram
        # (one latency truth — ISSUE 7); the router reads the same p90s
        # as its stale-pressure load fallback (ISSUE 10)
        self.latency = LatencyTracker(registry=self.metrics)
        self.routing = RoutingManager(registry, self.failures,
                                      latency=self.latency)
        self.routing.instances_fn = \
            lambda: self._server_instances().values()
        # structured slow/error query log (broker/querylog.py): JSONL +
        # the /debug/queries ring
        from pinot_tpu.broker.querylog import QueryLogger

        self.querylog = QueryLogger.from_config()
        self.querylog.broker_id = broker_id
        # failure-handling knobs (reference: pinot.broker.* config keys):
        # retry re-sends a failed instance's segments to a replica before
        # declaring partialResult; hedging duplicates a slow request to a
        # second replica after the per-server rolling p90 (SET
        # useHedging=true overrides per query)
        from pinot_tpu.common.config import Configuration

        conf = Configuration()
        self.retry_enabled = conf.get_bool(
            "pinot.broker.failure.retry.enabled", True)
        self.hedging_enabled = conf.get_bool(
            "pinot.broker.hedging.enabled", False)
        # fixed hedge delay override; <= 0 means adaptive (rolling p90)
        self.hedge_delay_s = conf.get_float(
            "pinot.broker.hedging.delay.ms", 0.0) / 1e3
        # broker result cache (ISSUE 10, broker/result_cache.py): OFF by
        # default — partial-result and chaos semantics (tests and the
        # fault bench deliberately repeat queries against faulted
        # replicas) must stay exact unless the operator opts in via
        # pinot.broker.resultcache.enabled / the constructor / SET
        # useResultCache=true
        from pinot_tpu.broker.result_cache import BrokerResultCache

        self.result_cache_default = conf.get_bool(
            "pinot.broker.resultcache.enabled", False) \
            if result_cache is None else bool(result_cache)
        self.result_cache = BrokerResultCache(
            max_entries=int(conf.get_float(
                "pinot.broker.resultcache.max.entries", 512)),
            max_bytes=int(conf.get_float(
                "pinot.broker.resultcache.max.bytes", float(32 << 20))),
            stale_retention_s=conf.get_float(
                "pinot.broker.resultcache.stale.retention.s", 30.0))
        # feedback-driven plan advisor (ISSUE 17, engine/advisor.py):
        # the broker's own memo store — measured stage-1 build rows per
        # multi-stage template feed the distributed-demotion probe and
        # the join-strategy pick where the registry's doc-count estimate
        # used to decide alone. None when pinot.advisor.enabled=false.
        from pinot_tpu.engine.advisor import PlanAdvisor

        self.advisor = PlanAdvisor.from_config(conf)
        # per-tenant priority admission + load shedding (ISSUE 14,
        # broker/admission.py): OFF by default — every existing
        # single-tenant deployment and test keeps its exact semantics
        # unless the operator opts in (pinot.broker.admission.enabled /
        # the constructor). ``admission`` may be a ready controller, a
        # truthy flag (config-built controller), or None (config decides).
        from pinot_tpu.broker.admission import TenantAdmissionController

        if isinstance(admission, TenantAdmissionController):
            self.admission: Optional[TenantAdmissionController] = admission
        elif (admission if admission is not None
              else conf.get_bool("pinot.broker.admission.enabled", False)):
            self.admission = TenantAdmissionController.from_config(conf)
        else:
            self.admission = None
        # bounded-staleness degradation default (SET maxStalenessMs
        # overrides per query): how old a result-cache entry a SHED query
        # may be served instead of a 429. 0 = degrade only when the query
        # explicitly opts in.
        self.shed_max_staleness_ms = conf.get_float(
            "pinot.broker.shed.max.staleness.ms", 0.0)
        # per-table {instance: freshness epoch} observed piggybacked in
        # responses (merged with heartbeat epochs at validation time)
        self._epoch_obs: dict = {}
        self._epoch_lock = threading.Lock()
        # hot-path memos (the <5ms cache-hit budget AND the cluster
        # scaling gate: per-query broker CPU must stay far below per-query
        # server CPU): every registry-derived per-query lookup — table
        # names, physical-table split + hybrid time boundary — is cached
        # under ONE routing generation read per query (exact: all inputs
        # ride routing sections); the heartbeat epoch view keys on a
        # 0.25s clock (within the heartbeat transport delay itself)
        self._gen_memo: dict = {"gen": None}
        self._inst_memo: tuple = (-1.0, {})
        # optional TTL on the per-query routing-generation READ itself
        # (pinot.broker.routing.gen.ttl.ms, default 0 = always fresh):
        # on file-registry clusters the version read is a real syscall
        # round-trip per query; a small TTL trades that for an equally
        # small routing/invalidation delay (the reference's ZK-watch
        # propagation is asynchronous in just the same way)
        self.routing_gen_ttl_s = conf.get_float(
            "pinot.broker.routing.gen.ttl.ms", 0.0) / 1e3
        self._gen_ttl_memo = None  # (gen, monotonic ts)
        self._rc_gauges = []
        if self.result_cache_default:
            # cache-enabled brokers only: the process-global registry keys
            # gauges by (name, broker_id), and a cache-OFF broker sharing
            # this id (the common probe/bench pattern) would overwrite a
            # live cache's gauges — then delete them on its own close().
            # Two cache-ENABLED brokers in one process still need distinct
            # broker ids, like servers do for the PR-7 leak guard.
            for gname, fn in (
                    ("resultCacheEntries",
                     (lambda _c=self.result_cache: len(_c))),
                    ("resultCacheBytes",
                     (lambda _c=self.result_cache: _c.bytes))):
                self.metrics.gauge(gname, fn, tag=self.broker_id)
                self._rc_gauges.append(gname)
        self._channels: dict[str, QueryRouterChannel] = {}
        self._channels_lock = threading.Lock()
        self._request_id = itertools.count(1)
        self._pool = futures.ThreadPoolExecutor(max_workers=16)
        # fleet front door (ISSUE 18): a draining broker answers typed
        # (errorCode 503 / HTTP 503) so rotating clients move to a peer;
        # queries_served feeds the heartbeat-piggybacked QPS counter
        self.draining = False
        self.queries_served = 0

    def drain_response(self) -> dict:
        """Typed refusal a draining broker returns instead of executing:
        clients rotate to a live peer on sight of it (the HTTP surface
        maps it to a 503)."""
        return {
            "resultTable": None, "numDocsScanned": 0, "timeUsedMs": 0.0,
            "brokerDraining": True, "brokerId": self.broker_id,
            "exceptions": [{
                "errorCode": 503,
                "message": f"broker {self.broker_id} is draining",
            }],
        }

    def close(self) -> None:
        for gname in self._rc_gauges:
            self.metrics.remove_gauge(gname, tag=self.broker_id)
        self._rc_gauges = []
        for ch in self._channels.values():
            ch.close()
        self._pool.shutdown(wait=False)

    def _routing_gen(self) -> int:
        """The per-query routing-generation read, optionally TTL-memoized
        (see routing_gen_ttl_s). TTL 0 reads the registry every query."""
        ttl = self.routing_gen_ttl_s
        if ttl <= 0:
            return self.registry.routing_generation()
        now = time.monotonic()
        memo = self._gen_ttl_memo
        if memo is not None and now - memo[1] < ttl:
            return memo[0]
        gen = self.registry.routing_generation()
        self._gen_ttl_memo = (gen, now)
        return gen

    def _note_abandoned(self, fut, inst: str) -> None:
        """A straggler attempt resolved AFTER its entry settled (hedge
        loser, cancelled-too-late retry): its outcome still feeds the
        failure detector — a blackholed replica must not stay HEALTHY
        just because a hedge won every race."""
        from pinot_tpu.engine.datatable import (
            ServerQueryError,
            ServerShuttingDown,
        )

        try:
            exc = fut.exception()
        except futures.CancelledError:
            return
        # ServerShuttingDown is a ServerQueryError on the wire but a
        # FAILURE to the detector (same treatment harvest gives it): a
        # draining server must stay backed off, not bounce back healthy
        if exc is None or (isinstance(exc, ServerQueryError)
                           and not isinstance(exc, ServerShuttingDown)):
            self.failures.mark_success(inst)
        else:
            self.failures.mark_failure(inst)

    def _server_instances(self) -> dict:
        """{instance id: InstanceInfo} for servers, memoized 0.25s — the
        scatter path's endpoint lookups and the result cache's heartbeat
        epoch view share one instances read per tick instead of one per
        query. A restarted server's stale endpoint surfaces as a transport
        failure inside the window; the replica retry path absorbs it."""
        now = time.monotonic()
        ts, info = self._inst_memo
        if now - ts > 0.25:
            info = {i.instance_id: i
                    for i in self.registry.instances(Role.SERVER)}
            self._inst_memo = (now, info)
        return info

    def _channel(self, instance_id: str) -> Optional[QueryRouterChannel]:
        info = self._server_instances().get(instance_id)
        if info is None:
            return None
        with self._channels_lock:  # pool threads race per-instance channels
            ch = self._channels.get(instance_id)
            if ch is None or ch.endpoint != info.endpoint:
                if ch is not None:
                    ch.close()
                ch = QueryRouterChannel(info.endpoint, tls=self.tls)
                self._channels[instance_id] = ch
            return ch

    # ---- per-generation registry view ------------------------------------
    def _gen_view(self, gen=None) -> dict:
        """The per-query registry lookups, memoized per routing
        generation. One generation read (``gen=None``) — or zero, when the
        caller already holds it — replaces the table-name walk and the
        per-table physical split on every query of a steady cluster."""
        if gen is None:
            gen = self.registry.routing_generation()
        view = self._gen_memo
        if view.get("gen") != gen:
            view = {"gen": gen, "tables": set(self.registry.tables()),
                    "phys": {}}
            self._gen_memo = view
        return view

    def _tables_set(self, gen=None) -> set:
        return self._gen_view(gen)["tables"]

    def _hb_epochs(self) -> dict:
        """{logical table: {instance: epoch}} from server heartbeats —
        rides the shared 0.25s instances memo, so the added staleness
        window is the same order as the heartbeat transport delay (sync
        tick) it rides on."""
        out: dict = {}
        for i in self._server_instances().values():
            for base, ep in (getattr(i, "table_epochs", None)
                             or {}).items():
                if ep:
                    out.setdefault(base, {})[i.instance_id] = int(ep)
        return out

    # piggybacked epoch observations not corroborated by a heartbeat
    # expire after this window: a restarted server (fresh process, no
    # epochs yet) stops heartbeating the table, and its old ratcheted
    # observation must not keep pre-restart cache entries valid forever
    EPOCH_OBS_TTL_S = 10.0

    def _epoch_view(self, raw_table: str) -> dict:
        """{instance: freshness epoch} for the logical table: live server
        heartbeat epochs merged with (possibly fresher) piggybacked
        response reports — the staleness contract a cached entry is
        validated against on every hit."""
        from pinot_tpu.common import freshness

        base = freshness.base_table(raw_table)
        view = dict(self._hb_epochs().get(base, {}))
        now = time.monotonic()
        with self._epoch_lock:
            for inst, (ep, seen) in self._epoch_obs.get(base, {}).items():
                # nonzero only: an epoch-0 (never-mutated) observation is
                # restart-stable — post-restart state is identical, and
                # segment-set changes ride the routing generation — and
                # cache hits don't scatter, so expiring it would force a
                # spurious refill miss every TTL on immutable tables
                if ep and inst not in view \
                        and now - seen > self.EPOCH_OBS_TTL_S:
                    continue
                if ep > view.get(inst, -1):
                    view[inst] = ep
        return view

    def _note_epoch(self, physical_table: str, instance_id: str,
                    epoch: int) -> None:
        if epoch is None or epoch < 0:
            return
        from pinot_tpu.common import freshness

        base = freshness.base_table(physical_table)
        with self._epoch_lock:
            per = self._epoch_obs.setdefault(base, {})
            # last-write-wins, no ratchet: the server is authoritative for
            # its own epoch, and a LOWER value is how a restarted process
            # (fresh counter) surfaces under traffic — ratcheting past it
            # would keep pre-restart cache entries validating forever. An
            # out-of-order older response regressing the view briefly just
            # invalidates an entry spuriously (conservative, self-heals on
            # the next response)
            per[instance_id] = (epoch, time.monotonic())

    def _result_cache_key(self, q, for_explain: bool = False,
                          precomputed=None):
        """Cache key for this query, or None when the query must not ride
        the cache (disabled, traced, chaos-armed, or explicitly opted
        out). ``for_explain`` keys the underlying query of an EXPLAIN so
        the plan can render CACHED_RESULT. ``precomputed``: a key the
        caller already derived via ``key_for`` (the admission path's
        adm_key) — reused so the template walk + digest run once per
        query."""
        opts = q.options_ci()
        # quoted SET values arrive as strings: 'false' must opt OUT, not
        # truthy-enable a stale-tolerant path the user refused — the
        # shared helper folds them uniformly (common/options.py)
        enabled = bool_option(opts, "useresultcache",
                              self.result_cache_default)
        if not enabled or faults.ACTIVE:
            # chaos harness armed: fault tests repeat queries on purpose
            # and must observe every injected failure, not a cached hit
            return None
        if q.explain and not for_explain:
            return None
        if opts.get("trace") or opts.get("faultinject"):
            return None
        if precomputed is not None:
            return precomputed
        from pinot_tpu.broker.querylog import template_key

        return self.result_cache.key_for(q, template_key(q))

    def _max_load_score(self):
        """Broker-wide overload signal: the worst decayed LoadTracker
        score across known servers (None when every score is stale — the
        shed ladder then stands down rather than shedding blind)."""
        scores = (self.routing.loads.score(i)
                  for i in self._server_instances())
        vals = [s for s in scores if s is not None]
        return max(vals) if vals else None

    def _shed_response(self, sql: str, q, decision, adm_key,
                       t0: float) -> dict:
        """Load-shedding with graceful degradation (ISSUE 14): a query
        admission refused is first offered a BOUNDED-STALENESS result-
        cache read — ``SET maxStalenessMs`` (or the broker's configured
        default) caps how old an entry may serve; the response is flagged
        ``servedStale`` with the entry's age and a typed
        ``sheddingReason``, never silently degraded. Only when no
        eligible entry exists does the broker answer 429 — with
        ``retryAfterSeconds`` computed from the TENANT's actual bucket
        refill time (capped at 5 s), and the tenant + priority class in
        the response and the query log."""
        self.metrics.count("queriesShed")
        opts = q.options_ci()
        max_stale_ms = opts.get("maxstalenessms")
        if max_stale_ms is None:
            max_stale_ms = self.shed_max_staleness_ms
        try:
            max_stale_ms = float(max_stale_ms)
        except (TypeError, ValueError):
            max_stale_ms = 0.0
        if max_stale_ms > 0 and adm_key is not None:
            stale, age_s = self.result_cache.get_stale(
                adm_key, max_stale_ms / 1e3)
            if stale is not None:
                self.metrics.count("queriesShedStaleServed")
                self.admission.num_shed_stale_served += 1
                resp = dict(stale)
                resp.pop("__epochView__", None)
                resp["servedStale"] = True
                resp["staleAgeMs"] = round(age_s * 1e3, 1)
                resp["sheddingReason"] = decision.reason
                resp["tenant"] = decision.tenant
                resp["priorityClass"] = decision.priority
                resp["requestId"] = next(self._request_id)
                resp["timeUsedMs"] = round((time.time() - t0) * 1000, 3)
                self.metrics.time_ms("query", resp["timeUsedMs"])
                return self._log_query(sql, q, resp, t0)
        self.metrics.count("queriesAdmissionRejected")
        retry_s = max(0.05, float(decision.retry_after_s))
        return self._log_query(sql, q, {
            "exceptions": [{
                "errorCode": 429,
                "message": f"admission rejected for tenant "
                           f"{decision.tenant!r} "
                           f"(priority {decision.priority}): "
                           f"{decision.reason}"}],
            "retryAfterSeconds": round(retry_s, 3),
            "sheddingReason": decision.reason,
            "tenant": decision.tenant,
            "priorityClass": decision.priority,
        }, t0)

    # ---- request handling ------------------------------------------------
    def execute(self, sql: str, principal: str = None) -> dict:
        """HTTP POST /query/sql equivalent (PinotClientRequest →
        BaseBrokerRequestHandler.handleRequest). ``principal``: the
        authenticated identity (broker HTTP basic auth) — the tenant key
        for priority admission when enabled (ISSUE 14); queries may also
        self-identify via ``SET workloadName``."""
        from pinot_tpu.common import trace

        t0 = time.time()
        self.metrics.count("queries")
        if self.draining:
            # fleet drain (ISSUE 18): typed refusal, never a hang or a
            # half-executed query — rotating clients retry a live peer
            self.metrics.count("queriesRefusedDraining")
            return self.drain_response()
        if sql.strip().rstrip(";").strip().upper() == "SHOW TABLES":
            # catalog surface for standards clients (the JDBC driver's
            # DatabaseMetaData.getTables role, backed by the controller's
            # /tables REST in the reference): logical names, type suffix
            # stripped, hybrid halves collapsed
            names = sorted({
                t[: -len(suffix)] if t.endswith(suffix) else t
                for t in self.registry.tables()
                for suffix in ("_OFFLINE", "_REALTIME")
                if t.endswith(suffix)
            } | {t for t in self.registry.tables()
                 if not t.endswith(("_OFFLINE", "_REALTIME"))})
            return {
                "resultTable": {
                    "dataSchema": {"columnNames": ["tableName"],
                                   "columnDataTypes": ["STRING"]},
                    "rows": [[n] for n in names],
                },
                "exceptions": [],
                "numDocsScanned": 0,
                "totalDocs": 0,
                "timeUsedMs": round((time.time() - t0) * 1000, 3),
            }
        tracer = None
        q = None
        try:
            from pinot_tpu.sql.compiler import compile_select, is_multistage
            from pinot_tpu.sql.parser import parse_sql

            stmt = parse_sql(sql)
            if is_multistage(stmt):
                # join / window query: two-stage execution — stage-1 leaf
                # scans ride the ordinary scatter-gather below (recursive
                # single-stage queries, each debiting admission/quota as
                # its own first-class query), stage 2 runs broker-local
                return self._execute_multistage(stmt, sql, t0,
                                                principal=principal)
            q = optimize_query(compile_select(stmt))
            # ONE routing-generation read serves this whole query: quota
            # rate memo, table-name fold, physical split, routing snapshot
            # and the result cache all share it
            gen = self._routing_gen()
            q = self._resolve_table_case(q, gen)
            if q.explain and getattr(q, "analyze", False):
                # EXPLAIN ANALYZE (ISSUE 11): execute the underlying
                # query through the FULL scatter path (traced, so the
                # per-server phase ladder and roofline records fill),
                # then render the plan annotated with the actuals
                return self._explain_analyze_single(sql, q)
            if q.explain:
                from pinot_tpu.engine.explain import explain_plan

                plan = explain_plan(_NoEngine(), q)
                ck = self._result_cache_key(q, for_explain=True)
                if ck is not None and self.result_cache.peek_fresh(
                        ck, self._epoch_view(q.table_name), gen):
                    # the very next execution of this query would serve
                    # from the broker result cache — surface it on top
                    rows = plan["resultTable"]["rows"]
                    lines = ["CACHED_RESULT(broker result cache: "
                             "fresh entry)"] + [r[0] for r in rows]
                    plan["resultTable"]["rows"] = [
                        [ln, i, i - 1] for i, ln in enumerate(lines)]
                return plan
            # tenant + priority resolution (ISSUE 14): the authenticated
            # principal wins, then SET workloadName, then the shared
            # 'default' bucket; ``adm_key`` is the literal digest the
            # sub-RTT queue-jump memo and the bounded-staleness shed
            # path key on (computed regardless of the fresh cache's
            # trace/chaos gating — shedding must find entries even when
            # the FRESH path is opted out)
            tenant = pclass = None
            adm_key = None
            if self.admission is not None:
                from pinot_tpu.broker.querylog import template_key

                tenant, pclass = self.admission.resolve(q, principal)
                adm_key = self.result_cache.key_for(q, template_key(q))
            cache_key = self._result_cache_key(q, precomputed=adm_key)
            cache_gen = None
            cache_view = None
            if cache_key is not None:
                # the generation and epoch view are captured BEFORE the
                # scatter: a cluster change mid-flight stores an entry
                # that can never validate (conservative), not one that
                # serves stale
                cache_gen = gen
                cache_view = self._epoch_view(q.table_name)
                cached = self.result_cache.get(
                    cache_key, cache_view, cache_gen)
                if cached is not None:
                    # queue jumping (ISSUE 14): a fresh result-cache hit
                    # costs no server work, so it bypasses BOTH tenant
                    # admission and the table quota — sub-RTT serving
                    # never waits behind (or is starved by) cold scans —
                    # and marks this literal digest sub-RTT so its
                    # repeats admit at a fraction of a token
                    self.metrics.count("resultCacheHits")
                    resp = dict(cached)
                    resp["resultCacheHit"] = True
                    if self.admission is not None:
                        self.admission.note_sub_rtt(adm_key)
                        resp["tenant"] = tenant
                        resp["priorityClass"] = pclass
                    resp["requestId"] = next(self._request_id)
                    resp["timeUsedMs"] = round((time.time() - t0) * 1000, 3)
                    self.metrics.time_ms("query", resp["timeUsedMs"])
                    return self._log_query(sql, q, resp, t0)
                self.metrics.count("resultCacheMisses")
            if self.admission is not None:
                decision = self.admission.try_admit(
                    tenant, pclass, load_score=self._max_load_score(),
                    sub_rtt=self.admission.is_sub_rtt(adm_key))
                if not decision.admitted:
                    # degrade before rejecting: bounded-staleness cache
                    # read (SET maxStalenessMs), else a typed 429 whose
                    # Retry-After is THIS tenant's actual refill time
                    return self._shed_response(sql, q, decision,
                                               adm_key, t0)
            if not self.quota.acquire(q.table_name, gen):
                # quota rejection before any fan-out
                # (BaseBrokerRequestHandler's quota check placement)
                self.metrics.count("queriesQuotaExceeded")
                return self._log_query(sql, q, {"exceptions": [{
                    "errorCode": 429,
                    "message": f"query quota exceeded for table "
                               f"{q.table_name!r}"}],
                    # pacing hint for clients (Retry-After analog): the
                    # token bucket refills within about a second
                    "retryAfterSeconds": 0.5}, t0)
            if q.options_ci().get("trace"):
                tracer = trace.start_trace()
            resp = self._scatter_gather(q, sql, gen, tenant=tenant,
                                        priority=pclass)
            if tracer is not None:
                resp.setdefault("traceInfo", {})["broker"] = tracer.to_json()
                if tracer.trace_id:
                    resp["traceId"] = tracer.trace_id
        except Exception as e:  # noqa: BLE001 — in-band errors like the reference
            self.metrics.count("queryErrors")
            return self._log_query(sql, q, {"exceptions": [{
                "errorCode": 450,
                "message": f"{type(e).__name__}: {e}"}]}, t0)
        finally:
            if tracer is not None:
                trace.end_trace()
        own_epochs = resp.pop("__epochView__", None)
        resp["timeUsedMs"] = round((time.time() - t0) * 1000, 3)
        self.metrics.time_ms("query", resp["timeUsedMs"])
        if self.admission is not None:
            resp["tenant"] = tenant
            resp["priorityClass"] = pclass
            if resp.get("partialsCacheHit"):
                # a server answered from its device partials cache: this
                # literal digest is sub-RTT — its repeats queue-jump
                self.admission.note_sub_rtt(adm_key)
        if cache_key is not None:
            resp["resultCacheHit"] = False
            if not resp.get("exceptions") and not resp.get("partialResult"):
                # only COMPLETE successes cache. The recorded view is the
                # PRE-scatter view overlaid with the epochs THIS query's
                # own partials piggybacked — never the global observation
                # state at put time, which may already hold epochs newer
                # than the data these rows reflect (a concurrent ingest +
                # query landing mid-gather would stamp stale rows fresh)
                put_view = dict(cache_view or {})
                put_view.update(own_epochs or {})
                self.result_cache.put(cache_key, resp, put_view, cache_gen)
        return self._log_query(sql, q, resp, t0)

    # ---- streaming result delivery (ISSUE 18) ----------------------------
    # One chunked front door for every query shape: eligible single-stage
    # selections ride the per-segment server DataTable streams end to end
    # (server → broker → client with bounded broker RSS — each block is
    # decoded, reduced, trimmed, yielded, freed), everything else
    # (aggregations, ORDER BY, joins, SHOW TABLES, traced queries) falls
    # back to the buffered execute() re-chunked, so a client can use the
    # cursor API unconditionally. Chunk protocol:
    #   {"type": "schema", "columnNames": [...], "columnDataTypes": [...]}
    #   {"type": "rows", "rows": [[...], ...]}     (0..N chunks)
    #   {"type": "final", ...response stats/exceptions, no resultTable}
    # Rows are converted per block by the SAME reduce/finalize code the
    # buffered path uses (offset/limit neutralized per block, applied
    # broker-globally), so the concatenated chunks are bit-identical to
    # the buffered resultTable rows.

    STREAM_CHUNK_ROWS = 50_000

    def execute_stream(self, sql: str, principal: str = None,
                       chunk_rows: int = 0):
        """Generator form of execute(): yields schema / rows / final
        chunks (see the protocol above). The broker never materializes
        the full result — RSS is bounded by one server block plus one
        yielded chunk."""
        t0 = time.time()
        chunk_rows = int(chunk_rows) or self.STREAM_CHUNK_ROWS
        if self.draining:
            self.metrics.count("queries")
            self.metrics.count("queriesRefusedDraining")
            yield {"type": "final", **self.drain_response()}
            return
        q = None
        eligible = False
        try:
            from pinot_tpu.sql.compiler import compile_select, is_multistage
            from pinot_tpu.sql.parser import parse_sql

            if sql.strip().rstrip(";").strip().upper() != "SHOW TABLES":
                stmt = parse_sql(sql)
                if not is_multistage(stmt):
                    q = optimize_query(compile_select(stmt))
                    opts = q.options_ci()
                    # same eligibility rule as the unary path's
                    # server-stream branch: any-subset selection
                    # semantics, untraced, not opted out
                    eligible = (not q.explain and not q.aggregations()
                                and not q.distinct and not q.order_by
                                and opts.get("streaming") is not False
                                and not opts.get("trace"))
        except Exception as e:  # noqa: BLE001 — in-band, like execute()
            self.metrics.count("queries")
            self.metrics.count("queryErrors")
            yield {"type": "final", **self._log_query(sql, None, {
                "exceptions": [{"errorCode": 450,
                                "message": f"{type(e).__name__}: {e}"}],
            }, t0)}
            return
        if not eligible:
            # buffered fallback (execute() counts the query + logs it)
            resp = self.execute(sql, principal=principal)
            yield from self._chunk_buffered(resp, chunk_rows)
            return
        self.metrics.count("queries")
        yield from self._stream_single_stage(q, sql, principal, t0,
                                             chunk_rows)

    @staticmethod
    def _chunk_buffered(resp: dict, chunk_rows: int):
        """Re-chunk a buffered response onto the streaming protocol."""
        rt = resp.get("resultTable")
        if rt:
            schema = rt.get("dataSchema") or {}
            yield {"type": "schema",
                   "columnNames": schema.get("columnNames") or [],
                   "columnDataTypes": schema.get("columnDataTypes") or []}
            rows = rt.get("rows") or []
            for i in range(0, len(rows), chunk_rows):
                yield {"type": "rows", "rows": rows[i:i + chunk_rows]}
        final = {k: v for k, v in resp.items() if k != "resultTable"}
        final["type"] = "final"
        yield final

    def _stream_single_stage(self, q: QueryContext, sql: str,
                             principal: str, t0: float, chunk_rows: int):
        """Admission/quota bracket for the streaming scatter — the same
        decisions as execute(), but a rejection is a typed final chunk
        (no stale-cache degrade: streaming skips the result cache)."""
        gen = self._routing_gen()
        try:
            q = self._resolve_table_case(q, gen)
            tenant = pclass = None
            if self.admission is not None:
                from pinot_tpu.broker.querylog import template_key

                tenant, pclass = self.admission.resolve(q, principal)
                adm_key = self.result_cache.key_for(q, template_key(q))
                decision = self.admission.try_admit(
                    tenant, pclass, load_score=self._max_load_score(),
                    sub_rtt=self.admission.is_sub_rtt(adm_key))
                if not decision.admitted:
                    self.metrics.count("queriesAdmissionRejected")
                    retry_s = max(0.05, float(decision.retry_after_s))
                    yield {"type": "final", **self._log_query(sql, q, {
                        "exceptions": [{
                            "errorCode": 429,
                            "message": f"admission rejected for tenant "
                                       f"{decision.tenant!r} (priority "
                                       f"{decision.priority}): "
                                       f"{decision.reason}"}],
                        "retryAfterSeconds": round(retry_s, 3),
                        "sheddingReason": decision.reason,
                        "tenant": decision.tenant,
                        "priorityClass": decision.priority,
                    }, t0)}
                    return
            if not self.quota.acquire(q.table_name, gen):
                self.metrics.count("queriesQuotaExceeded")
                yield {"type": "final", **self._log_query(sql, q, {
                    "exceptions": [{
                        "errorCode": 429,
                        "message": f"query quota exceeded for table "
                                   f"{q.table_name!r}"}],
                    "retryAfterSeconds": 0.5}, t0)}
                return
        except Exception as e:  # noqa: BLE001
            self.metrics.count("queryErrors")
            yield {"type": "final", **self._log_query(sql, q, {
                "exceptions": [{"errorCode": 450,
                                "message": f"{type(e).__name__}: {e}"}],
            }, t0)}
            return
        reserved: list = []
        try:
            yield from self._stream_scatter(q, sql, reserved, gen, t0,
                                            tenant, pclass, chunk_rows)
        finally:
            self.routing.release(reserved)

    def _stream_scatter(self, q: QueryContext, sql: str, reserved: list,
                        gen, t0: float, tenant, priority, chunk_rows: int):
        """The streaming scatter body: route like the unary path, then
        walk the scatter entries SEQUENTIALLY, turning each server's
        per-segment DataTable blocks into row chunks as they arrive.
        Sequential order is what makes the output bit-identical to the
        buffered reduce (results concatenate in the same entry/block
        order) AND what bounds RSS to one in-flight block."""
        from pinot_tpu.common.trace import span

        q = self._expand_star(q)
        request_id = next(self._request_id)
        trace_id = f"{self.broker_id}-{request_id}"
        opts = q.options_ci()
        timeout_s = self.timeout_s
        if "timeoutms" in opts:
            timeout_s = max(0.001, float(opts["timeoutms"]) / 1000.0)
        deadline = Deadline(timeout_s)
        # per-block finalize runs with offset/limit neutralized — the
        # broker applies the query's real offset/limit globally below
        q_all = dataclasses.replace(q, offset=0, limit=1 << 62)

        exceptions: list = []
        totals = {"numDocsScanned": 0, "totalDocs": 0,
                  "numSegmentsQueried": 0, "numSegmentsProcessed": 0,
                  "numSegmentsMatched": 0, "numSegmentsPrunedByServer": 0}
        n_servers: set = set()
        responded: set = set()
        sent_schema = False
        skip = q.offset
        remaining = q.limit
        rows_streamed = 0

        scatter = []  # (instance, physical, segments, time_filter)
        replicas: dict = {}
        fully_pruned = []
        try:
            with span("broker.route"):
                for physical, tf in self._physical_tables(q.table_name,
                                                          gen):
                    routing, reps, rinfo = \
                        self.routing.routing_with_replicas(
                            physical, reserve=True, gen=gen)
                    reserved.extend(rinfo.get("reserved", ()))
                    if not routing:
                        continue
                    for seg, insts in reps.items():
                        replicas[(physical, seg)] = insts
                    records, time_col = self._pruning_inputs(physical, gen)
                    for inst, segs in routing.items():
                        kept, _pruned, _bv = prune_segments(
                            q, records, segs, time_col, tf)
                        if kept:
                            scatter.append((inst, physical, kept, tf))
                        else:
                            fully_pruned.append(
                                (inst, physical, segs[:1], tf))
            if not scatter and fully_pruned:
                scatter.append(fully_pruned[0])
            if not scatter:
                raise KeyError(
                    f"no routing entry for table {q.table_name!r}")

            def open_stream(inst, phys, segs, tf, attempt):
                if faults.ACTIVE:
                    faults.inject("transport.submit", target=inst,
                                  bound_ms=deadline.remaining_ms())
                ch = self._channel(inst)
                if ch is None:
                    raise ConnectionError(
                        f"server {inst} not registered")
                budget_ms = max(1.0, deadline.remaining_ms())
                payload = make_instance_request(
                    sql, segs, request_id, self.broker_id, table=phys,
                    time_filter=tf, timeout_ms=budget_ms, trace=False,
                    trace_id=trace_id, attempt=attempt,
                    workload=tenant, priority=priority)
                return ch.submit_streaming(payload, budget_ms / 1e3 + 0.25)

            with span("broker.stream"), self.metrics.timed("scatterMs"):
                for inst, phys, segs, tf in scatter:
                    if remaining <= 0 or deadline.expired():
                        break
                    attempt, kind = inst, "primary"
                    entry_tried = {inst}
                    entry_yielded = False
                    while True:
                        n_servers.add(attempt)
                        stream = None
                        try:
                            stream = open_stream(attempt, phys, segs, tf,
                                                 kind)
                            for block in stream:
                                r = decode(bytes(block))
                                st = r.stats
                                if st.server_pressure >= 0 or \
                                        st.server_inflight >= 0:
                                    self.routing.loads.observe(
                                        attempt,
                                        max(0, st.server_pressure),
                                        max(0, st.server_inflight))
                                self._note_epoch(phys, attempt,
                                                 st.table_epoch)
                                totals["numDocsScanned"] += \
                                    st.num_docs_scanned
                                totals["totalDocs"] += st.total_docs
                                totals["numSegmentsQueried"] += \
                                    st.num_segments_queried
                                totals["numSegmentsProcessed"] += \
                                    st.num_segments_processed
                                totals["numSegmentsMatched"] += \
                                    st.num_segments_matched
                                totals["numSegmentsPrunedByServer"] += \
                                    st.num_segments_pruned
                                if not r.rows:
                                    continue
                                table = finalize(
                                    q_all, merge_intermediates(
                                        q_all, [r]))
                                if not sent_schema:
                                    yield {"type": "schema",
                                           "columnNames":
                                               table.column_names,
                                           "columnDataTypes":
                                               table.column_types}
                                    sent_schema = True
                                rows = table.rows
                                if skip:
                                    if skip >= len(rows):
                                        skip -= len(rows)
                                        rows = []
                                    else:
                                        rows = rows[skip:]
                                        skip = 0
                                if rows:
                                    entry_yielded = True
                                    if len(rows) > remaining:
                                        rows = rows[:remaining]
                                    remaining -= len(rows)
                                    rows_streamed += len(rows)
                                    for i in range(0, len(rows),
                                                   chunk_rows):
                                        yield {"type": "rows",
                                               "rows": [list(x) for x in
                                                        rows[i:i +
                                                             chunk_rows]]}
                                # drop this block's row materializations
                                # NOW — locals otherwise pin the previous
                                # block's tuples/arrays until the next
                                # loop iteration rebinds them, doubling
                                # the streaming high-water mark
                                rows = table = r = None
                                if remaining <= 0:
                                    stream.cancel()
                                    break
                                if deadline.expired():
                                    stream.cancel()
                                    exceptions.append({
                                        "errorCode": 250,
                                        "message":
                                            f"QUERY_TIMEOUT: {attempt} "
                                            f"stream cut at the "
                                            f"{timeout_s * 1e3:.0f}ms "
                                            f"query budget"})
                                    break
                            responded.add(attempt)
                            self.failures.mark_success(attempt)
                            break  # entry done
                        except Exception as exc:  # noqa: BLE001
                            from pinot_tpu.engine.datatable import (
                                NoSegmentsHosted,
                                QueryTimeoutError,
                                ServerQueryError,
                            )

                            if isinstance(exc, NoSegmentsHosted):
                                self.failures.mark_success(attempt)
                                responded.add(attempt)
                                break
                            if isinstance(exc, QueryTimeoutError):
                                self.failures.mark_success(attempt)
                                exceptions.append({
                                    "errorCode": 250,
                                    "message": f"{attempt}: {exc}"})
                                break
                            if isinstance(exc, ServerQueryError):
                                # query-level error: in-band, no retry
                                self.failures.mark_success(attempt)
                                yield {"type": "final",
                                       **self._log_query(sql, q, {
                                           "exceptions": [{
                                               "errorCode": 200,
                                               "message":
                                                   f"{attempt}: {exc}"}],
                                       }, t0)}
                                return
                            self.failures.mark_failure(attempt)
                            # retry on a whole-entry replica ONLY while
                            # none of this entry's rows were yielded —
                            # a mid-entry replay would duplicate rows
                            alt = None
                            if self.retry_enabled and not entry_yielded \
                                    and not deadline.expired():
                                cands = None
                                for seg in segs:
                                    insts = set(replicas.get(
                                        (phys, seg), ()))
                                    cands = insts if cands is None \
                                        else cands & insts
                                pool = [i for i in (cands or ())
                                        if i not in entry_tried]
                                healthy = [i for i in pool
                                           if self.failures.is_healthy(i)]
                                alt = (healthy or pool or [None])[0]
                            if alt is None:
                                exceptions.append({
                                    "errorCode": 427,
                                    "message": f"SERVER_NOT_RESPONDING: "
                                               f"{attempt}: {exc}"})
                                break
                            self.metrics.count("retriedRequests")
                            entry_tried.add(alt)
                            attempt, kind = alt, "retry"
                    if exceptions and exceptions[-1].get(
                            "errorCode") == 250:
                        break  # budget gone: no further entries
        except Exception as e:  # noqa: BLE001 — routing/compile errors
            self.metrics.count("queryErrors")
            yield {"type": "final", **self._log_query(sql, q, {
                "exceptions": [{"errorCode": 450,
                                "message": f"{type(e).__name__}: {e}"}],
            }, t0)}
            return
        if not sent_schema and not exceptions:
            # zero matching rows anywhere: still surface the shape
            # (column names from the query; types unknown → STRING)
            yield {"type": "schema",
                   "columnNames": [
                       q.column_name(i)
                       for i in range(len(q.select_expressions))],
                   "columnDataTypes":
                       ["STRING"] * len(q.select_expressions)}
        if any(x["errorCode"] == 250 for x in exceptions):
            self.metrics.count("queryTimeouts")
        resp = {
            "exceptions": exceptions,
            "partialResult": bool(exceptions),
            "streamed": True,
            "numRowsStreamed": rows_streamed,
            "numServersQueried": len(n_servers),
            "numServersResponded": len(responded),
            "requestId": request_id,
            "traceId": trace_id,
            "timeUsedMs": round((time.time() - t0) * 1000, 3),
        }
        resp.update(totals)
        self.metrics.time_ms("query", resp["timeUsedMs"])
        if self.admission is not None:
            resp["tenant"] = tenant
            resp["priorityClass"] = priority
        yield {"type": "final", **self._log_query(sql, q, resp, t0)}

    def _explain_analyze_single(self, sql: str, q: QueryContext) -> dict:
        """Single-stage EXPLAIN ANALYZE: strip the keyword pair, re-enter
        execute() with tracing forced on (routing / retry / hedging /
        quota / logging all apply to the real run), annotate the static
        plan with the response's actuals. The executed response rides as
        ``analyzedResponse`` — callers verify its rows are bit-identical
        to the plain form."""
        from pinot_tpu.engine.explain import explain_plan

        return self._explain_analyze_via(
            sql, lambda: explain_plan(_NoEngine(), q))

    def _explain_analyze_via(self, sql: str, render_static) -> dict:
        """The shared EA sequence (single-stage AND multistage): strip
        ``EXPLAIN ANALYZE``, re-execute with trace forced on and the
        partials cache bypassed (the kernel must actually RUN to be
        measured; results are bit-identical either way), pass errors
        through verbatim, annotate the static plan from
        ``render_static()``, attach the executed response."""
        from pinot_tpu.engine.explain import annotate_analyze
        from pinot_tpu.sql.parser import strip_explain_analyze

        stripped = strip_explain_analyze(sql)
        if stripped == sql:  # nothing stripped: render the static plan
            return render_static()
        inner = self.execute(
            "SET trace = true; SET usePartialsCache = false; " + stripped)
        if inner.get("exceptions"):
            return inner
        out = annotate_analyze(render_static(), inner)
        out["analyzedResponse"] = inner
        return out

    def _execute_multistage(self, stmt, sql: str, t0: float,
                            principal: str = None) -> dict:
        """Two-stage (join / window) execution at the broker. Stage-1 leaf
        scans are plain single-stage SELECT queries issued through
        ``self.execute`` — so routing, replica retry, hedging, the failure
        detector and per-table quotas all apply to them unchanged — and
        the join + window + stage-2 reduce run broker-local through the
        SAME query2 runner the embedded engine uses. The build side must
        be a broker-routable table (dimension tables replicated across
        servers: the star-schema shape this engine targets)."""
        import numpy as np

        from pinot_tpu.query2.logical import (
            BROADCAST_MAX_BUILD_ROWS,
            _sql_ident,
            compile_plan,
            to_sql,
        )
        from pinot_tpu.query2.runner import (
            MAX_STAGE1_ROWS,
            needed_columns,
            run_plan,
        )

        def _table_keys(table: str):
            """Exact registry keys first, then the same case-insensitive
            fold _resolve_table_case applies to single-stage queries."""
            keys = [table, f"{table}_OFFLINE", f"{table}_REALTIME"]
            names = set(self.registry.tables())
            if not (set(keys) & names):
                low = table.lower()
                for n in names:
                    if n.lower() in (low, f"{low}_offline",
                                     f"{low}_realtime"):
                        keys.append(n)
            return keys

        def _schema_for(table: str):
            for key in _table_keys(table):
                schema = self.registry.table_schema(key)
                if schema is not None:
                    return schema
            return None

        def catalog(table: str):
            schema = _schema_for(table)
            if schema is None:
                raise KeyError(table)
            cfg = None
            for key in _table_keys(table):
                cfg = self.registry.table_config(key)
                if cfg is not None:
                    break
            is_dim = bool(cfg is not None
                          and getattr(cfg, "is_dim_table", False))
            return tuple(schema.column_names()), is_dim

        plan = compile_plan(stmt, catalog)

        # plan-advisor hookup (ISSUE 17): measured build rows from past
        # executions of this template sharpen the demotion probe and the
        # join-strategy pick; SET useAdvisor=false bypasses both
        advisor, adv_key = None, None
        adv_notes: list = []
        if self.advisor is not None:
            from pinot_tpu.engine.advisor import advisor_enabled
            from pinot_tpu.broker.querylog import template_key

            try:
                if advisor_enabled(plan.stage2.options_ci()):
                    advisor = self.advisor
                    adv_key = template_key(plan)
            except Exception:  # noqa: BLE001 — advice is optional
                pass

        # ---- distributed stage-2 demotion probe (ISSUE 16) --------------
        # A fact-fact join whose build side is past the broadcast cap is
        # exactly the shape where the broker-local shuffle stops scaling:
        # every build row funnels through this one process no matter how
        # many servers host the table. Demote it to the server-side
        # mailbox exchange (query2/exchange.py) when the fleet can route
        # it. SET joinStrategy='distributed' forces the path; a forced-
        # but-unroutable plan (hybrid split, unknown table, no live
        # servers) falls through to the broker-local mirror and the
        # response reports the EFFECTIVE strategy. The probe runs BEFORE
        # the EXPLAIN early-return below, so the static plan text renders
        # the EFFECTIVE (post-demotion) strategy in STAGE_BOUNDARY —
        # previously only the response/querylog saw the demotion. The
        # advisor's MEASURED stage-1 build rows (post-pushdown) replace
        # the registry's raw doc-count estimate once converged — a heavy
        # pushdown filter no longer demotes a join whose build side
        # actually arrives small. Quota/admission are not debited on the
        # distributed path: it has no per-table leaf queries, and stage-1
        # cost lands on the servers' own schedulers.
        dist = None
        if len(plan.joins) == 1 and not plan.windows:
            want = plan.strategy == "DISTRIBUTED"
            if not want and plan.strategy == "SHUFFLE" \
                    and not plan.strategy_forced:
                build = plan.joins[0].build
                est = self._estimated_docs(build.table, _table_keys)
                build_docs = est
                if advisor is not None:
                    measured = advisor.measured_build_rows(
                        adv_key, build.alias)
                    if measured is not None:
                        build_docs = measured
                        if (measured > BROADCAST_MAX_BUILD_ROWS) \
                                != (est > BROADCAST_MAX_BUILD_ROWS):
                            adv_notes.append(
                                f"ADVISOR(distributedDemotion="
                                f"{'on' if measured > BROADCAST_MAX_BUILD_ROWS else 'off'}: "
                                f"measured={measured} default={est})")
                want = build_docs > BROADCAST_MAX_BUILD_ROWS
            if want and not plan.explain:
                try:
                    dist = self._distributed_spec(plan, _table_keys,
                                                  _schema_for)
                except Exception:  # noqa: BLE001 — probe must not fail
                    log.exception("distributed routability probe failed; "
                                  "falling back to broker-local join")
                    dist = None
            elif want and plan.explain:
                # EXPLAIN renders the routable outcome without paying
                # the full spec build when the probe fails
                try:
                    dist = self._distributed_spec(plan, _table_keys,
                                                  _schema_for)
                except Exception:  # noqa: BLE001 — display only
                    dist = None
        if dist is not None and plan.strategy != "DISTRIBUTED":
            # demotion mutates the plan so EXPLAIN's STAGE_BOUNDARY, the
            # query log's template_key, and the strategy column all see
            # what actually ran
            plan.strategy = "DISTRIBUTED"
            dist["demoted"] = True

        if plan.explain:
            from pinot_tpu.engine.explain import explain_multistage

            if not getattr(plan, "analyze", False):
                return explain_multistage(None, plan)
            # EXPLAIN ANALYZE on a join/window plan: execute the real
            # two-stage query (leaves traced through the ordinary
            # scatter-gather), then annotate the static plan tree
            return self._explain_analyze_via(
                sql, lambda: explain_multistage(None, plan))

        # the user's SET options (trace, numGroupsLimit, ...) ride every
        # leaf scan — the scatter-gather below is where the PR-6 deadline
        # and tracing contracts live. joinStrategy is stage-2-only, and
        # timeoutMs is rewritten per leaf to the REMAINING budget (leaves
        # run sequentially; each full-budget leaf would let a 2-join query
        # take 3x its deadline). Quota is debited by each leaf's own
        # execute (once per referenced table); a second probe-table
        # acquire here would double-charge joins. Note each leaf ALSO
        # counts as its own broker query in metrics and may log its own
        # querylog entry — deliberate: leaves are first-class queries and
        # hiding them would understate broker load.
        base_opts = []
        budget_ms = None
        for k, v in plan.stage2.options:
            kl = str(k).lower()
            if kl == "joinstrategy":
                continue
            if kl == "timeoutms":
                budget_ms = float(v)
                continue
            base_opts.append((str(k), v))

        def _set_prefix():
            opts = list(base_opts)
            if budget_ms is not None:
                remaining = budget_ms - (time.time() - t0) * 1000
                if remaining <= 0:
                    return None  # expired
                opts.append(("timeoutMs", int(max(1, remaining))))
            prefix = ""
            for k, v in opts:
                if isinstance(v, bool):
                    lit = "TRUE" if v else "FALSE"
                elif isinstance(v, str):
                    lit = "'" + v.replace("'", "''") + "'"
                else:
                    lit = str(v)
                prefix += f"SET {_sql_ident(k)} = {lit}; "
            return prefix

        def _timeout_resp():
            self.metrics.count("queryTimeouts")
            return self._log_query(sql, plan, {"exceptions": [{
                "errorCode": 250,
                "message": f"query timeout: multi-stage budget "
                           f"({budget_ms:.0f} ms) exhausted"}]}, t0)

        # ---- distributed stage-2 dispatch (tentpole, ISSUE 16) ----------
        # the demotion probe ran above (before the EXPLAIN early-return);
        # here the routable plan hands off to the mailbox exchange
        if dist is not None:
            if adv_key is not None and advisor is not None:
                advisor.observe(adv_key, join_strategy="DISTRIBUTED",
                                demoted=bool(dist.get("demoted")))
                dist["adv_key"] = adv_key
            if adv_notes:
                dist["adv_notes"] = adv_notes
            return self._execute_distributed(plan, sql, t0, budget_ms,
                                             dist)

        counters = {"numDocsScanned": 0, "numSegmentsQueried": 0,
                    "numServersQueried": 0, "numServersResponded": 0,
                    "numRetries": 0, "numHedges": 0, "totalDocs": 0,
                    "numSegmentsCold": 0}
        leaf_partial = False
        trace_info: dict = {}
        table_rows = {}
        leaf_rows: dict = {}       # alias -> stage-1 row count (ANALYZE)
        roofline_recs: list = []   # leaf + join-step roofline flights
        need = needed_columns(plan)
        for src in plan.sources:
            cols = need[src.alias]
            push = plan.pushdown.get(src.alias)
            set_prefix = _set_prefix()
            if set_prefix is None:
                return _timeout_resp()
            leaf = (f"{set_prefix}SELECT "
                    f"{', '.join(_sql_ident(c) for c in cols)} "
                    f"FROM {_sql_ident(src.table)}")
            if push is not None:
                leaf += f" WHERE {to_sql(push)}"
            # cap + 1 so an exact-cap row set is distinguishable from a
            # truncated one (the embedded path's strict > check)
            leaf += f" LIMIT {MAX_STAGE1_ROWS + 1}"
            r = self.execute(leaf, principal=principal)
            if r.get("traceInfo"):
                trace_info[f"leaf:{src.alias}"] = r["traceInfo"]
            for rec in r.get("roofline") or ():
                roofline_recs.append(
                    {**rec, "kernel": f"leaf:{src.alias}:"
                                      f"{rec.get('kernel', 'kernel')}"})
            if r.get("exceptions"):
                # surface the leaf's typed error verbatim (429 keeps its
                # retryAfterSeconds pacing hint, 250 stays a timeout)
                # with the stage-1 context prepended
                excs = [dict(e) for e in r["exceptions"]]
                for e in excs:
                    e["message"] = (f"stage-1 scan of table "
                                    f"{src.table!r}: "
                                    f"{e.get('message', 'unknown')}")
                resp = {"exceptions": excs}
                if r.get("retryAfterSeconds") is not None:
                    resp["retryAfterSeconds"] = r["retryAfterSeconds"]
                if r.get("partialResult"):
                    resp["partialResult"] = True
                return self._log_query(sql, plan, resp, t0)
            # a cold-tier leaf partial has NO exception (honest rows +
            # numSegmentsCold) — the join result built on it is partial
            # too, and must say so
            if r.get("partialResult"):
                leaf_partial = True
            for k in counters:
                counters[k] += int(r.get(k) or 0)
            rows = r["resultTable"]["rows"]
            leaf_rows[src.alias] = len(rows)
            if len(rows) > MAX_STAGE1_ROWS:
                raise RuntimeError(
                    f"stage-1 row set for table {src.table!r} hit the "
                    f"{MAX_STAGE1_ROWS}-row cap; add a more selective "
                    f"filter")
            arrays: dict = {}
            if rows:
                for c, vals in zip(cols, zip(*rows)):
                    arrays[c] = np.asarray(vals)
            else:
                schema = _schema_for(src.table)
                for c in cols:
                    spec = getattr(schema, "fields", {}).get(c)
                    dt = spec.data_type.np_dtype if spec is not None \
                        else np.float64
                    arrays[c] = np.empty(0, dtype=dt)
            table_rows[src.alias] = arrays

        if budget_ms is not None and \
                (time.time() - t0) * 1000 >= budget_ms:
            # leaves consumed the whole budget: a late broker-local join
            # would return a success AFTER the client's deadline
            return _timeout_resp()
        result, meta = run_plan(plan, table_rows, device=None,
                                advisor=advisor, advisor_key=adv_key)
        roofline_recs.extend(meta.get("roofline") or ())
        adv_notes.extend(meta.get("advisorDecisions") or ())
        resp = result.to_json()
        resp.update(counters)
        resp.update({
            "exceptions": [],
            "partialResult": leaf_partial,
            "requestId": f"{self.broker_id}_{next(self._request_id)}",
            "numStages": meta["numStages"],
            "numJoinedRows": meta["numJoinedRows"],
            "leafRows": leaf_rows,
            "timeUsedMs": round((time.time() - t0) * 1000, 3),
        })
        if roofline_recs:
            resp["roofline"] = roofline_recs
        if trace_info:
            resp["traceInfo"] = trace_info
        if meta["joinStrategy"]:
            resp["joinStrategy"] = meta["joinStrategy"]
            # partition fan-out of the executed join — the broker-local
            # SHUFFLE baseline column next to the distributed exchange's
            # partition count (previously only the strategy name showed)
            resp["joinFanout"] = meta["joinFanout"]
        if adv_notes:
            resp["advisorDecisions"] = list(dict.fromkeys(adv_notes))
        self.metrics.time_ms("query", resp["timeUsedMs"])
        return self._log_query(sql, plan, resp, t0)

    # ---- distributed stage-2 exchange (ISSUE 16) -------------------------
    def _estimated_docs(self, raw: str, table_keys) -> int:
        """Registry-metadata doc count for the demotion heuristic: the
        sum of SegmentRecord.n_docs over the table's physical keys (same
        per-generation memo the pruner reads — no segment I/O)."""
        names = set(self.registry.tables())
        total = 0
        for key in dict.fromkeys(table_keys(raw)):
            if key not in names:
                continue
            records, _ = self._pruning_inputs(key)
            for rec in records.values():
                total += int(getattr(rec, "n_docs", 0) or 0)
        return total

    def _distributed_spec(self, plan, table_keys, schema_for):
        """Routability probe for the distributed exchange. Returns the
        per-alias replica maps + wire dtypes, or None when the plan
        cannot run fleet-side — hybrid time-boundary split, unknown
        table, or a segment with no live replica — and the caller falls
        back to the broker-local join."""
        import numpy as np

        from pinot_tpu.query2.runner import needed_columns

        names = set(self.registry.tables())
        need = needed_columns(plan)
        insts = self._server_instances()
        routing: dict = {}
        for src in plan.sources:
            matches = [k for k in dict.fromkeys(table_keys(src.table))
                       if k in names]
            if len(matches) != 1:
                # hybrid tables need the broker's time-boundary split;
                # their joins stay on the broker-local path
                return None
            physical = matches[0]
            rmap, replicas, _ = \
                self.routing.routing_with_replicas(physical)
            if rmap is None:
                return None
            # only servers with a live endpoint can host a mailbox
            replicas = {seg: [i for i in ins if i in insts]
                        for seg, ins in replicas.items()}
            if any(not ins for ins in replicas.values()):
                return None
            schema = schema_for(src.table)
            fields = getattr(schema, "fields", {}) if schema else {}
            dtypes = {}
            for c in need[src.alias]:
                spec = fields.get(c)
                dt = spec.data_type.np_dtype if spec is not None \
                    else np.dtype(np.float64)
                # np dtype wire names ('<i8', '|O', ...): the worker
                # casts zero-row scans so even an empty payload ships
                # correctly typed (the empty-leaf dtype guard)
                dtypes[c] = np.dtype(dt).str
            routing[src.alias] = {"table": physical,
                                  "replicas": replicas,
                                  "dtypes": dtypes}
        return {"routing": routing}

    def _distributed_assign(self, dist: dict, excluded: set):
        """One attempt's worker assignment: per alias, each segment goes
        to one live, non-excluded replica (healthy instances first); the
        partition space is 2x the worker count, owners round-robin. None
        when some segment has no usable replica left — coverage is
        impossible and the query must settle as a typed partial."""
        import zlib

        insts = self._server_instances()
        # the stage-2 fleet: EVERY live, non-excluded instance holding a
        # replica of any involved table — partition ownership must span
        # the fleet even when the segment scans land on fewer servers
        # (the whole point of the exchange is that join+agg scale with
        # the server count, not with where stage 1 happened to read)
        fleet: set = set()
        for route in dist["routing"].values():
            for replicas in route["replicas"].values():
                fleet.update(i for i in replicas
                             if i not in excluded and i in insts)
        if not fleet:
            return None
        # healthy-first at the fleet level too: a struck-but-live
        # instance drops out of partition ownership until it recovers
        # (the detector's adaptive routing), unless nothing healthy
        # remains
        healthy_fleet = {i for i in fleet
                         if self.failures.is_healthy(i)} or fleet
        load = {w: 0 for w in fleet}
        used: set = set()
        segments: dict = {}
        for alias, route in dist["routing"].items():
            per: dict = {}
            for seg, replicas in sorted(route["replicas"].items()):
                pool = [i for i in replicas
                        if i not in excluded and i in insts]
                if not pool:
                    return None
                healthy = [i for i in pool
                           if self.failures.is_healthy(i)]
                cands = healthy or pool
                # least-loaded deterministic spread, crc32 tie-break
                # (not hash(): stable across processes) — independent
                # per-segment picks can all collapse onto one replica,
                # serializing stage 1 behind a single server
                pick = min(cands, key=lambda i: (
                    load[i], zlib.crc32(f"{seg}|{i}".encode())))
                load[pick] += 1
                used.add(pick)
                per.setdefault(pick, []).append(seg)
            segments[alias] = per
        # every scan host must run the stage; union covers the segment
        # whose only surviving replica is an unhealthy instance
        worker_list = sorted(healthy_fleet | used)
        n_parts = max(1, 2 * len(worker_list))
        owners = {str(p): worker_list[p % len(worker_list)]
                  for p in range(n_parts)}
        endpoints = {w: insts[w].endpoint for w in worker_list}
        return {"workers": worker_list, "partitions": n_parts,
                "owners": owners, "segments": segments,
                "endpoints": endpoints}

    def _execute_distributed(self, plan, sql: str, t0: float,
                             budget_ms, dist: dict) -> dict:
        """Scatter one ExecuteStage request per worker: each scans its
        routed stage-1 segments, hash-partitions by join key, ships the
        partitions peer-to-peer (query2/exchange.py mailboxes), joins +
        partially aggregates its owned partitions, and answers ONE
        mergeable DataTable — the broker only merges and finalizes, the
        same division of labor stage 1 always had.

        Failure handling mirrors the scatter-gather's replica retry: a
        typed EXCHANGE_TRANSFER_FAILED names the implicated PEER (the
        answering worker is healthy), the broker excludes that instance,
        re-picks the assignment from the replica maps, and re-runs the
        whole exchange ONCE under a fresh exchange id (partial mailboxes
        are not resumable). No coverage or a second failure settles as a
        typed partialResult — never a hang past the deadline."""
        import json as _json
        import re

        from pinot_tpu.engine.datatable import (
            ServerQueryError,
            ServerShuttingDown,
            decode,
        )
        from pinot_tpu.engine.reduce import finalize, merge_intermediates

        total_ms = budget_ms if budget_ms is not None \
            else self.timeout_s * 1000.0
        trace_on = any(str(k).lower() == "trace" and bool(v)
                       for k, v in plan.stage2.options)
        request_id = f"{self.broker_id}_{next(self._request_id)}"
        max_attempts = 2 if self.retry_enabled else 1
        excluded: set = set()
        retries = 0
        last_err = "no routable workers"
        for attempt in range(1, max_attempts + 1):
            remaining = total_ms - (time.time() - t0) * 1000.0
            if remaining <= 0:
                self.metrics.count("queryTimeouts")
                return self._log_query(sql, plan, {
                    "exceptions": [{
                        "errorCode": 250,
                        "message": f"query timeout: distributed stage-2 "
                                   f"budget ({total_ms:.0f} ms) "
                                   f"exhausted"}],
                    "partialResult": True,
                    "joinStrategy": "DISTRIBUTED",
                    "numRetries": retries}, t0)
            assign = self._distributed_assign(dist, excluded)
            if assign is None:
                last_err = (f"segment coverage impossible with "
                            f"{sorted(excluded)} excluded ({last_err})")
                break
            workers = assign["workers"]
            # keep retry headroom on the first attempt (when one is still
            # possible): the stage deadline is what bounds a blackholed
            # transfer, so the retry must have budget left after it fires
            can_retry = self.retry_enabled and attempt < max_attempts
            stage_ms = max(remaining / 2.0, remaining - 2000.0) \
                if can_retry else remaining
            exchange_id = f"ex_{request_id}_{attempt}"
            reqs = {}
            for w in workers:
                reqs[w] = _json.dumps({
                    "exchangeId": exchange_id,
                    "sql": sql,
                    "requestId": request_id,
                    "brokerId": self.broker_id,
                    "timeoutMs": stage_ms,
                    "traceEnabled": trace_on,
                    "traceId": f"{request_id}:{attempt}",
                    "partitions": assign["partitions"],
                    "partitionOwners": assign["owners"],
                    "endpoints": assign["endpoints"],
                    "senders": assign["workers"],
                    "routing": {
                        alias: {
                            "table": route["table"],
                            "segments":
                                assign["segments"][alias].get(w, []),
                            "dtypes": route["dtypes"],
                        } for alias, route in dist["routing"].items()},
                }).encode("utf-8")

            def _call(w, payload):
                ch = self._channel(w)
                if ch is None:
                    raise RuntimeError(f"no endpoint for {w}")
                # RPC timeout rides above the server-side stage deadline:
                # the typed in-band answer must win over DEADLINE_EXCEEDED
                return decode(ch.execute_stage(
                    payload, timeout_s=stage_ms / 1e3 + 2.0))

            futs = {w: self._pool.submit(_call, w, reqs[w])
                    for w in workers}
            parts, failures = {}, {}
            for w, fut in futs.items():
                try:
                    parts[w] = fut.result()
                except Exception as e:  # noqa: BLE001 — typed below
                    failures[w] = e
            if not failures:
                return self._distributed_response(
                    plan, sql, t0, dist, assign, parts, request_id,
                    retries, merge_intermediates, finalize)
            # attribution: a typed transfer failure names the PEER; the
            # answering worker is healthy (same convention as harvest —
            # ServerQueryError that isn't ShuttingDown marks success)
            implicated = None
            for w, e in failures.items():
                m = re.search(r"EXCHANGE_TRANSFER_FAILED peer=(\S+?):",
                              str(e))
                if m:
                    implicated = m.group(1)
                    break
            if implicated is None:
                implicated = next(iter(failures))
            for w, e in failures.items():
                if w == implicated:
                    continue
                if isinstance(e, ServerQueryError) \
                        and not isinstance(e, ServerShuttingDown):
                    self.failures.mark_success(w)
                else:
                    self.failures.mark_failure(w)
            self.failures.mark_failure(implicated)
            for w in parts:
                self.failures.mark_success(w)
            excluded.add(implicated)
            last_err = "; ".join(
                f"{w}: {type(e).__name__}: {e}"
                for w, e in list(failures.items())[:3])
            if attempt < max_attempts:
                retries += 1
                self.metrics.count("exchangeRetries")
                log.warning("distributed stage-2 attempt %d failed "
                            "(implicated %s); retrying without it: %s",
                            attempt, implicated, last_err)
        self.metrics.count("queryErrors")
        expired = (total_ms - (time.time() - t0) * 1000.0) <= 0
        return self._log_query(sql, plan, {
            "exceptions": [{
                "errorCode": 250 if expired else 200,
                "message": f"distributed stage-2 failed after "
                           f"{retries + 1} attempt(s): {last_err}"}],
            "partialResult": True,
            "requestId": request_id,
            "joinStrategy": "DISTRIBUTED",
            "numRetries": retries,
            "timeUsedMs": round((time.time() - t0) * 1000, 3)}, t0)

    def _distributed_response(self, plan, sql, t0, dist, assign, parts,
                              request_id, retries, merge_intermediates,
                              finalize) -> dict:
        """Merge worker partials, finalize stage 2 (HAVING/ORDER/LIMIT
        run here, broker-side, exactly like the broker-local path), and
        assemble the response with the exchange counters."""
        workers = assign["workers"]
        merged = merge_intermediates(
            plan.stage2, [parts[w] for w in workers])
        st = merged.stats
        for w in parts:
            self.failures.mark_success(w)
        resp = finalize(plan.stage2, merged).to_json()
        elapsed = round((time.time() - t0) * 1000, 3)
        per_server = {w: {
            "stage2Rows": int(parts[w].stats.stage2_rows),
            "shippedPartitions":
                int(parts[w].stats.exchange_partitions_shipped),
            "shippedBytes": int(parts[w].stats.exchange_bytes_shipped),
            "spills": int(parts[w].stats.exchange_spill_count),
            "leafRows": {a: int(v) for a, v
                         in (parts[w].stats.leaf_rows or {}).items()},
        } for w in workers}
        resp.update({
            "exceptions": [],
            "partialResult": st.num_segments_cold > 0,
            "requestId": request_id,
            "numStages": 2,
            "numServersQueried": len(workers),
            "numServersResponded": len(parts),
            "numRetries": retries,
            "numHedges": 0,
            "numDocsScanned": int(st.num_docs_scanned),
            "numSegmentsQueried": int(st.num_segments_queried),
            "numSegmentsCold": int(st.num_segments_cold),
            "totalDocs": int(st.total_docs),
            "numJoinedRows": int(st.stage2_rows),
            "leafRows": {a: int(v)
                         for a, v in (st.leaf_rows or {}).items()},
            "joinStrategy": "DISTRIBUTED",
            "joinFanout": int(assign["partitions"]),
            "numPartitionsShipped": int(st.exchange_partitions_shipped),
            "exchangeBytes": int(st.exchange_bytes_shipped),
            "exchangeSpillCount": int(st.exchange_spill_count),
            "exchange": {
                "partitions": int(assign["partitions"]),
                "numWorkers": len(workers),
                "servers": per_server,
            },
            "timeUsedMs": elapsed,
        })
        if dist.get("demoted"):
            resp["joinStrategyDemoted"] = True
        # plan-advisor (ISSUE 17): stamp probe overrides + any worker-side
        # decisions, and feed the MEASURED per-alias leaf rows back so the
        # next demotion probe decides from observation, not the registry
        adv_lines = list(dist.get("adv_notes") or [])
        for line in (st.advisor_decisions or []):
            if line not in adv_lines:
                adv_lines.append(line)
        if adv_lines:
            resp["advisorDecisions"] = adv_lines
        adv_key = dist.get("adv_key")
        if adv_key and self.advisor is not None and st.leaf_rows:
            self.advisor.observe(
                adv_key,
                build_rows={a: int(v) for a, v in st.leaf_rows.items()})
        trace_info = {f"stage2:{w}": parts[w].trace
                      for w in workers if parts[w].trace}
        if trace_info:
            resp["traceInfo"] = trace_info
        self.metrics.count("exchangeQueries")
        self.metrics.count("exchangeBytes",
                           int(st.exchange_bytes_shipped))
        self.metrics.count("exchangePartitionsShipped",
                           int(st.exchange_partitions_shipped))
        if st.exchange_spill_count:
            self.metrics.count("exchangeSpills",
                               int(st.exchange_spill_count))
        self.metrics.time_ms("query", elapsed)
        return self._log_query(sql, plan, resp, t0)

    def _log_query(self, sql: str, q, resp: dict, t0: float) -> dict:
        """Feed the structured query log on EVERY terminal broker path
        (success, partial, error, quota) and pass the response through.
        Logging must never fail a query."""
        time_used = resp.get("timeUsedMs")
        if time_used is None:
            time_used = round((time.time() - t0) * 1000, 3)
        # fleet attribution (ISSUE 18): every terminal response says WHICH
        # broker answered — rotation tests and merged fleet query logs
        # both key on it — and feeds this broker's heartbeat QPS counter
        resp.setdefault("brokerId", self.broker_id)
        self.queries_served += 1
        try:
            from pinot_tpu.broker.querylog import template_key

            self.querylog.record(
                sql, resp, time_used,
                table=q.table_name if q is not None else None,
                # deferred: the keep policy drops most healthy fast
                # queries before the template tree walk would run
                template=(lambda _q=q: template_key(_q))
                if q is not None else None)
        except Exception:  # noqa: BLE001
            log.exception("query log record failed")
        return resp

    def _resolve_table_case(self, q: QueryContext,
                            gen=None) -> QueryContext:
        """Case-insensitive table resolution against the registry
        (BaseBrokerRequestHandler.java:245-254 / TableCache's
        ignore-case lookup): FROM mytable matches a registered MyTable.
        Exact matches win; ambiguous case-folds keep the literal name."""
        raw = q.table_name
        names = self._tables_set(gen)
        candidates = {raw, f"{raw}_OFFLINE", f"{raw}_REALTIME"}
        if candidates & names:
            return q
        low = raw.lower()
        # physical-name fold first (FROM sAlEs_OFFLINE → sales_OFFLINE),
        # then the base-name fold (FROM SALES → sales)
        physical = {n for n in names if n.lower() == low}
        base = {QueryQuotaManager._base_name(n) for n in names}
        matches = physical or {b for b in base if b.lower() == low}
        if len(matches) != 1:
            return q
        return dataclasses.replace(q, table_name=matches.pop())

    def _expand_star(self, q: QueryContext) -> QueryContext:
        """SELECT * resolves against the registry schema (looked up via the
        physical table key) so the broker's reduce sees the same select
        positions the servers produced."""
        from pinot_tpu.query.rewrite import expand_star

        if not any(e.is_identifier and e.name == "*"
                   for e in q.select_expressions):
            return q  # no star: don't pay a schema read per query
        schema = None
        for key in (q.table_name, f"{q.table_name}_OFFLINE", f"{q.table_name}_REALTIME"):
            schema = self.registry.table_schema(key)
            if schema is not None:
                break
        if schema is None:
            return q
        return expand_star(q, schema.column_names())

    def _physical_tables(self, raw: str, gen=None) -> list:
        """Raw table name → [(physical key, time filter or None)].

        A hybrid table (both _OFFLINE and _REALTIME registered) is split at
        the time boundary = max offline segment end time: offline answers
        time <= boundary, realtime answers time > boundary
        (routing/timeboundary/TimeBoundaryManager.java +
        BaseBrokerRequestHandler.java:387-395).

        Memoized per routing generation (exact: the name set, table config
        and boundary inputs all ride routing sections) — the steady-state
        hot path pays a dict lookup, not a registry walk per query."""
        view = self._gen_view(gen)
        hit = view["phys"].get(raw)
        if hit is not None:
            return hit
        out = self._split_physical(raw, view["tables"])
        view["phys"][raw] = out
        return out

    def _pruning_inputs(self, physical: str, gen=None) -> tuple:
        """(segment records, time column) for broker-side pruning,
        memoized per routing generation like the physical split (segment
        records and table config both ride routing sections)."""
        view = self._gen_view(gen)
        hit = view.get(("prune", physical))
        if hit is not None:
            return hit
        records = self.registry.segments(physical)
        cfg = self.registry.table_config(physical)
        out = (records, cfg.time_column if cfg is not None else None)
        view[("prune", physical)] = out
        return out

    def _split_physical(self, raw: str, tables: set) -> list:
        if raw in tables:
            return [(raw, None)]
        off, rt = f"{raw}_OFFLINE", f"{raw}_REALTIME"
        out = []
        boundary = None
        if off in tables and rt in tables:
            cfg = self.registry.table_config(off)
            if cfg is not None and cfg.time_column is not None:
                # boundary counts only SERVABLE offline segments: a freshly
                # pushed segment (e.g. a realtimeToOffline move) must not
                # advance the boundary before any server can answer for it,
                # or its window would transiently vanish from hybrid results
                view, records, _ = self.registry.routing_snapshot(off)
                ends = [
                    r.end_time
                    for name, r in records.items()
                    if r.end_time is not None and name in view
                ]
                if ends:
                    # TimeBoundaryManager semantics: back off one time unit
                    # from the max offline end time — realtime rows with
                    # ts <= maxEnd not yet pushed offline would otherwise be
                    # invisible to both sides (offline lacks them, gt filter
                    # excludes them).
                    bval = max(ends)
                    if isinstance(bval, int):
                        bval -= 1
                    else:
                        # float time columns: back off one ULP so ts == maxEnd
                        # rows route to realtime (same semantics as minus one
                        # unit at float resolution)
                        import math

                        bval = math.nextafter(float(bval), -math.inf)
                    boundary = (cfg.time_column, bval)
        if off in tables:
            tf = None if boundary is None else                 {"column": boundary[0], "op": "le", "value": boundary[1]}
            out.append((off, tf))
        if rt in tables:
            tf = None if boundary is None else                 {"column": boundary[0], "op": "gt", "value": boundary[1]}
            out.append((rt, tf))
        if not out:
            raise KeyError(f"table {raw!r} not found")
        return out

    def _scatter_gather(self, q: QueryContext, sql: str, gen=None,
                        tenant: str = None, priority: str = None) -> dict:
        """Thin reservation bracket around the scatter body: routing
        reserves the picked instances' outstanding counts atomically with
        the pick (concurrent queries balance instead of herding), and the
        release is guaranteed here however the query settles.
        ``tenant``/``priority`` (ISSUE 14) stamp every instance request
        so the servers' weighted-fair schedulers isolate tenants."""
        reserved: list = []
        try:
            return self._scatter_gather_inner(q, sql, reserved, gen,
                                              tenant, priority)
        finally:
            self.routing.release(reserved)

    def _scatter_gather_inner(self, q: QueryContext, sql: str,
                              reserved: list, gen=None,
                              tenant: str = None,
                              priority: str = None) -> dict:
        from pinot_tpu.common.trace import active, span

        q = self._expand_star(q)
        request_id = next(self._request_id)
        # trace id: minted per request, stamped into EVERY scatter
        # request (primary + retries + hedges, each tagged with its
        # attempt kind) so per-server spans join back to one query
        tracer = active()
        trace_id = f"{self.broker_id}-{request_id}"
        if tracer is not None:
            tracer.trace_id = trace_id
        trace_on = tracer is not None
        # per-query failure-handling counters (the query log's view; the
        # registry counters aggregate the same events process-wide)
        attempt_counts = {"retries": 0, "hedges": 0}
        # per-query timeout override (SET timeoutMs = N — the reference's
        # timeoutMs query option). The Deadline is THE budget: every
        # scatter request ships the remaining window, every gather wait is
        # clamped to it, and expiry yields a typed QUERY_TIMEOUT partial.
        opts = q.options_ci()
        timeout_s = self.timeout_s
        if "timeoutms" in opts:
            timeout_s = max(0.001, float(opts["timeoutms"]) / 1000.0)
        deadline = Deadline(timeout_s)
        # SET faultInject='point[@target]=mode[:arg][#times];...' arms the
        # chaos harness from a query (one-shot per entry unless the spec
        # says otherwise) — the SQL-driven face of PINOT_TPU_FAULTS
        fi = opts.get("faultinject")
        if fi:
            for f in faults.parse_spec(str(fi)):
                if f.times is None:
                    f.times = 1
                faults.install(f)

        scatter = []  # (instance, physical table, segments, time_filter)
        replicas: dict = {}  # (physical, segment) -> serving instances
        n_servers = set()
        num_pruned = 0
        num_pruned_value = 0  # excluded by per-column min/max stats alone
        fully_pruned = []  # fallback: keep one segment so reduce sees a shape
        # replica-group attribution (ISSUE 10 satellite): how many groups
        # this query's routing touched + the chosen group's load score, so
        # the query log and bench can attribute tail latency to routing
        rg_queried = 0
        rg_load_score = None
        rg_name = None
        # freshness epochs piggybacked by THIS query's own partials — the
        # result cache records these (merged over the pre-scatter view),
        # never the global observation state at put time, which can hold
        # epochs newer than the data this query actually scanned
        own_epochs: dict = {}
        with span("broker.route"):
            for physical, time_filter in self._physical_tables(q.table_name,
                                                               gen):
                routing, reps, rinfo = \
                    self.routing.routing_with_replicas(physical,
                                                       reserve=True,
                                                       gen=gen)
                reserved.extend(rinfo.get("reserved", ()))
                rg_queried += int(rinfo.get("numReplicaGroupsQueried", 0)
                                  or 0)
                if rinfo.get("loadScore") is not None and \
                        (rg_load_score is None
                         or rinfo["loadScore"] > rg_load_score):
                    rg_load_score = rinfo["loadScore"]
                    rg_name = rinfo.get("replicaGroup")
                if not routing:
                    continue
                for seg, insts in reps.items():
                    replicas[(physical, seg)] = insts
                records, time_col = self._pruning_inputs(physical, gen)
                for inst, segs in routing.items():
                    kept, pruned, by_value = prune_segments(
                        q, records, segs, time_col, time_filter)
                    num_pruned += pruned
                    num_pruned_value += by_value
                    if kept:
                        scatter.append((inst, physical, kept, time_filter))
                        n_servers.add(inst)
                    else:
                        fully_pruned.append(
                            (inst, physical, segs[:1], time_filter))
        if not scatter and fully_pruned:
            # every segment pruned: query one anyway — the server's min/max
            # pruner short-circuits it, and the reduce gets a typed empty
            # result instead of a synthesized one
            inst, phys, segs, tf = fully_pruned[0]
            num_pruned -= len(segs)
            # the re-queried segment no longer counts as pruned in EITHER
            # number; the clamp is exact — by-value can only exceed the new
            # total when the re-added segment itself was value-pruned
            num_pruned_value = min(num_pruned_value, max(0, num_pruned))
            scatter.append((inst, phys, segs, tf))
            n_servers.add(inst)
        if not scatter:
            raise KeyError(f"no routing entry for table {q.table_name!r}")

        # Streaming execution (StreamingReduceService analog): selection
        # without ORDER BY has any-subset semantics, so servers stream one
        # DataTable block per segment and the broker cancels every stream
        # as soon as offset+limit rows arrived — no full materialization on
        # either side. SET streaming = false forces the unary path.
        use_streaming = (
            not q.aggregations() and not q.distinct and not q.order_by
            and opts.get("streaming") is not False
            # tracing rides the unary DataTable header; streaming blocks
            # don't carry spans, so a traced query takes the unary path
            and not opts.get("trace")
        )
        row_budget = q.offset + q.limit
        rows_seen = [0]
        rows_lock = threading.Lock()

        def call(instance_id: str, physical: str, segments: list, time_filter,
                 attempt: str = "primary"):
            if faults.ACTIVE:
                # chaos seam: drop / delay / blackhole this replica's RPC
                # (a blackhole sleeps at most the remaining budget — the
                # gRPC deadline would have freed the thread the same way)
                faults.inject("transport.submit", target=instance_id,
                              bound_ms=deadline.remaining_ms())
            ch = self._channel(instance_id)
            if ch is None:
                raise ConnectionError(f"server {instance_id} not registered")
            # ship the REMAINING budget, not the original timeout: the
            # server bounds every downstream wait by it and answers a
            # typed QUERY_TIMEOUT instead of computing an abandoned result
            budget_ms = max(1.0, deadline.remaining_ms())
            payload = make_instance_request(
                sql, segments, request_id, self.broker_id,
                table=physical, time_filter=time_filter,
                timeout_ms=budget_ms,
                # every attempt ships the trace flag + id, tagged with its
                # kind, so a retried/hedged query still traces end to end
                trace=trace_on, trace_id=trace_id, attempt=attempt,
                # tenant + priority class (ISSUE 14): the server's
                # weighted-fair scheduler groups slots by tenant
                workload=tenant, priority=priority,
            )
            # small grace past the shipped budget: the server's own
            # deadline fires first; the RPC deadline is the backstop
            rpc_timeout_s = budget_ms / 1e3 + 0.25
            t0 = time.perf_counter()
            if not use_streaming:
                parts = [decode(ch.submit(payload, rpc_timeout_s))]
            else:
                stream = ch.submit_streaming(payload, rpc_timeout_s)
                parts = []
                contributed = 0
                try:
                    for block in stream:
                        r = decode(bytes(block))
                        parts.append(r)
                        n = len(next(iter(r.rows.values()))) if r.rows else 0
                        with rows_lock:
                            rows_seen[0] += n
                            contributed += n
                            done = rows_seen[0] >= row_budget
                        if done:
                            stream.cancel()
                            break
                except BaseException:
                    # a failed attempt's blocks are DISCARDED: roll their
                    # rows back out of the shared budget, or a successful
                    # retry would report a "complete" result that silently
                    # stopped other entries' streams short of LIMIT
                    with rows_lock:
                        rows_seen[0] -= contributed
                    raise
            # rolling latency feeds the adaptive hedge delay (p90)
            self.latency.record(instance_id, time.perf_counter() - t0)
            return parts

        from pinot_tpu.engine.datatable import (
            NoSegmentsHosted,
            QueryTimeoutError,
            ServerQueryError,
            ServerShuttingDown,
        )

        # ---- scatter with per-entry failure handling ---------------------
        # Each scatter entry tracks every attempt (primary + retry +
        # hedge) WITH the segment list that attempt covers: a retry may
        # have to SPLIT the failed instance's segments across several
        # replicas when no single replica serves them all, and the reduce
        # must never count a segment twice when both a primary and its
        # hedge answer. Transient failures of a fully-served entry are
        # dropped (the result is complete); only unrecovered failures
        # surface as partialResult exceptions.
        entries_lock = threading.Lock()
        entries = []

        def submit_attempt(e, inst, segs=None, kind="primary"):
            segs = e["segs"] if segs is None else segs
            fut = self._pool.submit(call, inst, e["phys"], segs, e["tf"],
                                    kind)
            with entries_lock:
                e["futs"].append((fut, inst, frozenset(segs), kind))
            fut.add_done_callback(lambda _f, _ev=e["ev"]: _ev.set())
            return fut

        def alternate_for(e):
            """A not-yet-attempted replica serving EVERY segment of the
            entry (healthy first, then backing-off as a last resort).
            None when no single replica covers the list (hedging skips;
            retry falls back to a split — retry_groups)."""
            cands = None
            for seg in e["segs"]:
                insts = set(replicas.get((e["phys"], seg), ()))
                cands = insts if cands is None else cands & insts
            cands = [i for i in (cands or ()) if i not in e["attempted"]]
            healthy = [i for i in cands if self.failures.is_healthy(i)]
            pool = healthy or cands
            return pool[0] if pool else None

        def retry_groups(e):
            """{instance: [segments]} re-covering the entry's list on
            not-yet-attempted replicas, split per segment when needed
            (healthy replicas first; fewest instances greedily). Segments
            with no remaining replica are left out — they surface as the
            partial's exceptions."""
            groups: dict = {}
            for seg in e["segs"]:
                cands = [i for i in replicas.get((e["phys"], seg), ())
                         if i not in e["attempted"]]
                healthy = [i for i in cands if self.failures.is_healthy(i)]
                pool = healthy or cands
                if not pool:
                    continue
                pick = next((i for i in pool if i in groups), pool[0])
                groups.setdefault(pick, []).append(seg)
            return groups

        # hedging (SET useHedging=true / pinot.broker.hedging.enabled):
        # after the target replica's rolling p90 (or the configured fixed
        # delay), duplicate a still-unanswered request to a second
        # replica; first complete wins, the loser is cancelled/ignored.
        # Streaming selections don't hedge — the duplicate's blocks would
        # double-count against the shared row budget.
        hedging = (not use_streaming) and (
            bool_option(opts, "usehedging", None) is True
            or (self.hedging_enabled
                and bool_option(opts, "usehedging", None) is not False))

        for inst, phys, segs, tf in scatter:
            entries.append({
                "inst": inst, "phys": phys, "segs": segs, "tf": tf,
                "futs": [], "ev": threading.Event(), "attempted": {inst},
                "consumed": set(),
            })
        if len(entries) == 1 and not hedging and not faults.ACTIVE:
            # replica-group routing's common case: the WHOLE query goes to
            # one server. Run the primary attempt inline on this thread —
            # the pool handoff + event wakeup are pure overhead (several
            # cross-thread futex round-trips per query, each a sentry trip
            # under sandboxed kernels) when there is nothing to overlap.
            # Failures still flow through harvest's retry machinery via
            # the pre-resolved future. Chaos runs keep the pool path: the
            # deadline-bounded event wait is what bounds a blackholed RPC.
            e = entries[0]
            fut: futures.Future = futures.Future()
            try:
                fut.set_result(call(e["inst"], e["phys"], e["segs"],
                                    e["tf"], "primary"))
            except BaseException as exc:  # noqa: BLE001 — future carries it
                fut.set_exception(exc)
            with entries_lock:
                e["futs"].append(
                    (fut, e["inst"], frozenset(e["segs"]), "primary"))
            e["ev"].set()
        else:
            for e in entries:
                submit_attempt(e, e["inst"])

        def maybe_hedge(e):
            if deadline.expired():
                return
            with entries_lock:
                if any(f.done() for f, _i, _s, _k in e["futs"]):
                    return
                alt = alternate_for(e)
                # no single replica covers the list: hedge the split form
                # (disjoint subsets — the coverage-aware resolve composes
                # them exactly like a split retry)
                groups = {alt: e["segs"]} if alt is not None \
                    else retry_groups(e)
                if not groups:
                    return
                e["attempted"].update(groups)
            self.metrics.count("hedgedRequests")
            attempt_counts["hedges"] += 1
            for inst2, segs2 in groups.items():
                submit_attempt(e, inst2, segs2, kind="hedge")

        timers = []
        if hedging:
            for e in entries:
                fixed = self.hedge_delay_s
                delay = fixed if fixed > 0 else self.latency.p90_s(e["inst"])
                delay = max(0.005, min(delay, deadline.remaining_s() * 0.5))
                t = threading.Timer(delay, maybe_hedge, args=(e,))
                t.daemon = True
                t.start()
                timers.append(t)

        results, exceptions = [], []
        query_errors = []
        server_traces = {}
        server_roofline = []  # per-flight roofline records, instance-tagged
        responded = set()  # instances whose response was USED
        attempted_all = set()

        def harvest(e):
            """Resolve one entry within the deadline → (successes, errors)
            where successes is a list of (parts, inst) whose segment
            coverage is DISJOINT (no segment reduced twice even when both
            a primary and its hedge answered) and errors is the
            unrecovered (errorCode, message) list — empty when the entry
            was fully served."""
            retried = False
            errors = []  # (errorCode, message) — dropped if fully served
            successes = []  # (covered frozenset, parts, inst)
            all_segs = frozenset(e["segs"])

            def resolved():
                """Disjoint success subset covering the whole entry, or
                None. A single full-coverage attempt (primary or hedge)
                wins outright; split retries compose by disjoint union."""
                full = next((s for s in successes if s[0] >= all_segs),
                            None)
                if full is not None:
                    return [full]
                chosen, covered = [], set()
                for s in successes:
                    if not (s[0] & covered):
                        chosen.append(s)
                        covered |= s[0]
                return chosen if covered >= all_segs else None

            def best_partial():
                """Maximal disjoint subset when full coverage is out of
                reach (partialResult: honest parts + honest exceptions)."""
                chosen, covered = [], set()
                for s in successes:
                    if not (s[0] & covered):
                        chosen.append(s)
                        covered |= s[0]
                return chosen

            def try_retry():
                nonlocal retried
                if not self.retry_enabled or retried or deadline.expired():
                    return
                groups = retry_groups(e)
                if not groups:
                    return
                retried = True
                self.metrics.count("retriedRequests")
                attempt_counts["retries"] += 1
                with entries_lock:
                    e["attempted"].update(groups)
                for inst2, segs2 in groups.items():
                    submit_attempt(e, inst2, segs2, kind="retry")

            def finish(done):
                """Cancel/ignore still-pending attempts, settle errors.
                Attempts that can no longer be cancelled (already
                running — e.g. the blackholed loser of a won hedge race)
                still report their eventual outcome to the failure
                detector, so a dead replica doesn't stay HEALTHY just
                because a hedge always wins first."""
                with entries_lock:
                    futs = list(e["futs"])
                for f, i, _s, _k in futs:
                    if id(f) in e["consumed"]:
                        continue
                    if f.cancel():
                        # the attempt never ran: if its routing claimed a
                        # half-open probe slot, free it — no outcome will
                        self.failures.release_probe(i)
                    else:
                        f.add_done_callback(
                            lambda _f, _i=i: self._note_abandoned(_f, _i))
                if done is not None:
                    if errors:
                        # a replica answered after a failure: recovered —
                        # the result is complete, no partialResult
                        self.metrics.count("recoveredRequests")
                    return [(s[1], s[2], s[3]) for s in done], []
                return [(s[1], s[2], s[3]) for s in best_partial()], errors

            while True:
                with entries_lock:
                    futs = list(e["futs"])
                ready = [t for t in futs
                         if t[0].done() and id(t[0]) not in e["consumed"]]
                if not ready:
                    done = resolved()
                    if done is not None:
                        return finish(done)
                    live = [t for t in futs if id(t[0]) not in e["consumed"]]
                    if not live:
                        return finish(None)  # every attempt consumed
                    left = deadline.remaining_s()
                    if left <= 0:
                        # budget gone with attempts still in flight:
                        # typed QUERY_TIMEOUT per pending instance — the
                        # broker answers within deadline + grace, never
                        # hangs on a straggler
                        errors.extend(
                            (250, f"QUERY_TIMEOUT: {i} did not respond "
                                  f"within the {timeout_s * 1e3:.0f}ms "
                                  f"query budget")
                            for _f, i, _s, _k in live)
                        return finish(None)
                    e["ev"].wait(min(left, 0.25))
                    e["ev"].clear()
                    continue
                for fut, inst, segs_of, kind in ready:
                    e["consumed"].add(id(fut))
                    if fut.cancelled():
                        continue
                    try:
                        parts = fut.result()
                    except NoSegmentsHosted:
                        # benign routing/sync race: segments moved between
                        # the external-view read and the RPC; not a
                        # failure — the attempt's share counts covered
                        self.failures.mark_success(inst)
                        successes.append((segs_of, [], inst, kind))
                        continue
                    except QueryTimeoutError as exc:
                        # server-side typed timeout: the server is healthy,
                        # the budget just ran out there
                        self.failures.mark_success(inst)
                        errors.append((250, f"{inst}: {exc}"))
                        continue  # a hedge may still win
                    except ServerShuttingDown as exc:
                        # retriable by contract: the submit was rejected
                        # before any execution touched the data
                        self.failures.mark_failure(inst)
                        errors.append(
                            (427, f"SERVER_NOT_RESPONDING: {inst}: {exc}"))
                        try_retry()
                        continue
                    except ServerQueryError as exc:
                        # query-level error (bad column etc.): the server
                        # is healthy; report in-band, don't poison the
                        # detector, and don't retry — a replica would fail
                        # identically
                        self.failures.mark_success(inst)
                        query_errors.append(
                            {"errorCode": 200, "message": f"{inst}: {exc}"})
                        return finish(None)
                    except Exception as exc:  # noqa: BLE001 — transport
                        self.failures.mark_failure(inst)
                        errors.append(
                            (427, f"SERVER_NOT_RESPONDING: {inst}: {exc}"))
                        try_retry()
                        continue
                    self.failures.mark_success(inst)
                    successes.append((segs_of, parts, inst, kind))
                done = resolved()
                if done is not None:
                    return finish(done)

        with span("broker.scatter_gather"), self.metrics.timed("scatterMs"):
            for e in entries:
                served, errs = harvest(e)
                attempted_all |= e["attempted"]
                exceptions.extend(
                    {"errorCode": code, "message": msg}
                    for code, msg in errs)
                for parts, inst, kind in served:
                    # traceInfo keyed by instance, retry/hedge attempts
                    # tagged; a server answering several entries (hybrid
                    # split, split retries) MERGES its span lists — no
                    # duplicate and no dropped server spans
                    tkey = inst if kind == "primary" else f"{inst} ({kind})"
                    for r in parts:
                        if r.trace is not None:
                            server_traces.setdefault(tkey, []).extend(r.trace)
                        # roofline flight records (ISSUE 11): instance-
                        # tagged for EXPLAIN ANALYZE / the query log
                        for rec in getattr(r, "roofline", None) or ():
                            server_roofline.append(
                                {**rec, "instance": tkey})
                        # piggybacked load + freshness (ISSUE 10): feed
                        # the decayed load score and the result cache's
                        # per-table epoch view BEFORE stats merge away
                        # the per-instance values
                        st = r.stats
                        if st.server_pressure >= 0 or st.server_inflight >= 0:
                            self.routing.loads.observe(
                                inst, max(0, st.server_pressure),
                                max(0, st.server_inflight))
                        self._note_epoch(e["phys"], inst, st.table_epoch)
                        if st.table_epoch is not None and \
                                st.table_epoch > own_epochs.get(inst, -1):
                            own_epochs[inst] = st.table_epoch
                        results.append(r)
                    if parts:
                        responded.add(inst)
        for t in timers:
            t.cancel()
        if any(x["errorCode"] == 250 for x in exceptions):
            self.metrics.count("queryTimeouts")
        if query_errors:
            return {"exceptions": query_errors}
        if not results:
            self.metrics.count("serverFailures", len(exceptions))
            if any(x["errorCode"] == 250 for x in exceptions):
                # nothing answered before the budget expired: a typed
                # in-band QUERY_TIMEOUT response, delivered promptly —
                # not an opaque ConnectionError after N server waits
                resp_timeout = {
                    "exceptions": exceptions,
                    "partialResult": True,
                    "numServersQueried": len(n_servers | attempted_all),
                    "numServersResponded": len(responded),
                    "numRetries": attempt_counts["retries"],
                    "numHedges": attempt_counts["hedges"],
                    "numReplicaGroupsQueried": rg_queried,
                    "requestId": request_id,
                }
                if rg_load_score is not None:
                    resp_timeout["loadScore"] = rg_load_score
                    resp_timeout["replicaGroup"] = rg_name
                return resp_timeout
            raise ConnectionError(f"all servers failed: {exceptions}")

        with span("broker.reduce"):
            merged = merge_intermediates(q, results)
            table = finalize(q, merged)
        resp = table.to_json()
        if server_traces:
            resp["traceInfo"] = server_traces
        stats = merged.stats
        resp.update(
            {
                "exceptions": exceptions,
                # a cold-tier segment answered as an in-flight partial:
                # the rows are honest-but-incomplete, so the response is
                # partial (which also keeps it OUT of the result cache)
                "partialResult": bool(exceptions)
                or stats.num_segments_cold > 0,
                # queried counts every instance the broker dispatched to
                # (primary fan-out + retries + hedges); responded counts
                # the instances whose answers the reduce actually used
                "numServersQueried": len(n_servers | attempted_all),
                "numServersResponded": len(responded),
                "numRetries": attempt_counts["retries"],
                "numHedges": attempt_counts["hedges"],
                # replica-group routing attribution (ISSUE 10): groups
                # touched + the chosen group's load score at pick time
                "numReplicaGroupsQueried": rg_queried,
                "numDocsScanned": stats.num_docs_scanned,
                "numEntriesScannedInFilter": stats.num_entries_scanned_in_filter,
                "numEntriesScannedPostFilter": stats.num_entries_scanned_post_filter,
                "numSegmentsQueried": stats.num_segments_queried,
                "numSegmentsPrunedByBroker": num_pruned,
                "numSegmentsPrunedByValue": num_pruned_value,
                "numSegmentsPrunedByServer": stats.num_segments_pruned,
                "numBlocksPruned": stats.num_blocks_pruned,
                # cold-tier segments served as honest in-flight partials
                # while their deep-store hydration proceeds (ISSUE 12) —
                # non-zero means a repeat of this query will cover more
                "numSegmentsCold": stats.num_segments_cold,
                "numSegmentsProcessed": stats.num_segments_processed,
                "numSegmentsMatched": stats.num_segments_matched,
                "totalDocs": stats.total_docs,
                "numGroupsLimitReached": stats.num_groups_limit_reached,
                # any server partial answered from its device partials
                # cache (sub-RTT serving; querylog --per-template
                # aggregates this into per-template hit rates)
                "partialsCacheHit": stats.partials_cache_hit,
                # summed across servers, like the reference's V3 metadata
                "threadCpuTimeNs": stats.thread_cpu_time_ns,
                "schedulerWaitMs": round(stats.scheduler_wait_ms, 3),
                # kernel roofline accounting (ISSUE 11), summed across
                # server partials; the per-flight detail rides "roofline"
                "deviceBytesMoved": stats.device_bytes_moved,
                "deviceKernelMs": round(stats.device_kernel_ms, 3),
                "deviceLinkMs": round(stats.device_link_ms, 3),
                "requestId": request_id,
            }
        )
        if server_roofline:
            resp["roofline"] = server_roofline
        if stats.advisor_decisions:
            # plan-advisor stamps (ISSUE 17): the decisions the answering
            # servers' launches ran with, deduped by the stats merge
            resp["advisorDecisions"] = list(stats.advisor_decisions)
        if rg_load_score is not None:
            resp["loadScore"] = rg_load_score
            resp["replicaGroup"] = rg_name
        # internal side channel for the result cache's put (stripped by
        # execute before the response leaves the broker)
        resp["__epochView__"] = own_epochs
        return resp
