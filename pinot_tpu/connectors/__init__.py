"""DataFrame connectors: the spark/flink connector roles, pythonic form.

Reference analogs:
- pinot-connectors/pinot-spark-connector (DataSource v2 READ: scan a
  Pinot table into a distributed DataFrame) → ``read_table`` /
  ``query_df`` producing a pandas DataFrame;
- pinot-connectors/pinot-flink-connector (SINK: stream rows into
  segments) → ``write_table`` building + uploading segments from a
  DataFrame through the controller.

pandas is the DataFrame runtime of this build the way Spark/Flink are the
reference's; the read path rides the same broker SQL surface the spark
connector's gRPC server read rides.
"""

from __future__ import annotations

import math
from typing import Optional


def _quote_ident(name: str) -> str:
    """SQL-quote an identifier ("" escaping) unless it is already a plain
    (possibly dotted, db.table-style) identifier; a table/column name
    containing a quote must not rewrite the query it is interpolated into.
    Dotted names pass through unquoted so the parser's last-segment
    resolution (sql/parser.py parse_table_name) keeps working."""
    import re

    if re.fullmatch(r"[A-Za-z_$][\w$]*(\.[A-Za-z_$][\w$]*)*", name):
        return name
    return '"' + name.replace('"', '""') + '"'


def _quote_literal(value: str) -> str:
    """SQL string literal with '' escaping (the parser's string grammar)."""
    return "'" + str(value).replace("'", "''") + "'"


def query_df(source, sql: str):
    """One SQL query → pandas DataFrame. ``source``: a Broker, an engine,
    a DB-API Connection, or a broker URL string."""
    import pandas as pd

    resp = _execute(source, sql)
    if resp.get("exceptions"):
        raise RuntimeError(f"query failed: {resp['exceptions']}")
    table = resp.get("resultTable") or {"dataSchema": {"columnNames": []},
                                        "rows": []}
    return pd.DataFrame(table["rows"],
                        columns=table["dataSchema"]["columnNames"])


def read_table(source, table: str, columns=None, where: Optional[str] = None,
               batch_rows: int = 100_000):
    """Full-table scan → pandas DataFrame (spark-connector read role).

    Pages per SEGMENT by the $docId virtual column — the same
    partition-by-segment shape the spark connector's per-split reads use
    ($docId is segment-local, so global paging would be wrong) — keeping
    every request bounded by batch_rows instead of one giant LIMIT."""
    import pandas as pd

    cols = ", ".join(_quote_ident(c) for c in columns) if columns else "*"
    table = _quote_ident(table)
    base_where = f"({where}) AND " if where else ""
    # page over each segment's RAW doc-id range (MAX($docId)+1), not its
    # matching-row count — a filter would otherwise shrink the page span
    # and drop matching rows near the segment tail
    per_seg = _execute(
        source,
        f"SELECT $segmentName, MAX($docId) FROM {table}"
        + (f" WHERE {where}" if where else "")
        + " GROUP BY $segmentName ORDER BY $segmentName LIMIT 100000")
    if per_seg.get("exceptions"):
        raise RuntimeError(f"read_table failed: {per_seg['exceptions']}")
    if per_seg.get("numGroupsLimitReached") or \
            len(per_seg["resultTable"]["rows"]) >= 100_000:
        # a truncated segment listing would silently export a partial
        # table — refuse loudly (bulk-export API, not best-effort)
        raise RuntimeError(
            "read_table: segment discovery truncated (>100k segments or "
            "numGroupsLimit reached); export per partition/time range "
            "instead")
    frames = []
    for seg_name, max_doc in per_seg["resultTable"]["rows"]:
        n = int(max_doc) + 1
        for page in range(max(1, math.ceil(int(n) / batch_rows))):
            lo, hi = page * batch_rows, (page + 1) * batch_rows
            sql = (f"SELECT {cols} FROM {table} WHERE {base_where}"
                   f"$segmentName = {_quote_literal(seg_name)} AND "
                   f"$docId >= {lo} AND $docId < {hi} LIMIT {batch_rows}")
            frames.append(query_df(source, sql))
    return pd.concat(frames, ignore_index=True) if frames else pd.DataFrame()


def write_table(df, schema, table: str, controller, segment_rows: int = 1_000_000,
                segment_prefix: Optional[str] = None) -> list:
    """DataFrame → segments → controller upload (flink-connector sink
    role). Returns the uploaded segment names."""
    import os
    import shutil
    import tempfile

    cfg = controller.registry.table_config(controller.resolve(table))
    if cfg is None:
        raise KeyError(f"table {table!r} not found")
    from pinot_tpu.storage.creator import build_segment

    prefix = segment_prefix or f"{table}_df"
    names = []
    n = len(df)
    for i in range(max(1, math.ceil(n / segment_rows))):
        part = df.iloc[i * segment_rows: (i + 1) * segment_rows]
        cols = {}
        for name in part.columns:
            spec = schema.fields.get(name)
            if spec is not None and not spec.single_value:
                cols[name] = list(part[name])
            else:
                cols[name] = part[name].to_numpy()
        seg_name = f"{prefix}_{i}"
        tmp = tempfile.mkdtemp()
        try:
            d = os.path.join(tmp, seg_name)
            build_segment(schema, cols, d, cfg, seg_name)
            # upload copies into the deep store; the local build dir is
            # scratch and must not accumulate across pipeline runs
            controller.upload_segment(table, d)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        names.append(seg_name)
    return names


def _execute(source, sql: str) -> dict:
    if isinstance(source, str):
        from pinot_tpu.client import connect

        with connect(source) as conn:
            return conn._execute(sql)
    if hasattr(source, "execute"):  # Broker or QueryEngine
        return source.execute(sql)
    if hasattr(source, "_execute"):  # DB-API Connection
        return source._execute(sql)
    raise TypeError(f"unsupported source {type(source).__name__}")
