"""Controller admin REST: table metadata endpoints with per-principal ACLs.

Minimal analog of the reference's controller API resources
(pinot-controller/.../api/resources/PinotTableRestletResource.java) over
the cluster registry, with ``BasicAuthAccessControlFactory``-style
enforcement (common/auth.py): a principal only sees / reads the tables its
``principals.<user>.tables=`` list grants.

    GET /health               liveness (open, like the reference)
    GET /tables               {"tables": [...]} filtered to the principal
    GET /tables/<name>        {"config": ..., "schema": ...} or 403/404
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from pinot_tpu.common.auth import BasicAuthAccessControl


class ControllerHttpServer:
    def __init__(self, registry, host: str = "127.0.0.1", port: int = 0,
                 users: Optional[dict] = None, acls: Optional[dict] = None,
                 access_control: Optional[BasicAuthAccessControl] = None):
        self.registry = registry
        if access_control is None and users:
            access_control = BasicAuthAccessControl(users, acls)
        elif access_control is None and acls:
            # ACLs without credentials cannot be enforced (see broker twin)
            raise ValueError("table acls require users (or access_control)")
        self._access = access_control
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _principal(self):
                if outer._access is None:
                    return ""
                return outer._access.authenticate(
                    self.headers.get("Authorization"))

            def do_GET(self):
                if self.path == "/health":
                    self._send(200, {"status": "OK"})
                    return
                principal = self._principal()
                if principal is None:
                    self.send_response(401)
                    self.send_header("WWW-Authenticate",
                                     'Basic realm="pinot-tpu-controller"')
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                if self.path.rstrip("/") == "/cluster/load":
                    # per-instance pressure + heartbeat age + autoscaler
                    # state (ISSUE 14) — the clusterstat --load payload.
                    # Cluster-wide data: principals with table grant
                    # lists are denied, like the broker's /metrics.
                    if outer._access is not None and \
                            outer._access.is_restricted(principal):
                        self._send(403, {"error": "Permission denied: "
                                                  "cluster load spans "
                                                  "tables outside this "
                                                  "principal's grants"})
                        return
                    import time as _time

                    from pinot_tpu.cluster.registry import (
                        HB_STALE_S,
                        Role,
                    )

                    now_ms = _time.time() * 1000
                    instances = {}
                    for i in outer.registry.instances(Role.SERVER):
                        age_ms = max(0.0, now_ms - i.last_heartbeat_ms)
                        instances[i.instance_id] = {
                            "pressure": float(
                                getattr(i, "pressure", 0.0) or 0.0),
                            "heartbeatAgeMs": round(age_ms, 1),
                            # the shared 3-interval staleness rule
                            # (registry HB_STALE_S — same cut the
                            # LoadTracker and autoscaler apply)
                            "live": age_ms <= HB_STALE_S * 1000.0,
                            "endpoint": i.endpoint,
                        }
                    self._send(200, {
                        "instances": instances,
                        "autoscaler": outer.registry.autoscaler_state(),
                    })
                    return
                if self.path.rstrip("/") == "/brokers":
                    # fleet discovery (ISSUE 18): every registered broker
                    # with liveness, drain state, and the QPS / cache-hit
                    # counters its heartbeat piggybacked — what a DB-API
                    # client rotates over and clusterstat --brokers
                    # renders. Cluster-wide data: restricted principals
                    # are denied, like /cluster/load.
                    if outer._access is not None and \
                            outer._access.is_restricted(principal):
                        self._send(403, {"error": "Permission denied: "
                                                  "broker fleet spans "
                                                  "tables outside this "
                                                  "principal's grants"})
                        return
                    import time as _time

                    from pinot_tpu.cluster.registry import (
                        HB_STALE_S,
                        Role,
                    )

                    now_ms = _time.time() * 1000
                    brokers = {}
                    for i in outer.registry.instances(Role.BROKER):
                        age_ms = max(0.0, now_ms - i.last_heartbeat_ms)
                        st = i.stats or {}
                        brokers[i.instance_id] = {
                            "url": st.get("url"),
                            "live": age_ms <= HB_STALE_S * 1000.0,
                            "draining": bool(st.get("draining")),
                            "heartbeatAgeMs": round(age_ms, 1),
                            "qps": float(st.get("qps", 0.0) or 0.0),
                            "queries": int(st.get("queries", 0) or 0),
                            "cacheHitRate": float(
                                st.get("cacheHitRate", 0.0) or 0.0),
                        }
                    self._send(200, {"brokers": brokers})
                    return
                if self.path == "/tables":
                    tables = outer.registry.tables()
                    if outer._access is not None:
                        tables = outer._access.allowed_tables(
                            principal, tables)
                    self._send(200, {"tables": sorted(tables)})
                    return
                heat_name = tier_name = None
                if self.path.startswith("/tables/") \
                        and self.path.rstrip("/").endswith("/heat"):
                    heat_name = self.path[len("/tables/"):].rstrip("/")
                    heat_name = heat_name[: -len("/heat")].strip("/")
                if self.path.startswith("/tables/") \
                        and self.path.rstrip("/").endswith("/tiers"):
                    tier_name = self.path[len("/tables/"):].rstrip("/")
                    tier_name = tier_name[: -len("/tiers")].strip("/")
                if tier_name:
                    # GET /tables/{t}/tiers (ISSUE 12): per-segment tier
                    # map aggregated from the servers' heartbeat tier
                    # snapshots — what the tier-aware assignment places
                    # by and clusterstat --tiers renders. Same non-empty-
                    # segment rule as /heat (a table literally named
                    # "tiers" keeps its metadata route).
                    if outer._access is not None and \
                            not outer._access.allows(principal, tier_name):
                        self._send(403, {"error": f"Permission denied on "
                                                  f"table {tier_name!r}"})
                        return
                    from pinot_tpu.controller.controller import (
                        aggregate_tiers,
                    )

                    self._send(200,
                               aggregate_tiers(outer.registry, tier_name))
                    return
                if heat_name:
                    # GET /tables/{t}/heat (ISSUE 11): cluster-aggregated
                    # per-segment access temperature from the servers'
                    # heartbeat-piggybacked heat snapshots — the tier
                    # lifecycle's promotion/demotion input. Requires a
                    # NON-EMPTY table segment: plain GET /tables/heat is
                    # the metadata route for a table literally named
                    # "heat", not an aggregation over ''.
                    name = heat_name
                    if outer._access is not None and \
                            not outer._access.allows(principal, name):
                        self._send(403, {"error": f"Permission denied on "
                                                  f"table {name!r}"})
                        return
                    from pinot_tpu.controller.controller import (
                        aggregate_heat,
                    )

                    self._send(200, aggregate_heat(outer.registry, name))
                    return
                if self.path.startswith("/tables/"):
                    name = self.path[len("/tables/"):].strip("/")
                    if outer._access is not None and \
                            not outer._access.allows(principal, name):
                        # deny BEFORE existence resolution: a denied
                        # principal can't probe the table namespace
                        self._send(403, {"error": f"Permission denied on "
                                                  f"table {name!r}"})
                        return
                    # raw names resolve their typed variants, like the
                    # reference's table resource
                    cfg, resolved = None, name
                    for cand in (name, f"{name}_OFFLINE", f"{name}_REALTIME"):
                        cfg = outer.registry.table_config(cand)
                        if cfg is not None:
                            resolved = cand
                            break
                    if cfg is None:
                        self._send(404, {"error": f"table {name!r} not found"})
                        return
                    schema = outer.registry.table_schema(resolved)
                    self._send(200, {
                        "config": cfg.to_json(),
                        "schema": schema.to_json() if schema else None,
                    })
                    return
                self._send(404, {"error": "not found"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="controller-http",
            daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
