"""Controller autoscaler: pressure-driven server elasticity (ISSUE 14).

The closing of the overload loop: PR 10's heartbeats piggyback every
server's scheduler ``pressure()`` (admitted + queued queries) into the
registry; this periodic task watches those signals and

- **scales OUT** when the live fleet's mean pressure stays above the
  high-water mark for ``sustain_ticks`` consecutive ticks: it asks the
  deployment's ``spawn_fn`` for one more server, then republishes
  replica-group membership through the PR-10 minimal-movement rebalance
  (``run_replica_group_repair``) so only the segments the new member
  must carry actually move;
- **scales IN** when mean pressure stays below the low-water mark: the
  least-loaded server drains FIRST (``drain_fn`` → PR 6's graceful
  ``ServerInstance.stop()`` — new submits answer retriable
  SERVER_SHUTTING_DOWN and the broker re-routes, so scale-in causes
  zero query errors), and membership republishes afterward.

Heartbeat-STALE instances (no heartbeat within ``hb_stale_s`` — the
same 3-interval rule the broker's LoadTracker applies) contribute
neither capacity nor pressure: a crashed server must read as missing
capacity (scale out), never as an idle peer (scale in).

The reference has no autoscaler at all — Pinot clusters resize by
operator action + manual rebalance; this is the ``QueryScheduler``
survey's missing elasticity leg built on our registry/heartbeat seams.

Deployment wiring: ``spawn_fn() -> instance_id | None`` and
``drain_fn(instance_id) -> bool`` abstract HOW servers start/stop —
in-process ``ServerInstance`` for tests/bench, ``admin start-server``
subprocesses or a k8s scale call in production. Attach via
``Controller.attach_autoscaler``; the controller's periodic loop runs
``tick()`` on the global-lead holder only, and every tick publishes the
autoscaler's state into the registry (``tools/clusterstat.py --load``).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

from pinot_tpu.cluster.registry import HB_STALE_S, Role

log = logging.getLogger("pinot_tpu.autoscaler")


class ControllerAutoscaler:
    def __init__(self, controller,
                 spawn_fn: Callable[[], Optional[str]],
                 drain_fn: Callable[[str], bool],
                 min_servers: int = 1, max_servers: int = 4,
                 high_water: float = 4.0, low_water: float = 0.5,
                 sustain_ticks: int = 3, cooldown_ticks: int = 2,
                 hb_stale_s: float = HB_STALE_S):
        if low_water >= high_water:
            raise ValueError("low_water must sit below high_water "
                             f"({low_water} >= {high_water})")
        self.controller = controller
        self.registry = controller.registry
        self.spawn_fn = spawn_fn
        self.drain_fn = drain_fn
        self.min_servers = max(1, int(min_servers))
        self.max_servers = max(self.min_servers, int(max_servers))
        self.high_water = float(high_water)
        self.low_water = float(low_water)
        self.sustain_ticks = max(1, int(sustain_ticks))
        self.cooldown_ticks = max(0, int(cooldown_ticks))
        self.hb_stale_s = float(hb_stale_s)
        self._above = 0
        self._below = 0
        self._cooldown = 0
        self.actions: list = []   # bounded history of scale decisions
        self.num_scale_out = 0
        self.num_scale_in = 0

    # ---- signal ----------------------------------------------------------
    def _live_pressure(self) -> tuple:
        """([live instance ids sorted by pressure], mean pressure).
        Heartbeat-stale instances are excluded from BOTH sides: a crashed
        server is missing capacity, not an idle peer."""
        now_ms = time.time() * 1000
        live = []
        for i in self.registry.instances(Role.SERVER):
            age_s = max(0.0, (now_ms - i.last_heartbeat_ms) / 1e3)
            if age_s <= self.hb_stale_s:
                live.append((float(getattr(i, "pressure", 0.0) or 0.0),
                             i.instance_id))
        live.sort()
        mean = sum(p for p, _ in live) / len(live) if live else 0.0
        return [inst for _p, inst in live], mean

    # ---- the control loop ------------------------------------------------
    def tick(self) -> Optional[dict]:
        """One autoscale decision; returns the action taken (or None).
        Called from the controller periodic loop on the global lead."""
        live, mean = self._live_pressure()
        n = len(live)
        action = None
        if self._cooldown > 0:
            # let the previous action's rebalance + routing settle before
            # judging pressure again — scaling on a half-moved cluster's
            # transient pressure would oscillate
            self._cooldown -= 1
        else:
            if mean >= self.high_water and n < self.max_servers:
                self._above += 1
                self._below = 0
            elif mean <= self.low_water and n > self.min_servers:
                self._below += 1
                self._above = 0
            else:
                self._above = self._below = 0
            if self._above >= self.sustain_ticks:
                action = self._scale_out(n, mean)
            elif self._below >= self.sustain_ticks:
                action = self._scale_in(live, mean)
        self._publish(n, mean, action)
        return action

    def _scale_out(self, n: int, mean: float) -> Optional[dict]:
        try:
            new_id = self.spawn_fn()
        except Exception:
            log.exception("autoscaler spawn failed")
            new_id = None
        self._above = 0
        self._cooldown = self.cooldown_ticks
        if new_id is None:
            return None
        self.num_scale_out += 1
        # grow replica groups for the hot tables with MINIMAL movement:
        # the PR-10 repair rebuilds membership over the new live set and
        # moves only the segments the group change requires
        try:
            self.controller.run_replica_group_repair()
        except Exception:
            log.exception("post-scale-out replica-group repair failed")
        return self._note("scale_out", new_id, n + 1, mean)

    def _scale_in(self, live: list, mean: float) -> Optional[dict]:
        # drain the LEAST-loaded live server (live is pressure-sorted);
        # PR 6's graceful drain is the exit path: in-flight queries
        # finish, new submits re-route — zero query errors by contract
        victim = live[0]
        try:
            ok = bool(self.drain_fn(victim))
        except Exception:
            log.exception("autoscaler drain of %s failed", victim)
            ok = False
        self._below = 0
        self._cooldown = self.cooldown_ticks
        if not ok:
            return None
        self.num_scale_in += 1
        try:
            self.controller.run_replica_group_repair()
        except Exception:
            log.exception("post-scale-in replica-group repair failed")
        return self._note("scale_in", victim, len(live) - 1, mean)

    def _note(self, kind: str, instance: str, n_after: int,
              mean: float) -> dict:
        action = {"action": kind, "instance": instance,
                  "servers_after": n_after,
                  "mean_pressure": round(mean, 2),
                  "ts": round(time.time(), 1)}
        self.actions.append(action)
        del self.actions[:-16]  # bounded history
        log.info("autoscaler %s %s (fleet -> %d, pressure %.2f)",
                 kind, instance, n_after, mean)
        return action

    def _publish(self, n: int, mean: float, action) -> None:
        """Registry-published state: what clusterstat --load renders."""
        try:
            self.registry.set_autoscaler_state({
                "servers": n,
                "min": self.min_servers, "max": self.max_servers,
                "meanPressure": round(mean, 2),
                "highWater": self.high_water, "lowWater": self.low_water,
                "aboveTicks": self._above, "belowTicks": self._below,
                "cooldownTicks": self._cooldown,
                "scaleOuts": self.num_scale_out,
                "scaleIns": self.num_scale_in,
                "lastAction": action or (self.actions[-1]
                                         if self.actions else None),
            })
        except Exception:
            log.exception("autoscaler state publish failed")
