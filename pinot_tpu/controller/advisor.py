"""Config recommender + table tuner (controller long-tail).

Reference analogs:
- recommender (pinot-controller/.../recommender/RecommenderDriver.java):
  workload description (schema + sample queries + QPS) → suggested
  indexing config, via per-rule engines (inverted/sorted/bloom/no-dict);
- tuner (pinot-controller/.../tuner/TableConfigTuner.java): adjust an
  EXISTING table's config from observed segment metadata.

Both produce an IndexingConfig delta + human-readable rationale; the
tuner can apply its suggestion through the registry (the reference's
recommender is advisory too — it returns config, users apply it).
"""

from __future__ import annotations

import dataclasses
from collections import Counter

from pinot_tpu.common.table_config import IndexingConfig
from pinot_tpu.query.context import FilterNodeType, PredicateType


def _walk_predicates(f, out):
    if f is None:
        return
    if f.type is FilterNodeType.PREDICATE:
        out.append(f.predicate)
        return
    for c in f.children or ():
        _walk_predicates(c, out)


def recommend_config(schema, sample_queries, qps: float = 100.0) -> dict:
    """Workload-driven indexing recommendation (RecommenderDriver role).

    Rules (each mirrors a reference rule engine):
    - EQ/IN-filtered dimensions → inverted index; the most-filtered one →
      sorted-column candidate (the reference's FlagQueryRuleParams +
      InvertedSortedIndexJointRule);
    - RANGE-filtered columns → range index (RangeIndexRule);
    - high-selectivity EQ columns → bloom filter (BloomFilterRule);
    - repeated GROUP BY shape with SUM/COUNT/MIN/MAX/DISTINCTCOUNTHLL →
      star-tree config (the aggregate-metrics rule);
    - LIKE/REGEXP-filtered dimensions → fst (trigram) index.
    """
    from pinot_tpu.sql.compiler import compile_query

    eq_cols: Counter = Counter()
    range_cols: Counter = Counter()
    regex_cols: Counter = Counter()
    groupby_shapes: Counter = Counter()
    st_pairs: dict = {}
    for sql in sample_queries:
        try:
            q = compile_query(sql)
        except Exception:  # noqa: BLE001 — advisory: skip unparsable input
            continue
        preds = []
        _walk_predicates(q.filter, preds)
        for p in preds:
            if not p.lhs.is_identifier:
                continue
            col = p.lhs.name
            if p.type in (PredicateType.EQ, PredicateType.IN,
                          PredicateType.NOT_EQ, PredicateType.NOT_IN):
                eq_cols[col] += 1
            elif p.type is PredicateType.RANGE:
                range_cols[col] += 1
            elif p.type in (PredicateType.LIKE, PredicateType.REGEXP_LIKE):
                regex_cols[col] += 1
        if q.group_by and all(g.is_identifier for g in q.group_by):
            dims = tuple(sorted(g.name for g in q.group_by))
            aggs = q.aggregations()
            if aggs and all(a.name in ("count", "sum", "min", "max", "avg",
                                       "distinctcounthll") for a in aggs):
                groupby_shapes[dims] += 1
                pairs = st_pairs.setdefault(dims, set())
                for a in aggs:
                    if a.name == "count":
                        pairs.add("COUNT__*")
                    elif a.name == "avg":
                        if a.args and a.args[0].is_identifier:
                            pairs.add(f"SUM__{a.args[0].name}")
                            pairs.add("COUNT__*")
                    elif a.args and a.args[0].is_identifier:
                        pairs.add(f"{a.name.upper()}__{a.args[0].name}")

    from pinot_tpu.common.datatypes import FieldRole

    dim_names = {n for n, s in schema.fields.items()
                 if s.role is not FieldRole.METRIC}
    inverted = [c for c, _ in eq_cols.most_common() if c in dim_names]
    sorted_candidate = inverted[0] if inverted else None
    rationale = []
    if inverted:
        rationale.append(
            f"inverted index on {inverted}: EQ/IN filters seen "
            f"{dict(eq_cols)} times")
    if sorted_candidate:
        rationale.append(
            f"sort segments on {sorted_candidate!r}: most-filtered "
            f"dimension (binary-search doc runs beat bitmaps)")
    rng = [c for c in range_cols if range_cols[c] >= 2]
    if rng:
        rationale.append(f"range index on {rng}: repeated range filters")
    bloom = [c for c in eq_cols if eq_cols[c] >= 2]
    fst = list(regex_cols)
    if fst:
        rationale.append(f"fst (trigram) index on {fst}: LIKE/REGEXP filters")
    star_tree_configs = []
    for dims, count in groupby_shapes.most_common(1):
        if count >= 2 and qps >= 10:
            from pinot_tpu.common.table_config import StarTreeIndexConfig

            star_tree_configs.append(StarTreeIndexConfig(
                dimensions_split_order=list(dims),
                function_column_pairs=sorted(st_pairs[dims]),
            ))
            rationale.append(
                f"star-tree over {list(dims)}: group-by shape repeated "
                f"{count}x at {qps} QPS")
    return {
        "indexing": IndexingConfig(
            inverted_index_columns=inverted,
            range_index_columns=rng,
            bloom_filter_columns=bloom,
            fst_index_columns=fst,
            star_tree_configs=star_tree_configs,
        ),
        "sorted_column": sorted_candidate,
        "rationale": rationale,
    }


def tune_table(registry, table: str, segments) -> dict:
    """Observed-metadata tuner (TableConfigTuner role): inspect hosted
    segments' column stats and grow the table's IndexingConfig; returns
    {indexing, changes} and writes the updated config back when anything
    changed."""
    cfg = registry.table_config(table)
    if cfg is None:
        raise KeyError(f"table {table!r} not found")
    idx = cfg.indexing
    changes = []
    bloom = set(idx.bloom_filter_columns)
    inverted = set(idx.inverted_index_columns)
    if segments:
        seg = segments[0]
        n = max(1, seg.n_docs)
        for col in seg.column_names():
            meta = seg.column_metadata(col)
            card = meta.cardinality or 0
            if not meta.single_value:
                continue
            # high-selectivity point-lookup columns: bloom pays
            if card > 0.5 * n and col not in bloom and meta.has_dictionary:
                bloom.add(col)
                changes.append(
                    f"bloom on {col!r} (cardinality {card} ~ docs {n})")
            # low-card dimensions: inverted postings are tiny and beat scans
            if 1 < card <= 1000 and col not in inverted \
                    and meta.has_dictionary and not meta.is_sorted:
                inverted.add(col)
                changes.append(
                    f"inverted on {col!r} (cardinality {card})")
    new_idx = dataclasses.replace(
        idx,
        bloom_filter_columns=sorted(bloom),
        inverted_index_columns=sorted(inverted),
    )
    if changes:
        new_cfg = dataclasses.replace(cfg, indexing=new_idx)
        registry.set_table_config(table, new_cfg)
    return {"indexing": new_idx, "changes": changes}
