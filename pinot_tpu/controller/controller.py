"""Controller role: table/segment lifecycle + assignment + maintenance.

Equivalent of the reference's controller (pinot-controller/:
PinotHelixResourceManager table/segment/instance CRUD, segment assignment
strategies under assignment/segment/, TableRebalancer minimal-movement
rebalance, RetentionManager, PinotLLCRealtimeSegmentManager creating
consuming partitions). Helix writes become registry transactions; servers
reconcile by polling (server/server.py sync loop).
"""

from __future__ import annotations

import logging
import os
import threading
import time
import zlib
from typing import Optional

import numpy as np

from pinot_tpu.cluster.registry import (
    ClusterRegistry,
    InstanceInfo,
    Role,
    SegmentRecord,
    SegmentState,
)
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig, TableType
from pinot_tpu.storage.segment import ImmutableSegment

log = logging.getLogger("pinot_tpu.controller")


def aggregate_heat(registry: ClusterRegistry, table: str) -> dict:
    """Cluster-wide segment-temperature view for one table (ISSUE 11):
    merges every server heartbeat's piggybacked heat snapshot
    (server/heat.py) across instances and the table's physical variants
    — decayed rates sum (a 2-replica hot segment is twice as hot to the
    cluster), lifetime counters sum, last access takes the max.  The
    payload behind ``GET /tables/{t}/heat`` and
    ``tools/clusterstat.py``; the ranking ROADMAP 3's tier
    promotion/demotion policy will consume."""
    candidates = {table, f"{table}_OFFLINE", f"{table}_REALTIME"}
    segs: dict = {}
    reporting = 0
    for info in registry.instances(Role.SERVER):
        h = getattr(info, "heat", None) or {}
        seen = False
        for t in candidates:
            per = h.get(t)
            if not per:
                continue
            seen = True
            for seg, rec in per.items():
                agg = segs.setdefault(seg, {
                    "rate": 0.0, "bytesRate": 0.0, "accesses": 0,
                    "bytes": 0, "lastAccessTs": 0.0, "instances": 0})
                agg["rate"] = round(
                    agg["rate"] + float(rec.get("rate") or 0.0), 4)
                agg["bytesRate"] = round(
                    agg["bytesRate"] + float(rec.get("bytesRate") or 0.0), 1)
                agg["accesses"] += int(rec.get("accesses") or 0)
                agg["bytes"] += int(rec.get("bytes") or 0)
                agg["lastAccessTs"] = max(
                    agg["lastAccessTs"], float(rec.get("lastAccessTs") or 0))
                agg["instances"] += 1
        if seen:
            reporting += 1
    return {
        "table": table,
        "instancesReporting": reporting,
        "segments": dict(sorted(segs.items(),
                                key=lambda kv: -kv[1]["rate"])),
    }


_TIER_RANK = {"hot": 0, "warm": 1, "cold": 2}


def aggregate_tiers(registry: ClusterRegistry, table: str) -> dict:
    """Cluster-wide per-segment tier view for one table (ISSUE 12):
    merges every server heartbeat's piggybacked tier map
    (server/tiering.py TierManager.snapshot()) across instances and the
    table's physical variants. A segment's cluster tier is the HOTTEST
    any replica reports — one hot replica means the cluster still pays
    (and benefits from) hot-tier serving, and the tier-aware assignment
    must not strip it. The payload behind ``GET /tables/{t}/tiers`` and
    ``tools/clusterstat.py --tiers``."""
    candidates = {table, f"{table}_OFFLINE", f"{table}_REALTIME"}
    segs: dict = {}
    reporting = 0
    for info in registry.instances(Role.SERVER):
        tiers = getattr(info, "tiers", None) or {}
        seen = False
        for t in candidates:
            per = tiers.get(t)
            if not per:
                continue
            seen = True
            for seg, tier in per.items():
                ent = segs.setdefault(seg, {"tier": tier, "instances": {}})
                ent["instances"][info.instance_id] = tier
                if _TIER_RANK.get(tier, 1) < _TIER_RANK.get(ent["tier"], 1):
                    ent["tier"] = tier
        if seen:
            reporting += 1
    return {
        "table": table,
        "instancesReporting": reporting,
        "segments": segs,
    }


def _column_stats_fields(meta) -> dict:
    """Per-column min/max from segment metadata, JSON-plain, for the
    SegmentRecord the broker prunes on (SegmentZKMetadata's column
    min/max role). Non-scalar values (bytes) are skipped — the broker
    treats missing stats as "may match"."""
    stats = {}
    for cm in meta.columns.values():
        mn, mx = cm.min_value, cm.max_value
        if isinstance(mn, np.generic):
            mn = mn.item()
        if isinstance(mx, np.generic):
            mx = mx.item()
        if mn is None or mx is None or \
                isinstance(mn, bytes) or isinstance(mx, bytes):
            continue
        stats[cm.name] = {"min": mn, "max": mx}
    return {"column_stats": stats} if stats else {}


def _partition_record_fields(meta) -> dict:
    """Partition metadata of the first partitioned column, for broker-side
    pruning (SegmentPartitionConfig → SegmentZKMetadata partition metadata
    in the reference)."""
    for cm in meta.columns.values():
        if cm.partition_function and cm.partitions:
            return {
                "partition_column": cm.name,
                "partition_ids": list(cm.partitions),
                "partition_function": cm.partition_function,
                "num_partitions": cm.num_partitions,
            }
    return {}


class SegmentAssigner:
    """Balanced assignment: each segment gets `replication` replicas on the
    least-loaded live servers (assignment/segment/OfflineSegmentAssignment +
    SegmentAssignmentUtils balanced strategy). Liveness = heartbeat within
    ``live_ttl_ms`` (servers heartbeat from their sync loop), so hard-dead
    instances never receive new segments."""

    def __init__(self, registry: ClusterRegistry, live_ttl_ms: int = 30_000):
        self.registry = registry
        self.live_ttl_ms = live_ttl_ms

    def _live_servers(self):
        return self.registry.instances(Role.SERVER, live_ttl_ms=self.live_ttl_ms)

    def _load(self) -> dict:
        counts: dict[str, int] = {
            i.instance_id: 0 for i in self._live_servers()
        }
        for table in self.registry.tables():
            for seg, instances in self.registry.assignment(table).items():
                for inst in instances:
                    if inst in counts:
                        counts[inst] += 1
        return counts

    def assign(self, replication: int) -> list:
        counts = self._load()
        if not counts:
            raise RuntimeError("no live servers to assign to")
        ordered = sorted(counts, key=lambda i: counts[i])
        want = max(1, min(replication, len(ordered)))
        # failure-domain spread (AzureEnvironmentProvider role,
        # common/environment.py): replicas prefer DISTINCT fd: domains so
        # one fault boundary can't take out every copy; falls back to
        # pure least-loaded when domains are absent or too few
        from pinot_tpu.common.environment import domain_of

        infos = {i.instance_id: i for i in self._live_servers()}
        picked, seen_fd = [], set()
        for inst in ordered:
            fd = domain_of(infos.get(inst))
            if fd is not None and fd in seen_fd:
                continue
            picked.append(inst)
            if fd is not None:
                seen_fd.add(fd)
            if len(picked) >= want:
                return picked
        for inst in ordered:  # not enough distinct domains: top up by load
            if inst not in picked:
                picked.append(inst)
                if len(picked) >= want:
                    break
        return picked

    def rebalance(self, table: str, replication: int,
                  servers: Optional[list] = None) -> dict:
        """Minimal-movement rebalance (rebalance/TableRebalancer.java): keep
        existing replicas where possible, move only to fix replication or
        heavy skew. ``servers`` overrides the liveness-derived target set
        (the dead-instance repair passes the conservative hard-live set so
        a merely-slow server isn't stripped of its replicas)."""
        if servers is None:
            servers = [i.instance_id for i in self._live_servers()]
        if not servers:
            return {}
        current = self.registry.assignment(table)
        target_total = sum(max(1, min(replication, len(servers))) for _ in current)
        per_server = -(-target_total // len(servers))  # ceil: balanced load cap
        counts = {s: 0 for s in servers}
        new: dict[str, list] = {}
        # first pass: keep existing placements that still fit
        for seg, instances in current.items():
            kept = []
            for inst in instances:
                if inst in counts and counts[inst] < per_server and len(kept) < replication:
                    kept.append(inst)
                    counts[inst] += 1
            new[seg] = kept
        # second pass: top up replication from least-loaded servers
        for seg, kept in new.items():
            want = max(1, min(replication, len(servers)))
            for inst in sorted(counts, key=lambda s: counts[s]):
                if len(kept) >= want:
                    break
                if inst not in kept:
                    kept.append(inst)
                    counts[inst] += 1
        self.registry.set_assignment(table, new)
        return new

    # ---- replica groups (ISSUE 10) ---------------------------------------
    # assignment/segment/ReplicaGroupSegmentAssignmentStrategy +
    # InstanceReplicaGroupPartitionSelector analog: live servers are
    # partitioned into R named groups, each holding ONE complete replica
    # of the table; every segment places exactly one copy in each group.
    # The broker then routes a whole query to a single group's instances
    # (instead of ad-hoc per-segment replica picks), which is what makes
    # per-group load attribution — and near-linear multi-server QPS —
    # possible.

    def build_replica_groups(self, table: str, replication: int) -> dict:
        """Minimal-change group membership for the live server set: keep
        every surviving member in its current group, fill new servers into
        the smallest groups, and only then level residual skew. Returns
        {group name: [instance ids]} (empty when no live servers)."""
        live = sorted(i.instance_id for i in self._live_servers())
        if not live:
            return {}
        r = max(1, min(replication, len(live)))
        names = [f"rg_{i}" for i in range(r)]
        old = self.registry.replica_groups(table)
        groups: dict = {}
        assigned: set = set()
        for name in names:
            members = [m for m in old.get(name, ())
                       if m in live and m not in assigned]
            groups[name] = members
            assigned.update(members)
        for inst in live:
            if inst not in assigned:
                smallest = min(names, key=lambda n: (len(groups[n]), n))
                groups[smallest].append(inst)
        # level heavy skew (dissolved groups / uneven survivors): move one
        # member at a time from the largest to the smallest group
        while True:
            small = min(names, key=lambda n: (len(groups[n]), n))
            big = max(names, key=lambda n: (len(groups[n]), n))
            if len(groups[big]) - len(groups[small]) <= 1:
                break
            groups[small].append(groups[big].pop())
        return groups

    def _plan_replica_group_assignment(self, table: str,
                                       replication: int) -> tuple:
        """Pure planning half of the replica-group rebalance: the
        (groups, assignment) a rebalance WOULD write, computed without
        touching the registry — tier-aware callers (rebalance_tiered)
        post-process the plan and publish only real changes, so a
        steady-state periodic pass never churns the routing generation."""
        groups = self.build_replica_groups(table, replication)
        if not groups:
            return {}, {}
        records = self.registry.segments(table)
        current = self.registry.assignment(table)
        seg_names = sorted(set(records) | set(current))
        new: dict = {}
        for name in sorted(groups):
            members = groups[name]
            if not members:
                continue
            cap = -(-max(1, len(seg_names)) // len(members))
            counts = {m: 0 for m in members}
            placed: dict = {}
            mset = set(members)
            # pass 1: partition-determined + sticky placements
            for seg in seg_names:
                rec = records.get(seg)
                cur = [i for i in current.get(seg, ()) if i in mset]
                if rec is not None and rec.partition_ids:
                    pick = members[int(rec.partition_ids[0]) % len(members)]
                elif cur and counts[cur[0]] < cap:
                    pick = cur[0]
                else:
                    continue  # homeless: place in pass 2, least-loaded
                placed[seg] = pick
                counts[pick] += 1
            # pass 2: everything else goes least-loaded
            for seg in seg_names:
                if seg in placed:
                    continue
                pick = min(members, key=lambda m: (counts[m], m))
                placed[seg] = pick
                counts[pick] += 1
            for seg, pick in placed.items():
                new.setdefault(seg, []).append(pick)
        return groups, new

    def rebalance_replica_groups(self, table: str, replication: int) -> dict:
        """(Re)build groups + per-group segment placement; writes both the
        group map and the assignment. Movement is minimal: membership
        keeps survivors in place, and unpartitioned segments move only to
        fix replication or to fill a joined server up to its fair share
        (ceil(n_segments / group size)). Partitioned segments place
        DETERMINISTICALLY by partition id — co-partitioned segments land
        on the same member, so a partition-EQ query (which the broker
        prunes with the same common/pruning.py algebra the server uses)
        touches exactly one instance per group."""
        groups, new = self._plan_replica_group_assignment(table, replication)
        if not groups:
            return {}
        self.registry.set_replica_groups(table, groups)
        self.registry.set_assignment(table, new)
        return new

    def rebalance_tiered(self, table: str, replication: int,
                         tiers: dict) -> dict:
        """Tier-aware replica-group assignment (ISSUE 12): hot/warm
        segments keep the full R-way replica-group placement (device- and
        host-backed serving capacity chases the hot set); COLD segments
        trim to a SINGLE copy — the object store is their durability, so
        extra replicas only burn disk and sync traffic. ``tiers`` maps
        segment → tier (or → the aggregate_tiers per-segment dict).

        Movement is minimal twice over: the underlying plan is PR-10's
        sticky rebalance (unflipped segments keep their placement), a
        cold segment keeps its first surviving current replica (the copy
        already on disk somewhere), and NOTHING is published unless the
        plan actually differs from the registry — a steady-state periodic
        pass bumps no routing generation and blows no broker caches. A
        temperature flip therefore moves exactly the flipped segments."""
        groups, new = self._plan_replica_group_assignment(table, replication)
        if not groups:
            return {}
        current = self.registry.assignment(table)
        for seg, tinfo in tiers.items():
            tier = tinfo.get("tier") if isinstance(tinfo, dict) else tinfo
            if tier != "cold" or seg not in new:
                continue
            keep = [i for i in current.get(seg, ()) if i in new[seg]][:1] \
                or new[seg][:1]
            new[seg] = keep
        if groups != self.registry.replica_groups(table):
            self.registry.set_replica_groups(table, groups)
        if {k: sorted(v) for k, v in new.items()} != \
                {k: sorted(v) for k, v in current.items()}:
            self.registry.set_assignment(table, new)
        return new

    def assign_with_groups(self, table: str, rec) -> Optional[list]:
        """Upload-path placement when a replica-group map exists: one
        member per group (partition-aware, else least-loaded by current
        assignment). None when the table has no usable group map — the
        caller falls back to the balanced legacy strategy."""
        groups = self.registry.replica_groups(table)
        live = {i.instance_id for i in self._live_servers()}
        groups = {n: [m for m in ms if m in live] for n, ms in groups.items()}
        groups = {n: ms for n, ms in groups.items() if ms}
        if not groups:
            return None
        current = self.registry.assignment(table)
        counts: dict = {}
        for insts in current.values():
            for i in insts:
                counts[i] = counts.get(i, 0) + 1
        out = []
        for name in sorted(groups):
            members = groups[name]
            if rec is not None and rec.partition_ids:
                pick = members[int(rec.partition_ids[0]) % len(members)]
            else:
                pick = min(members, key=lambda m: (counts.get(m, 0), m))
            counts[pick] = counts.get(pick, 0) + 1
            if pick not in out:
                out.append(pick)
        return out


class Controller:
    def __init__(self, registry: ClusterRegistry, deep_store_dir: str,
                 controller_id: str = "controller_0"):
        from pinot_tpu.storage.fs import create_fs

        self.registry = registry
        self.deep_store = deep_store_dir
        # deep-store IO routes through the PinotFS SPI: swapping the scheme
        # (s3://, gs://) swaps the storage backend via the plugin registry
        self.fs = create_fs(deep_store_dir)
        self.fs.mkdir(deep_store_dir)
        self.assigner = SegmentAssigner(registry)
        self.controller_id = controller_id
        registry.register_instance(InstanceInfo(controller_id, Role.CONTROLLER))
        # HA state (start_ha): which lead partitions this controller holds.
        # HA never started → is_lead_for() says yes to everything (the
        # single-controller deployment needs no election); HA STOPPED is a
        # tombstone that leads NOTHING — a drained controller whose
        # periodic loop hasn't been torn down yet must not fall back to
        # "I lead everything" and split-brain with the survivor.
        self._ha_thread: Optional[threading.Thread] = None
        self._ha_stopped = False
        self._held_partitions: set = set()
        # pressure-driven elasticity (ISSUE 14, controller/autoscaler.py)
        # — attach_autoscaler wires it; run_autoscale rides the periodic
        # loop as a cluster-wide (global-lead) duty
        self.autoscaler = None

    def table_heat(self, table: str) -> dict:
        """Aggregated per-segment access temperature for ``table``
        (ISSUE 11) — the GET /tables/{t}/heat payload."""
        return aggregate_heat(self.registry, table)

    def table_tiers(self, table: str) -> dict:
        """Aggregated per-segment tier view for ``table`` (ISSUE 12) —
        the GET /tables/{t}/tiers payload."""
        return aggregate_tiers(self.registry, table)

    def attach_autoscaler(self, spawn_fn, drain_fn, **kwargs):
        """Wire the pressure-driven autoscaler (ISSUE 14): ``spawn_fn()``
        starts one more server (returns its instance id), ``drain_fn(id)``
        gracefully drains one (PR 6's ServerInstance.stop contract).
        Watermarks/sustain knobs ride ``kwargs`` — see
        controller/autoscaler.py. Returns the attached instance."""
        from pinot_tpu.controller.autoscaler import ControllerAutoscaler

        self.autoscaler = ControllerAutoscaler(
            self, spawn_fn, drain_fn, **kwargs)
        return self.autoscaler

    def run_autoscale(self):
        """One autoscaler tick (periodic-loop step, global-lead only —
        two controllers scaling the same fleet would double-spawn)."""
        if self.autoscaler is None or not self._leads_global():
            return None
        return self.autoscaler.tick()

    def run_tier_rebalance(self) -> dict:
        """Tier-aware placement pass (ISSUE 12): replica-group tables
        whose servers report per-segment tiers re-place so COLD segments
        hold a single copy and hot/warm segments keep full replication.
        Publishes nothing when the plan matches the registry (see
        rebalance_tiered), so running it every periodic tick is free in
        the steady state. Returns {table: [segments whose replica set
        changed]}."""
        changed: dict = {}
        for table in self.registry.tables():
            if not self.is_lead_for(table):
                continue  # another controller leads this table (HA)
            if not self.registry.replica_groups(table):
                continue  # tier-aware placement rides replica groups
            tiers = aggregate_tiers(self.registry, table).get("segments", {})
            if not tiers:
                continue  # no server reports tiering for this table
            cfg = self.registry.table_config(table)
            if cfg is None:
                continue
            before = self.registry.assignment(table)
            after = self.assigner.rebalance_tiered(
                table, self._table_replication(cfg), tiers)
            moved = sorted(
                seg for seg in set(before) | set(after)
                if sorted(before.get(seg, ())) != sorted(after.get(seg, ())))
            if moved:
                changed[table] = moved
        return changed

    # ---- HA: lease-based leader election + lead-controller partitioning --
    # The reference runs N controllers with Helix leader election and
    # per-table lead-controller partitioning (pinot-controller/.../
    # LeadControllerManager.java:1, lead-controller resource). Here the
    # registry's atomic lease tx is the arbiter: tables hash onto
    # LEAD_PARTITIONS lease slots; each live controller (re)acquires what
    # it can every tick, so slots of a dead controller expire and
    # survivors absorb them within one lease TTL. Client-initiated calls
    # (add_table, upload_segment, rebalance) stay valid on ANY controller,
    # exactly like the reference's REST surface — only background duties
    # are partitioned.

    LEAD_PARTITIONS = 4

    @staticmethod
    def _lead_lease_name(p: int) -> str:
        return f"controller/lead/{p}"

    def start_ha(self, lease_ttl_ms: int = 3000,
                 interval_s: float = 0.5) -> None:
        """Join the controller quorum: acquire/renew lead-partition leases
        on a timer. Safe to call on every controller process; they split
        the partitions and fail over on lease expiry."""
        if self._ha_thread is not None:
            return
        self._ha_ttl_ms = lease_ttl_ms
        self._ha_stopped = False
        self._ha_stop = threading.Event()

        def loop():
            while not self._ha_stop.wait(interval_s):
                try:
                    self._ha_tick()
                except Exception:
                    log.exception("HA lease tick failed")

        self._ha_tick()  # hold leases before the thread's first wait
        self._ha_thread = threading.Thread(
            target=loop, name=f"ha-{self.controller_id}", daemon=True)
        self._ha_thread.start()

    def _ha_tick(self) -> None:
        # fair share: live controllers split the partitions (ceil so every
        # slot has an eligible holder); a dead peer's heartbeat stales out
        # of the live set, its quota-raised survivors absorb the expired
        # leases. One registry tx renews/acquires/yields + heartbeats.
        live = {i.instance_id for i in self.registry.instances(
            Role.CONTROLLER, live_ttl_ms=max(3 * self._ha_ttl_ms, 2000))}
        live.add(self.controller_id)
        quota = -(-self.LEAD_PARTITIONS // len(live))
        order = sorted(range(self.LEAD_PARTITIONS),
                       key=lambda p: (p not in self._held_partitions, p))
        held_names = self.registry.lease_tick(
            self.controller_id, [self._lead_lease_name(p) for p in order],
            quota, self._ha_ttl_ms)
        held = {p for p in range(self.LEAD_PARTITIONS)
                if self._lead_lease_name(p) in held_names}
        if held != self._held_partitions:
            log.info("controller %s lead partitions: %s -> %s",
                     self.controller_id, sorted(self._held_partitions),
                     sorted(held))
        self._held_partitions = held

    def stop_ha(self, release: bool = True) -> None:
        """``release=False`` models a crash: leases stay until TTL expiry,
        which is exactly what a standby's takeover test needs."""
        if self._ha_thread is None:
            return
        self._ha_stop.set()
        self._ha_thread.join(5)
        self._ha_thread = None
        self._ha_stopped = True  # tombstone: lead NOTHING from now on
        if release:
            for p in list(self._held_partitions):
                self.registry.release_lease(
                    self._lead_lease_name(p), self.controller_id)
            # leave the quorum's liveness window too, so survivors
            # re-quota immediately instead of waiting out the TTL
            self.registry.expire_heartbeat(self.controller_id)
        self._held_partitions = set()

    def _ha_active(self) -> bool:
        return self._ha_thread is not None or self._ha_stopped

    def is_lead_for(self, table: str) -> bool:
        """Does this controller own the background duties for ``table``?"""
        if not self._ha_active():
            return True  # HA never started: single controller leads all
        p = zlib.crc32(table.encode("utf-8")) % self.LEAD_PARTITIONS
        return p in self._held_partitions

    def _leads_global(self) -> bool:
        """Cluster-wide (non-table-scoped) duties run on the partition-0
        holder only."""
        return not self._ha_active() or 0 in self._held_partitions

    # ---- table lifecycle -------------------------------------------------
    def add_table(self, config: TableConfig, schema: Schema) -> None:
        """Tables register under their type-suffixed physical name
        (sales_OFFLINE / sales_REALTIME) — a raw name with both parts is a
        hybrid table and the broker splits queries at the time boundary."""
        self.registry.add_table(config, schema, key=config.table_name_with_type)
        if config.table_type == TableType.REALTIME and config.stream is not None:
            self._assign_stream_partitions(config)

    def drop_table(self, table: str) -> None:
        self.registry.drop_table(table)

    def update_schema(self, table: str, schema: Schema) -> None:
        """Additive schema evolution (SchemaUtils.validate backward-compat
        rules): new columns may be added; existing columns must keep their
        type and single/multi-value shape. Servers pick up the new schema
        on their next sync tick and synthesize default values for columns
        absent from old segments."""
        # hybrid tables evolve BOTH physical variants in step — a stale
        # realtime schema would serve KeyErrors for the new columns
        keys = [k for k in (table, f"{table}_OFFLINE", f"{table}_REALTIME")
                if self.registry.table_schema(k) is not None]
        if not keys:
            raise KeyError(f"table {table!r} not found")
        for key in keys:
            old = self.registry.table_schema(key)
            for name in old.column_names():
                new_field = schema.fields.get(name)
                if new_field is None:
                    raise ValueError(
                        f"schema evolution cannot drop column {name!r}")
                old_field = old.field(name)
                if new_field.data_type is not old_field.data_type or \
                        new_field.single_value != old_field.single_value or \
                        new_field.role is not old_field.role:
                    raise ValueError(
                        f"schema evolution cannot change column {name!r} "
                        f"(type/shape/role must stay fixed)")
        for key in keys:
            self.registry.update_schema(key, schema)

    def _realtime_replication(self, config: TableConfig) -> int:
        """Replica consumers per partition. Upsert tables pin to 1: each
        replica maintains independent validDocIds state, and adopted
        segments would desync it (the reference requires strict replica
        routing for upsert for the same reason)."""
        if config.upsert.mode != "NONE":
            return 1
        return max(1, config.replication)

    def _assign_stream_partitions(self, config: TableConfig) -> None:
        """Stream partition → [servers], replication-aware round-robin
        (PinotLLCRealtimeSegmentManager's consuming-segment creation; every
        listed replica consumes, commits arbitrate via the completion FSM)."""
        from pinot_tpu.stream.spi import create_consumer_factory

        servers = sorted(
            i.instance_id
            for i in self.registry.instances(Role.SERVER,
                                             live_ttl_ms=self.assigner.live_ttl_ms)
        )
        if not servers:
            raise RuntimeError("no servers available for realtime partitions")
        n = create_consumer_factory(config.stream).partition_count()
        reps = min(self._realtime_replication(config), len(servers))
        mapping = {
            p: [servers[(p + r) % len(servers)] for r in range(reps)]
            for p in range(n)
        }
        self.registry.set_partition_assignment(config.table_name_with_type, mapping)

    def run_realtime_repair(self) -> dict:
        """RealtimeSegmentValidationManager analog: re-home partitions whose
        consumers died so ingestion continues (the new owner resumes from
        the last completed commit in the registry)."""
        live = sorted(
            i.instance_id
            for i in self.registry.instances(Role.SERVER,
                                             live_ttl_ms=self.assigner.live_ttl_ms)
        )
        changed = {}
        for table in self.registry.tables():
            if not self.is_lead_for(table):
                continue  # another controller leads this table (HA partitioning)
            cfg = self.registry.table_config(table)
            if cfg is None or cfg.stream is None:
                continue
            pa = self.registry.partition_assignment(table)
            if not pa or not live:
                continue
            want = min(self._realtime_replication(cfg), len(live))
            new_pa = {}
            dirty = False
            for p, insts in pa.items():
                alive = [i for i in insts if i in live]
                if len(alive) < want:
                    for cand in live:
                        if len(alive) >= want:
                            break
                        if cand not in alive:
                            alive.append(cand)
                    dirty = True
                elif len(alive) != len(insts):
                    dirty = True
                new_pa[p] = alive
            if dirty:
                self.registry.set_partition_assignment(table, new_pa)
                changed[table] = new_pa
        # Hard-dead repair (the reference gets this from Helix dropping the
        # dead participant's ephemeral node + the periodic validators):
        # 1. scrub dead instances from the external view — a killed server
        #    can't deregister itself, and stale EV entries keep brokers
        #    routing (and 427-ing) at it;
        # 2. rebalance tables whose ASSIGNMENT references a dead instance,
        #    against the conservatively-live server set — this restores
        #    replication on live servers AND bounds the assignment ghosts
        #    merge_instances publishing would otherwise accumulate.
        # Conservative cut: 2x the liveness TTL — a server mid-way through
        # a long segment download heartbeats late but isn't dead — and
        # never sweep when NO server looks live (host suspend/resume makes
        # every heartbeat stale at once; a routing blackout is worse than
        # stale entries).
        if live:
            hard_live = {
                i.instance_id
                for i in self.registry.instances(
                    Role.SERVER, live_ttl_ms=self.assigner.live_ttl_ms * 2)
            }
            registered = {i.instance_id
                          for i in self.registry.instances(Role.SERVER)}
            dead = registered - hard_live
            if dead:
                self.registry.scrub_instances(dead)
                for table in self.registry.tables():
                    if not self.is_lead_for(table):
                        continue  # another controller leads this table (HA partitioning)
                    assign = self.registry.assignment(table)
                    if not any(dead & set(v) for v in assign.values()):
                        continue
                    cfg = self.registry.table_config(table)
                    if cfg is None:
                        continue
                    if self.registry.replica_groups(table):
                        self.assigner.rebalance_replica_groups(
                            table, self._table_replication(cfg))
                    else:
                        self.assigner.rebalance(
                            table, self._table_replication(cfg),
                            servers=sorted(hard_live),
                        )
        return changed

    # ---- segment lifecycle -----------------------------------------------
    def resolve(self, table: str) -> str:
        """Raw name → physical registry key (OFFLINE preferred for pushes)."""
        tables = set(self.registry.tables())
        if table in tables:
            return table
        for suffix in ("_OFFLINE", "_REALTIME"):
            if f"{table}{suffix}" in tables:
                return f"{table}{suffix}"
        raise KeyError(f"table {table!r} not found")

    def upload_segment(self, table: str, segment_dir: str,
                       copy_to_deep_store: bool = True) -> SegmentRecord:
        """Segment push (PinotSegmentUploadDownloadRestletResource →
        PinotHelixResourceManager.addNewSegment → IdealState update)."""
        table = self.resolve(table)
        cfg = self.registry.table_config(table)
        if cfg is None:
            raise KeyError(f"table {table!r} not found")
        seg = ImmutableSegment(segment_dir)
        location = segment_dir
        if copy_to_deep_store:
            location = os.path.join(self.deep_store, table, seg.name)
            if os.path.abspath(location) != os.path.abspath(segment_dir):
                self.fs.copy(segment_dir, location)
        meta = seg.metadata
        record = SegmentRecord(
            name=seg.name, table=table, n_docs=seg.n_docs, location=location,
            state=SegmentState.ONLINE, start_time=meta.start_time,
            end_time=meta.end_time, crc=meta.crc,
            **_partition_record_fields(meta),
            **_column_stats_fields(meta),
        )
        instances = self.assigner.assign_with_groups(table, record)
        if instances is None:
            instances = self.assigner.assign(self._table_replication(cfg))
        self.registry.add_segment(record, instances)
        return record

    @staticmethod
    def _table_replication(cfg: TableConfig) -> int:
        # dim tables replicate everywhere (DimensionTableDataManager model);
        # assign() caps at the live-server count
        return 1_000_000 if cfg.is_dim_table else cfg.replication

    def delete_segment(self, table: str, name: str) -> None:
        table = self.resolve(table)
        rec = self.registry.segments(table).get(name)
        self.registry.remove_segment(table, name)
        if rec is not None and rec.location.startswith(self.deep_store):
            self.fs.delete(rec.location)

    def rebalance(self, table: str) -> dict:
        table = self.resolve(table)
        cfg = self.registry.table_config(table)
        if cfg is None:
            raise KeyError(f"table {table!r} not found")
        if self.registry.replica_groups(table):
            # replica-group-aware tables stay replica-group-aware: a plain
            # rebalance must not silently collapse the group structure
            return self.assigner.rebalance_replica_groups(
                table, self._table_replication(cfg))
        return self.assigner.rebalance(table, self._table_replication(cfg))

    def setup_replica_groups(self, table: str) -> dict:
        """Opt a table into replica-group segment assignment (ISSUE 10):
        partitions the live servers into ``replication`` named groups and
        places every segment once per group. From here on uploads place
        group-aware and ``rebalance``/the periodic repair keep the group
        map consistent with membership. Returns the new assignment."""
        table = self.resolve(table)
        cfg = self.registry.table_config(table)
        if cfg is None:
            raise KeyError(f"table {table!r} not found")
        return self.assigner.rebalance_replica_groups(
            table, self._table_replication(cfg))

    def run_replica_group_repair(self) -> list:
        """Rebalance-on-join/leave for replica-group tables: when the live
        server set no longer matches a table's group membership (a server
        joined, died, or deregistered), rebuild the groups with minimal
        movement. Runs from the periodic loop like the other repairs."""
        live = {i.instance_id for i in self.assigner._live_servers()}
        fixed = []
        for table in self.registry.tables():
            if not self.is_lead_for(table):
                continue  # another controller leads this table (HA partitioning)
            groups = self.registry.replica_groups(table)
            if not groups:
                continue
            members = {m for ms in groups.values() for m in ms}
            if members != live and live:
                cfg = self.registry.table_config(table)
                if cfg is None:
                    continue
                self.assigner.rebalance_replica_groups(
                    table, self._table_replication(cfg))
                fixed.append(table)
        return fixed

    # ---- minion task generation (PinotTaskManager analog) ----------------
    def run_task_generation(self, now_ms: Optional[int] = None) -> list:
        """Scan table task_configs and enqueue due minion tasks."""
        from pinot_tpu.minion.generator import generate_tasks

        return generate_tasks(self.registry, now_ms)

    def run_task_repair(self, stale_ms: int = 600_000) -> dict:
        """Repair after a minion death (TaskMetricsEmitter/stale-task sweep
        analog, mirroring the completion FSM's stale-COMMITTING takeover):

        - RUNNING tasks untouched for ``stale_ms`` requeue as PENDING
          (FAILED once their claim attempts are exhausted);
        - IN_PROGRESS lineage entries untouched for ``stale_ms`` unwind —
          their TO segments are routing-excluded, so deleting them first and
          then dropping the entry can never double-route.
        """
        # Unwind stale swaps BEFORE requeueing their tasks: a re-claimed
        # task starts a fresh lineage + uploads a fresh replacement, and a
        # later unwind of the OLD entry must never race with (or delete
        # segments belonging to) the new attempt.
        reverted = []
        # no per-table lead guard here: task generation/repair run as ONE
        # cluster-wide duty on the partition-0 holder (periodic loop), so
        # the stale-task sweep and the lineage unwind can't split brains
        for table in self.registry.tables():
            for lid, entry in self.registry.stale_in_progress_lineage(
                    table, stale_ms).items():
                # CAS-claim the unwind first: if the executor completed the
                # flip in the meantime, the TO set is live data — touching
                # it would delete the only remaining copy.
                if not self.registry.try_abort_lineage(table, lid):
                    continue
                for name in entry["to"]:
                    if name in self.registry.segments(table):
                        self.delete_segment(table, name)
                self.registry.revert_lineage(table, lid)
                reverted.append((table, lid))
        requeued = self.registry.requeue_stale_tasks(stale_ms)
        return {"requeued_tasks": requeued, "reverted_lineage": reverted}

    def run_segment_relocation(self, now_ms: Optional[int] = None) -> dict:
        """Tier storage relocation (relocation/SegmentRelocator.java
        analog): segments older than a tier's segment_age_ms move to live
        servers carrying the tier's server_tag (Helix-tag analog on
        InstanceInfo.tags); servers reconcile the new assignment on their
        next sync (download there, refcounted unload here). Returns
        {table: {segment: {tier, to}}}."""
        import time as _time

        now = now_ms if now_ms is not None else int(_time.time() * 1000)
        live = self.registry.instances(
            Role.SERVER, live_ttl_ms=self.assigner.live_ttl_ms)
        by_tag: dict = {}
        for i in live:
            for t in getattr(i, "tags", ()) or ():
                by_tag.setdefault(t, []).append(i.instance_id)
        moved: dict = {}
        for table in self.registry.tables():
            if not self.is_lead_for(table):
                continue  # another controller leads this table (HA partitioning)
            cfg = self.registry.table_config(table)
            tiers = getattr(cfg, "tiers", None) if cfg else None
            if not tiers:
                continue
            assign = self.registry.assignment(table)
            recs = self.registry.segments(table)
            new = {k: list(v) for k, v in assign.items()}
            dirty = False
            repl = self._table_replication(cfg)
            for name, rec in recs.items():
                # age by the segment's data END TIME like run_retention and
                # the reference's TimeBasedTierSegmentSelector — push time
                # only when the table has no time column (a backfilled
                # segment of old data must tier by its data, not its push)
                basis = rec.end_time if rec.end_time is not None \
                    else rec.push_time_ms
                age = now - (basis or now)
                tier = None
                # oldest-threshold tier wins when several match
                for t in sorted(tiers, key=lambda t: t["segment_age_ms"],
                                reverse=True):
                    if age >= t["segment_age_ms"]:
                        tier = t
                        break
                if tier is None:
                    continue
                targets = sorted(by_tag.get(tier["server_tag"], []))
                if not targets:
                    continue  # no capacity on the tier: stay put
                k = max(1, min(repl, len(targets)))
                # spread segments across the tier (balanced like the
                # reference relocator) — a fixed prefix would pile every
                # segment onto the lexicographically-first tagged server
                start = zlib.crc32(name.encode()) % len(targets)
                want = sorted(targets[(start + j) % len(targets)]
                              for j in range(k))
                if sorted(new.get(name, [])) != want:
                    new[name] = want
                    dirty = True
                    moved.setdefault(table, {})[name] = {
                        "tier": tier["name"], "to": want}
            if dirty:
                self.registry.set_assignment(table, new)
        return moved

    def recommend_config(self, schema, sample_queries,
                         qps: float = 100.0) -> dict:
        """Workload-driven config advisor (recommender/RecommenderDriver
        role) — advisory, nothing is applied."""
        from pinot_tpu.controller.advisor import recommend_config

        return recommend_config(schema, sample_queries, qps)

    def tune_table(self, table: str) -> dict:
        """Observed-metadata config tuner (tuner/TableConfigTuner role):
        grows the registered table's IndexingConfig from hosted segment
        stats and persists the update."""
        from pinot_tpu.controller.advisor import tune_table
        from pinot_tpu.storage.segment import ImmutableSegment

        table = self.resolve(table)
        segs = []
        for name, rec in self.registry.segments(table).items():
            if rec.location and os.path.isdir(rec.location):
                segs.append(ImmutableSegment(rec.location))
                break  # stats from one representative segment suffice
        return tune_table(self.registry, table, segs)

    def start_periodic_tasks(self, interval_s: float = 60.0) -> None:
        """ControllerPeriodicTaskScheduler analog: retention, realtime
        repair, minion task generation and stale-task repair on a timer
        (the reference schedules RetentionManager, RealtimeSegmentValidation-
        Manager and PinotTaskManager the same way)."""
        if getattr(self, "_periodic_thread", None) is not None:
            return
        self._periodic_stop = threading.Event()

        def loop():
            while not self._periodic_stop.wait(interval_s):
                # table-scoped duties filter per table (is_lead_for inside
                # their loops); cluster-wide duties run on the partition-0
                # holder only
                steps = [self.run_retention, self.run_realtime_repair,
                         self.run_dim_table_replication,
                         self.run_replica_group_repair,
                         self.run_segment_relocation,
                         self.run_tier_rebalance]
                if self._leads_global():
                    steps += [self.run_task_generation, self.run_task_repair,
                              self.run_autoscale]
                for step in steps:
                    try:
                        step()
                    except Exception:
                        log.exception("periodic task %s failed", step.__name__)

        self._periodic_thread = threading.Thread(
            target=loop, name="controller-periodic", daemon=True
        )
        self._periodic_thread.start()

    def stop_periodic_tasks(self) -> None:
        if getattr(self, "_periodic_thread", None) is not None:
            self._periodic_stop.set()
            self._periodic_thread.join(5)
            self._periodic_thread = None

    def run_dim_table_replication(self) -> list:
        """Keep dimension tables replicated to EVERY live server as
        membership changes (the reference re-assigns dim tables on server
        join; without this, LOOKUP fails on fact segments placed on a
        server that joined after the dim upload)."""
        live = {i.instance_id for i in self.assigner._live_servers()}
        fixed = []
        for table in self.registry.tables():
            if not self.is_lead_for(table):
                continue  # another controller leads this table (HA partitioning)
            cfg = self.registry.table_config(table)
            if cfg is None or not cfg.is_dim_table:
                continue
            assignment = self.registry.assignment(table)
            if any(set(insts) != live for insts in assignment.values()):
                self.assigner.rebalance(table, self._table_replication(cfg))
                fixed.append(table)
        return fixed

    # ---- periodic maintenance (RetentionManager analog) ------------------
    def run_retention(self, now_ms: Optional[int] = None) -> list:
        """Drop segments whose time range fell out of the retention window."""
        from pinot_tpu.minion.generator import _busy_segments

        now_ms = now_ms or int(time.time() * 1000)
        dropped = []
        for table in self.registry.tables():
            if not self.is_lead_for(table):
                continue  # another controller leads this table (HA partitioning)
            cfg = self.registry.table_config(table)
            if cfg is None or cfg.retention_days is None:
                continue
            cutoff = now_ms - cfg.retention_days * 86_400_000
            # segments mid-swap or claimed by a minion task are off limits:
            # deleting a FROM segment while its replace is IN_PROGRESS would
            # drop rows from routed results mid-swap (they age out of the
            # busy set once the task/lineage resolves, and get deleted then)
            busy = _busy_segments(self.registry, table)
            for name, rec in self.registry.segments(table).items():
                if name in busy:
                    continue
                if rec.end_time is not None and rec.end_time < cutoff:
                    self.delete_segment(table, name)
                    dropped.append((table, name))
        return dropped
