"""Kafka stream plugin against a faked kafka-python module.

The image carries no Kafka client, so these tests install a minimal fake
``kafka`` module (TopicPartition/KafkaConsumer with assign/seek/poll) and
assert the plugin maps the SPI correctly — offsets, batching, resume —
plus the clear gating error when the library is absent.
"""

import sys
import types

import pytest

from pinot_tpu.common.table_config import StreamConfig


class _FakeRecord:
    def __init__(self, offset, value, key=None, timestamp=0):
        self.offset = offset
        self.value = value
        self.key = key
        self.timestamp = timestamp


class _FakeTopicPartition:
    def __init__(self, topic, partition):
        self.topic, self.partition = topic, partition

    def __hash__(self):
        return hash((self.topic, self.partition))

    def __eq__(self, other):
        return (self.topic, self.partition) == (other.topic, other.partition)


_LOG: dict = {}  # (topic, partition) -> list[_FakeRecord]


class _FakeKafkaConsumer:
    def __init__(self, **kwargs):
        self.kwargs = kwargs
        self._pos: dict = {}
        self._assigned = []
        self.closed = False

    def assign(self, tps):
        self._assigned = list(tps)

    def seek(self, tp, offset):
        self._pos[tp] = offset

    def poll(self, timeout_ms=0):
        out = {}
        for tp in self._assigned:
            log = _LOG.get((tp.topic, tp.partition), [])
            pos = self._pos.get(tp, 0)
            batch = [r for r in log if r.offset >= pos][:100]
            if batch:
                out[tp] = batch
                self._pos[tp] = batch[-1].offset + 1
        return out

    def partitions_for_topic(self, topic):
        parts = {p for (t, p) in _LOG if t == topic}
        return parts or None

    def beginning_offsets(self, tps):
        return {tp: min((r.offset for r in
                         _LOG.get((tp.topic, tp.partition), [])), default=0)
                for tp in tps}

    def close(self):
        self.closed = True


@pytest.fixture()
def fake_kafka(monkeypatch):
    mod = types.ModuleType("kafka")
    mod.TopicPartition = _FakeTopicPartition
    mod.KafkaConsumer = _FakeKafkaConsumer
    monkeypatch.setitem(sys.modules, "kafka", mod)
    _LOG.clear()
    yield mod
    _LOG.clear()


def _config():
    return StreamConfig(stream_type="kafka", topic="events", decoder="json",
                        properties={"bootstrap.servers": "b1:9092",
                                    "kafka.consumer.client_id": "pinot-tpu"})


class TestKafkaPlugin:
    def test_gating_error_without_library(self, monkeypatch):
        monkeypatch.setitem(sys.modules, "kafka", None)
        from pinot_tpu.stream.kafka_stream import KafkaConsumerFactory

        with pytest.raises(RuntimeError, match="kafka-python"):
            KafkaConsumerFactory(_config())

    def test_factory_registered_via_spi(self, fake_kafka):
        from pinot_tpu.stream.spi import create_consumer_factory

        _LOG[("events", 0)] = []
        _LOG[("events", 1)] = []
        factory = create_consumer_factory(_config())
        assert factory.partition_count() == 2

    def test_fetch_resume_and_decode(self, fake_kafka):
        from pinot_tpu.stream.kafka_stream import KafkaConsumerFactory
        from pinot_tpu.stream.spi import StreamPartitionMsgOffset

        _LOG[("events", 0)] = [
            _FakeRecord(5, b'{"a": 1}'), _FakeRecord(6, b'{"a": 2}')]
        factory = KafkaConsumerFactory(_config())
        assert factory.earliest_offset(0).value == 5
        consumer = factory.create_partition_consumer(0)
        batch = consumer.fetch_messages(StreamPartitionMsgOffset(5), 100)
        assert [m.offset.value for m in batch.messages] == [5, 6]
        assert batch.messages[0].payload == b'{"a": 1}'
        assert batch.next_offset.value == 7
        # resume from next_offset: empty batch, offset preserved
        batch2 = consumer.fetch_messages(batch.next_offset, 100)
        assert len(batch2) == 0 and batch2.next_offset.value == 7
        # late-arriving record is picked up from the held position
        _LOG[("events", 0)].append(_FakeRecord(7, b'{"a": 3}'))
        batch3 = consumer.fetch_messages(batch2.next_offset, 100)
        assert [m.offset.value for m in batch3.messages] == [7]
        consumer.close()

    def test_consumer_kwargs_passthrough(self, fake_kafka):
        from pinot_tpu.stream.kafka_stream import KafkaPartitionConsumer

        _LOG[("events", 0)] = []
        c = KafkaPartitionConsumer(_config(), 0)
        assert c._consumer.kwargs["bootstrap_servers"] == "b1:9092"
        assert c._consumer.kwargs["client_id"] == "pinot-tpu"
        assert c._consumer.kwargs["enable_auto_commit"] is False

    def test_kwargs_coercion_and_auto_commit_guard(self, fake_kafka):
        """String properties coerce to the types kafka-python expects;
        auto-commit cannot be silently re-enabled (r3 review)."""
        from pinot_tpu.stream.kafka_stream import KafkaPartitionConsumer

        _LOG[("events", 0)] = []
        cfg = StreamConfig(
            stream_type="kafka", topic="events", decoder="json",
            properties={"kafka.consumer.max_poll_records": "500",
                        "kafka.consumer.check_crcs": "false",
                        "kafka.consumer.client_id": "cid"})
        c = KafkaPartitionConsumer(cfg, 0)
        assert c._consumer.kwargs["max_poll_records"] == 500
        assert c._consumer.kwargs["check_crcs"] is False
        assert c._consumer.kwargs["client_id"] == "cid"
        bad = StreamConfig(
            stream_type="kafka", topic="events", decoder="json",
            properties={"kafka.consumer.enable_auto_commit": "true"})
        with pytest.raises(ValueError, match="auto_commit"):
            KafkaPartitionConsumer(bad, 0)

    def test_single_probe_serves_all_earliest_offsets(self, fake_kafka):
        """partition_count + every earliest_offset ride ONE probe (r3
        review: 64 partitions must not mean 65 broker connections)."""
        from pinot_tpu.stream.kafka_stream import KafkaConsumerFactory

        for p in range(4):
            _LOG[("events", p)] = [_FakeRecord(10 + p, b"{}")]
        created = []
        orig = fake_kafka.KafkaConsumer

        def counting(**kw):
            c = orig(**kw)
            created.append(c)
            return c

        fake_kafka.KafkaConsumer = counting
        factory = KafkaConsumerFactory(_config())
        assert factory.partition_count() == 4
        for p in range(4):
            assert factory.earliest_offset(p).value == 10 + p
        assert len(created) == 1  # one probe total
        fake_kafka.KafkaConsumer = orig

    def test_end_to_end_realtime_ingest(self, fake_kafka, tmp_path):
        """The realtime manager consumes through the kafka plugin exactly
        as through the memory stream."""
        from pinot_tpu.common.datatypes import DataType
        from pinot_tpu.common.schema import Schema
        from pinot_tpu.common.table_config import TableConfig, TableType
        from pinot_tpu.engine.engine import QueryEngine
        from pinot_tpu.realtime.manager import RealtimeTableDataManager

        _LOG[("events", 0)] = [
            _FakeRecord(i, f'{{"k": "u{i % 3}", "v": {i}}}'.encode())
            for i in range(30)
        ]
        schema = Schema.build(name="ev", dimensions=[("k", DataType.STRING)],
                              metrics=[("v", DataType.LONG)])
        cfg = TableConfig(
            table_name="ev", table_type=TableType.REALTIME,
            stream=StreamConfig(stream_type="kafka", topic="events",
                                decoder="json",
                                segment_flush_threshold_rows=1000))
        eng = QueryEngine(device_executor=None)
        mgr = RealtimeTableDataManager(schema, cfg, eng.table("ev"),
                                       str(tmp_path / "rt"))
        mgr.start()
        try:
            import time

            deadline = time.time() + 10
            while time.time() < deadline:
                r = eng.execute("SELECT COUNT(*), SUM(v) FROM ev")
                if not r.get("exceptions") and \
                        r["resultTable"]["rows"] == [[30, 435]]:
                    break
                time.sleep(0.05)
            r = eng.execute("SELECT COUNT(*), SUM(v) FROM ev")
            assert r["resultTable"]["rows"] == [[30, 435]]
        finally:
            mgr.stop(commit_remaining=False)
