"""Theta sketch distinct counting: accuracy, merge algebra, engine path.

Reference analog: DistinctCountThetaSketchAggregationFunction over
DataSketches theta — error-bounded estimates with order-insensitive
merges and bounded state.
"""

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.ops import theta
from pinot_tpu.storage.creator import build_segment


class TestThetaOps:
    def test_exact_below_nominal(self):
        vals = np.arange(1000, dtype=np.int64)
        th, h = theta.build(vals, k=4096)
        assert th == int(theta.MAX_HASH)
        assert theta.estimate(th, h) == 1000.0

    @pytest.mark.parametrize("n_unique", [50_000, 200_000])
    def test_estimate_error_bounded(self, n_unique):
        k = 4096
        vals = np.arange(n_unique, dtype=np.int64)
        th, h = theta.build(vals, k)
        assert len(h) <= k
        est = theta.estimate(th, h)
        # KMV relative error ~ 1/sqrt(k) = 1.6%; allow 3 sigma
        assert abs(est - n_unique) / n_unique < 3 / np.sqrt(k)

    def test_merge_matches_union(self):
        k = 2048
        rng = np.random.default_rng(7)
        a = rng.integers(0, 60_000, 80_000)
        b = rng.integers(30_000, 90_000, 80_000)
        tha, ha = theta.build(a, k)
        thb, hb = theta.build(b, k)
        th, h = theta.merge(tha, ha, thb, hb, k)
        union = len(np.union1d(np.unique(a), np.unique(b)))
        est = theta.estimate(th, h)
        assert abs(est - union) / union < 3 / np.sqrt(k)
        # merge is symmetric
        th2, h2 = theta.merge(thb, hb, tha, ha, k)
        assert th == th2 and np.array_equal(h, h2)

    def test_duplicates_dont_inflate(self):
        vals = np.tile(np.arange(100, dtype=np.int64), 1000)
        th, h = theta.build(vals, k=1024)
        assert theta.estimate(th, h) == 100.0

    def test_string_values(self):
        vals = np.array([f"user_{i}" for i in range(5000)])
        th, h = theta.build(vals, k=8192)
        assert theta.estimate(th, h) == 5000.0


class TestThetaThroughEngine:
    def test_group_by_and_wire_roundtrip(self, tmp_path):
        from pinot_tpu.engine.datatable import decode, encode
        from pinot_tpu.engine.reduce import finalize
        from pinot_tpu.query.optimizer import optimize_query
        from pinot_tpu.sql.compiler import compile_query

        schema = Schema.build(
            name="t",
            dimensions=[("k", DataType.STRING), ("u", DataType.LONG)],
            metrics=[("v", DataType.LONG)],
        )
        rng = np.random.default_rng(5)
        segs = []
        per_key_uniques: dict = {"a": set(), "b": set()}
        for i in range(3):
            n = 20_000
            ks = np.array(["a", "b"])[rng.integers(0, 2, n)]
            us = rng.integers(0, 30_000, n).astype(np.int64)
            for kk, uu in zip(ks, us):
                per_key_uniques[kk].add(int(uu))
            segs.append(build_segment(
                schema, {"k": ks, "u": us, "v": np.zeros(n, np.int64)},
                str(tmp_path / f"s{i}"), TableConfig(table_name="t"), f"s{i}"))
        engine = QueryEngine(device_executor=None)
        q = optimize_query(compile_query(
            "SELECT k, DISTINCTCOUNTTHETASKETCH(u, 4096) FROM t "
            "GROUP BY k ORDER BY k"))
        # server-style: per-segment partials -> wire -> broker merge
        partials = [decode(encode(engine.execute_segments(q, [s])))
                    for s in segs]
        from pinot_tpu.engine.reduce import merge_intermediates

        merged = merge_intermediates(q, partials)
        rows = finalize(q, merged).rows
        for key, est in rows:
            truth = len(per_key_uniques[key])
            assert abs(est - truth) / truth < 3 / np.sqrt(4096), (key, est, truth)

    def test_scalar_through_sql(self, tmp_path):
        schema = Schema.build(
            name="t", dimensions=[("u", DataType.LONG)],
            metrics=[("v", DataType.LONG)])
        n = 50_000
        rng = np.random.default_rng(2)
        us = rng.integers(0, 20_000, n).astype(np.int64)
        seg = build_segment(
            schema, {"u": us, "v": np.zeros(n, np.int64)},
            str(tmp_path / "s"), TableConfig(table_name="t"), "s0")
        engine = QueryEngine(device_executor=None)
        engine.add_segment("t", seg)
        r = engine.execute("SELECT DISTINCTCOUNTTHETASKETCH(u) FROM t")
        assert not r.get("exceptions"), r
        est = r["resultTable"]["rows"][0][0]
        truth = len(np.unique(us))
        assert abs(est - truth) / truth < 0.05