"""Theta sketch distinct counting: accuracy, merge algebra, engine path.

Reference analog: DistinctCountThetaSketchAggregationFunction over
DataSketches theta — error-bounded estimates with order-insensitive
merges and bounded state.
"""

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.ops import theta
from pinot_tpu.storage.creator import build_segment


class TestThetaOps:
    def test_exact_below_nominal(self):
        vals = np.arange(1000, dtype=np.int64)
        th, h = theta.build(vals, k=4096)
        assert th == int(theta.MAX_HASH)
        assert theta.estimate(th, h) == 1000.0

    @pytest.mark.parametrize("n_unique", [50_000, 200_000])
    def test_estimate_error_bounded(self, n_unique):
        k = 4096
        vals = np.arange(n_unique, dtype=np.int64)
        th, h = theta.build(vals, k)
        assert len(h) <= k
        est = theta.estimate(th, h)
        # KMV relative error ~ 1/sqrt(k) = 1.6%; allow 3 sigma
        assert abs(est - n_unique) / n_unique < 3 / np.sqrt(k)

    def test_merge_matches_union(self):
        k = 2048
        rng = np.random.default_rng(7)
        a = rng.integers(0, 60_000, 80_000)
        b = rng.integers(30_000, 90_000, 80_000)
        tha, ha = theta.build(a, k)
        thb, hb = theta.build(b, k)
        th, h = theta.merge(tha, ha, thb, hb, k)
        union = len(np.union1d(np.unique(a), np.unique(b)))
        est = theta.estimate(th, h)
        assert abs(est - union) / union < 3 / np.sqrt(k)
        # merge is symmetric
        th2, h2 = theta.merge(thb, hb, tha, ha, k)
        assert th == th2 and np.array_equal(h, h2)

    def test_duplicates_dont_inflate(self):
        vals = np.tile(np.arange(100, dtype=np.int64), 1000)
        th, h = theta.build(vals, k=1024)
        assert theta.estimate(th, h) == 100.0

    def test_string_values(self):
        vals = np.array([f"user_{i}" for i in range(5000)])
        th, h = theta.build(vals, k=8192)
        assert theta.estimate(th, h) == 5000.0


class TestThetaThroughEngine:
    def test_group_by_and_wire_roundtrip(self, tmp_path):
        from pinot_tpu.engine.datatable import decode, encode
        from pinot_tpu.engine.reduce import finalize
        from pinot_tpu.query.optimizer import optimize_query
        from pinot_tpu.sql.compiler import compile_query

        schema = Schema.build(
            name="t",
            dimensions=[("k", DataType.STRING), ("u", DataType.LONG)],
            metrics=[("v", DataType.LONG)],
        )
        rng = np.random.default_rng(5)
        segs = []
        per_key_uniques: dict = {"a": set(), "b": set()}
        for i in range(3):
            n = 20_000
            ks = np.array(["a", "b"])[rng.integers(0, 2, n)]
            us = rng.integers(0, 30_000, n).astype(np.int64)
            for kk, uu in zip(ks, us):
                per_key_uniques[kk].add(int(uu))
            segs.append(build_segment(
                schema, {"k": ks, "u": us, "v": np.zeros(n, np.int64)},
                str(tmp_path / f"s{i}"), TableConfig(table_name="t"), f"s{i}"))
        engine = QueryEngine(device_executor=None)
        q = optimize_query(compile_query(
            "SELECT k, DISTINCTCOUNTTHETASKETCH(u, 4096) FROM t "
            "GROUP BY k ORDER BY k"))
        # server-style: per-segment partials -> wire -> broker merge
        partials = [decode(encode(engine.execute_segments(q, [s])))
                    for s in segs]
        from pinot_tpu.engine.reduce import merge_intermediates

        merged = merge_intermediates(q, partials)
        rows = finalize(q, merged).rows
        for key, est in rows:
            truth = len(per_key_uniques[key])
            assert abs(est - truth) / truth < 3 / np.sqrt(4096), (key, est, truth)

    def test_scalar_through_sql(self, tmp_path):
        schema = Schema.build(
            name="t", dimensions=[("u", DataType.LONG)],
            metrics=[("v", DataType.LONG)])
        n = 50_000
        rng = np.random.default_rng(2)
        us = rng.integers(0, 20_000, n).astype(np.int64)
        seg = build_segment(
            schema, {"u": us, "v": np.zeros(n, np.int64)},
            str(tmp_path / "s"), TableConfig(table_name="t"), "s0")
        engine = QueryEngine(device_executor=None)
        engine.add_segment("t", seg)
        r = engine.execute("SELECT DISTINCTCOUNTTHETASKETCH(u) FROM t")
        assert not r.get("exceptions"), r
        est = r["resultTable"]["rows"][0][0]
        truth = len(np.unique(us))
        assert abs(est - truth) / truth < 0.05

class TestThetaSetOps:
    """Set-operation form: filtered per-predicate sketches + post-merge set
    expression (the reference's DistinctCountThetaSketch filter/postAgg
    arguments), oracle-checked against exact set counts."""

    def test_set_algebra_primitives(self):
        rng = np.random.default_rng(11)
        a = np.unique(rng.integers(0, 50_000, 30_000))
        b = np.unique(rng.integers(25_000, 75_000, 30_000))
        k = 4096
        tha, ha = theta.build(a, k)
        thb, hb = theta.build(b, k)
        th, h = theta.intersect(tha, ha, thb, hb)
        exact = len(np.intersect1d(a, b))
        assert abs(theta.estimate(th, h) - exact) / exact < 0.1
        th, h = theta.a_not_b(tha, ha, thb, hb)
        exact = len(np.setdiff1d(a, b))
        assert abs(theta.estimate(th, h) - exact) / exact < 0.1

    def test_parse_set_expression(self):
        ast = theta.parse_set_expression("SET_INTERSECT($1, SET_UNION($2,$3))")
        assert ast == ("SET_INTERSECT", ("ref", 0),
                       ("SET_UNION", ("ref", 1), ("ref", 2)))
        assert theta.max_ref(ast) == 2
        with pytest.raises(ValueError):
            theta.parse_set_expression("SET_DIFF($1,$2,$3)")  # binary only
        with pytest.raises(ValueError):
            theta.parse_set_expression("SET_FROB($1,$2)")

    def _engine(self, rows):
        from pinot_tpu.storage.mutable import MutableSegment

        schema = Schema.build(
            name="ev",
            dimensions=[("dim", DataType.STRING), ("uid", DataType.INT)],
            metrics=[("m", DataType.INT)],
        )
        seg = MutableSegment(schema, "s")
        seg.index_batch(rows)
        eng = QueryEngine(device_executor=None)
        eng.table("ev").add_segment(seg)
        return eng

    def test_sql_set_ops_exact_mode_match_oracle(self):
        rng = np.random.default_rng(3)
        rows = []
        for i in range(20_000):
            uid = int(rng.integers(0, 5000))
            dim = "books" if (i % 2 == 0 and uid % 3 == 0) else (
                "tools" if uid % 5 == 0 else "other")
            rows.append({"dim": dim, "uid": uid, "m": i % 2})
        books = {r["uid"] for r in rows if r["dim"] == "books"}
        tools = {r["uid"] for r in rows if r["dim"] == "tools"}
        eng = self._engine(rows)
        # k far above the cardinalities -> exact mode -> exact equality
        for setex, want in [
            ("SET_INTERSECT($1,$2)", len(books & tools)),
            ("SET_UNION($1,$2)", len(books | tools)),
            ("SET_DIFF($1,$2)", len(books - tools)),
            ("SET_INTERSECT(SET_UNION($1,$2),$1)", len(books)),
        ]:
            sql = ("SELECT DISTINCTCOUNTTHETASKETCH(uid, "
                   "'nominalEntries=65536', 'dim = ''books''', "
                   f"'dim = ''tools''', '{setex}') FROM ev")
            r = eng.execute(sql)
            assert not r.get("exceptions"), r
            assert r["resultTable"]["rows"][0][0] == want, (setex, r)

    def test_sql_set_ops_groupby_and_approx(self):
        rng = np.random.default_rng(4)
        rows = []
        for i in range(30_000):
            uid = int(rng.integers(0, 8000))
            dim = "books" if uid % 2 == 0 else ("tools" if uid % 3 == 0 else "x")
            rows.append({"dim": dim, "uid": uid, "m": i % 2})
        eng = self._engine(rows)
        sql = ("SELECT m, DISTINCTCOUNTTHETASKETCH(uid, 'nominalEntries=1024',"
               " 'dim = ''books''', 'dim = ''tools''', "
               "'SET_UNION($1,$2)') FROM ev GROUP BY m ORDER BY m")
        r = eng.execute(sql)
        assert not r.get("exceptions"), r
        for m_val, est in r["resultTable"]["rows"]:
            exact = len({row["uid"] for row in rows
                         if row["m"] == m_val and row["dim"] in ("books", "tools")})
            assert abs(est - exact) / exact < 3 / np.sqrt(1024) + 0.05, (m_val, est, exact)

    def test_bad_ref_rejected(self):
        eng = self._engine([{"dim": "a", "uid": 1, "m": 0}])
        r = eng.execute(
            "SELECT DISTINCTCOUNTTHETASKETCH(uid, '', 'dim = ''a''', "
            "'SET_INTERSECT($1,$2)') FROM ev")
        assert r.get("exceptions"), r  # $2 with one filter is an error
