"""ADLS (abfss) PinotFS plugin against a faked azure-storage-blob
(pinot-adls analog): segment lifecycle + gating error without the SDK."""

import sys
import types

import pytest

_STORE: dict = {}  # (container, name) -> bytes


class _FakeDownload:
    def __init__(self, data):
        self._data = data

    def readall(self):
        return self._data


class _FakeBlobClient:
    def __init__(self, container, name):
        self.container = container
        self.name = name

    @property
    def url(self):
        return f"https://fake/{self.container}/{self.name}"


class _FakeNotFound(Exception):
    pass


_FakeNotFound.__name__ = "ResourceNotFoundError"


class _FakeContainerClient:
    def __init__(self, name):
        self.name = name

    def list_blobs(self, name_starts_with=""):
        return [types.SimpleNamespace(name=n)
                for (c, n) in sorted(_STORE)
                if c == self.name and n.startswith(name_starts_with)]

    def upload_blob(self, key, f, overwrite=False):
        _STORE[(self.name, key)] = f.read()

    def download_blob(self, key):
        return _FakeDownload(_STORE[(self.name, key)])

    def delete_blob(self, key):
        if (self.name, key) not in _STORE:
            raise _FakeNotFound(f"404 {key}")
        del _STORE[(self.name, key)]

    def get_blob_client(self, key):
        bc = _FakeBlobClient(self.name, key)
        container = self

        def start_copy(url):
            src_c, src_k = url.removeprefix("https://fake/").split("/", 1)
            _STORE[(container.name, key)] = _STORE[(src_c, src_k)]

        bc.start_copy_from_url = start_copy
        return bc


class _FakeService:
    @classmethod
    def from_connection_string(cls, conn):
        return cls()

    def get_container_client(self, name):
        return _FakeContainerClient(name)


@pytest.fixture()
def fake_azure(monkeypatch):
    blob_mod = types.ModuleType("azure.storage.blob")
    blob_mod.BlobServiceClient = _FakeService
    storage_mod = types.ModuleType("azure.storage")
    storage_mod.blob = blob_mod
    azure_mod = types.ModuleType("azure")
    azure_mod.storage = storage_mod
    monkeypatch.setitem(sys.modules, "azure", azure_mod)
    monkeypatch.setitem(sys.modules, "azure.storage", storage_mod)
    monkeypatch.setitem(sys.modules, "azure.storage.blob", blob_mod)
    _STORE.clear()
    yield
    _STORE.clear()


class TestAdlsFS:
    def test_gating_error_without_sdk(self, monkeypatch):
        monkeypatch.setitem(sys.modules, "azure", None)
        monkeypatch.setitem(sys.modules, "azure.storage", None)
        from pinot_tpu.storage.adlsfs import AdlsFS

        with pytest.raises(RuntimeError, match="azure-storage-blob"):
            AdlsFS()

    def test_scheme_registered(self, fake_azure):
        from pinot_tpu.storage.fs import create_fs

        assert type(create_fs("abfss://cont/x")).__name__ == "AdlsFS"

    def test_segment_lifecycle_and_sibling_isolation(self, fake_azure, tmp_path):
        from pinot_tpu.storage.adlsfs import AdlsFS

        a = tmp_path / "seg_1"
        b = tmp_path / "seg_10"
        (a / "sub").mkdir(parents=True)
        b.mkdir()
        (a / "m.json").write_text("{}")
        (a / "sub" / "x.bin").write_bytes(b"X")
        (b / "b.bin").write_bytes(b"B")

        fs = AdlsFS()
        fs.copy(str(a), "abfss://cont/t/seg_1")
        fs.copy(str(b), "abfss://cont/t/seg_10")
        assert fs.list_files("abfss://cont/t") == ["seg_1", "seg_10"]

        d = tmp_path / "dl"
        fs.copy("abfss://cont/t/seg_1", str(d))
        assert (d / "m.json").read_text() == "{}"
        assert (d / "sub" / "x.bin").read_bytes() == b"X"

        # remote copy + delete; sibling prefix (seg_1 vs seg_10) untouched
        fs.copy("abfss://cont/t/seg_1", "abfss://cont/t2/seg_1")
        assert fs.exists("abfss://cont/t2/seg_1")
        fs.delete("abfss://cont/t/seg_1")
        assert not fs.exists("abfss://cont/t/seg_1")
        assert fs.exists("abfss://cont/t/seg_10")
