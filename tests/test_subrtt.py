"""Sub-RTT serving tests (ISSUE 9).

1. ON-DEVICE FINAL REDUCE (ops/device_reduce.py): the in-kernel ORDER BY
   trim must be bit-identical to the host reduce across dense + sorted
   regimes, solo + 8-dev mesh, sealed + consuming(chunklet), asc/desc,
   group-column and aggregation order keys — and must NOT engage for the
   shapes whose reduce needs the full table (HAVING, post-aggregation
   order expressions, numGroupsLimit pressure → host fallback).
2. DEVICE PARTIALS CACHE: repeat executions hit (flagged in the
   response), literal changes miss, and every invalidation edge —
   chunklet promotion, upsert-mask change, seal, batch-LRU eviction
   churn, entry-cap churn — stays bit-identical to a cold cache.
3. COALESCER STREAM WINDOWS: while cohort N is in its link flight,
   cohort N+1 buffers arrivals and dispatches when N's fetch completes
   (the double-buffered launch/fetch stream).
"""

import threading
import time

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import ChunkletConfig, TableConfig
from pinot_tpu.engine.engine import QueryEngine
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.mutable import MutableSegment
from pinot_tpu.storage.segment import ImmutableSegment

N = 9000
N_ZONES = 120


def _data(n=N, seed=7):
    rng = np.random.default_rng(seed)
    return {
        "zone": np.array([f"z{i:03d}" for i in range(N_ZONES)])[
            rng.integers(0, N_ZONES, n)],
        "hour": rng.integers(0, 24, n).astype(np.int32),
        "fare": rng.integers(1, 10_000, n).astype(np.int64),
    }


def _schema(name="t"):
    return Schema.build(
        name=name,
        dimensions=[("zone", DataType.STRING)],
        metrics=[("hour", DataType.INT), ("fare", DataType.LONG)])


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    base = tmp_path_factory.mktemp("subrtt")
    data = _data()
    cfg = TableConfig(table_name="t")
    out = []
    for i in range(3):
        sl = slice(i * N // 3, (i + 1) * N // 3)
        build_segment(_schema(), {k: v[sl] for k, v in data.items()},
                      str(base / f"s{i}"), cfg, f"s{i}")
        out.append(ImmutableSegment(str(base / f"s{i}")))
    return out


def make_engine(segs, device="auto"):
    eng = QueryEngine(device_executor=device)
    for s in segs:
        eng.add_segment("t", s)
    return eng


@pytest.fixture(scope="module")
def engines(segs):
    return make_engine(segs), make_engine(segs, device=None)


def rows_of(eng, sql):
    r = eng.execute(sql)
    assert not r.get("exceptions"), (sql, r)
    return r["resultTable"]["rows"]


TRIMMED_QUERIES = [
    # aggregation order keys, asc + desc, with group-col tiebreaks
    "SELECT zone, COUNT(*) FROM t GROUP BY zone "
    "ORDER BY COUNT(*) DESC LIMIT 10",
    "SELECT zone, SUM(fare) FROM t GROUP BY zone "
    "ORDER BY SUM(fare) DESC, zone LIMIT 5",
    "SELECT zone, SUM(fare) FROM t GROUP BY zone "
    "ORDER BY SUM(fare), zone DESC LIMIT 5",
    "SELECT zone, AVG(fare) FROM t WHERE hour < 12 GROUP BY zone "
    "ORDER BY AVG(fare) LIMIT 7",
    "SELECT zone, MIN(fare), MAX(fare) FROM t GROUP BY zone "
    "ORDER BY MIN(fare), zone LIMIT 6",
    "SELECT zone, MINMAXRANGE(fare) FROM t GROUP BY zone "
    "ORDER BY MINMAXRANGE(fare) DESC, zone LIMIT 4",
    # group-column order keys
    "SELECT zone, COUNT(*) FROM t GROUP BY zone ORDER BY zone LIMIT 9",
    "SELECT zone, COUNT(*) FROM t GROUP BY zone ORDER BY zone DESC LIMIT 9",
    # no ORDER BY: terminal truncation in group order
    "SELECT zone, COUNT(*), SUM(fare) FROM t GROUP BY zone LIMIT 12",
    # ORDER BY an agg that is NOT selected (aggregations() carries it)
    "SELECT zone FROM t GROUP BY zone ORDER BY SUM(fare) DESC LIMIT 8",
    # OFFSET pagination rides the keep bound
    "SELECT zone, COUNT(*) FROM t GROUP BY zone "
    "ORDER BY COUNT(*) DESC, zone LIMIT 10 OFFSET 5",
]


class TestDeviceReduceParity:
    @pytest.mark.parametrize("sql", TRIMMED_QUERIES)
    def test_trimmed_matches_host_and_untrimmed(self, engines, sql):
        dev, host = engines
        want = rows_of(host, sql)
        assert rows_of(dev, sql) == want
        off = "SET useDeviceReduce=false; SET usePartialsCache=false; " + sql
        assert rows_of(dev, off) == want

    def test_trim_actually_ran(self, segs):
        eng = make_engine(segs)
        d0 = eng.device.device_reduce_queries
        rows_of(eng, TRIMMED_QUERIES[0])
        assert eng.device.device_reduce_queries == d0 + 1
        # and the trimmed fetch moves fewer bytes than the full table
        b0 = eng.device.fetch_bytes_total
        rows_of(eng, "SET usePartialsCache=false; " + TRIMMED_QUERIES[1])
        trimmed = eng.device.fetch_bytes_total - b0
        b0 = eng.device.fetch_bytes_total
        rows_of(eng, "SET useDeviceReduce=false; SET usePartialsCache=false; "
                + TRIMMED_QUERIES[1])
        untrimmed = eng.device.fetch_bytes_total - b0
        assert 0 < trimmed < untrimmed

    def test_mesh_parity(self, segs, engines):
        from pinot_tpu.engine.device import DeviceExecutor
        from pinot_tpu.parallel.mesh import make_mesh

        _, host = engines
        eng = QueryEngine(device_executor=DeviceExecutor(mesh=make_mesh(8)))
        for s in segs:
            eng.add_segment("t", s)
        for sql in TRIMMED_QUERIES[:4] + TRIMMED_QUERIES[8:9]:
            assert rows_of(eng, sql) == rows_of(host, sql), sql

    def test_sorted_regime_topk(self, tmp_path):
        """High-cardinality (radix) regime: the trim consumes the keyed
        merged table (skeys), solo and on the mesh."""
        from pinot_tpu.engine.device import DeviceExecutor
        from pinot_tpu.parallel.mesh import make_mesh

        rng = np.random.default_rng(3)
        n = 12000
        cols = {
            "a": np.array([f"a{i:04d}" for i in range(2500)])[
                rng.integers(0, 2500, n)],
            "b": np.array([f"b{i:04d}" for i in range(2500)])[
                rng.integers(0, 2500, n)],
            "v": rng.integers(1, 1000, n).astype(np.int64),
        }
        schema = Schema.build(
            name="hc", dimensions=[("a", DataType.STRING),
                                   ("b", DataType.STRING)],
            metrics=[("v", DataType.LONG)])
        build_segment(schema, cols, str(tmp_path / "s0"),
                      TableConfig(table_name="hc"), "s0")
        seg = ImmutableSegment(str(tmp_path / "s0"))
        host = QueryEngine(device_executor=None)
        solo = QueryEngine()
        mesh = QueryEngine(device_executor=DeviceExecutor(mesh=make_mesh(8)))
        for e in (host, solo, mesh):
            e.add_segment("hc", seg)
        sql = ("SELECT a, b, SUM(v) FROM hc GROUP BY a, b "
               "ORDER BY SUM(v) DESC, a, b LIMIT 8")
        want = rows_of(host, sql)
        assert rows_of(solo, sql) == want
        assert rows_of(mesh, sql) == want
        shapes = {t[0] for (t, *_rest) in solo.device._pipelines}
        assert "groupby_sorted" in shapes

    def test_consuming_chunklet_parity(self):
        cfg = TableConfig(
            table_name="rt",
            chunklets=ChunkletConfig(enabled=True, rows_per_chunklet=2048,
                                     device_min_rows=0))
        data = _data(n=7000, seed=11)
        rows = [{"zone": str(data["zone"][i]), "hour": int(data["hour"][i]),
                 "fare": int(data["fare"][i])} for i in range(7000)]
        seg = MutableSegment(_schema("rt"), "rt__0__0__0", cfg)
        seg.index_batch(rows)
        seg.chunklet_index.promote()
        dev = QueryEngine()
        host = QueryEngine(device_executor=None)
        dev.table("rt").add_segment(seg)
        host.table("rt").add_segment(seg)
        sql = ("SELECT zone, COUNT(*), SUM(fare) FROM rt GROUP BY zone "
               "ORDER BY SUM(fare) DESC, zone LIMIT 10")
        assert rows_of(dev, sql) == rows_of(host, sql)

    def test_having_and_post_agg_order_not_trimmed(self, engines, segs):
        """Shapes whose reduce needs every group must skip the trim and
        still match the host bit-for-bit."""
        dev, host = engines
        eng = make_engine(segs)  # fresh executor: clean counters
        for sql in (
            "SELECT zone, COUNT(*) FROM t GROUP BY zone "
            "HAVING COUNT(*) > 50 ORDER BY COUNT(*) DESC, zone LIMIT 10",
            "SELECT zone, SUM(fare) FROM t GROUP BY zone "
            "ORDER BY SUM(fare) / COUNT(*) DESC, zone LIMIT 10",
        ):
            assert rows_of(eng, sql) == rows_of(host, sql), sql
        assert eng.device.device_reduce_queries == 0

    def test_num_groups_limit_fallback(self, engines):
        """numGroupsLimit pressure makes the trimmed table unable to
        reproduce the host's present-order drop: the fetch falls back to
        the host path, results and flags stay identical."""
        dev, host = engines
        sql = ("SET numGroupsLimit=15; SELECT zone, COUNT(*) FROM t "
               "GROUP BY zone ORDER BY COUNT(*) DESC LIMIT 10")
        rd, rh = dev.execute(sql), host.execute(sql)
        assert not rd.get("exceptions") and not rh.get("exceptions")
        assert rd["resultTable"]["rows"] == rh["resultTable"]["rows"]
        assert rd["numGroupsLimitReached"] == rh["numGroupsLimitReached"]

    def test_server_partial_mode_sorted(self, tmp_path):
        """Non-terminal sole partial (server→broker): the in-kernel trim
        applies the trim_group_by keep bound, and the finalized answer
        matches the host server's."""
        from pinot_tpu.engine.reduce import finalize, trim_group_by
        from pinot_tpu.query.optimizer import optimize_query
        from pinot_tpu.sql.compiler import compile_query

        rng = np.random.default_rng(5)
        n = 10000
        cols = {
            "a": np.array([f"a{i:04d}" for i in range(2500)])[
                rng.integers(0, 2500, n)],
            "b": np.array([f"b{i:04d}" for i in range(2500)])[
                rng.integers(0, 2500, n)],
            "v": rng.integers(1, 1000, n).astype(np.int64),
        }
        schema = Schema.build(
            name="hc", dimensions=[("a", DataType.STRING),
                                   ("b", DataType.STRING)],
            metrics=[("v", DataType.LONG)])
        build_segment(schema, cols, str(tmp_path / "s0"),
                      TableConfig(table_name="hc"), "s0")
        seg = ImmutableSegment(str(tmp_path / "s0"))
        dev = QueryEngine()
        host = QueryEngine(device_executor=None)
        dev.add_segment("hc", seg)
        host.add_segment("hc", seg)
        q = optimize_query(compile_query(
            "SELECT a, b, SUM(v) FROM hc GROUP BY a, b "
            "ORDER BY SUM(v) DESC, a, b LIMIT 8"))
        got, want = [], []
        for eng, out in ((dev, got), (host, want)):
            tdm = eng.tables["hc"]
            acq = tdm.acquire()
            try:
                merged = eng.execute_segments(q, acq, terminal=False)
                merged = trim_group_by(q, merged)  # the server-side step
                out.append(finalize(q, merged).rows)
            finally:
                tdm.release(acq)
        assert got == want
        # the sorted table (100k slots) exceeds the 5000-row keep bound,
        # so the partial-mode trim genuinely engaged
        assert dev.device.device_reduce_queries >= 1


class TestPartialsCache:
    def test_repeat_hits_and_flag(self, segs):
        eng = make_engine(segs)
        d = eng.device
        sql = TRIMMED_QUERIES[0]
        r1 = eng.execute(sql)
        h0, m0 = d.partials_hits, d.partials_misses
        r2 = eng.execute(sql)
        assert d.partials_hits == h0 + 1
        assert r1["resultTable"]["rows"] == r2["resultTable"]["rows"]
        assert r1["partialsCacheHit"] is False
        assert r2["partialsCacheHit"] is True
        # a different literal is a different digest: miss, correct result
        r3 = eng.execute(
            "SELECT zone, AVG(fare) FROM t WHERE hour < 5 GROUP BY zone "
            "ORDER BY AVG(fare) LIMIT 7")
        assert d.partials_misses > m0
        assert r3["partialsCacheHit"] is False
        # SET usePartialsCache=false bypasses both lookup and insert
        h1, m1 = d.partials_hits, d.partials_misses
        eng.execute("SET usePartialsCache=false; " + sql)
        assert (d.partials_hits, d.partials_misses) == (h1, m1)

    def test_hbm_stats_and_bytes(self, segs):
        eng = make_engine(segs)
        rows_of(eng, TRIMMED_QUERIES[0])
        stats = eng.device.hbm_stats()
        assert stats["partials_cache_entries"] == 1
        assert stats["partials_cache_bytes"] > 0
        assert stats["device_reduce_queries"] == 1
        assert stats["device_reduce_ms"] >= 0

    def test_entry_cap_eviction_churn(self, segs, engines):
        _, host = engines
        eng = make_engine(segs)
        d = eng.device
        d.MAX_CACHED_PARTIALS = 1
        sqls = [f"SELECT SUM(fare) FROM t WHERE hour < {h}"
                for h in (3, 9, 15)]
        want = [rows_of(host, s) for s in sqls]
        for _round in range(3):
            for s, w in zip(sqls, want):
                assert rows_of(eng, s) == w
        assert d.partials_evictions > 0
        assert len(d._partials) <= 1
        assert d.partials_bytes >= 0

    def test_batch_eviction_drops_entries(self, segs, tmp_path, engines):
        """MAX_CACHED_BATCHES=1 churn: alternating tables evict batches;
        their cached partials die with them and every result stays
        bit-identical to a cold cache (the host oracle)."""
        _, host = engines
        data2 = _data(n=4000, seed=23)
        build_segment(_schema("t2"), data2, str(tmp_path / "u0"),
                      TableConfig(table_name="t2"), "u0")
        seg2 = ImmutableSegment(str(tmp_path / "u0"))
        host2 = QueryEngine(device_executor=None)
        host2.add_segment("t2", seg2)
        eng = make_engine(segs)
        eng.add_segment("t2", seg2)
        eng.device.MAX_CACHED_BATCHES = 1
        q1 = TRIMMED_QUERIES[1]
        q2 = ("SELECT zone, COUNT(*) FROM t2 GROUP BY zone "
              "ORDER BY COUNT(*) DESC, zone LIMIT 6")
        w1, w2 = rows_of(host, q1), rows_of(host2, q2)
        for _round in range(3):
            assert rows_of(eng, q1) == w1
            assert rows_of(eng, q2) == w2
        assert eng.device.batch_evictions > 0
        # entries for evicted batches are gone: at most the live batch's
        assert all(k[1] in eng.device._batches
                   for k in eng.device._partials)

    def _consuming(self, rows_per=1024, n=5000, seed=29, upsert=False):
        cfg = TableConfig(
            table_name="rt",
            chunklets=ChunkletConfig(enabled=True,
                                     rows_per_chunklet=rows_per,
                                     device_min_rows=0))
        data = _data(n=n, seed=seed)
        rows = [{"zone": str(data["zone"][i]), "hour": int(data["hour"][i]),
                 "fare": int(data["fare"][i])} for i in range(n)]
        seg = MutableSegment(_schema("rt"), "rt__0__0__0", cfg,
                             enable_upsert=upsert)
        seg.index_batch(rows)
        seg.chunklet_index.promote()
        dev = QueryEngine()
        host = QueryEngine(device_executor=None)
        dev.table("rt").add_segment(seg)
        host.table("rt").add_segment(seg)
        return seg, rows, dev, host

    RT_SQL = ("SELECT zone, COUNT(*), SUM(fare) FROM rt GROUP BY zone "
              "ORDER BY SUM(fare) DESC, zone LIMIT 10")

    def test_promotion_invalidation(self):
        seg, rows, dev, host = self._consuming()
        assert rows_of(dev, self.RT_SQL) == rows_of(host, self.RT_SQL)
        assert dev.execute(self.RT_SQL)["partialsCacheHit"] is True
        # more rows + promotion: the chunklet set changes; the repeat
        # query must see the new rows, never a stale cached buffer
        extra = [{"zone": "z000", "hour": 1, "fare": 9999}] * 2100
        seg.index_batch(extra)
        seg.chunklet_index.promote()
        r = dev.execute(self.RT_SQL)
        assert r["partialsCacheHit"] is False
        assert r["resultTable"]["rows"] == rows_of(host, self.RT_SQL)

    def test_upsert_invalidation(self):
        seg, rows, dev, host = self._consuming(upsert=True)
        assert rows_of(dev, self.RT_SQL) == rows_of(host, self.RT_SQL)
        assert dev.execute(self.RT_SQL)["partialsCacheHit"] is True
        # an upsert invalidation INSIDE a promoted block dirties the
        # chunklet: the device batch re-forms without it, the cached
        # entry cannot serve, results match the masked host scan
        seg.invalidate(10)
        r = dev.execute(self.RT_SQL)
        assert r["partialsCacheHit"] is False
        assert r["resultTable"]["rows"] == rows_of(host, self.RT_SQL)

    def test_seal_invalidation(self, tmp_path):
        seg, rows, dev, host = self._consuming(seed=31)
        rows_of(dev, self.RT_SQL)
        pref = f"<chunklet:{seg.segment_name}:"
        assert any(any(pref in d for d in k[1])
                   for k in dev.device._partials)
        seg.seal(str(tmp_path / "sealed"))
        assert not any(any(pref in d for d in k[1])
                       for k in dev.device._partials)

    def test_invalidate_partials_direct(self, segs):
        from pinot_tpu.engine.device import invalidate_cached_partials

        eng = make_engine(segs)
        rows_of(eng, TRIMMED_QUERIES[0])
        assert len(eng.device._partials) == 1
        invalidate_cached_partials(segs[0].dir)
        assert len(eng.device._partials) == 0
        assert eng.device.partials_bytes == 0


class TestStreamWindows:
    def test_successor_buffers_until_predecessor_fetch(self):
        """Double-buffered launch/fetch: arrivals during cohort N's link
        flight accumulate into ONE successor cohort that dispatches when
        N's fetch completes."""
        from pinot_tpu.engine.inflight import LaunchCoalescer

        co = LaunchCoalescer(window_s=0.001, stream_cap_s=5.0)
        co.force = True
        release_fetch = threading.Event()
        dispatched = []

        def launch_fn(members):
            dispatched.append(list(members))

            def resolve():
                release_fetch.wait(10)
                return {"x": np.zeros((len(members), 1))}

            return resolve

        # cohort 1: leader dispatches, fetch blocks on release_fetch
        c1, _ = co.join("k", {"p": 1}, launch_fn)
        t1 = threading.Thread(target=lambda: c1.resolve_member(0))
        t1.start()
        time.sleep(0.05)
        # cohort 2: two arrivals during cohort 1's flight
        out = [None, None]

        def second(i):
            c, idx = co.join("k", {"p": 10 + i}, launch_fn)
            out[i] = (c, idx)

        w0 = threading.Thread(target=second, args=(0,))
        w0.start()
        time.sleep(0.1)
        w1 = threading.Thread(target=second, args=(1,))
        w1.start()
        time.sleep(0.2)
        # predecessor still fetching: the successor must NOT have
        # dispatched yet (its window keys off c1.fetch_done)
        assert len(dispatched) == 1
        assert co.stream_windows == 1
        release_fetch.set()
        t1.join(10)
        w0.join(10)
        w1.join(10)
        assert len(dispatched) == 2
        # BOTH second-wave arrivals buffered into one cohort
        assert len(dispatched[1]) == 2
        c2a, _ = out[0]
        c2b, _ = out[1]
        assert c2a is c2b
        # cohort 2 resolves normally
        c2a.resolve_member(0)

    def test_all_abandoned_cohort_signals_fetch_done(self, segs):
        """Members that release() without fetching (deadline expiry,
        upstream failure) must still conclude the cohort: once every
        member abandons, fetch_done fires and the next stream window
        dispatches immediately instead of polling out its cap."""
        eng = make_engine(segs)
        dev = eng.device
        dev.partials_cache_enabled = False  # handles must reach the cohort
        co = dev.coalescer
        co.force = True
        from pinot_tpu.query.optimizer import optimize_query
        from pinot_tpu.sql.compiler import compile_query

        q = optimize_query(compile_query(
            "SELECT zone, COUNT(*) FROM t GROUP BY zone"))
        q = eng._expand_star(q, segs[0])
        try:
            handle = dev.launch(q, list(segs))
            handle.release()  # abandoned, never fetched
        finally:
            co.force = False
        done = co._last_dispatched.get(next(iter(co._last_dispatched)))
        assert done is not None and done.is_set()
        assert dev.inflight == 0

    def test_stream_cap_bounds_abandoned_predecessor(self):
        """A predecessor nobody ever fetches must not stall the stream
        past stream_cap_s."""
        from pinot_tpu.engine.inflight import LaunchCoalescer

        co = LaunchCoalescer(window_s=0.001, stream_cap_s=0.05)
        co.force = True

        def launch_fn(members):
            return lambda: {"x": np.zeros((len(members), 1))}

        c1, _ = co.join("k", {"p": 1}, launch_fn)  # never fetched
        t0 = time.monotonic()
        c2, _ = co.join("k", {"p": 2}, launch_fn)
        took = time.monotonic() - t0
        assert took < 2.0  # bounded by the cap, not the 10s member wait
        assert c2.ready.is_set()


class TestExplainAndLog:
    def test_explain_lines(self, engines):
        dev, _ = engines
        r = dev.execute("EXPLAIN PLAN FOR " + TRIMMED_QUERIES[0])
        ops = [row[0] for row in r["resultTable"]["rows"]]
        assert any(op.strip().startswith("DEVICE_REDUCE(trim=10")
                   for op in ops), ops
        assert any(op.strip().startswith("CACHED_PARTIALS(")
                   for op in ops), ops
        # HAVING: no trim line
        r2 = dev.execute(
            "EXPLAIN PLAN FOR SELECT zone, COUNT(*) FROM t GROUP BY zone "
            "HAVING COUNT(*) > 2 ORDER BY COUNT(*) DESC LIMIT 10")
        ops2 = [row[0] for row in r2["resultTable"]["rows"]]
        assert not any("DEVICE_REDUCE" in op for op in ops2), ops2

    def test_querylog_per_template_hit_rate(self):
        from pinot_tpu.tools.querylog import summarize

        entries = [
            {"template": "T1", "timeUsedMs": 5.0,
             "counters": {"partialsCacheHit": True}},
            {"template": "T1", "timeUsedMs": 9.0,
             "counters": {"partialsCacheHit": False}},
            {"template": "T2", "timeUsedMs": 4.0,
             "counters": {"partialsCacheHit": True}},
        ]
        s = summarize(entries, per_template=True)
        assert s["templates"]["T1"]["cacheHitRate"] == 0.5
        assert s["templates"]["T2"]["cacheHitRate"] == 1.0
