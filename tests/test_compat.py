"""Compatibility verifier (CompatibilityOpsRunner / compCheck.sh analog)."""

import textwrap

from pinot_tpu.tools.compat import load_suite, main, run_suite_file


class TestCompatRunner:
    def test_sample_suite_passes(self):
        results = run_suite_file("compat/sample-suite.yaml", timeout_s=30.0)
        assert results, "suite executed no ops"
        failures = [r for r in results if r[2] != "PASS"]
        assert not failures, failures

    def test_cli_exit_codes(self, tmp_path, capsys):
        good = tmp_path / "good.yaml"
        good.write_text(textwrap.dedent("""
            operations:
              - type: tableOp
                op: CREATE
                schema:
                  name: t1
                  dimensions: [[k, STRING]]
                  metrics: [[v, LONG]]
                tableConfig: {table_name: t1}
              - type: segmentOp
                op: UPLOAD
                table: t1
                segmentName: s0
                rows: [{k: a, v: 1}]
              - type: queryOp
                sql: SELECT SUM(v) FROM t1
                expectedRows: [[1]]
        """))
        assert main(["--suite", str(good)]) == 0
        assert "3/3 ops passed" in capsys.readouterr().out

        bad = tmp_path / "bad.yaml"
        bad.write_text(textwrap.dedent("""
            operations:
              - type: tableOp
                op: CREATE
                schema:
                  name: t2
                  dimensions: [[k, STRING]]
                  metrics: [[v, LONG]]
                tableConfig: {table_name: t2}
              - type: segmentOp
                op: UPLOAD
                table: t2
                segmentName: s0
                rows: [{k: a, v: 1}]
              - type: queryOp
                sql: SELECT SUM(v) FROM t2
                expectedRows: [[999]]
        """))
        assert main(["--suite", str(bad), "--timeout", "3"]) == 1
        out = capsys.readouterr().out
        assert "FAIL" in out and "2/3 ops passed" in out

    def test_yaml_and_json_suites(self, tmp_path):
        y = tmp_path / "s.yaml"
        y.write_text("operations:\n  - {type: queryOp, sql: 'SELECT 1'}\n")
        assert load_suite(str(y))["operations"][0]["type"] == "queryOp"
        j = tmp_path / "s.json"
        j.write_text('{"operations": [{"type": "queryOp", "sql": "SELECT 1"}]}')
        assert load_suite(str(j))["operations"][0]["type"] == "queryOp"
