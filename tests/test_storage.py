"""Storage substrate tests: dictionary, segment create/load round-trip,
inverted index, bloom, device upload. Mirrors the reference's tier-1 unit
tests for index creators/readers (SURVEY.md section 4)."""

import numpy as np
import pytest

from pinot_tpu.common.datatypes import DataType
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.storage.bloom import BloomFilter
from pinot_tpu.storage.creator import build_segment
from pinot_tpu.storage.dictionary import Dictionary
from pinot_tpu.storage.segment import Encoding, ImmutableSegment


class TestDictionary:
    def test_build_roundtrip_ints(self):
        raw = np.array([5, 3, 5, 7, 3, 3], dtype=np.int64)
        d, ids = Dictionary.build(raw)
        assert list(d.values) == [3, 5, 7]
        np.testing.assert_array_equal(d.take(ids), raw)

    def test_strings_sorted(self):
        raw = np.array(["b", "a", "c", "a"], dtype=np.str_)
        d, ids = Dictionary.build(raw)
        assert list(d.values) == ["a", "b", "c"]
        assert d.index_of("c") == 2
        assert d.index_of("zz") == -1

    def test_ids_of_partial_hits(self):
        d, _ = Dictionary.build(np.array([10, 20, 30]))
        np.testing.assert_array_equal(d.ids_of([20, 25, 30, 5]), [1, 2])

    def test_range_ids(self):
        d, _ = Dictionary.build(np.array([10, 20, 30, 40]))
        assert d.range_ids(15, 35) == (1, 3)
        assert d.range_ids(20, 30, lower_inclusive=False) == (2, 3)
        assert d.range_ids(None, 30, upper_inclusive=False) == (0, 2)
        assert d.range_ids(100, None) == (4, 4)


class TestSegmentRoundTrip:
    def test_metadata(self, baseball_segment, baseball_columns):
        seg = baseball_segment
        assert seg.n_docs == len(baseball_columns["runs"])
        m = seg.column_metadata("playerName")
        assert m.encoding == Encoding.DICT and m.has_dictionary and m.has_bloom
        r = seg.column_metadata("runs")
        assert r.encoding == Encoding.RAW
        assert r.min_value == int(baseball_columns["runs"].min())
        assert r.max_value == int(baseball_columns["runs"].max())

    def test_values_roundtrip(self, baseball_segment, baseball_columns):
        for col in ("playerName", "yearID", "runs", "salary"):
            got = baseball_segment.values(col)
            np.testing.assert_array_equal(
                np.asarray(got), np.asarray(baseball_columns[col]).astype(got.dtype)
            )

    def test_reload_from_disk(self, baseball_segment, baseball_columns):
        seg2 = ImmutableSegment(baseball_segment.dir)
        np.testing.assert_array_equal(seg2.values("runs"), baseball_columns["runs"])

    def test_inverted_index(self, baseball_segment, baseball_columns):
        docs, off = baseball_segment.inverted("teamID")
        d = baseball_segment.dictionary("teamID")
        team = "team_7"
        tid = d.index_of(team)
        got = np.asarray(docs[off[tid] : off[tid + 1]])
        expect = np.nonzero(np.asarray(baseball_columns["teamID"]) == team)[0]
        np.testing.assert_array_equal(got, expect)

    def test_bloom(self, baseball_segment):
        bf = BloomFilter.load(baseball_segment._path("playerName.bloom.npy"))
        assert bf.might_contain("player_003")
        # fpp is ~1%, a random absent key should essentially always miss
        misses = sum(not bf.might_contain(f"absent_{i}") for i in range(200))
        assert misses >= 190


class TestMultiValue:
    def test_mv_column_roundtrip(self, tmp_path):
        schema = Schema.build(
            "mvtab",
            multi_value_dimensions=[("tags", DataType.STRING)],
            metrics=[("v", DataType.INT)],
        )
        cols = {"tags": [["a", "b"], ["b"], [], ["c", "a", "a"]], "v": [1, 2, 3, 4]}
        cfg = TableConfig(table_name="mvtab", indexing=IndexingConfig(inverted_index_columns=["tags"]))
        seg = build_segment(schema, cols, str(tmp_path / "mv0"), cfg, "mv0")
        off = seg.mv_offsets("tags")
        np.testing.assert_array_equal(off, [0, 2, 3, 3, 6])
        d = seg.dictionary("tags")
        docs, ioff = seg.inverted("tags")
        aid = d.index_of("a")
        np.testing.assert_array_equal(np.asarray(docs[ioff[aid] : ioff[aid + 1]]), [0, 3, 3])


class TestDeviceUpload:
    def test_device_segment_padding(self, baseball_segment):
        from pinot_tpu.storage.device import DeviceSegment

        ds = DeviceSegment(baseball_segment, columns=["playerName", "runs"])
        assert ds.padded % 1024 == 0 and ds.padded >= ds.n_docs
        ids = np.asarray(ds.column("playerName").data)
        assert ids.shape == (ds.padded,)
        assert (ids[ds.n_docs :] == -1).all()
        runs = np.asarray(ds.column("runs").data)
        assert runs.dtype == np.int32
        np.testing.assert_array_equal(runs[: ds.n_docs], baseball_segment.values("runs"))

    def test_batch_stacking(self, baseball_schema, baseball_columns, tmp_path):
        from pinot_tpu.storage.device import DeviceSegmentBatch

        segs = []
        for i, sl in enumerate([slice(0, 3000), slice(3000, 5000)]):
            cols = {k: np.asarray(v)[sl] for k, v in baseball_columns.items()}
            segs.append(
                build_segment(baseball_schema, cols, str(tmp_path / f"s{i}"), segment_name=f"s{i}")
            )
        batch = DeviceSegmentBatch(segs, columns=["runs"])
        arr = np.asarray(batch.column("runs").data)
        assert arr.shape == (2, batch.pad_to)
        np.testing.assert_array_equal(batch.n_docs, [3000, 2000])


class TestReviewRegressions:
    def test_bytes_column_roundtrip(self, tmp_path):
        schema = Schema.build("bt", dimensions=[("b", DataType.BYTES)], metrics=[("v", DataType.INT)])
        cols = {"b": [b"\x01\x02", b"\xff", b"\x01\x02"], "v": [1, 2, 3]}
        seg = build_segment(schema, cols, str(tmp_path / "b0"))
        got = [bytes(x) for x in seg.values("b")]
        assert got == [b"\x01\x02", b"\xff", b"\x01\x02"]

    def test_ids_of_no_truncation_false_hit(self):
        d, _ = Dictionary.build(np.array(["abc", "zz"], dtype=np.str_))
        assert len(d.ids_of(["abcd"])) == 0
        assert list(d.ids_of(["abc", "abcd", "zz"])) == [0, 1]

    def test_ids_of_empty_dictionary(self):
        d, _ = Dictionary.build(np.array([], dtype=np.int64))
        assert len(d.ids_of([1, 2])) == 0

    def test_ids_of_float_query_on_int_dict(self):
        d, _ = Dictionary.build(np.array([1, 2, 3], dtype=np.int64))
        assert len(d.ids_of(np.array([2.5]))) == 0
        assert list(d.ids_of(np.array([2.0]))) == [1]

    def test_empty_segment(self, tmp_path):
        schema = Schema.build("e", dimensions=[("a", DataType.STRING)], metrics=[("m", DataType.INT)])
        seg = build_segment(schema, {"a": [], "m": []}, str(tmp_path / "e0"))
        assert seg.n_docs == 0
